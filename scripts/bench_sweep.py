"""On-chip tuning sweep for the north-star e2e workload.

Runs a sequence of single-measurement subprocesses (bench.py's isolation
pattern: a crashed/wedged TPU worker must not take the orchestrator down)
covering the tuning axes PERF.md lists as unmeasured:

  * dense flash Pallas kernel vs XLA streaming (scripts/bench_kernels.py)
    at the axial shape the crop-384 workload produces;
  * e2e depth-12 step time across {kernel on/off}, {attn_batch_chunk},
    {flash_tile_elems}, {mds_bwd_iters}.

Each attempt gets its own timeout; on the first TIMEOUT the sweep assumes
the tunnel wedged and stops launching (a wedged worker hangs every later
backend init), reporting what completed. Results append to
PERF_SWEEP.jsonl (one JSON line per measurement).

Usage: python scripts/bench_sweep.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "PERF_SWEEP.jsonl")
sys.path.insert(0, os.path.join(REPO, "scripts"))
from tpu_lock import LOCK_BUSY, tpu_lock  # noqa: E402  (tunnel lock)

E2E_WORKER = r"""
import json, sys, time
import jax
import numpy as np

spec = json.loads(sys.argv[1])

if spec.get("require_tpu") and jax.devices()[0].platform != "tpu":
    # structured skip (the overlap legs' pattern): the schedule/fusion
    # A/B legs are TPU measurements — a CPU fallback number would be
    # recorded as if it were one
    print(json.dumps({"skipped": "leg requires a TPU device",
                      "platform": jax.devices()[0].platform}))
    sys.exit(0)

from alphafold2_tpu.training import (
    DataConfig, TrainConfig, e2e_loss_fn, e2e_train_state_init,
    make_train_step, north_star_e2e_config, stack_microbatches,
    synthetic_structure_batches,
)

depth = spec["depth"]
# ONE source for the north-star config (training/presets.py); the sweep's
# tuning axes are override patches so a knob rename breaks loudly here.
# Knobs ABSENT from the spec follow the preset defaults (depth-aware
# attention chunk/tile resolver, promoted 25-iter classical MDS), so the
# base legs always measure exactly the driver-bench configuration.
ecfg, crop, msa_rows = north_star_e2e_config(
    depth,
    model_overrides=dict(
        **({"attn_flash_qb_target": spec["qb_target"]}
           if "qb_target" in spec else {}),
        **({"attn_batch_chunk": spec["batch_chunk"]}
           if "batch_chunk" in spec else {}),
        **({"attn_flash_tile_elems": spec["tile_elems"]}
           if "tile_elems" in spec else {}),
        **({"ff_chunk_size": spec["ff_chunk"]} if "ff_chunk" in spec else {}),
        **({"attn_flash_compute_dtype_logits": spec["logit_bf16"]}
           if "logit_bf16" in spec else {}),
        **({"trunk_schedule": spec["trunk_schedule"]}
           if "trunk_schedule" in spec else {}),
        **({"attn_gate": spec["attn_gate"]} if "attn_gate" in spec else {}),
        **{k: spec[k] for k in ("heads", "dim_head") if k in spec},
    ),
    e2e_overrides=dict(
        **({"mds_bwd_iters": spec["mds_bwd_iters"]}
           if "mds_bwd_iters" in spec else {}),
        **({"mds_unroll": spec["mds_unroll"]}
           if "mds_unroll" in spec else {}),
        **({"mds_init": spec["mds_init"]} if "mds_init" in spec else {}),
        **({"mds_iters": spec["mds_iters"]} if "mds_iters" in spec else {}),
    ),
)
# Kernel policy (spec["kernel"]):
#   "force" -> zero the auto-dispatch j-threshold so every supported shape
#              takes the Pallas kernel (AF2_FLASH_AUTO_MIN_J=0);
#   "auto"  -> exactly what the driver bench runs (shape-aware heuristic);
#   "off"   -> no Pallas anywhere (the AF2_DISABLE_FLASH_KERNEL kill-switch).
#              NOTE: stricter than the retired e2e_nokernel leg (24.43
#              s/step), which monkeypatched only the DENSE kernel off and
#              left the block-sparse kernel live — an "off" number is not
#              directly comparable to that baseline in sparse configs.
# Env is set before any tracing, so the dispatch gate reads it everywhere.
import os
if spec["kernel"] == "force":
    os.environ["AF2_FLASH_AUTO_MIN_J"] = "0"
elif spec["kernel"] == "off":
    os.environ["AF2_DISABLE_FLASH_KERNEL"] = "1"
elif spec["kernel"] != "auto":
    raise ValueError(f"bad kernel policy {spec['kernel']!r}")
if spec.get("unfuse_gate"):
    # fused_gate control arm: Pallas kernel still runs the attention
    # core, the sigmoid gate applies as a separate XLA epilogue
    # (ops/flash.py gate_epilogue_unfused) — the on/off delta is the
    # epilogue fusion alone, not kernel-core-vs-XLA-streaming
    os.environ["AF2_UNFUSE_GATE_EPILOGUE"] = "1"

tcfg = TrainConfig(learning_rate=3e-4, grad_accum=1)
dcfg = DataConfig(batch_size=1, max_len=crop, msa_rows=msa_rows, seed=0)
batch = jax.device_put(next(stack_microbatches(synthetic_structure_batches(dcfg), 1)))
state = e2e_train_state_init(jax.random.PRNGKey(0), ecfg, tcfg)
# resident weight bytes of this leg's param tree (chip-free shape
# arithmetic; computed BEFORE the step donates the state) — the
# denominator the quant legs' residency win is measured against
from alphafold2_tpu.ops.quant import tree_weight_bytes
weight_hbm_bytes = tree_weight_bytes(state["params"])
step = make_train_step(ecfg, tcfg, loss_fn=e2e_loss_fn)

def run_one(state, batch, rng):
    s2, metrics = step(state, batch, rng)
    return s2, metrics["loss"]

compiled = jax.jit(run_one, donate_argnums=(0,)).lower(
    state, batch, jax.random.PRNGKey(1)).compile()
state, loss = compiled(state, batch, jax.random.PRNGKey(1))
np.asarray(loss)  # fetch: dispatch-proof warmup
t0 = time.perf_counter()
state, loss = compiled(state, batch, jax.random.PRNGKey(2))
loss = float(np.asarray(loss))
dt = time.perf_counter() - t0
assert np.isfinite(loss), loss
# the cross-backend matrix contract: every row records WHICH arm ran —
# resolved by the registry at the axial folded shape this leg's
# attention actually hits (crop*3 x crop*3), under the leg's env policy
from alphafold2_tpu.ops import dispatch as _dispatch
backend_arm = _dispatch.resolve("flash_attention", request="auto",
                                i=crop * 3, j=crop * 3,
                                dh=ecfg.model.dim_head)
print(json.dumps({"sec_per_step": round(dt, 2), "loss": round(loss, 4),
                  "weight_hbm_bytes": weight_hbm_bytes,
                  "platform": jax.devices()[0].platform,
                  "backend_arm": backend_arm}))
"""


# int8 weight-quantization A/B (ISSUE 8 tentpole): SERVING-shaped
# inference — the trunk forward -> distogram -> MDS pipeline the engine
# AOT-compiles — at the north-star model configuration, f32 master
# weights vs the per-channel-PTQ int8 tree through the fused-dequant
# Pallas matmul. BOTH arms pin the same forced attention-kernel core
# (AF2_FLASH_AUTO_MIN_J=0), so the on/off delta isolates the weight
# path: int8 HBM weight traffic + in-kernel dequant vs full fp32 weight
# reads. weight_hbm_bytes rides along so the residency win and the
# latency delta come from the same row. TPU legs (require_tpu:
# structured skip elsewhere — a CPU number would not measure HBM).
QUANT_WORKER = r"""
import json, sys, time, os
spec = json.loads(sys.argv[1])
os.environ["AF2_FLASH_AUTO_MIN_J"] = "0"   # same forced kernel core, both arms
if spec["weight_dtype"] == "int8":
    # force the fused-dequant kernel: a silent XLA-dequant fallback would
    # record fp32-traffic numbers under the int8 leg's name
    os.environ["AF2_QUANT_KERNEL"] = "force"
import jax
import numpy as np

if spec.get("require_tpu") and jax.devices()[0].platform != "tpu":
    print(json.dumps({"skipped": "leg requires a TPU device",
                      "platform": jax.devices()[0].platform}))
    sys.exit(0)

import dataclasses
import jax.numpy as jnp
from alphafold2_tpu.models import alphafold2_init
from alphafold2_tpu.ops.quant import quantize_tree, tree_weight_bytes
from alphafold2_tpu.serving.pipeline import predict_structure
from alphafold2_tpu.training import north_star_e2e_config

ecfg, crop, msa_rows = north_star_e2e_config(spec["depth"])
cfg = dataclasses.replace(ecfg.model, weight_dtype=spec["weight_dtype"])
# fp32 master init, PTQ as the serving tier would at engine build
params = alphafold2_init(jax.random.PRNGKey(0), ecfg.model)
if spec["weight_dtype"] == "int8":
    params = quantize_tree(params)
weight_hbm_bytes = tree_weight_bytes(params)
params = jax.device_put(params)

L = spec.get("len", crop)
rs = np.random.RandomState(0)
tokens = jnp.asarray(rs.randint(0, 21, (1, L)), jnp.int32)
mask = jnp.ones((1, L), bool)
msa = jnp.asarray(rs.randint(0, 21, (1, msa_rows, L)), jnp.int32)
msa_mask = jnp.ones((1, msa_rows, L), bool)

def run(params, tokens, mask, msa, msa_mask, key):
    out = predict_structure(params, cfg, tokens, mask=mask, msa=msa,
                            msa_mask=msa_mask, rng=key,
                            mds_iters=25, mds_init="classical")
    return out["coords"], out["confidence"]

compiled = jax.jit(run).lower(
    params, tokens, mask, msa, msa_mask, jax.random.PRNGKey(1)).compile()
c, _ = compiled(params, tokens, mask, msa, msa_mask, jax.random.PRNGKey(1))
np.asarray(c)  # fetch: dispatch-proof warmup
iters = spec.get("iters", 3)
t0 = time.perf_counter()
for i in range(iters):
    c, _ = compiled(params, tokens, mask, msa, msa_mask,
                    jax.random.PRNGKey(2 + i))
c.block_until_ready()
dt = (time.perf_counter() - t0) / iters
assert np.isfinite(np.asarray(c)).all()
# record which arm actually served the weight path (the int8 arm pins
# AF2_QUANT_KERNEL=force above, so the resolver must answer pallas_tpu
# or raise; the f32 arm has no quant op in the program — record the
# attention arm it rode instead)
from alphafold2_tpu.ops import dispatch as _dispatch
if spec["weight_dtype"] == "int8":
    backend_arm = _dispatch.resolve("quant_matmul", request="auto",
                                    m=L, k=cfg.dim, n=cfg.dim,
                                    x_dtype=jnp.float32)
else:
    backend_arm = _dispatch.resolve("flash_attention", request="auto",
                                    i=L * 3, j=L * 3, dh=cfg.dim_head)
print(json.dumps({"sec_per_iter": round(dt, 3),
                  "weight_hbm_bytes": weight_hbm_bytes,
                  "platform": jax.devices()[0].platform,
                  "backend_arm": backend_arm}))
"""


# Chip-free quant leg: runs on ANY host (no require_tpu) so the int8
# arm's residency and quality numbers exist even while the TPU tunnel is
# unreachable. Three records in one row:
#   * north-star residency via jax.eval_shape (no params materialized):
#     weight_hbm_bytes f32 vs int8, full-tree ratio, and the >=3.5x
#     quantized-tensor ratio the ISSUE 8 acceptance pins (asserted);
#   * interpret-mode fused-dequant kernel vs the XLA dequant reference
#     arm on a real (small) model forward — allclose-pinned;
#   * int8-vs-fp32 quality deltas at the same small shapes: mean
#     distogram KL and top-L contact precision of the int8 arm scored
#     against the fp32 arm's contacts. telemetry.check gates these via
#     the *distogram_kl* (lower) / *contact_precision* (higher) rules.
QUANT_PARITY_WORKER = r"""
import json, sys, os
spec = json.loads(sys.argv[1])
import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.models import (
    Alphafold2Config, alphafold2_apply, alphafold2_init,
)
from alphafold2_tpu.ops.quant import (
    quantize_tree, quantized_path_bytes, tree_weight_bytes,
)
from alphafold2_tpu.training import north_star_e2e_config

from alphafold2_tpu.ops import dispatch as _dispatch

out = {"platform": jax.devices()[0].platform,
       # which arm the quant matmuls below actually resolve to on this
       # host (cross-backend matrix field — platform-qualifies the row)
       "backend_arm": _dispatch.resolve("quant_matmul", request="auto",
                                        m=32, k=32, n=32,
                                        x_dtype=jnp.float32)}

# 1) residency at the NORTH-STAR preset — pure shape arithmetic
ecfg, crop, msa_rows = north_star_e2e_config(spec.get("depth", 12))
shapes = jax.eval_shape(
    lambda k: alphafold2_init(k, ecfg.model), jax.random.PRNGKey(0))
qshapes = jax.eval_shape(quantize_tree, shapes)
before, after = quantized_path_bytes(shapes)
out["weight_hbm_bytes_f32"] = tree_weight_bytes(shapes)
out["weight_hbm_bytes_int8"] = tree_weight_bytes(qshapes)
out["weight_hbm_ratio"] = round(
    out["weight_hbm_bytes_f32"] / out["weight_hbm_bytes_int8"], 3)
out["quant_weight_ratio"] = round(before / after, 3)
assert out["quant_weight_ratio"] >= 3.5, out  # ISSUE 8 acceptance pin

# 2) kernel-vs-XLA parity + int8-vs-fp32 quality at CPU-runnable shapes
cfg = Alphafold2Config(dim=32, depth=2, heads=2, dim_head=16,
                       max_seq_len=48, msa_tie_row_attn=True)
params = alphafold2_init(jax.random.PRNGKey(1), cfg)
qp = quantize_tree(params)
rs = np.random.RandomState(0)
L = 32
seq = jnp.asarray(rs.randint(0, 21, (1, L)))
msa = jnp.asarray(rs.randint(0, 21, (1, 4, L)))
mask = jnp.ones((1, L), bool)
mmask = jnp.ones((1, 4, L), bool)

def logits_with(p, kernel_env):
    # eager apply: the dispatch gate re-reads AF2_QUANT_KERNEL per call
    os.environ["AF2_QUANT_KERNEL"] = kernel_env
    try:
        return np.asarray(alphafold2_apply(
            p, cfg, seq, msa, mask=mask, msa_mask=mmask), np.float32)
    finally:
        os.environ.pop("AF2_QUANT_KERNEL", None)

l_f32 = logits_with(params, "off")
l_krn = logits_with(qp, "force")  # fused-dequant kernel (interpret off-TPU)
l_xla = logits_with(qp, "off")    # XLA dequant reference arm
np.testing.assert_allclose(l_krn, l_xla, atol=5e-4)
out["kernel_vs_xla_max_abs"] = float(np.abs(l_krn - l_xla).max())

def softmax(z):
    z = z - z.max(-1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(-1, keepdims=True)

p_ref, p_q = softmax(l_f32), softmax(l_krn)
kl = (p_ref * (np.log(p_ref + 1e-9) - np.log(p_q + 1e-9))).sum(-1)
# floored at 1e-9: a recorded 0.0 baseline would turn ANY later nonzero
# KL into an infinite relative change under telemetry.check's
# lower-better rule — the floor keeps the gate's ratio math finite
out["distogram_kl"] = max(float(kl.mean()), 1e-9)

# top-L contact precision, int8 arm scored against the fp32 arm: rank
# pairs (i < j, |i-j| >= 3) by model distance (center_distogram), take
# each arm's L strongest contacts, precision = overlap / L. Rank-based,
# so it needs no absolute contact threshold a random-init distogram
# might never cross.
from alphafold2_tpu.geometry import center_distogram

def top_contacts(logits):
    d, _ = center_distogram(jnp.asarray(softmax(logits)))
    d = np.asarray(d)[0]
    ii, jj = np.triu_indices(L, k=3)
    order = np.argsort(d[ii, jj])[:L]
    return set(zip(ii[order].tolist(), jj[order].tolist()))

ref, got = top_contacts(l_f32), top_contacts(l_krn)
out["contact_precision"] = round(len(ref & got) / max(len(got), 1), 4)
print(json.dumps(out))
"""


# Chip-free featurization-overlap leg (ISSUE 11): drives a REAL tiny
# fleet (1 replica, precompiled) with a 2-worker featurize tier in front
# of the admission queue, with per-job featurize cost made non-trivial by
# a deterministic slow_featurize plan (a stand-in for real MSA assembly —
# the tier's value is structural, not CPU-speed-dependent). Records
#   featurize_overlap_ratio = (featurize busy + execute busy) / wall
# > 1 means CPU feature prep genuinely ran WHILE the engine dispatched
# (the ParaFold split working); a regression that re-serializes the tier
# drags the ratio to <= 1. Gated by telemetry.check's *overlap_ratio*
# higher-is-better rule once recorded.
FEATURIZE_WORKER = r"""
import json, sys, time
spec = json.loads(sys.argv[1])
import jax
import numpy as np

from alphafold2_tpu.constants import AA_ORDER
from alphafold2_tpu.models import Alphafold2Config, alphafold2_init
from alphafold2_tpu.reliability import Fault, FaultPlan
from alphafold2_tpu.serving import FleetConfig, ServingConfig, ServingFleet
from alphafold2_tpu.telemetry import Tracer

n = spec.get("n", 24)
delay = spec.get("featurize_delay_s", 0.08)
cfg = Alphafold2Config(dim=16, depth=1, heads=2, dim_head=8, max_seq_len=32)
params = alphafold2_init(jax.random.PRNGKey(0), cfg)
plan = FaultPlan(faults=(
    Fault("slow_featurize", at=0, count=n, delay_s=delay),
))
tracer = Tracer(enabled=True)
fleet = ServingFleet(
    params, cfg,
    ServingConfig(buckets=(16, 32), max_batch=4, max_queue=64,
                  max_wait_s=0.01, mds_iters=4, cache_capacity=0,
                  precompile=True),
    FleetConfig(replicas=1, queue_capacity=64, featurize_workers=2,
                probe_interval_s=0, default_timeout_s=300.0),
    injector=plan.injector(), tracer=tracer,
)
rng = np.random.RandomState(0)
seqs = ["".join(AA_ORDER[rng.randint(0, 20)] for _ in range(
    int(rng.randint(8, 32)))) for _ in range(n)]
t0 = time.perf_counter()
reqs = [fleet.submit(s) for s in seqs]
for r in reqs:
    r.result(timeout=300)
wall = time.perf_counter() - t0
fams = fleet.registry.collect()
feat_busy = sum(
    m.value
    for m in fams.get("featurize_busy_seconds_total", (None, {}))[1].values()
)
summary = tracer.summary()
exec_busy = summary.get("serving.execute", {}).get("total_s", 0.0)
fleet.shutdown(drain=True)
assert feat_busy > 0 and exec_busy > 0, (feat_busy, exec_busy)
ratio = (feat_busy + exec_busy) / wall
from alphafold2_tpu.ops import dispatch as _dispatch
print(json.dumps({
    "featurize_overlap_ratio": round(ratio, 3),
    "featurize_busy_s": round(feat_busy, 3),
    "execute_busy_s": round(exec_busy, 3),
    "wall_s": round(wall, 3),
    "n_requests": n,
    "platform": jax.devices()[0].platform,
    "backend_arm": _dispatch.resolve("flash_attention", request="auto",
                                     i=32, j=32, dh=8),
}))
"""


# Chip-free training-goodput leg (ISSUE 12): a short REAL run_resilient
# training run (tiny model) under the goodput ledger, with a
# deterministic slow_data fault plan stalling several fetches — so the
# leg proves badput ATTRIBUTION, not just a ratio: the injected stall
# must land in the data_fetch bucket, page a train_data_stall incident,
# and the buckets must sum to wall clock within 1%. Records
#   goodput_ratio            (telemetry.check *goodput* higher-better)
#   data_stall_badput_s      (*badput*/*stall* lower-better)
# so a pipeline regression that re-introduces data stalls gates
# automatically once recorded.
GOODPUT_WORKER = r"""
import json, sys, tempfile
spec = json.loads(sys.argv[1])
import jax
import numpy as np

from alphafold2_tpu.models import Alphafold2Config
from alphafold2_tpu.reliability import Fault, FaultPlan
from alphafold2_tpu.telemetry import MetricRegistry
from alphafold2_tpu.telemetry.goodput import (
    GoodputLedger, StragglerDetector, TrainTelemetry,
)
from alphafold2_tpu.telemetry.ops_plane import FlightRecorder
from alphafold2_tpu.training import (
    DataConfig, TrainConfig, make_train_step, resilient_batches,
    run_resilient, synthetic_microbatch_fn, train_state_init,
    with_fault_injection,
)

steps = spec.get("steps", 8)
delay = spec.get("stall_delay_s", 0.1)
cfg = Alphafold2Config(dim=16, depth=1, heads=2, dim_head=8, max_seq_len=32)
tcfg = TrainConfig(learning_rate=1e-3, grad_accum=1)
dcfg = DataConfig(batch_size=1, max_len=16, seed=0)

plan = FaultPlan(faults=(
    Fault("slow_data", at=2, count=max(2, steps // 2), delay_s=delay),
))
injector = plan.injector()
registry = MetricRegistry()
ledger = GoodputLedger(registry)
flight_dir = tempfile.mkdtemp()
recorder = FlightRecorder(flight_dir, registry=registry,
                          stats_fn=ledger.snapshot, min_interval_s=0)
detector = StragglerDetector(recorder=recorder, registry=registry,
                             patience=2, stall_fraction=0.5,
                             min_seconds=0.001)
telemetry = TrainTelemetry(ledger=ledger, detector=detector,
                           recorder=recorder)

fetch = resilient_batches(synthetic_microbatch_fn(dcfg, tcfg.grad_accum),
                          injector=injector)
state = train_state_init(jax.random.PRNGKey(0), cfg, tcfg)
step_fn = with_fault_injection(
    jax.jit(make_train_step(cfg, tcfg)), injector)
base_rng = jax.random.PRNGKey(1)
state = run_resilient(
    step_fn, state, fetch, steps=steps,
    make_rng=lambda i: jax.random.fold_in(base_rng, i),
    telemetry=telemetry,
)

snap = ledger.snapshot()
assert injector.exhausted(), "slow_data plan never fully delivered"
live_wall = ledger.wall()  # NOT snap["wall_s"] (that IS the bucket sum):
# only a live reading catches double-accounting inflating the sum
assert abs(sum(snap["buckets"].values()) - live_wall) \
    <= 0.01 * live_wall, (snap, live_wall)
stall_s = snap["buckets"]["data_fetch"]
assert stall_s >= delay, ("injected stall not booked as data-stall "
                          "badput", stall_s)
bundles = recorder.snapshot()["bundles"]
assert any("train_data_stall" in b for b in bundles), bundles
from alphafold2_tpu.ops import dispatch as _dispatch
print(json.dumps({
    "goodput_ratio": round(snap["goodput_ratio"], 4),
    "data_stall_badput_s": round(stall_s, 3),
    "wall_s": round(snap["wall_s"], 3),
    "steps_per_sec": round(steps / snap["wall_s"], 3),
    "n_steps": steps,
    "platform": jax.devices()[0].platform,
    "backend_arm": _dispatch.resolve("flash_attention", request="auto",
                                     i=16, j=16, dh=8),
}))
"""


# SP serving arm A/B (ISSUE 14 tentpole): the SAME serving-shaped
# bucket executable (engine AOT path: padded batch -> trunk -> distogram
# -> MDS) with the trunk dense vs sequence-parallel over an sp_shards
# mesh. TPU-only (require_tpu: a CPU ring measures nothing about ICI);
# additionally skips when the host exposes fewer devices than the mesh
# needs. The on-arm FORCES sp_seq at the bucket via the per-bucket
# override so the 16 GB heuristic cannot silently serve the dense twin
# under the SP leg's name.
SERVE_SP_WORKER = r"""
import json, sys, time, os
spec = json.loads(sys.argv[1])
import jax
import numpy as np

platform = jax.devices()[0].platform
if spec.get("require_tpu") and platform != "tpu":
    print(json.dumps({"skipped": "leg requires a TPU device",
                      "platform": platform}))
    sys.exit(0)
shards = spec["sp_shards"] if spec["sp_on"] else 0
if shards and len(jax.devices()) < shards:
    print(json.dumps({"skipped": f"SP mesh needs {shards} devices",
                      "platform": platform,
                      "devices": len(jax.devices())}))
    sys.exit(0)

import dataclasses
import jax.numpy as jnp
from alphafold2_tpu.models import alphafold2_init
from alphafold2_tpu.serving import ServingConfig, ServingEngine
from alphafold2_tpu.training import north_star_e2e_config
from alphafold2_tpu.constants import AA_ORDER
from alphafold2_tpu.ops import dispatch as _dispatch

bucket = spec["bucket"]
ecfg, crop, msa_rows = north_star_e2e_config(spec["depth"])
cfg = dataclasses.replace(ecfg.model, max_seq_len=bucket)
params = alphafold2_init(jax.random.PRNGKey(0), cfg)
scfg = ServingConfig(
    buckets=(bucket,), max_batch=1, mds_iters=25, cache_capacity=0,
    precompile=True, request_timeout_s=None,
    sp_shards=shards,
    sp_schedules=(((bucket, "sp_seq"),) if shards else ()),
)
t0 = time.perf_counter()
eng = ServingEngine(params, cfg, scfg)
compile_s = time.perf_counter() - t0
rs = np.random.RandomState(0)
seqs = ["".join(AA_ORDER[i] for i in rs.randint(0, 20, bucket))
        for _ in range(spec.get("iters", 3) + 1)]
try:
    eng.predict(seqs[0])  # warmup dispatch
    t0 = time.perf_counter()
    for s in seqs[1:]:
        res = eng.predict(s)
    dt = (time.perf_counter() - t0) / (len(seqs) - 1)
    assert np.isfinite(res.coords).all()
    sp_stats = eng.stats().get("sp")
finally:
    eng.shutdown()
out = {"sec_per_iter": round(dt, 3), "bucket": bucket,
       "sp_shards": shards, "compile_s": round(compile_s, 1),
       "platform": platform,
       "backend_arm": _dispatch.resolve(
           "flash_attention", request="auto", i=bucket, j=bucket,
           dh=cfg.dim_head)}
if sp_stats:
    plan = sp_stats["schedules"][str(bucket)]
    assert plan["schedule"] == "sp_seq", plan
    out["sp_total_bytes"] = plan["total_bytes"]
print(json.dumps(out))
"""


# Chip-free routed-fleet leg (ISSUE 14): the length-adaptive router end
# to end on the virtual CPU mesh — a mixed-length trace over a real
# two-pool fleet (dense short pool + sp_seq long pool), asserting every
# in-ladder request completes on its expected pool with ZERO too_long
# failures, and recording the per-pool queue-wait signals the per-pool
# autoscalers consume. Runs on ANY host (pins JAX_PLATFORMS=cpu + the
# 8-device virtual platform, like the overlap lint): the row is real
# today, not armed.
SERVE_ROUTED_WORKER = r"""
import json, sys, time, os
spec = json.loads(sys.argv[1])
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
import jax
import numpy as np

from alphafold2_tpu.models import Alphafold2Config, alphafold2_init
from alphafold2_tpu.serving import (
    FleetConfig, PoolSpec, SequenceTooLongError, ServingConfig,
    ServingFleet,
)
from alphafold2_tpu.constants import AA_ORDER

cfg = Alphafold2Config(dim=16, depth=1, heads=2, dim_head=8,
                       max_seq_len=32)
params = alphafold2_init(jax.random.PRNGKey(0), cfg)
scfg = ServingConfig(buckets=(8, 16), max_batch=2, max_wait_s=0.01,
                     mds_iters=4, request_timeout_s=None)
fleet = ServingFleet(
    params, cfg, scfg,
    FleetConfig(probe_interval_s=0, reprobe_interval_s=30.0,
                default_timeout_s=None,
                pools=(PoolSpec("short", replicas=1, buckets=(8, 16)),
                       PoolSpec("long", replicas=1, sp_shards=2,
                                buckets=(8, 16, 32)))))
rs = np.random.RandomState(0)
n = spec.get("n", 16)
lens = [int(rs.randint(4, 17)) if i % 2 else int(rs.randint(17, 33))
        for i in range(n)]
t0 = time.perf_counter()
reqs = []
shed = 0
for i, L in enumerate(lens + [40]):  # the 40-mer must shed, not fail
    seq = "".join(AA_ORDER[j] for j in rs.randint(0, 20, L))
    try:
        reqs.append((L, fleet.submit(seq)))
    except SequenceTooLongError:
        shed += 1
by_pool = {"short": 0, "long": 0}
for L, r in reqs:
    res = r.result(timeout=600)
    st = fleet.stats()["replicas"][res.replica]
    expect = "short" if L <= 16 else "long"
    assert st["pool"] == expect, (L, res.replica, st["pool"])
    by_pool[expect] += 1
wall = time.perf_counter() - t0
stats = fleet.stats()
hists = stats["telemetry"]["metrics"]["histograms"]
waits = {name: hists.get(
    f'fleet_pool_queue_wait_seconds{{pool="{name}"}}', {})
    for name in ("short", "long")}
assert stats["requests"]["failed"] == 0, stats["requests"]
assert stats["shed"].get("too_long", 0) == 1 and shed == 1
fleet.shutdown()
out = {"sec_per_iter": round(wall / len(reqs), 3),
       "routed_short": by_pool["short"], "routed_long": by_pool["long"],
       "routed_long_frac": round(by_pool["long"] / len(reqs), 3),
       "too_long_shed": shed,
       "platform": "cpu", "backend_arm": "xla_ref"}
for name, w in waits.items():
    if isinstance(w, dict) and w.get("p95") is not None:
        out[f"pool_queue_wait_p95_{name}"] = round(w["p95"], 4)
print(json.dumps(out))
"""


# Serving cost plane (ISSUE 15): chip-free leg — a REAL tiny fleet on
# CPU serves a short trace, then the row records what the cost ledger
# measured: per-request chip-seconds for the served cells, the serving
# goodput ratio, and the headroom model's capacity column. Gated by
# telemetry.check's *chip_seconds* (lower) / *serve_goodput* /
# *headroom* (higher) rules, platform-qualified like every row.
SERVE_COSTS_WORKER = r"""
import json, sys, time, os
spec = json.loads(sys.argv[1])
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
import numpy as np

from alphafold2_tpu.models import Alphafold2Config, alphafold2_init
from alphafold2_tpu.serving import FleetConfig, ServingConfig, ServingFleet
from alphafold2_tpu.constants import AA_ORDER

cfg = Alphafold2Config(dim=16, depth=1, heads=2, dim_head=8,
                       max_seq_len=16)
params = alphafold2_init(jax.random.PRNGKey(0), cfg)
fleet = ServingFleet(
    params, cfg,
    ServingConfig(buckets=(8, 16), max_batch=2, max_wait_s=0.01,
                  mds_iters=4, request_timeout_s=None),
    FleetConfig(replicas=2, probe_interval_s=0, reprobe_interval_s=30.0,
                default_timeout_s=None))
rs = np.random.RandomState(0)
n = spec.get("n", 16)
t0 = time.perf_counter()
reqs = []
for i in range(n):
    L = int(rs.randint(4, 17))
    seq = "".join(AA_ORDER[j] for j in rs.randint(0, 20, L))
    reqs.append(fleet.submit(seq))
for r in reqs:
    r.result(timeout=600)
wall = time.perf_counter() - t0
fleet.sample_gauges()
time.sleep(0.06)
fleet.sample_gauges()  # second pass: arrival-rate EMA + headroom arm
st = fleet.stats()
cells = [c for c in st["costs"]["cells"] if c["requests"]]
assert cells, "no cost-ledger cell measured"
# traffic-weighted per-request chip cost over the served cells
total_req = sum(c["requests"] for c in cells)
csr = sum(c["chip_seconds_per_request"] * c["requests"]
          for c in cells) / total_req
goodput = st["serve_goodput"]["pools"]["default"]["goodput_ratio"]
# sums-to-wall within 1% against the ledger's LIVE clock wall (the
# snapshot's wall_s is the bucket sum — comparing against it would be
# a tautology); accounted can only exceed wall via cross-thread
# accounting overlap, which this bounds
for name in st["serve_goodput"]["replicas"]:
    tot = sum(fleet.goodput.totals(name).values())
    wall_now = fleet.goodput.wall(name)
    assert tot <= wall_now * 1.01 + 1e-6, (name, tot, wall_now)
head = st["headroom"].get("default", {})
out = {"sec_per_iter": round(wall / n, 4),
       "serve_chip_seconds_per_request": round(csr, 5),
       "serve_goodput_ratio": round(goodput, 4),
       "cells_measured": len(cells),
       "platform": "cpu", "backend_arm": "xla_ref"}
if head.get("capacity_per_sec"):
    out["capacity_per_sec"] = round(head["capacity_per_sec"], 3)
    out["headroom_ratio"] = round(head["headroom_ratio"], 4)
fleet.shutdown()
print(json.dumps(out))
"""


# Cross-backend dispatch matrix (ISSUE 13 tentpole): one leg per
# (hot op, backend arm) over the ops/dispatch.py registry. The arm is
# pinned via AF2_KERNEL_BACKEND_<OP> and VERIFIED against the resolver
# (a leg that silently resolved elsewhere would record one arm's numbers
# under another's name — the worker asserts instead). xla_ref legs run
# on ANY host — which is the point: the CPU-degraded tunnel finally
# produces real, platform-qualified timed rows (telemetry.check keys
# them `<leg>.<platform>.<backend_arm>.<metric>`, so they gate against
# CPU baselines only). pallas_tpu / gpu legs carry require_platform and
# record structured skips until that hardware answers — armed, never
# silenced (skips are not "done").
DISPATCH_WORKER = r"""
import json, sys, time, os
spec = json.loads(sys.argv[1])
op, arm = spec["op"], spec["arm"]
os.environ["AF2_KERNEL_BACKEND_" + op.upper()] = arm
import jax
import jax.numpy as jnp
import numpy as np

platform = jax.devices()[0].platform
base = {"op": op, "backend_arm": arm, "platform": platform}
need = spec.get("require_platform")
# "gpu" must admit every GPU spelling jax reports (cuda/rocm on newer
# builds) — the registry's own platform set, mirrored in the worker
satisfied = {"gpu": ("gpu", "cuda", "rocm")}.get(need, (need,))
if need and platform not in satisfied:
    print(json.dumps({**base, "skipped": f"leg requires a {need} device"}))
    sys.exit(0)

from alphafold2_tpu.ops import dispatch

iters = spec.get("iters", 5)
key = jax.random.PRNGKey(0)


def timeit(fn, *args):
    compiled = jax.jit(fn).lower(*args).compile()
    np.asarray(jax.tree_util.tree_leaves(compiled(*args))[0])  # warmup
    t0 = time.perf_counter()
    for _ in range(iters):
        out = compiled(*args)
    jax.tree_util.tree_leaves(out)[0].block_until_ready()
    return (time.perf_counter() - t0) / iters


if op in ("flash_attention", "fused_attention"):
    from alphafold2_tpu.ops.flash import flash_attention

    B, i, j, h, dh = 8, 512, 512, 8, 64
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (B, i, h, dh))
    k = jax.random.normal(ks[1], (B, j, h, dh))
    v = jax.random.normal(ks[2], (B, j, h, dh))
    resolved = dispatch.resolve(op, request="auto", i=i, j=j, dh=dh)
    assert resolved == arm, (resolved, arm)
    if op == "flash_attention":
        dt = timeit(lambda q, k, v: flash_attention(q, k, v), q, k, v)
    else:
        pair_bias = jax.random.normal(ks[3], (B, h, i, j))
        gate = jax.random.normal(ks[4], (B, i, h, dh))
        dt = timeit(
            lambda q, k, v, pb, g: flash_attention(
                q, k, v, pair_bias=pb, gate=g), q, k, v, pair_bias, gate)
    shape = f"B{B}_i{i}_j{j}_h{h}_dh{dh}"
elif op == "quant_matmul":
    from alphafold2_tpu.ops.quant import quant_matmul, quantize_weight

    m, kk, n = 2048, 512, 512
    x = jax.random.normal(key, (m, kk))
    qw, scale = quantize_weight(
        jax.random.normal(jax.random.PRNGKey(1), (kk, n)))
    resolved = dispatch.resolve(op, request="auto", m=m, k=kk, n=n,
                                x_dtype=x.dtype)
    assert resolved == arm, (resolved, arm)
    dt = timeit(lambda x, qw, s: quant_matmul(x, qw, s), x, qw, scale)
    shape = f"m{m}_k{kk}_n{n}"
elif op == "sparse_attention":
    from alphafold2_tpu.ops.attention import AttentionConfig, attention_init
    from alphafold2_tpu.ops.sparse import SparseConfig, sparse_attention_apply

    n, dim = 1024, 128
    cfg = AttentionConfig(dim=dim, heads=4, dim_head=32)
    scfg = SparseConfig(block_size=16, max_seq_len=2048)
    params = attention_init(key, cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, n, dim))
    resolved = dispatch.resolve(op, request="auto", n=n)
    assert resolved == arm, (resolved, arm)
    dt = timeit(
        lambda p, x: sparse_attention_apply(p, cfg, scfg, x), params, x)
    shape = f"n{n}_dim{dim}_bs{scfg.block_size}"
elif op == "merge_lse":
    # one simulated 2-hop ring on plain arrays: exactly the per-hop
    # compute each arm runs inside parallel/sequence.py's fori_loop,
    # without needing a mesh on this host
    from alphafold2_tpu.ops.flash import (
        hop_attention_lse, merge_lse, stream_block)

    BH, n, dh = 16, 512, 64
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (BH, n, dh))
    k1, k2 = jnp.split(jax.random.normal(ks[1], (BH, 2 * n, dh)), 2, axis=1)
    v1, v2 = jnp.split(jax.random.normal(ks[2], (BH, 2 * n, dh)), 2, axis=1)
    bias = jnp.zeros((BH, n), jnp.float32)
    scale = dh ** -0.5
    resolved = dispatch.resolve(op, request="auto", i=n, j=n, dh=dh)
    assert resolved == arm, (resolved, arm)
    if resolved == "pallas_tpu":
        def hops(q, k1, v1, k2, v2, bias):
            out, lse = hop_attention_lse(q, k1, v1, bias, scale)
            out2, lse2 = hop_attention_lse(q, k2, v2, bias, scale)
            return merge_lse(out, lse, out2, lse2)[0]
    else:
        # the stream_block recurrence both XLA-family arms run
        def hops(q, k1, v1, k2, v2, bias):
            q4 = q.reshape(BH, n, 1, dh)
            m0 = jnp.full((BH, 1, n), float("-inf"), jnp.float32)
            l0 = jnp.zeros((BH, 1, n), jnp.float32)
            a0 = jnp.zeros((BH, 1, n, dh), jnp.float32)
            m, l, a = stream_block(q4, k1.reshape(BH, n, 1, dh),
                                   v1.reshape(BH, n, 1, dh), bias,
                                   m0, l0, a0, scale)
            m, l, a = stream_block(q4, k2.reshape(BH, n, 1, dh),
                                   v2.reshape(BH, n, 1, dh), bias,
                                   m, l, a, scale)
            return a / jnp.where(l > 0, l, 1.0)[..., None]
    dt = timeit(hops, q, k1, v1, k2, v2, bias)
    shape = f"BH{BH}_n{n}_dh{dh}_hops2"
else:
    raise ValueError(f"unknown dispatch op {op!r}")

print(json.dumps({**base, "sec_per_iter": round(dt, 5), "shape": shape,
                  "iters": iters}))
"""


# Communication-compute overlap A/B (the multi-chip distribution story,
# ISSUE 5): times the double-buffered vs synchronous schedules of the two
# overlapped paths — ring attention and the backward-overlapped DP-accum
# step — over ALL devices the probe exposes. On the current single-chip
# tunnel this records a structured skip (a mesh of 1 has no transfers to
# hide); the first healthy MULTI-chip probe quantifies the win
# automatically. The schedule is baked at trace time from
# AF2_COMM_OVERLAP, set per-arm below before any tracing.
OVERLAP_WORKER = r"""
import json, sys, time, os
spec = json.loads(sys.argv[1])
os.environ["AF2_COMM_OVERLAP"] = "1" if spec["overlap"] else "0"
import jax
import jax.numpy as jnp
import numpy as np

n_dev = len(jax.devices())
if n_dev < 2:
    print(json.dumps({"skipped": "single-device probe: overlap needs a "
                      "multi-chip mesh", "devices": n_dev}))
    sys.exit(0)

from jax.sharding import PartitionSpec as P
from alphafold2_tpu import compat
from alphafold2_tpu.models import Alphafold2Config
from alphafold2_tpu.parallel import (
    make_dp_overlap_train_step, make_mesh, ring_attention,
)
from alphafold2_tpu.training import (
    DataConfig, TrainConfig, distogram_loss_fn, stack_microbatches,
    synthetic_batches,
)
from alphafold2_tpu.training.harness import train_state_init

from alphafold2_tpu.ops import dispatch as _dispatch

iters = spec.get("iters", 10)
out = {"devices": n_dev, "overlap": spec["overlap"],
       "platform": jax.devices()[0].platform,
       # the per-hop arm the ring legs below resolve to (per-shard key
       # length 512) — the cross-backend matrix field
       "backend_arm": _dispatch.resolve("merge_lse", request="auto",
                                        i=512, j=512, dh=64)}

# ring attention: per-shard 512 keys x 8 heads x 64 dh — big enough that
# the per-hop transfer is bandwidth-bound, P-1 hops around the full ring
mesh = make_mesh({"seq": n_dev})
sp = P(None, "seq", None, None)
key = jax.random.PRNGKey(0)
q, k, v = (jax.random.normal(kk, (1, 512 * n_dev, 8, 64), jnp.bfloat16)
           for kk in jax.random.split(key, 3))
ring = jax.jit(compat.shard_map(
    lambda q, k, v: ring_attention(q, k, v, "seq"),
    mesh=mesh, in_specs=(sp, sp, sp), out_specs=sp))
np.asarray(ring(q, k, v))  # compile + warmup
t0 = time.perf_counter()
for _ in range(iters):
    r = ring(q, k, v)
r.block_until_ready()
out["ring_sec"] = round((time.perf_counter() - t0) / iters, 5)

# DP-accum step: small trunk, grad_accum 4 — the psum/backward overlap
cfg = Alphafold2Config(dim=64, depth=2, heads=4, dim_head=16,
                       max_seq_len=64)
tcfg = TrainConfig(learning_rate=1e-3, grad_accum=4)
dcfg = DataConfig(batch_size=n_dev, max_len=48, seed=0)
batch = jax.device_put(
    next(stack_microbatches(synthetic_batches(dcfg), tcfg.grad_accum)))
dp_mesh = make_mesh({"data": n_dev})
state = train_state_init(jax.random.PRNGKey(1), cfg, tcfg)
step, _ = make_dp_overlap_train_step(
    cfg, tcfg, dp_mesh, batch, loss_fn=distogram_loss_fn,
    donate_state=False)
s2, m = step(state, batch)
float(m["loss"])  # compile + warmup fetch
t0 = time.perf_counter()
for _ in range(iters):
    s2, m = step(state, batch)
loss = float(m["loss"])
out["dp_sec"] = round((time.perf_counter() - t0) / iters, 5)
assert np.isfinite(loss), loss
out["loss"] = round(loss, 4)
print(json.dumps(out))
"""


def err_tail(stderr: str, returncode: int) -> str:
    """Diagnostic-bearing error summary of a failed subprocess.

    The last stderr line alone is useless for XLA/jax failures — an OOM's
    final line is a bar of '=' signs (PERF_SWEEP e2e_chunk0, session 5).
    Prefer the last line that names an error; fall back to the last
    non-blank line; always include the tail for context.
    """
    lines = [ln for ln in (stderr or "").splitlines() if ln.strip()]
    if not lines:
        return f"rc={returncode} (no stderr)"
    import re

    marker = None
    for ln in reversed(lines):
        if re.search(r"Error|Exception|RESOURCE_EXHAUSTED|OOM|Aborted|"
                     r"assert|Traceback", ln):
            marker = ln.strip()
            break
    tail = " | ".join(ln.strip() for ln in lines[-3:])
    msg = marker if marker else tail
    if marker and marker not in tail:
        msg = f"{marker} | {tail}"
    return msg[-400:]


def run_sub(code_or_path, argv, timeout):
    t0 = time.time()
    if os.path.exists(code_or_path):
        cmd = [sys.executable, code_or_path, *argv]
    else:
        cmd = [sys.executable, "-c", code_or_path, *argv]
    try:
        with tpu_lock(timeout=120):  # one tunnel client at a time
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=timeout,
                cwd=REPO,
            )
    except TimeoutError:
        return None, LOCK_BUSY, time.time() - t0
    except subprocess.TimeoutExpired:
        return None, "timeout", time.time() - t0
    if proc.returncode != 0:
        return None, err_tail(proc.stderr, proc.returncode), time.time() - t0
    results = []
    for line in proc.stdout.strip().splitlines():
        try:
            results.append(json.loads(line))
        except ValueError:
            continue
    if not results:
        return None, "no JSON in output", time.time() - t0
    return (results if len(results) > 1 else results[0]), None, time.time() - t0


def record(entry):
    with open(OUT, "a") as f:
        f.write(json.dumps(entry) + "\n")
    print(json.dumps(entry), flush=True)


def run_and_record(name, code_or_path, argv, timeout, extra=None):
    """One measurement subprocess; (False, res) = tunnel wedged, stop the
    sweep (a wedged worker hangs every later backend init)."""
    res, err, dt = run_sub(code_or_path, argv, timeout)
    record({"bench": name, **(extra or {}), "result": res, "error": err,
            "wall": round(dt, 1)})
    if err == "timeout":
        record({"bench": "sweep", "error": "tunnel wedged; stopping"})
        return False, res
    if err == LOCK_BUSY:
        # another client (e.g. the round-end driver bench) owns the tunnel:
        # stop instead of burning a lock-timeout per leg
        record({"bench": "sweep", "error": "TPU lock busy; stopping"})
        return False, res
    return True, res


# the ops/dispatch.py registry, mirrored here so the orchestrator never
# imports jax (worker isolation — a wedged backend must not take the
# sweep down). Drift is loud, not silent: each worker asserts
# dispatch.resolve(op, ...) == the leg's pinned arm, so a renamed or
# removed op fails its leg instead of recording misattributed rows.
DISPATCH_OPS = ("flash_attention", "fused_attention", "quant_matmul",
                "sparse_attention", "merge_lse")


def dispatch_matrix_legs():
    """(name, spec) for the op x arm cross-backend matrix: xla_ref runs
    on ANY host (real CPU rows today); pallas_tpu / gpu legs stay armed
    behind structured skips until that hardware answers a probe."""
    legs = []
    for op in DISPATCH_OPS:
        legs.append((f"disp_{op}_xla_ref", {"op": op, "arm": "xla_ref"}))
        legs.append((f"disp_{op}_pallas_tpu",
                     {"op": op, "arm": "pallas_tpu",
                      "require_platform": "tpu"}))
        legs.append((f"disp_{op}_gpu",
                     {"op": op, "arm": "gpu", "require_platform": "gpu"}))
    return legs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="kernel microbench + one e2e config only")
    ap.add_argument("--depth", type=int, default=12)
    ap.add_argument("--skip-micro", action="store_true",
                    help="e2e knob sweep only")
    ap.add_argument("--dispatch-only", action="store_true",
                    help="run only the cross-backend dispatch matrix "
                         "(op x arm) legs — chip-free xla_ref rows "
                         "record on any host")
    ap.add_argument("--serving-only", action="store_true",
                    help="run only the ISSUE-14 serving legs: the "
                         "chip-free routed-fleet row (records on any "
                         "host) plus the serve_sp_on/off A/B (TPU-only, "
                         "structured skip elsewhere)")
    ap.add_argument("--xla-micro", action="store_true",
                    help="also run the XLA-streaming micro leg (known to "
                         "compile >550s at the chunk shape — see PERF.md; "
                         "its timeout-kill can wedge the tunnel)")
    ap.add_argument("--force-all", action="store_true",
                    help="re-run legs already recorded in PERF_SWEEP.jsonl")
    args = ap.parse_args()

    # Legs that already have a successful measurement recorded are skipped
    # by default: recovered-tunnel time is scarce, and the watcher restarts
    # the whole sweep on every recovery.
    # keyed by (name, spec): a --quick/--depth smoke record must not
    # suppress the real-configuration measurement of the same leg
    def done_key(name, spec):
        return (name, json.dumps(spec, sort_keys=True) if spec else "")

    def is_skip(res):
        # structured skips (require_tpu legs on a CPU-degraded tunnel,
        # single-device overlap probes) are NOT measurements: counting
        # them as done would silence the leg forever — "timed on the
        # next healthy chip" is the whole contract
        if isinstance(res, dict):
            return "skipped" in res
        if isinstance(res, list):
            return all(isinstance(i, dict) and "skipped" in i for i in res)
        return False

    done = set()
    prior = {}  # done_key -> latest recorded result (for alias legs)
    if not args.force_all and os.path.exists(OUT):
        with open(OUT) as f:
            for line in f:
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if e.get("result") is not None and not is_skip(e["result"]):
                    key = done_key(e.get("bench"), e.get("spec"))
                    done.add(key)
                    prior[key] = e["result"]

    # 1d) cross-backend dispatch matrix (ISSUE 13). In --dispatch-only
    # mode it is the whole run; otherwise it runs AFTER the e2e legs
    # (healthy-tunnel minutes go to the big measurements first).
    def run_dispatch_matrix():
        for name, spec in dispatch_matrix_legs():
            if done_key(name, spec) in done:
                print(f"skip {name}: already recorded in {OUT}", flush=True)
                continue
            ok, _ = run_and_record(name, DISPATCH_WORKER,
                                   [json.dumps(spec)], timeout=900,
                                   extra={"spec": spec})
            if not ok:
                sys.exit(3)  # wedged-tunnel code: watchers retry later

    # 1e) SP serving arm + routed fleet (ISSUE 14): serve_routed is
    # chip-free (real row on any host); the serve_sp A/B times the
    # serving-shaped SP-vs-dense executable on TPU only (structured skip
    # elsewhere — armed, never marked done). The on-arm forces sp_seq at
    # the bucket; the off-arm is the dense twin of the SAME bucket.
    def serving_legs():
        return (
            ("serve_routed", {"n": 16}, SERVE_ROUTED_WORKER, 900),
            # ISSUE 15: the cost-ledger row — chip-free, real on any host
            ("serve_costs", {"n": 16}, SERVE_COSTS_WORKER, 900),
            ("serve_sp_on",
             {"depth": args.depth, "bucket": 1024, "sp_shards": 4,
              "sp_on": True, "require_tpu": True}, SERVE_SP_WORKER, 2100),
            ("serve_sp_off",
             {"depth": args.depth, "bucket": 1024, "sp_shards": 4,
              "sp_on": False, "require_tpu": True}, SERVE_SP_WORKER, 2100),
        )

    def run_serving_legs():
        for name, spec, worker, timeout in serving_legs():
            if done_key(name, spec) in done:
                print(f"skip {name}: already recorded in {OUT}", flush=True)
                continue
            ok, _ = run_and_record(name, worker, [json.dumps(spec)],
                                   timeout=timeout, extra={"spec": spec})
            if not ok:
                sys.exit(3)  # wedged-tunnel code: watchers retry later

    if args.serving_only:
        run_serving_legs()
        return

    if args.dispatch_only:
        run_dispatch_matrix()
        return

    # 1) e2e step-time sweep FIRST: it is the sweep's purpose, and a hang
    # in any later micro leg must not cost these measurements. Order is
    # by information value per minute of healthy-tunnel time:
    #   auto     — exactly the driver-bench configuration (validates the
    #              shape-aware dispatch heuristic on chip);
    #   qbt1152  — whole-row query blocks: the grid-collapse lever that
    #              could flip the short-j kernel verdict (PERF.md);
    #   mdsbwd25/tile26/chunk0 — streaming-path knob legs;
    #   chunk96  — LAST: it was mid-flight when the tunnel wedged on
    #              2026-07-31 (8 s CPU in 35 min — blocked before tracing,
    #              so likely a victim not the cause, but it has form).
    # the base spec pins ONLY depth + kernel policy: chunk/tile sizes and
    # the MDS arm follow the preset (depth-aware resolver, promoted
    # 25-iter classical MDS), so e2e_auto is exactly the driver-bench
    # configuration by construction
    base = dict(depth=args.depth, kernel="auto")
    variants = [("e2e_auto", base)]
    if not args.quick:
        variants += [
            # FF chunk size: the session-5 sweep left it fixed at 32768 —
            # 40 sequential lax.map+checkpoint blocks per FF pass, and the
            # pair stream runs TWO GEGLU FFs per reversible layer (~30% of
            # layer FLOPs). Bigger blocks = fewer sequential programs;
            # memory headroom exists at depth<=24 (intermediate is
            # chunk*2048*2B, so 262144 -> ~1 GB live per block)
            ("e2e_ff131072", {**base, "ff_chunk": 131072}),
            ("e2e_ff262144", {**base, "ff_chunk": 262144}),
            # whole-row QUERY blocks on the 1152 axes only (pick_block
            # leaves shorter axes unpadded): collapses the (BH, nqb) grid
            # 3x — the per-grid-step-overhead lever (PERF.md finding 3)
            ("e2e_qbt1152", {**base, "kernel": "force", "qb_target": 1152}),
            # heads 4 x dh 128 keeps inner width 512 but fills the
            # 128-lane tile that bf16 dh=64 pads 2x (session-3 finding 1)
            # on EVERY attention q/k/v/out tile — candidate biggest
            # single-chip lever; BASELINE config 5 pins dim/depth, not
            # the head split
            ("e2e_h4dh128", {**base, "heads": 4, "dim_head": 128}),
            # the RETIRED reference MDS arm (200 iterations, random init)
            # measured against the promoted (25, classical) default the
            # base legs now inherit: quantifies on chip what the cut
            # bought, and catches any regression the classical warm
            # start's eigendecomposition might cost at batch-1 latency
            ("e2e_mds200random",
             {**base, "mds_iters": 200, "mds_init": "random"}),
            # bf16 score/probability tiles in the XLA streaming path:
            # halves the attention passes' dominant HBM traffic (the f32
            # logit materialization — PERF.md round-5 traffic budget) at
            # bf16-rounding probability error (tests/test_flash.py). If
            # the traffic theory is right this is a direct ~2x on the
            # ~60%-of-layer pair attention; if it is noise, the sink is
            # elsewhere — decisive either way. PINNED kernel-off
            # (AF2_DISABLE_FLASH_KERNEL): logit_dtype applies only to the
            # streaming path and ops/flash.py raises loudly if any shape
            # reaches the Pallas dispatch — under kernel='auto' a flat
            # cross mode, qb-target tuning, or an AF2_FLASH_AUTO_MIN_J
            # override would turn this A/B into a trace-time ValueError
            # row instead of a measurement (ADVICE r5). The loud error
            # stays for user configs; only the sweep leg pins.
            ("e2e_logit_bf16", {**base, "logit_bf16": True,
                                "kernel": "off"}),
            ("e2e_mdsbwd25", {**base, "mds_bwd_iters": 25}),
            # MDS scan unroll: amortizes the sequential small-kernel
            # iterations' dispatch overhead (PERF.md "MDS latency")
            ("e2e_mdsunroll8", {**base, "mds_unroll": 8}),
            # the OLD chunk/tile values A/B'd against the depth-aware
            # resolver defaults (96 / 2^26 at depth <= 24) the base legs
            # now inherit — the direct on-chip test of the resolver
            # decision (session-5's chunk96 leg measured the reverse
            # direction against the then-32 base)
            ("e2e_tile25", {**base, "tile_elems": 1 << 25}),
            # e2e_chunk0 is RETIRED: measured OOM at compile (session 5,
            # PERF.md) — re-attempting a known-dead config risks a worker
            # crash for zero information
            ("e2e_chunk32", {**base, "batch_chunk": 32}),
            # branch-parallel trunk schedule A/B (ISSUE 7 tentpole): the
            # SAME step with the intra-layer pair/MSA branches expressed
            # as joined concurrent units vs the serial reference —
            # allclose-pinned, so any delta is schedule, not math. TPU
            # legs (require_tpu: structured skip elsewhere).
            ("branch_parallel_on",
             {**base, "trunk_schedule": "branch_parallel",
              "require_tpu": True}),
            # the off arm's measured configuration IS e2e_auto's (serial
            # is the preset default): the loop below records it as an
            # ALIAS of e2e_auto's TPU measurement instead of paying a
            # second multi-minute compile on the wedge-prone tunnel; it
            # only runs as its own subprocess when no e2e_auto TPU
            # number exists to copy
            ("branch_parallel_off",
             {**base, "trunk_schedule": "serial", "require_tpu": True}),
            # fused-gate A/B: gated attention with the gate fused into
            # the Pallas kernel's finish step (on) vs the SAME kernel
            # core with the gate applied as a separate XLA epilogue
            # multiply (off: AF2_UNFUSE_GATE_EPILOGUE) — identical math,
            # identical core, so the delta isolates the removed HBM
            # out-read/multiply/write pass. (A kernel:"off" arm would
            # also carry the whole kernel-core-vs-XLA-streaming delta,
            # already measured in the session-4 kernel on/off legs.)
            ("fused_gate_on",
             {**base, "attn_gate": True, "kernel": "force",
              "require_tpu": True}),
            ("fused_gate_off",
             {**base, "attn_gate": True, "kernel": "force",
              "unfuse_gate": True, "require_tpu": True}),
        ]
    e2e_results = dict(prior)  # done_key -> result, grown as legs run
    for name, spec in variants:
        key = done_key(name, spec)
        if key in done:
            print(f"skip {name}: already recorded in {OUT}", flush=True)
            continue
        if name == "branch_parallel_off":
            src = e2e_results.get(done_key("e2e_auto", base))
            # platform guard: older rows predate the worker's platform
            # field, and a CPU e2e_auto number must never masquerade as
            # a TPU leg's measurement — those fall through to a real run
            # (which structured-skips off-TPU anyway)
            if isinstance(src, dict) and src.get("platform") == "tpu":
                record({"bench": name, "spec": spec, "result": src,
                        "alias_of": "e2e_auto", "error": None, "wall": 0.0})
                print(f"{name}: aliased from e2e_auto (serial is the "
                      f"preset default — identical configuration)",
                      flush=True)
                continue
        ok, res = run_and_record(name, E2E_WORKER, [json.dumps(spec)],
                                 timeout=2100, extra={"spec": spec})
        if res is not None:
            e2e_results[key] = res
        if not ok:
            sys.exit(3)  # wedged-tunnel code: watchers retry later

    # 1b) communication-overlap A/B pair (multi-chip only; single-chip
    # probes record a structured skip and cost seconds). Both arms run
    # the SAME programs — only AF2_COMM_OVERLAP differs, baked at trace
    # time inside each worker.
    for name, spec in (
        ("overlap_on", {"overlap": True}),
        ("overlap_off", {"overlap": False}),
    ):
        if done_key(name, spec) in done:
            print(f"skip {name}: already recorded in {OUT}", flush=True)
            continue
        ok, _ = run_and_record(name, OVERLAP_WORKER, [json.dumps(spec)],
                               timeout=1200, extra={"spec": spec})
        if not ok:
            sys.exit(3)  # wedged-tunnel code: watchers retry later

    # 1c) int8 weight-quantization legs (ISSUE 8): quant_parity is
    # chip-free (residency + parity + quality deltas record NOW, on any
    # host); the quant_int8 on/off A/B times the serving-shaped forward
    # on TPU only (structured skip elsewhere — never marked done, so the
    # next healthy chip measures it automatically).
    # featurize_overlap (ISSUE 11) is chip-free like quant_parity: the
    # disaggregated-serving overlap ratio records on any host.
    # train_goodput (ISSUE 12) likewise: the goodput ledger's attribution
    # proof (injected data stall -> data_fetch badput + incident) is
    # structural, not chip-speed-dependent.
    for name, spec, worker, timeout in (
        ("quant_parity", {"depth": args.depth}, QUANT_PARITY_WORKER, 900),
        ("featurize_overlap", {"n": 24, "featurize_delay_s": 0.08},
         FEATURIZE_WORKER, 900),
        ("train_goodput", {"steps": 8, "stall_delay_s": 0.1},
         GOODPUT_WORKER, 900),
        ("quant_int8_on",
         {"depth": args.depth, "weight_dtype": "int8", "require_tpu": True},
         QUANT_WORKER, 2100),
        ("quant_int8_off",
         {"depth": args.depth, "weight_dtype": "f32", "require_tpu": True},
         QUANT_WORKER, 2100),
    ):
        if done_key(name, spec) in done:
            print(f"skip {name}: already recorded in {OUT}", flush=True)
            continue
        ok, _ = run_and_record(name, worker, [json.dumps(spec)],
                               timeout=timeout, extra={"spec": spec})
        if not ok:
            sys.exit(3)  # wedged-tunnel code: watchers retry later

    # 1d) the cross-backend dispatch matrix (see run_dispatch_matrix)
    run_dispatch_matrix()

    # 1e) SP serving + routed fleet (see serving_legs above)
    run_serving_legs()

    # 2) kernel microbench + block-size tuning at the chunk shape the model
    # actually calls (attn_batch_chunk=32 folded rows x 8 heads): the
    # full-fold backward OOMs from dh=64 lane padding and is not a shape
    # the model ever runs. The XLA-streaming comparison leg is OPT-IN
    # (--xla-micro): at this shape its compile ran >550 s (PERF.md) and the
    # timeout-kill is exactly the worker-crash that wedges the relay.
    micro = os.path.join(REPO, "scripts", "bench_kernels.py")
    micro_runs = []
    if not args.skip_micro:
        micro_runs.append(("micro_kernel", ["--paths", "kernel"]))
        for qb, kb in ((1152, 384), (1152, 1152), (384, 1152)):
            micro_runs.append((
                f"micro_kernel_qb{qb}_kb{kb}",
                ["--paths", "kernel", "--qb", str(qb), "--kb", str(kb)],
            ))
        if args.xla_micro:
            micro_runs.append(("micro_xla", ["--paths", "xla"]))
    for name, extra in micro_runs:
        if done_key(name, None) in done:
            print(f"skip {name}: already recorded in {OUT}", flush=True)
            continue
        ok, _ = run_and_record(
            name, micro, ["--b", "32", "--n", "1152", "--iters", "20", *extra],
            timeout=1500,
        )
        if not ok:
            sys.exit(3)  # wedged-tunnel code: watchers retry later


if __name__ == "__main__":
    main()
