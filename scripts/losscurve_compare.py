"""Loss-curve comparison vs the reference on REAL protein data.

VERDICT r2 missing #1: the north star says "matching PyTorch-GPU loss
curves", and until now "trains correctly" rested on output/grad parity
tests alone — never on an actual optimization trajectory. This script
runs the SAME distogram-pretraining workload (reference
train_pre.py:72-102 semantics) through BOTH frameworks:

  * identical model config (dim 256, depth 1, heads 8, dim_head 64 —
    the reference train_pre.py:59-64 defaults);
  * identical initial weights (the torch model's random init converted
    into our pytrees via models/convert.py — the parity-test machinery);
  * identical data: random crops of real experimental structures
    (RCSB 1h22 chain A, acetylcholinesterase — vendored at
    tests/data/1h22_protein_chain_1.pdb — plus RCSB 4k77 when a second
    source is available), N-atom coordinates bucketized exactly like
    get_bucketed_distance_matrix (reference train_pre.py:35-40);
  * identical optimization: Adam(lr=3e-4), one optimizer step per batch
    (the reference's GRADIENT_ACCUMULATE_EVERY sums losses without
    rescaling — running accum=1 on both sides compares the same
    effective step without replicating that quirk).

sidechainnet (the reference's dataset) cannot download in this
environment (zero egress), so the real-data stream is built from the
vendored experimental structures instead: same kind of data (real
backbone coordinates + real sequences), same label construction.

Outputs docs/losscurve/{losses.jsonl, LOSSCURVE.md, losscurve.png}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import hostenv  # noqa: E402

# CPU-intended (torch-reference parity + evals): the FULL pin, so this
# can never silently open a tunnel client beside a measurement — the
# env var alone loses to the axon platform pin (scripts/hostenv.py)
hostenv.force_cpu()

CROP = 128
REF_4K77 = "/root/reference/notebooks/data/4k77_protein.pdb"
VENDORED_4K77 = os.path.join(REPO, "tests", "data", "4k77_n_coords.npz")


def load_proteins():
    """-> list of (name, seq_tokens (L,), n_coords (L, 3)) real structures."""
    from alphafold2_tpu.constants import aa_to_tokens
    from alphafold2_tpu.geometry.pdb import parse_pdb

    proteins = []

    def add_from_pdb(name, path, chain=None):
        s = parse_pdb(path)
        if chain:
            s = s.select_chain(chain)
        seq = s.sequence()
        n = s.select_atoms(["N"]).coords()
        if len(seq) != len(n):
            raise ValueError(f"{name}: {len(seq)} residues vs {len(n)} N atoms")
        proteins.append((name, aa_to_tokens(seq), np.asarray(n, np.float32)))

    add_from_pdb("1h22", os.path.join(REPO, "tests", "data",
                                      "1h22_protein_chain_1.pdb"))

    # second real structure: derive once from the reference checkout's
    # public RCSB data file and vendor the ARRAYS (sequence + N coords)
    # so later rounds don't depend on /root/reference being present
    if os.path.exists(VENDORED_4K77):
        z = np.load(VENDORED_4K77)
        proteins.append(("4k77", z["tokens"], z["n_coords"]))
    elif os.path.exists(REF_4K77):
        add_from_pdb("4k77", REF_4K77)
        name, tokens, coords = proteins[-1]
        np.savez_compressed(VENDORED_4K77, tokens=tokens, n_coords=coords)
    return proteins


def make_batches(proteins, steps, crop=CROP, seed=42):
    """Fixed stream of (seq (1,crop) int32, mask (1,crop) bool,
    coords (1,crop,3) f32) crops, identical for both frameworks."""
    rng = np.random.RandomState(seed)
    batches = []
    for i in range(steps):
        name, tokens, coords = proteins[i % len(proteins)]
        start = rng.randint(0, len(tokens) - crop + 1)
        batches.append((
            tokens[None, start:start + crop].astype(np.int32),
            np.ones((1, crop), bool),
            coords[None, start:start + crop],
        ))
    return batches


# Fixed eval window at residues [200, 328) of proteins[0] (1h22). NOTE:
# this is NOT a held-out window — training crops start uniformly in
# [0, len-crop] of the same protein, so pairs inside it are trained on
# constantly; the metric is train-set recall (the model memorizing real
# structure it saw), not generalization. Round 3 mislabeled it; the
# honest zero-overlap eval (train on 4k77 only, evaluate on 1h22, a
# different protein) lives in scripts/generalization_run.py.
HELDOUT_START = 200


def heldout_distance_eval(params, cfg, proteins, crop=CROP,
                          start=HELDOUT_START, protein_index=0):
    """Distance-map metrics on proteins[protein_index]: (corr, mae,
    true_d, pred_d) over the distogram's expressible 2-20 A range. ONE
    definition shared by the artifact renderer, the extended-training
    eval trace, and the generalization run so they measure the same
    quantity. Whether the window is held out depends on the TRAINING
    stream the caller used — see the HELDOUT_START note above."""
    import jax
    import jax.numpy as jnp

    from alphafold2_tpu.geometry import center_distogram
    from alphafold2_tpu.models import alphafold2_apply

    name, tokens, coords = proteins[protein_index]
    seq = tokens[None, start:start + crop].astype(np.int32)
    true_d = np.linalg.norm(
        coords[start:start + crop, None] - coords[None, start:start + crop],
        axis=-1,
    )
    logits = alphafold2_apply(
        params, cfg, seq, None, mask=jnp.ones_like(jnp.asarray(seq), bool)
    )
    probs = jax.nn.softmax(np.asarray(logits, np.float32), axis=-1)
    dist, _ = center_distogram(probs, center="mean")
    pred_d = np.asarray(dist)[0]
    sel = (true_d > 2) & (true_d < 20) & ~np.eye(crop, dtype=bool)
    corr = float(np.corrcoef(true_d[sel], pred_d[sel])[0, 1])
    mae = float(np.abs(true_d[sel] - pred_d[sel]).mean())
    return corr, mae, true_d, pred_d


def run_torch(batches, model):
    """The reference training loop verbatim (train_pre.py:66-102,
    GRADIENT_ACCUMULATE_EVERY=1): Adam(3e-4), N-atom distance labels via
    bucketize(linspace(2, 20, 37)[:-1]), cross-entropy ignore -100."""
    import torch
    import torch.nn.functional as F
    from torch.optim import Adam

    optim = Adam(model.parameters(), lr=3e-4)
    boundaries = torch.linspace(2, 20, steps=37)
    losses = []
    t0 = time.time()
    for i, (seq, mask, coords) in enumerate(batches):
        seq_t = torch.from_numpy(seq).long()
        mask_t = torch.from_numpy(mask)
        coords_t = torch.from_numpy(coords)
        dist = torch.cdist(coords_t, coords_t, p=2)
        labels = torch.bucketize(dist, boundaries[:-1])
        labels.masked_fill_(~(mask_t[:, :, None] & mask_t[:, None, :]), -100)

        distogram = model(seq_t, mask=mask_t)
        loss = F.cross_entropy(
            distogram.permute(0, 3, 1, 2), labels, ignore_index=-100
        )
        loss.backward()
        optim.step()
        optim.zero_grad()
        losses.append(float(loss.item()))
        if i % 20 == 0:
            print(f"  torch step {i}: loss={losses[-1]:.4f} "
                  f"({time.time() - t0:.0f}s)", flush=True)
    return losses


def run_jax(batches, params, cfg, return_state=False):
    import jax

    from alphafold2_tpu.training import (
        TrainConfig,
        distogram_loss_fn,
        make_optimizer,
        make_train_step,
    )

    tcfg = TrainConfig(learning_rate=3e-4, grad_accum=1)
    opt = make_optimizer(tcfg)
    state = {
        "params": params,
        "opt_state": opt.init(params),
        "step": np.zeros((), np.int32),
    }
    step = jax.jit(make_train_step(cfg, tcfg, loss_fn=distogram_loss_fn))
    losses = []
    t0 = time.time()
    for i, (seq, mask, coords) in enumerate(batches):
        batch = {
            "seq": seq[None],  # leading grad-accum axis of 1
            "mask": mask[None],
            "coords": coords[None],
        }
        state, metrics = step(state, batch, None)
        losses.append(float(metrics["loss"]))
        if i % 20 == 0:
            print(f"  jax step {i}: loss={losses[-1]:.4f} "
                  f"({time.time() - t0:.0f}s)", flush=True)
    return (losses, state) if return_state else losses


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--out", default=os.path.join(REPO, "docs", "losscurve"))
    args = ap.parse_args()

    os.makedirs(args.out, exist_ok=True)
    proteins = load_proteins()
    print(f"proteins: {[(n, len(t)) for n, t, _ in proteins]}", flush=True)
    batches = make_batches(proteins, args.steps)

    # torch model first: its random init is the shared starting point
    import torch

    from ref_loader import load_reference
    from alphafold2_tpu.models import Alphafold2Config
    from alphafold2_tpu.models.convert import convert_alphafold2

    torch.manual_seed(0)
    ref = load_reference()
    model = ref.Alphafold2(dim=256, depth=1, heads=8, dim_head=64)
    cfg = Alphafold2Config(
        dim=256, depth=1, heads=8, dim_head=64, max_seq_len=2048
    )
    params = convert_alphafold2(model)

    print("running reference (torch CPU)...", flush=True)
    torch_losses = run_torch(batches, model)
    print("running alphafold2_tpu (jax)...", flush=True)
    jax_losses, jax_state = run_jax(batches, params, cfg, return_state=True)

    # persist the final weights for scripts/losscurve_artifact.py (which
    # renders the distance maps) so it never retrains, plus the stream
    # fingerprint so a stale cache fails loudly there
    import jax as _jax

    leaves = [np.asarray(l) for l in
              _jax.tree_util.tree_leaves(jax_state["params"])]
    np.savez_compressed(
        os.path.join(args.out, "final_params.npz"),
        steps=args.steps,
        stream=json.dumps([n for n, _, _ in proteins]),
        **{f"leaf_{i}": l for i, l in enumerate(leaves)},
    )

    with open(os.path.join(args.out, "losses.jsonl"), "w") as f:
        for i, (tl, jl) in enumerate(zip(torch_losses, jax_losses)):
            f.write(json.dumps({"step": i, "torch": round(tl, 6),
                                "jax": round(jl, 6)}) + "\n")

    d = np.abs(np.array(torch_losses) - np.array(jax_losses))
    summary = {
        "steps": args.steps,
        "torch_first": round(torch_losses[0], 4),
        "jax_first": round(jax_losses[0], 4),
        "torch_last": round(float(np.mean(torch_losses[-10:])), 4),
        "jax_last": round(float(np.mean(jax_losses[-10:])), 4),
        "max_abs_diff_first_25": round(float(d[:25].max()), 5),
        "max_abs_diff": round(float(d.max()), 5),
    }
    print(json.dumps(summary))
    with open(os.path.join(args.out, "summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
    return summary


if __name__ == "__main__":
    main()
