"""Microbenchmark: Pallas dense flash kernel vs XLA blockwise streaming.

Times `flash_attention` forward and forward+backward at the axial-attention
shapes the north-star workload produces (crop 384 -> 1152x1152 pair grid:
folded batch B=1152, seq n=1152, heads=8, dh=64), kernel vs XLA path.

Methodology matches bench.py: iterations run inside one jitted `lax.scan`
and the result is fetched before the clock stops, so remote-dispatch
backends (the axon tunnel) cannot fake the timing. Each config runs in-
process (executions are well under the ~60 s device-time crash threshold).

Usage: python scripts/bench_kernels.py [--b 1152 --n 1152 --iters 4]
Prints one JSON line per (path, direction) with TFLOP/s.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import hostenv  # noqa: E402

# single-client tunnel discipline; reentrant when bench_sweep already
# holds the lock around this subprocess (scripts/tpu_lock.py)
hostenv.tunnel_guard()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np


def _time_scan(fn, args, iters):
    """Run fn(args) `iters` times in one jitted scan; return sec/iter.

    The carry perturbs the first argument each iteration (by a numerically
    negligible but compiler-opaque amount), so the body is NOT loop-
    invariant: without this, XLA's loop-invariant code motion would hoist
    the whole computation out of the scan and the timing would measure one
    iteration, not `iters`.
    """

    def body(c, _):
        first = args[0] + (c * 1e-30).astype(args[0].dtype)
        out = fn(first, *args[1:])
        return jnp.sum(out.astype(jnp.float32)), None

    run = jax.jit(lambda: jax.lax.scan(body, jnp.float32(0.0), None, length=iters)[0])
    np.asarray(run())  # compile + warmup, fetched
    t0 = time.perf_counter()
    np.asarray(run())
    return (time.perf_counter() - t0) / iters


def bench(B, n, h, dh, iters, dtype, use_kernel, grad, key_frac_masked=0.0,
          qb=None, kb=None):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, n, h, dh), dtype)
    k = jax.random.normal(ks[1], (B, n, h, dh), dtype)
    v = jax.random.normal(ks[2], (B, n, h, dh), dtype)
    bias = jnp.zeros((B, n), jnp.float32)
    if key_frac_masked:
        nm = int(n * key_frac_masked)
        bias = bias.at[:, n - nm:].set(float("-inf"))

    from alphafold2_tpu.ops.flash import flash_attention

    def fwd(q, k, v):
        return flash_attention(
            q, k, v, bias, use_kernel=use_kernel,
            kernel_qb=qb, kernel_kb=kb,
        )

    if grad:
        def fn(q, k, v):
            loss, grads = jax.value_and_grad(
                lambda q, k, v: jnp.sum(fwd(q, k, v).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2),
            )(q, k, v)
            return loss + sum(jnp.sum(g.astype(jnp.float32)) for g in grads)
    else:
        fn = fwd

    sec = _time_scan(fn, (q, k, v), iters)
    # model FLOPs: QK^T + AV = 2 * 2 * B*h*n*n*dh; backward ~ 2.5x fwd
    fwd_flops = 4 * B * h * n * n * dh
    flops = fwd_flops * (3.5 if grad else 1.0)
    return sec, flops / sec / 1e12


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--b", type=int, default=1152)
    ap.add_argument("--n", type=int, default=1152)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dh", type=int, default=64)
    ap.add_argument("--iters", type=int, default=4)
    ap.add_argument("--masked", type=float, default=0.0)
    ap.add_argument("--paths", default="kernel,xla")
    ap.add_argument("--dirs", default="fwd,grad")
    ap.add_argument("--qb", type=int, default=None,
                    help="kernel query block (default: pick_block)")
    ap.add_argument("--kb", type=int, default=None,
                    help="kernel key block (default: pick_block)")
    args = ap.parse_args()

    dev = jax.devices()[0]
    dtype = jnp.bfloat16 if dev.platform == "tpu" else jnp.float32
    paths = args.paths.split(",")
    if dev.platform != "tpu" and "kernel" in paths:
        # off-TPU the Pallas kernel runs in interpret mode — Python-level
        # execution of thousands of grid rows never finishes at bench
        # shapes. Fail fast instead of hanging.
        print(json.dumps({"skipped": "kernel path requires TPU (interpret "
                          "mode would hang at bench shapes)"}), flush=True)
        paths = [p for p in paths if p != "kernel"]
    for path in paths:
        use_kernel = path == "kernel"
        for d in args.dirs.split(","):
            grad = d == "grad"
            sec, tflops = bench(
                args.b, args.n, args.heads, args.dh, args.iters,
                dtype, use_kernel, grad, args.masked,
                qb=args.qb, kb=args.kb,
            )
            blocks = (  # qb/kb only affect the kernel path
                f"_qb{args.qb or 'auto'}_kb{args.kb or 'auto'}"
                if use_kernel and (args.qb or args.kb) else ""
            )
            print(json.dumps({
                "path": path, "dir": d,
                "shape": f"B{args.b}_n{args.n}_h{args.heads}_dh{args.dh}"
                         + blocks,
                "sec_per_iter": round(sec, 4),
                "model_tflops_per_sec": round(tflops, 1),
                "platform": dev.platform,
            }), flush=True)


if __name__ == "__main__":
    main()
