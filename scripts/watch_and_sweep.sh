#!/bin/bash
# Probe the TPU tunnel every 8 minutes; on the first healthy probe, run the
# perf sweep (e2e knobs first, then kernel micro) and exit. The probe is a
# tiny subprocess matmul under a generous timeout — killing a client that
# is merely waiting on a wedged relay does not worsen the wedge (PERF.md).
cd "$(dirname "$0")/.."
for i in $(seq 1 60); do
  if timeout 240 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform == 'tpu', jax.devices()
x = jnp.ones((256, 256), jnp.bfloat16)
assert float(jnp.sum((x @ x).astype(jnp.float32))) > 0
print('healthy')
" 2>/dev/null | grep -q healthy; then
    echo "$(date -u +%H:%M:%S) chip healthy on probe $i; starting sweep"
    python scripts/bench_sweep.py
    rc=$?
    echo "$(date -u +%H:%M:%S) sweep finished rc=$rc"
    exit $rc
  fi
  echo "$(date -u +%H:%M:%S) probe $i: wedged"
  sleep 480
done
echo "no recovery within the watch window"
exit 1
