"""One place for the two host-environment disciplines every entry point
needs (VERDICT r4 next #6 — these lived in per-script memory and the one
time a script forgot, the tunnel wedged for hours, PERF.md):

  * force_cpu() — the FULL CPU pin for CPU-intended processes. The env
    var alone loses to the axon sitecustomize platform pin, silently
    opening a tunnel client beside a running measurement (the round-4
    wedge); the pin must clear the pool env AND update jax.config before
    any jax-importing code runs.

  * tunnel_guard() — for processes that MAY touch the tunnel: hold the
    single-client flock (scripts/tpu_lock.py) for the process's whole
    lifetime. Reentrant across process boundaries: a parent already
    holding the lock (tpu_lock CLI wrapper, or a `with tpu_lock()` body
    spawning measurement subprocesses) marks the environment, and the
    child's guard becomes a no-op instead of deadlocking against its
    parent.

Import from a script via the usual sys.path.insert(scripts/) pattern:

    import hostenv
    hostenv.force_cpu()          # CPU-intended scripts, OR
    hostenv.tunnel_guard()       # tunnel-using entry points
"""

from __future__ import annotations

import contextlib
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from tpu_lock import (  # noqa: E402,F401
    LOCK_HELD_ENV,
    LOCK_PATH,
    held_marker_valid,
    tpu_lock,
)

_guard_stack: contextlib.ExitStack | None = None


def force_cpu() -> None:
    """Pin this process to the CPU backend — completely.

    Must run before any code imports jax (callers put it at the top of
    main, right after argparse). Safe to call when jax is already
    imported ONLY if no computation ran yet.
    """
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def tunnel_guard(timeout: float | None = None) -> bool:
    """Hold the single-client tunnel lock until this process exits.

    Returns True when the lock is (now or already) held, False when the
    process is CPU-pinned and cannot touch the tunnel anyway. Raises
    TimeoutError (with a how-to message) when another client holds it.

    timeout: seconds to wait for a busy lock; default from
    AF2_TPU_LOCK_TIMEOUT, else 600 (a user prediction should queue
    behind a measurement leg, not corrupt it).
    """
    global _guard_stack
    if held_marker_valid():
        # a live ancestor holds it; our subprocess-tree is one client.
        # (An inherited marker whose holder is gone — the orphaned-child
        # reentrancy hole, ADVICE r5 — fails the validity check and
        # falls through to a real acquisition below.)
        return True
    if _guard_stack is not None:
        return True
    if (
        os.environ.get("JAX_PLATFORMS") == "cpu"
        and not os.environ.get("PALLAS_AXON_POOL_IPS")
    ):
        return False  # CPU-pinned: no tunnel client possible
    if timeout is None:
        timeout = float(os.environ.get("AF2_TPU_LOCK_TIMEOUT", 600))

    # one acquire implementation: tpu_lock() does the flock/retry/pid
    # bookkeeping; the ExitStack is deliberately never closed, so the
    # lock (and the held-marker env) lives until process exit
    stack = contextlib.ExitStack()
    try:
        stack.enter_context(tpu_lock(timeout=0))
    except TimeoutError:
        print(
            "waiting for the TPU tunnel lock (another client is using "
            "the tunnel; single-client discipline, scripts/tpu_lock.py)",
            file=sys.stderr,
            flush=True,
        )
        try:
            stack.enter_context(tpu_lock(timeout=timeout))
        except TimeoutError:
            raise TimeoutError(
                f"TPU tunnel lock {LOCK_PATH} held by another client "
                f"after {timeout:.0f}s — a measurement is likely "
                "running; retry later or raise AF2_TPU_LOCK_TIMEOUT"
            ) from None
    _guard_stack = stack
    return True
