"""TPU tunnel health probe — the ONE definition of "healthy".

Run as a subprocess under a timeout by bench.py and
scripts/watch_and_measure.sh (never in-process: a wedged relay can hang
backend init indefinitely, and `jax.devices()` alone is not proof — a
wedged relay can enumerate devices yet hang every execution, so the
probe runs a real matmul and fetches the result).

stdout contract:
  "platform: <name>"  — backend init succeeded; non-tpu means this host
                        deterministically has no TPU (callers should NOT
                        retry)
  "tpu-healthy"       — the matmul executed and returned; the chip is live
Exit code 0 only when healthy.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import hostenv

# fail-fast single-client discipline for AD-HOC invocations (the watcher
# and bench wrap this in the tpu_lock CLI, which the guard detects and
# no-ops): a probe must never queue behind a measurement, so timeout=0
hostenv.tunnel_guard(timeout=0)

import jax

d = jax.devices()[0]
print("platform:", d.platform, flush=True)
assert d.platform == "tpu", d

import jax.numpy as jnp

x = jnp.ones((256, 256), jnp.bfloat16)
assert float(jnp.sum((x @ x).astype(jnp.float32))) > 0
print("tpu-healthy")
