"""Refinement CLI: PDB in -> relaxed PDB out.

Reference parity: `scripts/refinement.py` (pose<->pdb converters + an
unimplemented FastRelax hook). This CLI actually runs: PyRosetta FastRelax
when installed, otherwise the jax_relax geometric fallback
(alphafold2_tpu/refinement.py).

Usage: python scripts/refinement.py input.pdb output.pdb [--iters 200]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import hostenv  # noqa: E402

hostenv.force_cpu()  # CPU-intended: must never open a tunnel client

import numpy as np  # noqa: E402

from alphafold2_tpu.geometry.pdb import coords_to_pdb, parse_pdb  # noqa: E402
from alphafold2_tpu.refinement import pyrosetta_available, run_fast_relax  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("input")
    ap.add_argument("output")
    ap.add_argument("--iters", type=int, default=200)
    args = ap.parse_args()

    structure = parse_pdb(args.input).select_atoms(("N", "CA", "C"))
    # keep only residues with a COMPLETE N/CA/C backbone: partial residues
    # (common in experimental PDBs) would misalign every later atom triplet
    by_res = {}
    for a in structure.atoms:
        by_res.setdefault((a.chain_id, a.res_seq), {})[a.name] = a
    complete = [
        k for k, atoms in sorted(by_res.items()) if {"N", "CA", "C"} <= set(atoms)
    ]
    dropped = len(by_res) - len(complete)
    if dropped:
        print(f"warning: dropping {dropped} residue(s) with incomplete backbone")
    from alphafold2_tpu.geometry.pdb import THREE_TO_ONE

    seq = "".join(THREE_TO_ONE.get(by_res[k]["CA"].res_name, "X") for k in complete)
    coords = np.asarray(
        [by_res[k][n].xyz for k in complete for n in ("N", "CA", "C")]
    )
    # peptide bonds exist only between same-chain residues with consecutive
    # numbering — chain breaks and gaps (incl. residues dropped above) must
    # not be welded by the relaxation
    peptide_mask = np.asarray(
        [
            complete[i][0] == complete[i + 1][0]
            and complete[i + 1][1] == complete[i][1] + 1
            for i in range(len(complete) - 1)
        ],
        bool,
    )
    n_breaks = int((~peptide_mask).sum())
    if n_breaks:
        print(f"note: {n_breaks} chain break(s)/gap(s) excluded from relaxation")
    backend = "pyrosetta FastRelax" if pyrosetta_available() else "jax_relax fallback"
    print(f"relaxing {len(seq)} residues via {backend}")
    relaxed = run_fast_relax(
        np.asarray(coords), seq, iters=args.iters, peptide_mask=peptide_mask
    )
    # carry per-residue confidence (B-factors, predict.py convention)
    # through relaxation — relaxation moves atoms, not confidence
    bfactors = np.asarray([by_res[k]["CA"].bfactor for k in complete])
    coords_to_pdb(args.output, relaxed, sequence=seq,
                  bfactors=bfactors if bfactors.any() else None)
    print(f"wrote {args.output}")


if __name__ == "__main__":
    main()
