"""Host-side Mosaic lowering check for the Pallas kernels.

`jax.export` with platforms=["tpu"] runs the full Pallas -> Mosaic
lowering for the TPU target on a CPU host — the stage where BlockSpec
shapes, layouts, scratch allocation, and dimension semantics are
validated — without needing a reachable chip (the final Mosaic -> TPU
binary step still happens at on-chip compile time). Run after any kernel
change while the tunnel is down; a lowering error here would otherwise
first surface as an on-chip compile failure during the round benchmark.

Usage: python scripts/check_mosaic_lowering.py
(the script pins the CPU platform and AF2_PALLAS_INTERPRET=0 itself —
the check is host-side by definition, and the ambient environment pins
JAX_PLATFORMS to the axon TPU tunnel, which must not be touched here)
"""

from __future__ import annotations

import os
import sys

os.environ["AF2_PALLAS_INTERPRET"] = "0"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# the config flag must be pinned too: the axon plugin re-pins the
# platform over the env var alone
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp


def main():
    from alphafold2_tpu.ops.flash_kernel import (
        flash_attention_lse,
        flash_attention_tpu,
    )
    from alphafold2_tpu.ops.sparse import SparseConfig
    from alphafold2_tpu.ops.sparse_kernel import block_sparse_attention_tpu

    checks = []

    # dense flash at the north-star chunk shape (self) and aligned-cross
    for name, (BH, i, j, dh) in (
        ("flash_self_1152", (256, 1152, 1152, 64)),
        ("flash_cross_aligned", (384 * 8, 3456, 128, 64)),
    ):
        q = jax.ShapeDtypeStruct((BH, i, dh), jnp.bfloat16)
        k = jax.ShapeDtypeStruct((BH, j, dh), jnp.bfloat16)
        v = jax.ShapeDtypeStruct((BH, j, dh), jnp.bfloat16)
        bias = jax.ShapeDtypeStruct((BH, j), jnp.float32)

        def fwdbwd(q, k, v, bias, dh=dh):  # bind: checks run after the loop
            out, vjp = jax.vjp(
                lambda q, k, v: flash_attention_tpu(q, k, v, bias, dh ** -0.5),
                q, k, v,
            )
            return vjp(out)

        def lse(q, k, v, bias, dh=dh):
            return flash_attention_lse(q, k, v, bias, dh ** -0.5)

        checks.append((f"{name}_fwdbwd", fwdbwd, (q, k, v, bias)))
        checks.append((f"{name}_lse", lse, (q, k, v, bias)))

    # whole-row query blocks (attn_flash_qb_target=1152): the e2e sweep
    # leg forcing this crashed the REMOTE compile (session 5) — check
    # whether the lowering itself is the problem or the relay was
    qw = jax.ShapeDtypeStruct((256, 1152, 64), jnp.bfloat16)
    bw = jax.ShapeDtypeStruct((256, 1152), jnp.float32)

    def fwdbwd_qb1152(q, k, v, bias):
        out, vjp = jax.vjp(
            lambda q, k, v: flash_attention_tpu(
                q, k, v, bias, 64 ** -0.5, qb=1152, kb=384
            ),
            q, k, v,
        )
        return vjp(out)

    checks.append(("flash_self_qb1152_fwdbwd", fwdbwd_qb1152, (qw, qw, qw, bw)))

    # block-sparse at its kernel-dispatch regime (n >= 4096)
    scfg = SparseConfig(block_size=128, max_seq_len=8192)
    sb, sn, sh, sdh = 1, 4096, 8, 64
    q4 = jax.ShapeDtypeStruct((sb, sn, sh, sdh), jnp.bfloat16)
    m2 = jax.ShapeDtypeStruct((sb, sn), jnp.bool_)

    def sparse_fwdbwd(q, k, v, mask):
        out, vjp = jax.vjp(
            lambda q, k, v: block_sparse_attention_tpu(q, k, v, scfg, mask),
            q, k, v,
        )
        return vjp(out)

    checks.append(("sparse_4096_fwdbwd", sparse_fwdbwd, (q4, q4, q4, m2)))

    failed = False
    for name, fn, args in checks:
        try:
            exp = jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)
            n_calls = exp.mlir_module().count("tpu_custom_call")
            assert n_calls > 0, "no tpu_custom_call in module — interpret leaked in"
            print(f"OK   {name}: Mosaic lowering passed ({n_calls} kernels)")
        except Exception as e:  # noqa: BLE001 - report and continue
            failed = True
            msg = str(e).splitlines()[0][:200]
            print(f"FAIL {name}: {type(e).__name__}: {msg}")
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
