"""Single-client TPU-tunnel lock.

The axon terminal serves ONE session; a second local client racing the
first deadlocks both and can wedge the relay for hours (observed
2026-08-01: a stray CPU-intended script initialized the axon backend
while a measurement worker was mid-leg — both blocked, the tunnel
wedged). Every process that may touch the tunnel must hold this lock for
its whole lifetime:

  python scripts/tpu_lock.py [--timeout SEC] -- CMD ARG...   # CLI wrapper
  with tpu_lock(timeout=...):                                # in-process

The lock is a plain flock(2) on .tpu.lock at the repo root — kernel-owned,
so it cannot leak: a killed or crashed holder releases it instantly
(no stale-pidfile failure mode). Holding it does NOT make killing a
mid-execution client safe (that still wedges the relay); it only prevents
the two-client collision.

CPU-only subprocesses must instead drop the tunnel env entirely:
`env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python ...` plus
`jax.config.update("jax_platforms", "cpu")` before any jax import user
code runs (the env var alone does not always win over the axon pin).
"""

from __future__ import annotations

import contextlib
import errno
import fcntl
import os
import re
import sys
import time

# AF2_TPU_LOCK_PATH override: tests isolate themselves from the real lock
# (a suite run during a live measurement must neither block it nor fail on it)
LOCK_PATH = os.environ.get("AF2_TPU_LOCK_PATH") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".tpu.lock"
)

# structured error sentinel for "another local client holds the tunnel
# lock" — compared by equality, never by substring (a worker crash whose
# stderr mentions the lock must not read as contention)
LOCK_BUSY = "tpu-lock-busy"

# set in the environment while the lock is held so measurement
# subprocesses spawned UNDER the lock don't deadlock re-acquiring it
# (the whole subprocess tree is one tunnel client); hostenv.tunnel_guard
# checks it. Format "<pid>:<starttime>" identifies the HOLDER (pid plus
# /proc starttime so a recycled pid cannot impersonate it): the marker is
# honored only while that holder STILL HOLDS the flock (lock-file pid
# match + flock probe) and is this process or a live ancestor — a
# backgrounded child that outlives the parent's release (or a marker
# leaked into an unrelated daemon's environment) falls back to the real
# flock instead of silently bypassing it (ADVICE r5; see
# held_marker_valid for the three conjunctive conditions).
LOCK_HELD_ENV = "AF2_TPU_LOCK_HELD"


def _proc_start(pid: int):
    """The kernel starttime ticks for `pid` (None when unreadable —
    process gone, or no /proc on this platform)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode("ascii", "replace")
        # comm may contain spaces/parens: fields resume after the LAST ')'
        fields = stat.rsplit(")", 1)[1].split()
        return fields[19]  # starttime, field 22 of stat(5)
    except (OSError, IndexError):
        return None


def _self_marker() -> str:
    pid = os.getpid()
    return f"{pid}:{_proc_start(pid) or ''}"


def _ancestor_markers():
    """{(pid, starttime)} for this process and its live ancestors."""
    out = set()
    pid = os.getpid()
    for _ in range(128):  # bound: no real process tree is deeper
        start = _proc_start(pid)
        if start is None:
            break
        out.add((pid, start))
        try:
            with open(f"/proc/{pid}/stat", "rb") as f:
                stat = f.read().decode("ascii", "replace")
            ppid = int(stat.rsplit(")", 1)[1].split()[1])
        except (OSError, IndexError, ValueError):
            break
        if ppid <= 0 or ppid == pid:
            break
        pid = ppid
    return out


def _flock_held() -> bool:
    """True if ANY process currently holds the flock.

    Probes READ-ONLY via /proc/locks where available: a probe must not
    itself take the lock, or a racing fail-fast client (`timeout=0`, the
    watcher path) would see a phantom holder during the probe window.
    Falls back to a momentary try-acquire only where /proc/locks does
    not exist.
    """
    try:
        st = os.stat(LOCK_PATH)
    except OSError:
        return False  # lock file never created: nobody ever held it
    try:
        with open("/proc/locks", "r") as f:
            want = (os.major(st.st_dev), os.minor(st.st_dev), st.st_ino)
            for line in f:
                parts = line.split()
                if "FLOCK" not in parts:
                    continue
                for p in parts:
                    bits = p.split(":")
                    if len(bits) == 3:
                        try:
                            dev_ino = (int(bits[0], 16), int(bits[1], 16),
                                       int(bits[2]))
                        except ValueError:
                            continue
                        if dev_ino == want:
                            return True
            return False
    except OSError:
        pass
    # no /proc/locks: momentary try-acquire (can race a concurrent
    # fail-fast probe into one spurious busy — unavoidable off-Linux)
    try:
        fd = os.open(LOCK_PATH, os.O_CREAT | os.O_RDWR, 0o644)
    except OSError:
        return False
    try:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            return True  # held by someone
        fcntl.flock(fd, fcntl.LOCK_UN)
        return False
    finally:
        os.close(fd)


def _lock_file_pid():
    """The pid the current/last holder wrote into the lock file (None
    when unreadable or never written)."""
    try:
        with open(LOCK_PATH, "rb") as f:
            data = f.read(64).decode("ascii", "replace")
    except OSError:
        return None
    m = re.search(r"pid=(\d+)", data)
    return int(m.group(1)) if m else None


def held_marker_valid() -> bool:
    """Is the AF2_TPU_LOCK_HELD marker trustworthy in THIS process?

    Three conjunctive conditions, so the marker is honored exactly while
    the subprocess tree genuinely is one tunnel client:

      1. the flock is CURRENTLY held by somebody (a holder that released
         — even one still alive — no longer covers its children);
      2. the pid recorded in the lock file matches the marker's holder
         (a third party holding the lock must not be mistaken for our
         ancestor);
      3. the holder (pid:starttime) is this process or a live ancestor
         (a recycled pid or a marker leaked into an unrelated daemon
         fails here; on platforms without /proc this ancestry check is
         skipped — conditions 1-2 still hold).

    Anything else (stale/inherited/garbled/legacy-"1" marker) is ignored
    so the kernel-owned flock decides.
    """
    raw = os.environ.get(LOCK_HELD_ENV)
    if not raw:
        return False
    pid_s, _, start = raw.partition(":")
    try:
        pid = int(pid_s)
    except ValueError:
        return False  # legacy/garbled marker: never bypass the flock
    # cheap no-flock checks first; the flock probe runs last so it only
    # ever fires for markers that already name a plausible holder
    file_pid = _lock_file_pid()
    if file_pid is not None and file_pid != pid:
        return False  # somebody ELSE holds (or last held) the lock
    if _proc_start(os.getpid()) is not None and (
        (pid, start) not in _ancestor_markers()
    ):
        return False  # holder is not this process or a live ancestor
    if not _flock_held():
        return False  # the recorded holder released (or died): stale
    return True


@contextlib.contextmanager
def tpu_lock(timeout: float = 0.0, poll: float = 2.0):
    """Hold the exclusive tunnel lock; raise TimeoutError if unavailable.

    timeout=0 means try once and fail immediately — right for probes,
    which must never queue behind a long measurement (the watcher retries
    on its own schedule anyway).
    """
    if held_marker_valid():
        # this process tree already holds the lock (hostenv.tunnel_guard
        # or an enclosing tpu_lock CLI/with-body): one client, reentrant
        yield
        return
    fd = os.open(LOCK_PATH, os.O_CREAT | os.O_RDWR, 0o644)
    deadline = time.monotonic() + timeout
    try:
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError as e:
                if e.errno not in (errno.EAGAIN, errno.EACCES):
                    raise
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"TPU lock {LOCK_PATH} held by another client"
                    ) from None
                time.sleep(poll)
        try:
            os.ftruncate(fd, 0)
            os.write(fd, f"pid={os.getpid()}\n".encode())
            had = os.environ.get(LOCK_HELD_ENV)
            os.environ[LOCK_HELD_ENV] = _self_marker()
            try:
                yield
            finally:
                if had is None:
                    os.environ.pop(LOCK_HELD_ENV, None)
                else:
                    os.environ[LOCK_HELD_ENV] = had
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
    finally:
        os.close(fd)


def main(argv: list[str]) -> int:
    timeout = 0.0
    if argv and argv[0] == "--timeout":
        timeout = float(argv[1])
        argv = argv[2:]
    if argv and argv[0] == "--":
        argv = argv[1:]
    if not argv:
        print("usage: tpu_lock.py [--timeout SEC] -- CMD ARG...",
              file=sys.stderr)
        return 2
    import subprocess

    try:
        with tpu_lock(timeout=timeout):
            return subprocess.run(argv).returncode
    except TimeoutError as e:
        print(f"tpu_lock: {e}", file=sys.stderr)
        return 75  # EX_TEMPFAIL: caller should retry later


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
