"""Single-client TPU-tunnel lock.

The axon terminal serves ONE session; a second local client racing the
first deadlocks both and can wedge the relay for hours (observed
2026-08-01: a stray CPU-intended script initialized the axon backend
while a measurement worker was mid-leg — both blocked, the tunnel
wedged). Every process that may touch the tunnel must hold this lock for
its whole lifetime:

  python scripts/tpu_lock.py [--timeout SEC] -- CMD ARG...   # CLI wrapper
  with tpu_lock(timeout=...):                                # in-process

The lock is a plain flock(2) on .tpu.lock at the repo root — kernel-owned,
so it cannot leak: a killed or crashed holder releases it instantly
(no stale-pidfile failure mode). Holding it does NOT make killing a
mid-execution client safe (that still wedges the relay); it only prevents
the two-client collision.

CPU-only subprocesses must instead drop the tunnel env entirely:
`env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python ...` plus
`jax.config.update("jax_platforms", "cpu")` before any jax import user
code runs (the env var alone does not always win over the axon pin).
"""

from __future__ import annotations

import contextlib
import errno
import fcntl
import os
import sys
import time

# AF2_TPU_LOCK_PATH override: tests isolate themselves from the real lock
# (a suite run during a live measurement must neither block it nor fail on it)
LOCK_PATH = os.environ.get("AF2_TPU_LOCK_PATH") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".tpu.lock"
)

# structured error sentinel for "another local client holds the tunnel
# lock" — compared by equality, never by substring (a worker crash whose
# stderr mentions the lock must not read as contention)
LOCK_BUSY = "tpu-lock-busy"

# set in the environment while the lock is held so measurement
# subprocesses spawned UNDER the lock don't deadlock re-acquiring it
# (the whole subprocess tree is one tunnel client); hostenv.tunnel_guard
# checks it
LOCK_HELD_ENV = "AF2_TPU_LOCK_HELD"


@contextlib.contextmanager
def tpu_lock(timeout: float = 0.0, poll: float = 2.0):
    """Hold the exclusive tunnel lock; raise TimeoutError if unavailable.

    timeout=0 means try once and fail immediately — right for probes,
    which must never queue behind a long measurement (the watcher retries
    on its own schedule anyway).
    """
    if os.environ.get(LOCK_HELD_ENV):
        # this process tree already holds the lock (hostenv.tunnel_guard
        # or an enclosing tpu_lock CLI/with-body): one client, reentrant
        yield
        return
    fd = os.open(LOCK_PATH, os.O_CREAT | os.O_RDWR, 0o644)
    deadline = time.monotonic() + timeout
    try:
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError as e:
                if e.errno not in (errno.EAGAIN, errno.EACCES):
                    raise
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"TPU lock {LOCK_PATH} held by another client"
                    ) from None
                time.sleep(poll)
        try:
            os.ftruncate(fd, 0)
            os.write(fd, f"pid={os.getpid()}\n".encode())
            had = os.environ.get(LOCK_HELD_ENV)
            os.environ[LOCK_HELD_ENV] = "1"
            try:
                yield
            finally:
                if had is None:
                    os.environ.pop(LOCK_HELD_ENV, None)
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
    finally:
        os.close(fd)


def main(argv: list[str]) -> int:
    timeout = 0.0
    if argv and argv[0] == "--timeout":
        timeout = float(argv[1])
        argv = argv[2:]
    if argv and argv[0] == "--":
        argv = argv[1:]
    if not argv:
        print("usage: tpu_lock.py [--timeout SEC] -- CMD ARG...",
              file=sys.stderr)
        return 2
    import subprocess

    try:
        with tpu_lock(timeout=timeout):
            return subprocess.run(argv).returncode
    except TimeoutError as e:
        print(f"tpu_lock: {e}", file=sys.stderr)
        return 75  # EX_TEMPFAIL: caller should retry later


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
