"""Extended real-data training (our framework only) past the parity run.

The 200-step comparison (scripts/losscurve_compare.py) proves trajectory
parity; this script continues OUR side from its saved final weights for
more optimizer steps on the same real-structure crop stream, tracking the
held-out distance-map correlation so the artifact can show the model
actually acquiring real structural signal (depth-1 dim-256, the reference
train_pre.py defaults). Appends to docs/losscurve/extended.jsonl.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import hostenv  # noqa: E402

hostenv.force_cpu()  # CPU-intended: must never open a tunnel client

OUT = os.path.join(REPO, "docs", "losscurve")


def main(extra_steps=800, eval_every=100):
    import jax
    import torch

    from losscurve_compare import (
        heldout_distance_eval,
        load_proteins,
        make_batches,
    )
    from ref_loader import load_reference
    from alphafold2_tpu.models import Alphafold2Config
    from alphafold2_tpu.models.convert import convert_alphafold2
    from alphafold2_tpu.training import (
        TrainConfig,
        distogram_loss_fn,
        make_optimizer,
        make_train_step,
    )

    torch.manual_seed(0)
    ref = load_reference()
    model = ref.Alphafold2(dim=256, depth=1, heads=8, dim_head=64)
    cfg = Alphafold2Config(
        dim=256, depth=1, heads=8, dim_head=64, max_seq_len=2048
    )
    init_params = convert_alphafold2(model)
    leaves, treedef = jax.tree_util.tree_flatten(init_params)

    # resume from the furthest saved weights: extended_params.npz (a prior
    # run of this script) or the parity run's final_params.npz
    ext = os.path.join(OUT, "extended_params.npz")
    src = ext if os.path.exists(ext) else os.path.join(OUT, "final_params.npz")
    z = np.load(src)
    base_steps = int(z["steps"])
    print(f"resuming from {src} at step {base_steps}", flush=True)
    params = jax.tree_util.tree_unflatten(
        treedef, [z[f"leaf_{i}"] for i in range(len(leaves))]
    )

    proteins = load_proteins()
    # continue the SAME stream past the parity run's end
    batches = make_batches(proteins, base_steps + extra_steps)[base_steps:]

    def heldout(params):
        corr, mae, _, _ = heldout_distance_eval(params, cfg, proteins)
        return corr, mae

    tcfg = TrainConfig(learning_rate=3e-4, grad_accum=1)
    opt = make_optimizer(tcfg)
    state = {
        "params": params,
        # fresh Adam state: the compare run does not persist moments, and
        # a warm restart at step ~200 of a 3e-4 constant-lr run is benign
        "opt_state": opt.init(params),
        "step": np.asarray(base_steps, np.int32),
    }
    step = jax.jit(make_train_step(cfg, tcfg, loss_fn=distogram_loss_fn))

    path = os.path.join(OUT, "extended.jsonl")
    c0, m0 = heldout(state["params"])
    print(f"step {base_steps}: heldout corr={c0:.4f} mae={m0:.3f}", flush=True)
    with open(path, "a") as f:
        f.write(json.dumps({"step": base_steps, "corr": round(c0, 4),
                            "mae": round(m0, 3)}) + "\n")
        t0 = time.time()
        for i, (seq, mask, xyz) in enumerate(batches):
            batch = {"seq": seq[None], "mask": mask[None], "coords": xyz[None]}
            state, metrics = step(state, batch, None)
            done = base_steps + i + 1
            if done % eval_every == 0:
                corr, mae = heldout(state["params"])
                row = {"step": done, "loss": round(float(metrics["loss"]), 4),
                       "corr": round(corr, 4), "mae": round(mae, 3)}
                f.write(json.dumps(row) + "\n")
                f.flush()
                print(f"{row} ({time.time() - t0:.0f}s)", flush=True)

    done = base_steps + len(batches)
    trained = jax.tree_util.tree_leaves(state["params"])
    np.savez_compressed(
        ext, steps=done,
        stream=json.dumps([n for n, _, _ in proteins]),
        **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(trained)},
    )
    print(json.dumps({"final_step": done, "saved": ext}))


if __name__ == "__main__":
    main()
