"""Structure evaluation CLI: predicted vs reference PDB -> RMSD / TM / GDT.

The reference computes these metrics only inside a manual notebook
(reference notebooks/structure_utils_tests.ipynb cells 10-20); this makes
the same comparison a one-liner. Structures are matched on their common
CA set (by residue number), Kabsch-aligned, and scored with the library
metrics (geometry/metrics.py — reference utils.py:563-624 parity).

Usage: python scripts/evaluate.py prediction.pdb truth.pdb [--chain A]
Prints one JSON line so runs can be collected into JSONL records.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def ca_map(structure):
    """residue number -> CA coordinate (filter chains BEFORE calling)."""
    out = {}
    for a in structure.atoms:
        if a.name == "CA" and a.res_seq not in out:
            out[a.res_seq] = a.xyz
    return out


def pick_chain(structure, wanted, label, path):
    chains = structure.chains()
    if not chains:
        raise SystemExit(f"no ATOM records in {label} file {path}")
    if wanted is None:
        return structure.select_chain(chains[0]), chains[0]
    if wanted not in chains:
        raise SystemExit(
            f"{label} file {path} has no chain {wanted!r} "
            f"(available: {', '.join(chains)})"
        )
    return structure.select_chain(wanted), wanted


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("prediction")
    ap.add_argument("truth")
    ap.add_argument("--chain", default=None,
                    help="chain of the TRUTH structure to score against "
                         "(default: first chain)")
    ap.add_argument("--pred-chain", default=None,
                    help="chain of the PREDICTION to score "
                         "(default: first chain)")
    args = ap.parse_args()

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import hostenv

    hostenv.force_cpu()  # host-side tool: never opens a tunnel client

    from alphafold2_tpu.geometry import GDT, Kabsch, RMSD, TMscore
    from alphafold2_tpu.geometry.pdb import parse_pdb

    pred, pred_chain = pick_chain(
        parse_pdb(args.prediction), args.pred_chain, "prediction",
        args.prediction,
    )
    truth, truth_chain = pick_chain(
        parse_pdb(args.truth), args.chain, "truth", args.truth,
    )

    pmap, tmap = ca_map(pred), ca_map(truth)
    common = sorted(set(pmap) & set(tmap))
    if len(common) < 3:
        raise SystemExit(
            f"only {len(common)} common CA residues between "
            f"{args.prediction} ({len(pmap)}) and {args.truth} "
            f"({len(tmap)}) — residue numbering must correspond"
        )

    import jax.numpy as jnp

    P = jnp.asarray(np.stack([pmap[i] for i in common]).T)  # (3, N)
    T = jnp.asarray(np.stack([tmap[i] for i in common]).T)
    aligned, ref = Kabsch(P, T)
    # MDS-derived structures carry a reflection ambiguity the phi fix can
    # miss on CA-only traces: score the better hand, report which
    mirrored, ref_m = Kabsch(P * jnp.array([[1.0], [1.0], [-1.0]]), T)
    r_a = float(RMSD(aligned, ref)[0])
    r_m = float(RMSD(mirrored, ref_m)[0])
    if r_m < r_a:
        aligned, ref, hand = mirrored, ref_m, "mirrored"
    else:
        hand = "direct"

    # TM/GDT normalized by the TRUTH chain length (standard convention:
    # residues the prediction does not cover count as failures), so partial
    # predictions cannot score inflated headline numbers; RMSD is over the
    # aligned common set as usual
    n_truth = len(tmap)
    result = {
        "chains": f"{pred_chain}->{truth_chain}",
        "n_residues": len(common),
        "coverage_pred": round(len(common) / max(1, len(pmap)), 3),
        "coverage_truth": round(len(common) / max(1, n_truth), 3),
        "rmsd": round(float(RMSD(aligned, ref)[0]), 3),
        "tm_score": round(float(TMscore(aligned, ref, norm_len=n_truth)[0]), 4),
        "gdt_ts": round(float(GDT(aligned, ref, norm_len=n_truth)[0]), 4),
        "gdt_ha": round(
            float(GDT(aligned, ref, mode="HA", norm_len=n_truth)[0]), 4),
        "hand": hand,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
