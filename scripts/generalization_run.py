"""Zero-overlap generalization eval across the two vendored structures.

Default direction trains on 4k77 and evaluates on never-seen 1h22;
`--train 1h22` runs the ROTATED direction (train 1h22, evaluate on
never-seen 4k77), giving a second independent transfer measurement —
different training distribution, different held-out target (VERDICT r4
next #7; a third distinct structure does not exist in this zero-egress
image).

Round 3 reported a "held-out" correlation measured on a window of the
SAME protein the training crops covered — train-set recall, not
generalization (VERDICT r3 weak #4). This script re-earns the claim
honestly: the training stream draws crops ONLY from RCSB 4k77 (280
residues), and the eval measures distance-map correlation on windows of
RCSB 1h22 (482 residues, acetylcholinesterase) — a protein the model
NEVER sees, in any crop, at any step. A held-in 4k77 window is tracked
alongside as the recall/generalization contrast.

Model + training match the reference's distogram-pretraining defaults
(reference train_pre.py:59-64: dim 256, depth 1, heads 8, dim_head 64;
Adam 3e-4, crop 128) so the number describes the same workload the
loss-curve parity run validates; init is our own alphafold2_init (no
torch dependency — parity of trajectories is losscurve_compare.py's
job, this script's job is what OUR framework learns that transfers).

Cross-protein transfer from a single 280-residue training structure is
expected to be modest — whatever the number is, it is reported as
measured (VERDICT r3 next-round #4: "whatever the number turns out to
be"). Appends eval rows to docs/losscurve/generalization.jsonl and is
resumable from its own checkpoint (generalization_params.npz,
gitignored); render with scripts/generalization_artifact.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

import hostenv  # noqa: E402

hostenv.force_cpu()  # CPU-intended: must never open a tunnel client

OUT = os.path.join(REPO, "docs", "losscurve")

# Both transfer directions over the two vendored structures (a third
# distinct real structure does not exist in this zero-egress image —
# searched: reference checkout, site-packages, whole filesystem; the
# reference's other PDBs are re-saves of 1h22). n>1 transfer evidence
# therefore comes from ROTATING train/eval (VERDICT r4 next #7):
# forward = train 4k77 / eval never-seen 1h22 (the round-4 run),
# reverse = train 1h22 / eval never-seen 4k77 — independent training
# distribution AND independent held-out target.
#
# Eval windows tile the held-out chain (crop 128): 1h22 (L=482) gets 5
# starts incl. the round-3 window [200, 328); 4k77 (L=280) admits
# starts 0..152, tiled 3 ways. The held-in window is train-set recall
# for contrast.
DIRECTIONS = {
    "4k77": dict(  # forward: train 4k77, eval 1h22
        train_index=1, eval_name="1h22", eval_index=0,
        eval_starts=(0, 118, 200, 236, 354),
        heldin_name="4k77", heldin_index=1, heldin_start=76,
        suffix="",
    ),
    "1h22": dict(  # reverse: train 1h22, eval 4k77
        train_index=0, eval_name="4k77", eval_index=1,
        eval_starts=(0, 76, 152),
        heldin_name="1h22", heldin_index=0, heldin_start=200,
        suffix="_rev",
    ),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=1000,
                    help="total optimizer steps (resumes from the "
                         "checkpoint's step count)")
    ap.add_argument("--eval-every", type=int, default=100)
    ap.add_argument("--train", choices=sorted(DIRECTIONS), default="4k77",
                    help="training protein; the other structure is the "
                         "never-seen eval target")
    args = ap.parse_args()
    d = DIRECTIONS[args.train]
    ckpt = os.path.join(OUT, f"generalization_params{d['suffix']}.npz")
    trace = os.path.join(OUT, f"generalization{d['suffix']}.jsonl")

    import jax

    from losscurve_compare import (
        CROP,
        heldout_distance_eval,
        load_proteins,
        make_batches,
    )
    from alphafold2_tpu.models import Alphafold2Config, alphafold2_init
    from alphafold2_tpu.training import (
        TrainConfig,
        distogram_loss_fn,
        make_optimizer,
        make_train_step,
    )

    proteins = load_proteins()
    names = [n for n, _, _ in proteins]
    assert names[:2] == ["1h22", "4k77"], names
    # the train protein ONLY — the eval structure never enters training
    train_proteins = [proteins[d["train_index"]]]

    cfg = Alphafold2Config(
        dim=256, depth=1, heads=8, dim_head=64, max_seq_len=2048
    )
    init_params = alphafold2_init(jax.random.PRNGKey(7), cfg)
    leaves, treedef = jax.tree_util.tree_flatten(init_params)

    base_steps = 0
    params = init_params
    if os.path.exists(ckpt):
        z = np.load(ckpt)
        assert str(z["train_stream"]) == args.train, z["train_stream"]
        base_steps = int(z["steps"])
        params = jax.tree_util.tree_unflatten(
            treedef, [z[f"leaf_{i}"] for i in range(len(leaves))]
        )
        print(f"resuming from {ckpt} at step {base_steps}", flush=True)
    if base_steps >= args.steps:
        print(f"checkpoint already at step {base_steps} >= {args.steps}; "
              "nothing to do", flush=True)
        return

    # same deterministic crop stream construction as the parity run,
    # restricted to the training protein
    batches = make_batches(train_proteins, args.steps, seed=42)[base_steps:]

    def eval_row(params, step, loss=None):
        gen = {}
        for start in d["eval_starts"]:
            corr, mae, _, _ = heldout_distance_eval(
                params, cfg, proteins, start=start,
                protein_index=d["eval_index"],
            )
            gen[str(start)] = {"corr": round(corr, 4), "mae": round(mae, 3)}
        corr_in, mae_in, _, _ = heldout_distance_eval(
            params, cfg, proteins, start=d["heldin_start"],
            protein_index=d["heldin_index"],
        )
        en, hn = d["eval_name"], d["heldin_name"]
        row = {
            "step": step,
            f"gen_{en}_mean_corr": round(
                float(np.mean([g["corr"] for g in gen.values()])), 4),
            f"gen_{en}_windows": gen,
            f"heldin_{hn}_corr": round(corr_in, 4),
            f"heldin_{hn}_mae": round(mae_in, 3),
        }
        if loss is not None:
            row["train_loss"] = round(float(loss), 4)
        return row

    tcfg = TrainConfig(learning_rate=3e-4, grad_accum=1)
    opt = make_optimizer(tcfg)
    state = {
        "params": params,
        # fresh Adam state on resume (same benign warm-restart the
        # extended run uses at constant lr)
        "opt_state": opt.init(params),
        "step": np.asarray(base_steps, np.int32),
    }
    step_fn = jax.jit(make_train_step(cfg, tcfg, loss_fn=distogram_loss_fn))

    def save_ckpt(params, step):
        leaves_now = jax.tree_util.tree_leaves(params)
        np.savez_compressed(
            ckpt, steps=step, train_stream=args.train,
            **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves_now)},
        )

    # fresh start TRUNCATES the trace: appending a new trajectory after
    # old rows would let the renderer splice two unrelated runs (its
    # dedup is by step); resume appends to the same trajectory
    with open(trace, "w" if base_steps == 0 else "a") as f:
        if base_steps == 0:
            row = eval_row(state["params"], 0)
            f.write(json.dumps(row) + "\n")
            f.flush()
            print(row, flush=True)
        t0 = time.time()
        for i, (seq, mask, xyz) in enumerate(batches):
            batch = {"seq": seq[None], "mask": mask[None], "coords": xyz[None]}
            state, metrics = step_fn(state, batch, None)
            done = base_steps + i + 1
            if done % args.eval_every == 0:
                row = eval_row(state["params"], done, metrics["loss"])
                f.write(json.dumps(row) + "\n")
                f.flush()
                # checkpoint at every eval boundary so an interrupted run
                # actually resumes (and the trace never mixes trajectories)
                save_ckpt(state["params"], done)
                print(f"{row} ({time.time() - t0:.0f}s)", flush=True)

    save_ckpt(state["params"], base_steps + len(batches))
    print(json.dumps({"final_step": base_steps + len(batches),
                      "train": args.train, "saved": ckpt}))


if __name__ == "__main__":
    main()
