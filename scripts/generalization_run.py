"""Zero-overlap generalization eval: train on 4k77, evaluate on 1h22.

Round 3 reported a "held-out" correlation measured on a window of the
SAME protein the training crops covered — train-set recall, not
generalization (VERDICT r3 weak #4). This script re-earns the claim
honestly: the training stream draws crops ONLY from RCSB 4k77 (280
residues), and the eval measures distance-map correlation on windows of
RCSB 1h22 (482 residues, acetylcholinesterase) — a protein the model
NEVER sees, in any crop, at any step. A held-in 4k77 window is tracked
alongside as the recall/generalization contrast.

Model + training match the reference's distogram-pretraining defaults
(reference train_pre.py:59-64: dim 256, depth 1, heads 8, dim_head 64;
Adam 3e-4, crop 128) so the number describes the same workload the
loss-curve parity run validates; init is our own alphafold2_init (no
torch dependency — parity of trajectories is losscurve_compare.py's
job, this script's job is what OUR framework learns that transfers).

Cross-protein transfer from a single 280-residue training structure is
expected to be modest — whatever the number is, it is reported as
measured (VERDICT r3 next-round #4: "whatever the number turns out to
be"). Appends eval rows to docs/losscurve/generalization.jsonl and is
resumable from its own checkpoint (generalization_params.npz,
gitignored); render with scripts/generalization_artifact.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

OUT = os.path.join(REPO, "docs", "losscurve")
CKPT = os.path.join(OUT, "generalization_params.npz")
TRACE = os.path.join(OUT, "generalization.jsonl")

# Fixed 1h22 eval windows (crop 128, protein length 482): tiled starts
# covering the whole chain, plus the round-3 window [200, 328) for
# comparability with the old (mislabeled) recall metric.
EVAL_STARTS_1H22 = (0, 118, 200, 236, 354)
HELD_IN_START_4K77 = 76  # center-ish window of the 280-residue train protein


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=1000,
                    help="total optimizer steps (resumes from the "
                         "checkpoint's step count)")
    ap.add_argument("--eval-every", type=int, default=100)
    args = ap.parse_args()

    import jax

    from losscurve_compare import (
        CROP,
        heldout_distance_eval,
        load_proteins,
        make_batches,
    )
    from alphafold2_tpu.models import Alphafold2Config, alphafold2_init
    from alphafold2_tpu.training import (
        TrainConfig,
        distogram_loss_fn,
        make_optimizer,
        make_train_step,
    )

    proteins = load_proteins()
    names = [n for n, _, _ in proteins]
    assert names[:2] == ["1h22", "4k77"], names
    train_proteins = [proteins[1]]  # 4k77 ONLY — 1h22 never enters training

    cfg = Alphafold2Config(
        dim=256, depth=1, heads=8, dim_head=64, max_seq_len=2048
    )
    init_params = alphafold2_init(jax.random.PRNGKey(7), cfg)
    leaves, treedef = jax.tree_util.tree_flatten(init_params)

    base_steps = 0
    params = init_params
    if os.path.exists(CKPT):
        z = np.load(CKPT)
        assert str(z["train_stream"]) == "4k77", z["train_stream"]
        base_steps = int(z["steps"])
        params = jax.tree_util.tree_unflatten(
            treedef, [z[f"leaf_{i}"] for i in range(len(leaves))]
        )
        print(f"resuming from {CKPT} at step {base_steps}", flush=True)
    if base_steps >= args.steps:
        print(f"checkpoint already at step {base_steps} >= {args.steps}; "
              "nothing to do", flush=True)
        return

    # same deterministic crop stream construction as the parity run,
    # restricted to the training protein
    batches = make_batches(train_proteins, args.steps, seed=42)[base_steps:]

    def eval_row(params, step, loss=None):
        gen = {}
        for start in EVAL_STARTS_1H22:
            corr, mae, _, _ = heldout_distance_eval(
                params, cfg, proteins, start=start, protein_index=0
            )
            gen[str(start)] = {"corr": round(corr, 4), "mae": round(mae, 3)}
        corr_in, mae_in, _, _ = heldout_distance_eval(
            params, cfg, proteins, start=HELD_IN_START_4K77, protein_index=1
        )
        row = {
            "step": step,
            "gen_1h22_mean_corr": round(
                float(np.mean([g["corr"] for g in gen.values()])), 4),
            "gen_1h22_windows": gen,
            "heldin_4k77_corr": round(corr_in, 4),
            "heldin_4k77_mae": round(mae_in, 3),
        }
        if loss is not None:
            row["train_loss"] = round(float(loss), 4)
        return row

    tcfg = TrainConfig(learning_rate=3e-4, grad_accum=1)
    opt = make_optimizer(tcfg)
    state = {
        "params": params,
        # fresh Adam state on resume (same benign warm-restart the
        # extended run uses at constant lr)
        "opt_state": opt.init(params),
        "step": np.asarray(base_steps, np.int32),
    }
    step_fn = jax.jit(make_train_step(cfg, tcfg, loss_fn=distogram_loss_fn))

    def save_ckpt(params, step):
        leaves_now = jax.tree_util.tree_leaves(params)
        np.savez_compressed(
            CKPT, steps=step, train_stream="4k77",
            **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves_now)},
        )

    # fresh start TRUNCATES the trace: appending a new trajectory after
    # old rows would let the renderer splice two unrelated runs (its
    # dedup is by step); resume appends to the same trajectory
    with open(TRACE, "w" if base_steps == 0 else "a") as f:
        if base_steps == 0:
            row = eval_row(state["params"], 0)
            f.write(json.dumps(row) + "\n")
            f.flush()
            print(row, flush=True)
        t0 = time.time()
        for i, (seq, mask, xyz) in enumerate(batches):
            batch = {"seq": seq[None], "mask": mask[None], "coords": xyz[None]}
            state, metrics = step_fn(state, batch, None)
            done = base_steps + i + 1
            if done % args.eval_every == 0:
                row = eval_row(state["params"], done, metrics["loss"])
                f.write(json.dumps(row) + "\n")
                f.flush()
                # checkpoint at every eval boundary so an interrupted run
                # actually resumes (and the trace never mixes trajectories)
                save_ckpt(state["params"], done)
                print(f"{row} ({time.time() - t0:.0f}s)", flush=True)

    save_ckpt(state["params"], base_steps + len(batches))
    print(json.dumps({"final_step": base_steps + len(batches),
                      "saved": CKPT}))


if __name__ == "__main__":
    main()
