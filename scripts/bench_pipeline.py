"""A/B bench: batch-shape ladder + pipelined dispatch on a bursty trace.

Measures what ISSUE 20 gates on — chip-seconds per request and dispatch
overlap — over the SAME bursty partial-batch trace and the SAME
tiny-but-real engine (real executables, CPU backend). Two arms:

  off — the classic engine: every bucket compiled at max_batch only,
        synchronous dispatch (assemble -> dispatch -> block_until_ready
        -> settle on one worker thread). Partial batches pay phantom-row
        chip time; the device idles through every host-side phase.
  on  — batch_ladder=True + pipeline_depth=2: partial batches run the
        smallest power-of-two rung that fits, and realization moves to
        the settle thread so batch N's device compute overlaps batch
        N±1's host work.

The trace is bursty by construction: short waves of 1-2 requests land
back to back (the pipeline's overlap window), separated by idle gaps
long enough that batches stay PARTIAL (the ladder's waste window) —
the traffic shape ParaFold/HelixFold-style serving actually sees.

Each arm writes a raw-bench-line artifact (`load_metrics`-compatible)
to BENCH_pipeline_off.json / BENCH_pipeline_on.json at the repo root,
then the telemetry.check gate runs in-process:

    *chip_seconds_per_request* = lower  : -0.25  (ladder must CUT >=25%)
    *overlap_ratio*            = higher : -0.10  (pipeline must overlap:
                                                  off arm is 1.0 by
                                                  construction, on arm
                                                  must measure > 1.0)

The equivalent CI command over the committed artifacts:

    python -m alphafold2_tpu.telemetry.check \
        --current BENCH_pipeline_on.json \
        --baseline BENCH_pipeline_off.json \
        --rule '*chip_seconds_per_request*=lower:-0.25' \
        --rule '*overlap_ratio*=higher:-0.10' \
        --rule 'goodput_wall_seconds=ignore:0'

(the wall ignore: the on arm AOT-warms every ladder rung where the off
arm compiles one shape, so cross-arm wall is apples-to-oranges — the
default `*_seconds*` lower-better rule would gate it backwards)

Chip-free by design: the PR 15 cost ledger prices whatever backend ran
the dispatch, and both legs are RATIOS over the same backend. Both
arms also self-check the PR 19/20 accounting invariants: the goodput
ledger's accounted seconds sum to <= wall (the watermark clamp means
pipelining never double-bills a second) and the cost-ledger total
reconciles with the goodput execute account exactly.

Usage: python scripts/bench_pipeline.py [--bursts N] [--mds-iters K]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

from alphafold2_tpu.constants import AA_ORDER  # noqa: E402
from alphafold2_tpu.models import Alphafold2Config, alphafold2_init  # noqa: E402
from alphafold2_tpu.serving import ServingConfig, ServingEngine  # noqa: E402
from alphafold2_tpu.telemetry.check import check  # noqa: E402

TINY = Alphafold2Config(dim=16, depth=1, heads=2, dim_head=8, max_seq_len=16)
AA = AA_ORDER.replace("W", "")

# the bursty partial-batch trace: each burst is three quick waves of
# 1/2/1 requests (they arrive inside the pipeline's overlap window),
# bursts are separated by a gap long enough that batches stay partial
BURST_WIDTHS = (1, 2, 1)
WAVE_PACE_S = 0.005
BURST_GAP_S = 0.12


def seq_of(length: int, offset: int = 0) -> str:
    return "".join(AA[(offset + i) % len(AA)] for i in range(length))


def run_arm(params, *, on: bool, bursts: int, mds_iters: int) -> dict:
    """One arm over the shared trace. precompile=True keeps compile wall
    out of the measured window on both arms (compile is excluded from
    execute billing either way; precompiling just removes the first-call
    latency skew between arms)."""
    cfg = ServingConfig(
        buckets=(16,), max_batch=4, max_queue=64, max_wait_s=0.01,
        request_timeout_s=300.0, cache_capacity=0, mds_iters=mds_iters,
        precompile=True,
        batch_ladder=on, pipeline_depth=(2 if on else 0),
    )
    eng = ServingEngine(params, TINY, cfg)
    try:
        reqs = []
        k = 0
        for _b in range(bursts):
            for width in BURST_WIDTHS:
                # distinct sequences: no cache hits, no coalescing —
                # every request is a real dispatch row
                reqs.append([eng.submit(seq_of(9 + (k + j) % 8,
                                               offset=5 * k + j))
                             for j in range(width)])
                k += 1
                time.sleep(WAVE_PACE_S)
            time.sleep(BURST_GAP_S)
        for wave in reqs:
            for r in wave:
                r.result(timeout=300)

        stats = eng.stats()
        n = stats["requests"]["completed"]
        assert n == bursts * sum(BURST_WIDTHS), stats["requests"]
        assert stats["requests"]["failed"] == 0

        # -- accounting invariants (both arms, before any gate) --------
        # (1) sums-to-wall: the watermark clamp means pipelined billing
        # never charges the same wall second twice
        accounted = sum(eng.goodput.totals("engine").values())
        wall = eng.goodput.wall("engine")
        assert accounted <= wall * 1.01 + 1e-6, (accounted, wall)
        # (2) ledger == goodput execute: every billed device-second
        # lands in exactly one cost cell AND the execute account
        chip_s = eng.costs.fleet_chip_seconds_total()
        execute_s = stats["serve_goodput"]["replicas"]["engine"][
            "buckets"]["execute"]
        assert abs(chip_s - execute_s) <= max(1e-6, 0.001 * execute_s), (
            chip_s, execute_s)

        if on:
            overlap = stats["pipeline"]["overlap_ratio"]
            assert stats["pipeline"]["inflight"] == 0, stats["pipeline"]
            assert overlap > 1.0, (
                f"pipelined arm measured no overlap: {stats['pipeline']}")
        else:
            # synchronous dispatch: span == window per batch by
            # construction — the ratio is identically 1.0
            overlap = 1.0
        row = {
            "metric": "serve_chip_seconds_per_request",
            "value": chip_s / n,
            "unit": "seconds/request",
            "backend": jax.default_backend(),
            "arm": "ladder+pipeline" if on else "sync-maxbatch",
            "requests": float(n),
            "batches": float(stats["batches"]["count"]),
            "pad_ratio": stats["batches"]["pad_ratio"],
            "mean_occupancy": stats["batches"]["mean_occupancy"],
            "overlap_ratio": overlap,
            "chip_seconds_total": chip_s,
            "goodput_execute_seconds": execute_s,
            "goodput_accounted_seconds": accounted,
            "goodput_wall_seconds": wall,
        }
        return row
    finally:
        eng.shutdown(timeout=60)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--bursts", type=int, default=8,
                    help="bursts per arm; each is 1+2+1 requests "
                         "(default 8 -> 32 requests)")
    ap.add_argument("--mds-iters", type=int, default=768,
                    help="MDS iterations — sizes per-dispatch device "
                         "time so overlap is measurable above host "
                         "noise (default 768: ~tens of ms per dispatch "
                         "on a laptop-class CPU)")
    args = ap.parse_args()
    if args.bursts < 2:
        ap.error("--bursts must be >= 2")

    params = alphafold2_init(jax.random.PRNGKey(0), TINY)
    n = args.bursts * sum(BURST_WIDTHS)
    print(f"trace: {args.bursts} bursts x {BURST_WIDTHS} waves = {n} "
          f"requests on {jax.default_backend()}, mds_iters={args.mds_iters}")
    baseline = run_arm(params, on=False, bursts=args.bursts,
                       mds_iters=args.mds_iters)
    print(f"  off: {baseline['value'] * 1e3:.2f} chip-ms/req over "
          f"{baseline['batches']:.0f} batches, pad ratio "
          f"{baseline['pad_ratio']:.2f}, overlap {baseline['overlap_ratio']:.2f}")
    current = run_arm(params, on=True, bursts=args.bursts,
                      mds_iters=args.mds_iters)
    print(f"  on:  {current['value'] * 1e3:.2f} chip-ms/req over "
          f"{current['batches']:.0f} batches, pad ratio "
          f"{current['pad_ratio']:.2f}, overlap {current['overlap_ratio']:.2f}")

    for name, row in (("BENCH_pipeline_off.json", baseline),
                      ("BENCH_pipeline_on.json", current)):
        path = os.path.join(REPO, name)
        with open(path, "w") as fh:
            json.dump(row, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"wrote {path}")

    gate = [("*chip_seconds_per_request*", "lower", -0.25),
            ("*overlap_ratio*", "higher", -0.10)]
    passed, rows = check(current, baseline, rules=gate)
    for r in rows:
        if r["direction"] is None:
            continue
        print(f"gate {r['metric']}={r['direction']}:{r['tolerance']:+.2f} "
              f"-> change {r['change']:+.1%} "
              f"[{'PASS' if r['status'] == 'ok' else 'FAIL'}]")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
