"""A/B bench: hedged dispatch against a straggling replica.

Measures what ISSUE 18 gates on — settle p99 under a long-tail straggler
— over the SAME trace, the SAME tiny-but-real fleet (real engines, real
executables, CPU backend), and the SAME committed fault plan (one
`straggle_dispatch` on r0 stalls a measured dispatch for --straggle-s
seconds). Two arms:

  off  — hedging disabled (hedge_p95_factor=0): the straggled request
         waits out the full stall; it IS the settle p99.
  on   — p95-derived hedging armed: once the per-pool service histogram
         arms, the straggling dispatch gets ONE budgeted duplicate on
         the healthy replica, first settle wins, and the loser's
         chip-seconds land in hedge_wasted_chip_seconds_total.

Each arm writes a raw-bench-line artifact (`load_metrics`-compatible) to
BENCH_hedge_off.json / BENCH_hedge_on.json at the repo root, then the
telemetry.check gate runs in-process:

    *settle_p99*        = lower : -0.30   (hedging must CUT p99 >= 30%)
    *chip_seconds_total* = lower : +cap   (extra chip-seconds bounded by
                                           the hedge-rate cap)

The equivalent CI command over the committed artifacts:

    python -m alphafold2_tpu.telemetry.check \
        --current BENCH_hedge_on.json --baseline BENCH_hedge_off.json \
        --rule '*settle_p99*=lower:-0.30' \
        --rule '*chip_seconds_total*=lower:0.25'

Chip-free by design: the stall is injected wall-clock, and the PR 15
cost ledger prices whatever backend ran the dispatch — the RATIOS the
gates check are backend-independent.

Usage: python scripts/bench_hedge.py [--straggle-s S] [--hedge-cap F]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

from alphafold2_tpu.constants import AA_ORDER  # noqa: E402
from alphafold2_tpu.models import Alphafold2Config, alphafold2_init  # noqa: E402
from alphafold2_tpu.reliability import Fault, FaultPlan  # noqa: E402
from alphafold2_tpu.serving import (  # noqa: E402
    FleetConfig,
    ServingConfig,
    ServingFleet,
)
from alphafold2_tpu.telemetry.check import check  # noqa: E402

TINY = Alphafold2Config(dim=16, depth=1, heads=2, dim_head=8, max_seq_len=16)
AA = AA_ORDER.replace("W", "")

WARMUP = 4   # sequential requests that arm the per-pool p95 histogram
TAIL = 5     # fast requests after the straggled wave


def seq_of(length: int, offset: int = 0) -> str:
    return "".join(AA[(offset + i) % len(AA)] for i in range(length))


def run_arm(params, *, hedge: bool, straggle_s: float, cap: float) -> dict:
    """One arm: 2 real replicas, precompiled buckets (compile noise must
    not masquerade as the straggle), one injected straggler on r0's
    first post-warmup dispatch."""
    injector = FaultPlan(faults=(
        Fault("straggle_dispatch", replica="r0", at=WARMUP,
              delay_s=straggle_s),
    )).injector()
    fleet = ServingFleet(
        params, TINY,
        ServingConfig(buckets=(8, 16), max_batch=2, max_queue=16,
                      max_wait_s=0.0, request_timeout_s=60.0,
                      cache_capacity=0, precompile=True),
        FleetConfig(replicas=2, probe_interval_s=0, reprobe_interval_s=30.0,
                    tick_interval_s=0.02,
                    retry_budget_capacity=10,
                    hedge_p95_factor=(2.0 if hedge else 0.0),
                    hedge_min_delay_s=0.05,
                    hedge_min_samples=WARMUP,
                    hedge_rate_cap=cap),
        injector=injector)
    try:
        # warmup: sequential submits arm the service-seconds p95
        for i in range(WARMUP):
            fleet.predict(seq_of(6 + i % 4, offset=i))
        # the measured wave: two concurrent submits — the one routed to
        # r0 hits the straggler; with hedging on, its duplicate settles
        # on the other replica long before the stall ends
        wave = [fleet.submit(seq_of(7 + i, offset=10 + i)) for i in range(2)]
        for req in wave:
            req.result(timeout=60)
        for i in range(TAIL):
            fleet.predict(seq_of(5 + i % 4, offset=20 + i))
        assert injector.exhausted(), "straggler was never delivered"

        if hedge:
            # the hedge loser (the straggled original) is still in flight
            # when its wave settles — wait for its waste to be booked so
            # the arm's chip-seconds are complete rather than flattered
            deadline = time.monotonic() + straggle_s + 10.0
            while (fleet.stats()["hedging"]["wasted_chip_seconds"] == 0
                   and time.monotonic() < deadline):
                time.sleep(0.02)

        stats = fleet.stats()
        n = stats["requests"]["completed"]
        assert n == WARMUP + 2 + TAIL, stats["requests"]
        assert stats["requests"]["failed"] == 0
        chip_s = fleet.costs.fleet_chip_seconds_total()
        row = {
            "metric": "serve_settle_p99_seconds",
            "value": stats["latency"]["p99"],
            "unit": "seconds",
            "backend": jax.default_backend(),
            "requests": float(n),
            "straggle_s": straggle_s,
            "chip_seconds_total": chip_s,
        }
        if hedge:
            h = stats["hedging"]
            assert h["issued"] >= 1, (
                f"hedging never fired: {h} "
                f"(denials say why — rate_cap means the cap is too low "
                f"for this trace length)")
            dispatches = n + h["issued"]
            row["hedge_issued"] = float(h["issued"])
            row["hedge_rate"] = h["issued"] / dispatches
            row["hedge_wasted_chip_seconds"] = h["wasted_chip_seconds"]
            assert row["hedge_rate"] <= cap, row
        return row
    finally:
        fleet.shutdown()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--straggle-s", type=float, default=0.75,
                    help="injected stall on r0's measured dispatch "
                         "(default 0.75)")
    ap.add_argument("--hedge-cap", type=float, default=0.25,
                    help="hedge rate cap — also the chip-seconds growth "
                         "bound the gate enforces (default 0.25)")
    args = ap.parse_args()
    if args.straggle_s <= 0:
        ap.error("--straggle-s must be > 0")
    if not 0 < args.hedge_cap <= 1:
        ap.error("--hedge-cap must be in (0, 1]")

    params = alphafold2_init(jax.random.PRNGKey(0), TINY)
    print(f"trace: {WARMUP} warmup + 2-wide straggled wave + {TAIL} tail "
          f"on {jax.default_backend()}, straggle {args.straggle_s:g}s")
    baseline = run_arm(params, hedge=False, straggle_s=args.straggle_s,
                       cap=args.hedge_cap)
    print(f"  off: settle p99 {baseline['value']:.3f}s, "
          f"{baseline['chip_seconds_total']:.3f} chip-s total")
    current = run_arm(params, hedge=True, straggle_s=args.straggle_s,
                      cap=args.hedge_cap)
    print(f"  on:  settle p99 {current['value']:.3f}s, "
          f"{current['chip_seconds_total']:.3f} chip-s total, "
          f"{current['hedge_issued']:.0f} hedge(s) "
          f"(rate {current['hedge_rate']:.2f}, "
          f"wasted {current['hedge_wasted_chip_seconds']:.3f} chip-s)")

    for name, row in (("BENCH_hedge_off.json", baseline),
                      ("BENCH_hedge_on.json", current)):
        path = os.path.join(REPO, name)
        with open(path, "w") as fh:
            json.dump(row, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"wrote {path}")

    gate = [("*settle_p99*", "lower", -0.30),
            ("*chip_seconds_total*", "lower", args.hedge_cap)]
    passed, rows = check(current, baseline, rules=gate)
    for r in rows:
        if r["direction"] is None:
            continue
        print(f"gate {r['metric']}={r['direction']}:{r['tolerance']:+.2f} "
              f"-> change {r['change']:+.1%} "
              f"[{'PASS' if r['status'] == 'ok' else 'FAIL'}]")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
