"""Shared chart style for the committed artifacts (dataviz method).

One palette + one axis-styling helper so the loss-curve and
generalization artifacts stay one visual system: categorical slots 1/2
(blue/orange) in fixed order, neutral text/grid grays, no rainbow.
Slot meaning is per-chart (documented at each call site); the COLORS are
the shared contract.
"""

SERIES_1 = "#2a78d6"  # categorical slot 1
SERIES_2 = "#eb6834"  # categorical slot 2
TEXT = "#40403e"
GRID = "#e8e8e4"


def style_axes(ax):
    """The shared spine/grid/tick treatment every artifact chart uses."""
    ax.grid(color=GRID, lw=0.6)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)
    for s in ("left", "bottom"):
        ax.spines[s].set_color(GRID)
    ax.tick_params(colors=TEXT)
