"""Summarize PERF_SWEEP.jsonl into a comparison table.

Groups e2e step-time variants against e2e_base (speedup column) and lists
kernel microbench rows with TFLOP/s. Prints markdown suitable for
pasting into PERF.md. If PERF_DECOMP.jsonl exists alongside (see
scripts/bench_decompose.py), renders the component decomposition too.

Usage: python scripts/summarize_sweep.py [path]
"""

from __future__ import annotations

import json
import os
import sys


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PERF_SWEEP.jsonl",
    )
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))

    # a sweep ABORT sentinel ({"bench": "sweep", "error": ...}) marks
    # everything before it as one (possibly partial) run; only summarize
    # the LAST run so the table never mixes measurements from different
    # code versions, and surface the abort if that run ended in one
    runs, cur = [], []
    for r in rows:
        if "bench" not in r:
            continue
        cur.append(r)
        if r["bench"] == "sweep":
            runs.append(cur)
            cur = []
    if cur:
        runs.append(cur)
    last_run = runs[-1] if runs else []
    aborted = next(
        (r["error"] for r in last_run if r["bench"] == "sweep"), None
    )
    if aborted:
        print(f"**sweep aborted: {aborted}** — partial results below\n")
    latest = {}
    for r in last_run:
        if r["bench"] != "sweep":
            latest[r["bench"]] = r

    e2e = {k: v for k, v in latest.items() if k.startswith("e2e_")}
    micro = {k: v for k, v in latest.items() if k.startswith("micro_")}

    base = e2e.get("e2e_base", {}).get("result") or {}
    base_sec = base.get("sec_per_step")
    if e2e:
        print("## e2e step-time sweep (depth as configured)\n")
        print("| variant | sec/step | vs base | loss | error |")
        print("|---|---|---|---|---|")
        for name, row in sorted(e2e.items()):
            res = row.get("result") or {}
            sec = res.get("sec_per_step")
            speed = (
                f"{base_sec / sec:.2f}x" if sec and base_sec else "-"
            )
            err = (row.get("error") or "")[:60]
            print(f"| {name} | {sec if sec is not None else '-'} | {speed} "
                  f"| {res.get('loss', '-')} | {err} |")
        print()
    if micro:
        print("## kernel microbench\n")
        print("| bench | dir | sec/iter | TFLOP/s | error |")
        print("|---|---|---|---|---|")
        for name, row in sorted(micro.items()):
            res = row.get("result")
            entries = res if isinstance(res, list) else [res] if res else []
            if not entries:
                print(f"| {name} | - | - | - | {(row.get('error') or '')[:60]} |")
            for e in entries:
                if not isinstance(e, dict) or "dir" not in e:
                    continue
                print(f"| {name} | {e['dir']} | {e.get('sec_per_iter', '-')} "
                      f"| {e.get('model_tflops_per_sec', '-')} | |")
    if not e2e and not micro:
        print("no sweep rows found in", path)

    decomp_path = os.path.join(os.path.dirname(path), "PERF_DECOMP.jsonl")
    if os.path.exists(decomp_path):
        summarize_decomp(decomp_path)


def summarize_decomp(path):
    """Render PERF_DECOMP.jsonl: latest row per (leg, depth), non-smoke."""
    latest, profile_ops = {}, []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if r.get("smoke"):
                continue
            if r.get("leg") == "profile_op":
                profile_ops.append(r)
                continue
            latest[(r.get("leg"), r.get("depth"))] = r
    if not latest and not profile_ops:
        return
    print("\n## component decomposition (PERF_DECOMP.jsonl)\n")
    print("| leg | depth | sec | TFLOP | TF/s | error |")
    print("|---|---|---|---|---|---|")
    for (leg, depth), r in sorted(latest.items(), key=lambda kv: str(kv[0])):
        # tflop_model (analytic, scan-proof) > tflop_xla > legacy tflop
        tf = r.get("tflop_model", r.get("tflop_xla", r.get("tflop", "-")))
        print(f"| {leg} | {depth} | {r.get('sec', '-')} "
              f"| {tf} | {r.get('tf_per_s', '-')} "
              f"| {(r.get('error') or '')[:60]} |")
    if profile_ops:
        print("\n### top ops by device time (perfetto trace, one step)\n")
        print("| op | total ms | count |")
        print("|---|---|---|")
        for r in profile_ops[-25:]:
            print(f"| {r.get('name', '?')[:80]} | {r.get('total_ms', '-')} "
                  f"| {r.get('count', '-')} |")


if __name__ == "__main__":
    main()
