"""North-star depth ladder: depth-24 monolithic + depth-48 segmented.

BASELINE.md's north star is >=1 optimizer step/sec/chip at depth 48,
crop 384, MSA 128 — and depth 48 has never been timed on chip (rounds
1-3). bench.py measures the ladder at round end under the driver's
~20 min budget; this script is the SAME measurement armed for the
recovery watcher, so the numbers land the moment the chip returns
instead of gambling on the tunnel being healthy at round end.

Each leg is one `bench.py --single-depth` subprocess (bench.py's
isolation pattern: a crashed TPU worker must not take the orchestrator
down). depth 24 runs monolithic (fits the tunneled worker's ~60 s
single-execution budget); depth 48 runs SEGMENTED
(training/segmented.py, 4 segments — the monolithic ~96 s execution
CRASHES the worker and wedges the relay, reference workload
/root/reference/train_end2end.py:104-183 at config-5 depth).

Rows append to PERF_LADDER.jsonl (committed). Legs with a successful
record are skipped (recovered-tunnel time is scarce; the watcher
restarts this script on every recovery). Exit 3 on a wedge signature
(timeout with nothing salvaged) so the watcher goes back to probing.

Usage: python scripts/bench_depth_ladder.py [--force-all]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))
from bench_sweep import err_tail  # noqa: E402  (shared failure summarizer)
from tpu_lock import LOCK_BUSY, tpu_lock  # noqa: E402  (tunnel lock)

OUT = os.path.join(REPO, "PERF_LADDER.jsonl")
BENCH = os.path.join(REPO, "bench.py")

# (depth, segments, subprocess timeout). Timeouts are hung-tunnel
# backstops sized at generous multiples of expected compile+run wall —
# NOT budget devices: killing an in-flight device execution wedges the
# relay (PERF.md), so these only fire when the tunnel is already hung.
LEGS = ((24, 0, 2400), (48, 4, 3000))


def run_leg(depth, segments, timeout):
    cmd = [sys.executable, BENCH, "--single-depth", str(depth)]
    if segments:
        cmd += ["--segments", str(segments)]

    def parse_last(stdout):
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", "replace")
        for line in reversed((stdout or "").strip().splitlines()):
            try:
                return json.loads(line)
            except ValueError:
                continue
        return None

    t0 = time.time()
    try:
        with tpu_lock(timeout=120):  # one tunnel client at a time
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout, cwd=REPO)
    except TimeoutError:
        return ({"depth": depth, "segments": segments, "error": LOCK_BUSY},
                time.time() - t0, False)
    except subprocess.TimeoutExpired as e:
        # salvage the train row if the worker printed it before hanging
        # (bench.py prints it before the inference leg)
        row = parse_last(e.stdout)
        if row is not None:
            row["worker_timed_out"] = True
            return row, time.time() - t0, True
        return ({"depth": depth, "segments": segments, "error": "timeout"},
                time.time() - t0, True)
    row = parse_last(proc.stdout)
    if proc.returncode != 0:
        if row is not None:
            row["worker_crashed_after_measurement"] = True
            return row, time.time() - t0, False
        return ({"depth": depth, "segments": segments,
                 "error": err_tail(proc.stderr, proc.returncode)},
                time.time() - t0, False)
    if row is None:
        return ({"depth": depth, "segments": segments,
                 "error": "no JSON"}, time.time() - t0, False)
    return row, time.time() - t0, False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--force-all", action="store_true",
                    help="re-run legs already recorded in PERF_LADDER.jsonl")
    args = ap.parse_args()

    done = set()
    if not args.force_all and os.path.exists(OUT):
        with open(OUT) as f:
            for line in f:
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if "error" not in e and "_tpu" in e.get("metric", ""):
                    done.add((e.get("depth"), e.get("segments", 0)))

    for depth, segments, timeout in LEGS:
        if (depth, segments) in done:
            print(f"skip depth {depth} seg {segments}: already in {OUT}",
                  flush=True)
            continue
        row, wall, timed_out = run_leg(depth, segments, timeout)
        row.setdefault("depth", depth)
        row.setdefault("segments", segments)
        row["wall"] = round(wall, 1)
        with open(OUT, "a") as f:
            f.write(json.dumps(row) + "\n")
        print(json.dumps(row), flush=True)
        if timed_out:
            print(json.dumps({"bench": "depth_ladder",
                              "error": "tunnel wedged; stopping"}),
                  flush=True)
            sys.exit(3)  # wedged-tunnel code: watchers retry later
        if row.get("error") == LOCK_BUSY:
            # another client (e.g. the round-end driver bench) owns the
            # tunnel: stop instead of burning a lock-timeout per leg
            print(json.dumps({"bench": "depth_ladder",
                              "error": "TPU lock busy; stopping"}),
                  flush=True)
            sys.exit(3)


if __name__ == "__main__":
    main()
