"""Render PERF_DECOMP.jsonl / PERF_LADDER.jsonl into the analysis table.

Reads the newest non-smoke row per (leg, depth) and prints:
  * the per-op forward+backward costs (op_s_*), each x8-blocks-per-layer
    context and as a share of the isolated trunk numbers;
  * the decomposition identities the measurement plan is built on
    (PERF.md): e2e ~= trunk_vg_s + geom_vg_s + optimizer, and
    trunk_vg_s/depth vs sum(op_s) (a lower bound — the reversible
    backward re-runs each op's forward once more for reconstruction);
  * tunnel transfer facts from the fetch_* rows (and the implied
    transfer share of any fetch-heavy twin that was also recorded).

Pure host-side text; run any time — it never touches the chip.
"""

from __future__ import annotations

import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
E2E_BASELINE_SEC = 24.41  # depth-12 e2e auto leg (PERF_SWEEP / PERF.md)


def latest_rows(path):
    rows = {}
    if not os.path.exists(path):
        return rows
    with open(path) as f:
        for line in f:
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if e.get("smoke") or "error" in e:
                continue
            key = (e.get("leg") or e.get("metric"), e.get("depth"))
            rows[key] = e  # later lines win: newest measurement per leg
    return rows


def main():
    rows = latest_rows(os.path.join(REPO, "PERF_DECOMP.jsonl"))
    if not rows:
        print("no non-smoke rows in PERF_DECOMP.jsonl yet")
        return

    def sec(leg, depth=12):
        e = rows.get((leg, depth))
        return e["sec"] if e else None

    print(f"= decomposition (depth 12; e2e baseline {E2E_BASELINE_SEC} s) =")
    for (leg, depth), e in sorted(rows.items()):
        if leg.startswith(("fetch_", "profile")):
            continue
        print(f"  {leg:28s} d{depth:<3} {e['sec']:9.3f} s"
              + (f"   {e['tf_per_s']:6.1f} TF/s" if e.get("tf_per_s") else ""))

    ops = {leg: e["sec"] for (leg, depth), e in rows.items()
           if leg.startswith("op_s_") and depth == 12}
    tf12 = sec("trunk_fwd")
    tvg = sec("trunk_vg_s")
    gvg = sec("geom_vg_s")
    if ops:
        total = sum(ops.values())
        print(f"\n  sum(op_s fwd+bwd) = {total:.3f} s/layer-ish")
        if tvg:
            print(f"  trunk_vg_s/depth  = {tvg / 12:.3f} s  "
                  f"(>= sum(op_s)/ratio; reversible adds ~1 fwd for "
                  f"reconstruction)")
        for leg, s in sorted(ops.items(), key=lambda kv: -kv[1]):
            print(f"    {leg:26s} {s:7.3f} s  ({100 * s / total:5.1f}%)")
    if tf12 is not None:
        tf2 = sec("trunk_fwd", 2)
        print(f"\n  trunk_fwd d12 = {tf12:.3f} s ({tf12 / 12 * 1e3:.0f} "
              f"ms/layer vs ~61 ms analytic roofline)")
        if tf2 is not None:
            slope = (tf12 - tf2) / 10
            fixed = tf2 - 2 * slope
            print(f"  trunk_fwd d2  = {tf2:.3f} s -> marginal "
                  f"{slope * 1e3:.0f} ms/layer, fixed {fixed:.2f} s")
    if tvg and gvg:
        print(f"\n  identity: trunk_vg_s + geom_vg_s = {tvg + gvg:.2f} s "
              f"vs e2e {E2E_BASELINE_SEC} s "
              f"(gap = optimizer + composition effects)")

    fetches = {leg: e for (leg, depth), e in rows.items()
               if leg.startswith("fetch_")}
    if fetches:
        print("\n= tunnel =")
        for leg, e in sorted(fetches.items()):
            rate = e.get("mb_per_s")
            print(f"  {leg:16s} {e['mb']:8.1f} MB in {e['sec']:8.4f} s"
                  + (f"  -> {rate:.1f} MB/s" if rate else ""))

    lad = latest_rows(os.path.join(REPO, "PERF_LADDER.jsonl"))
    if lad:
        print("\n= depth ladder (on-chip rows only) =")
        for (metric, depth), e in sorted(lad.items(), key=lambda kv: str(kv[0])):
            m = str(metric)
            # _cpu rows are smoke-shape validation runs, not measurements
            if "steps_per_sec" in m and "_cpu" not in m:
                print(f"  {metric}: {e.get('value')} steps/s "
                      f"(sec/step {e.get('sec_per_step')}, "
                      f"mfu {e.get('mfu')})")


if __name__ == "__main__":
    main()
