"""Render the zero-overlap generalization artifacts (docs/losscurve/).

Consumes the per-direction traces written by scripts/generalization_run.py
(forward: train 4k77 / eval never-seen 1h22 -> generalization.jsonl;
reverse: train 1h22 / eval never-seen 4k77 -> generalization_rev.jsonl),
producing:

  * generalization.png / generalization_rev.png — cross-protein (zero
    training overlap) mean distance-map correlation over training, with
    the per-window spread, against the held-in train-protein window
    (train-set recall) for contrast;
  * GENERALIZATION.md — the committed summary covering every direction
    that has a trace (n>=2 independent held-out structures when both
    have run — VERDICT r4 next #7).

Charting follows the dataviz method the other artifacts use: line chart
for change-over-time, categorical slots 1/2 (blue/orange) in fixed
order, no rainbow.
"""

from __future__ import annotations

import json
import os

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "docs", "losscurve")

# slot 1 = held-in (train-set recall), slot 2 = held-out
# (generalization) (shared palette: scripts/chartstyle.py)
import sys as _sys
_sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from chartstyle import GRID, SERIES_1, SERIES_2, TEXT, style_axes

DIRECTIONS = (
    dict(train="4k77", train_len=280, eval="1h22", eval_len=482,
         suffix=""),
    dict(train="1h22", train_len=482, eval="4k77", eval_len=280,
         suffix="_rev"),
)


def _render_direction(plt, d):
    """Render one direction's png; return its summary dict or None if the
    trace has not been produced yet."""
    path = os.path.join(OUT, f"generalization{d['suffix']}.jsonl")
    if not os.path.exists(path):
        return None
    en, hn = d["eval"], d["train"]
    by_step = {}
    with open(path) as f:
        for line in f:
            r = json.loads(line)
            by_step[r["step"]] = r  # dedup append-only reruns by step
    rows = [by_step[s] for s in sorted(by_step)]
    if not rows:
        # an in-flight run opens the trace before its first eval lands
        print(f"generalization{d['suffix']}.jsonl is empty; skipping",
              flush=True)
        return None
    steps = [r["step"] for r in rows]
    gen_mean = [r[f"gen_{en}_mean_corr"] for r in rows]
    heldin = [r[f"heldin_{hn}_corr"] for r in rows]
    win_corrs = np.array(
        [[r[f"gen_{en}_windows"][k]["corr"]
          for k in sorted(r[f"gen_{en}_windows"], key=int)] for r in rows]
    )  # (T, W)

    fig, ax = plt.subplots(figsize=(7, 4), dpi=150)
    ax.fill_between(steps, win_corrs.min(1), win_corrs.max(1),
                    color=SERIES_2, alpha=0.15, lw=0,
                    label=f"{en} per-window range "
                          f"({win_corrs.shape[1]} windows)")
    ax.plot(steps, gen_mean, color=SERIES_2, lw=1.8, marker="o", ms=3.5,
            label=f"held-OUT {en} mean (zero training overlap)")
    ax.plot(steps, heldin, color=SERIES_1, lw=1.6, ls=(0, (4, 2)),
            label=f"held-IN {hn} window (train-set recall)")
    ax.axhline(0, color=GRID, lw=0.8)
    ax.set_xlabel(f"optimizer step (training on {hn} crops ONLY)",
                  color=TEXT)
    ax.set_ylabel("distance-map correlation (2-20 Å)", color=TEXT)
    ax.set_title(
        f"Cross-protein generalization: train on {hn}, evaluate on {en}\n"
        f"(the model never sees any {en} residue at any step)",
        color=TEXT, fontsize=10,
    )
    style_axes(ax)
    ax.legend(frameon=False, fontsize=8, labelcolor=TEXT, loc="lower right")
    fig.tight_layout()
    png = f"generalization{d['suffix']}.png"
    fig.savefig(os.path.join(OUT, png))
    plt.close(fig)
    print(f"{png} written", flush=True)

    last = rows[-1]
    peak = max(gen_mean)
    return dict(
        d, png=png, last=last, peak=peak,
        peak_step=steps[int(np.argmax(gen_mean))],
        final_gen=last[f"gen_{en}_mean_corr"],
        final_heldin=last[f"heldin_{hn}_corr"],
        windows=last[f"gen_{en}_windows"],
    )


def _direction_md(s):
    en, hn = s["eval"], s["train"]
    win_md = "\n".join(
        f"| {k} | {s['windows'][k]['corr']} | {s['windows'][k]['mae']} |"
        for k in sorted(s["windows"], key=int)
    )
    # blank line first: GFM would otherwise parse a paragraph that
    # directly follows the table as another table row
    turn = (f"""
Training past the held-out peak (step {s['peak_step']}) trades transfer
for memorization: held-out declines from {s['peak']} while held-in
keeps climbing — the expected single-structure overfitting turn.
""" if s["final_gen"] < s["peak"] - 0.03 else "")
    return f"""## Train on {hn} ({s['train_len']} res), evaluate on \
never-seen {en} ({s['eval_len']} res)

![generalization {hn}->{en}]({s['png']})

At step {s['last']['step']}: **held-out {en} mean correlation
{s['final_gen']}** (peak {s['peak']} over the run) vs held-in {hn}
recall {s['final_heldin']}. Per {en} window at the final step:

| window start | corr (2-20 Å) | MAE (Å) |
|---|---|---|
{win_md}
{turn}
"""


def main():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    summaries = [s for s in (_render_direction(plt, d) for d in DIRECTIONS)
                 if s is not None]
    if not summaries:
        raise SystemExit("no generalization traces found")

    n = len(summaries)
    both = (" Transfer is measured in BOTH rotations of the two vendored "
            "structures — independent training distribution and "
            "independent never-seen target each way — so the claim rests "
            f"on n={n} held-out structures, not one."
            if n > 1 else "")
    sections = "\n".join(_direction_md(s) for s in summaries)
    regen = "\n".join(
        f"`python scripts/generalization_run.py --train {s['train']} "
        f"--steps {s['last']['step']}`"
        for s in summaries)
    with open(os.path.join(OUT, "GENERALIZATION.md"), "w") as f:
        f.write(f"""# Zero-overlap generalization, both directions

Round 3's "held-out 0.04 -> 0.61" headline was measured on a window of
the SAME protein the training crops covered — train-set recall, not
generalization (VERDICT r3). This artifact re-earns the claim honestly:
`scripts/generalization_run.py` trains the reference-default distogram
model (dim 256, depth 1, heads 8, dim_head 64, Adam 3e-4, crop 128 —
reference train_pre.py:59-64) on crops of ONE structure only and
evaluates distance-map correlation on fixed 128-residue windows of the
OTHER — a protein the model never sees, in any crop, at any
step.{both}

What transfers from a single training structure is generic protein
geometry — sequence-separation-dependent distance priors,
secondary-structure-scale contact patterns — which is exactly what a
depth-1 model can express. The numbers are reported as measured,
whatever they are (VERDICT r3 next #4).

{sections}

Regenerate:
{regen}
then `python scripts/generalization_artifact.py`.
""")
    print("GENERALIZATION.md written", flush=True)
    print(json.dumps({
        "directions": [
            {"train": s["train"], "eval": s["eval"],
             "final_step": s["last"]["step"],
             "gen_mean_corr": s["final_gen"],
             "heldin_corr": s["final_heldin"],
             "peak_gen_corr": s["peak"]}
            for s in summaries
        ]
    }))


if __name__ == "__main__":
    main()
