"""Render the zero-overlap generalization artifact (docs/losscurve/).

Consumes generalization.jsonl (scripts/generalization_run.py: train on
4k77 ONLY, evaluate on never-seen 1h22), producing:

  * generalization.png — cross-protein (1h22, zero training overlap)
    mean distance-map correlation over training, with the per-window
    spread, against the held-in 4k77 window (train-set recall) for
    contrast;
  * GENERALIZATION.md — the committed summary with per-window numbers.

Charting follows the dataviz method the other artifacts use: line chart
for change-over-time, categorical slots 1/2 (blue/orange) in fixed
order, no rainbow.
"""

from __future__ import annotations

import json
import os

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "docs", "losscurve")

# slot 1 = held-in (train-set recall), slot 2 = held-out
# (generalization) (shared palette: scripts/chartstyle.py)
import sys as _sys
_sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from chartstyle import GRID, SERIES_1, SERIES_2, TEXT, style_axes


def main():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    path = os.path.join(OUT, "generalization.jsonl")
    by_step = {}
    with open(path) as f:
        for line in f:
            r = json.loads(line)
            by_step[r["step"]] = r  # dedup append-only reruns by step
    rows = [by_step[s] for s in sorted(by_step)]
    steps = [r["step"] for r in rows]
    gen_mean = [r["gen_1h22_mean_corr"] for r in rows]
    heldin = [r["heldin_4k77_corr"] for r in rows]
    win_corrs = np.array(
        [[r["gen_1h22_windows"][k]["corr"]
          for k in sorted(r["gen_1h22_windows"], key=int)] for r in rows]
    )  # (T, W)

    fig, ax = plt.subplots(figsize=(7, 4), dpi=150)
    ax.fill_between(steps, win_corrs.min(1), win_corrs.max(1),
                    color=SERIES_2, alpha=0.15, lw=0,
                    label="1h22 per-window range (5 windows)")
    ax.plot(steps, gen_mean, color=SERIES_2, lw=1.8, marker="o", ms=3.5,
            label="held-OUT 1h22 mean (zero training overlap)")
    ax.plot(steps, heldin, color=SERIES_1, lw=1.6, ls=(0, (4, 2)),
            label="held-IN 4k77 window (train-set recall)")
    ax.axhline(0, color=GRID, lw=0.8)
    ax.set_xlabel("optimizer step (training on 4k77 crops ONLY)",
                  color=TEXT)
    ax.set_ylabel("distance-map correlation (2-20 Å)", color=TEXT)
    ax.set_title(
        "Cross-protein generalization: train on 4k77, evaluate on 1h22\n"
        "(the model never sees any 1h22 residue at any step)",
        color=TEXT, fontsize=10,
    )
    style_axes(ax)
    ax.legend(frameon=False, fontsize=8, labelcolor=TEXT, loc="lower right")
    fig.tight_layout()
    fig.savefig(os.path.join(OUT, "generalization.png"))
    plt.close(fig)
    print("generalization.png written", flush=True)

    last = rows[-1]
    peak = max(gen_mean)
    peak_step = steps[int(np.argmax(gen_mean))]
    win_md = "\n".join(
        f"| {k} | {last['gen_1h22_windows'][k]['corr']} | "
        f"{last['gen_1h22_windows'][k]['mae']} |"
        for k in sorted(last["gen_1h22_windows"], key=int)
    )
    with open(os.path.join(OUT, "GENERALIZATION.md"), "w") as f:
        f.write(f"""# Zero-overlap generalization: train on 4k77, evaluate on 1h22

Round 3's "held-out 0.04 -> 0.61" headline was measured on a window of
the SAME protein the training crops covered — train-set recall, not
generalization (VERDICT r3). This artifact re-earns the claim honestly:
`scripts/generalization_run.py` trains the reference-default distogram
model (dim 256, depth 1, Adam 3e-4, crop 128 — reference
train_pre.py:59-64) on crops of RCSB **4k77 only** (280 residues) and
evaluates on five fixed 128-residue windows of RCSB **1h22** (482
residues, acetylcholinesterase) — a protein the model never sees, in
any crop, at any step.

![generalization](generalization.png)

At step {last['step']}: **held-out 1h22 mean correlation
{last['gen_1h22_mean_corr']}** (peak {peak} over the run) vs held-in
4k77 recall {last['heldin_4k77_corr']}. Per 1h22 window at the final
step:

| window start | corr (2-20 Å) | MAE (Å) |
|---|---|---|
{win_md}

What transfers from a single 280-residue training structure is generic
protein geometry — sequence-separation-dependent distance priors,
secondary-structure-scale contact patterns — which is exactly what a
depth-1 model can express. {'Notably the held-in and held-out curves '
 'track each other closely — no memorization gap: the model underfits '
 'its single training protein and everything it learns is portable.'
 if last['gen_1h22_mean_corr'] >= last['heldin_4k77_corr'] - 0.05
 else 'The held-in curve sitting above the held-out one is the '
 'memorization gap.'}{f''' Training past the held-out peak (step
{peak_step}) trades transfer for memorization: held-out declines from
{peak} while held-in keeps climbing — the expected single-structure
overfitting turn, visible end to end in the curve.'''
 if last['gen_1h22_mean_corr'] < peak - 0.03 else ''} The number is
reported as measured, whatever it is (VERDICT r3 next #4).

Regenerate: `python scripts/generalization_run.py --steps
{last['step']}`, then `python scripts/generalization_artifact.py`.
""")
    print("GENERALIZATION.md written", flush=True)
    print(json.dumps({"final_step": last["step"],
                      "gen_1h22_mean_corr": last["gen_1h22_mean_corr"],
                      "heldin_4k77_corr": last["heldin_4k77_corr"],
                      "peak_gen_corr": peak}))


if __name__ == "__main__":
    main()
