"""The kernel dispatch surface: one registry, every hot op, every backend.

HelixFold (arxiv 2207.05477) ran the same model fast on a different
hardware stack by putting one dispatch surface over per-hardware
kernels; FastFold (arxiv 2203.00854) chose the execution strategy per
workload shape. This module is that surface for this repo: every hot op
(dense/fused flash attention, the int8 fused-dequant matmul, block-
sparse attention, the ring-attention hop) registers named ARMS —

  * ``pallas_tpu`` — the Pallas Mosaic kernel (interpret mode off-TPU,
    which is what the chip-free parity tier exercises);
  * ``gpu``        — the GPU arm. Pallas-Triton lowering for these
    kernels is not available on this JAX build
    (`pallas_triton_lowerable`), so the arm is the optimized-XLA
    blockwise path (the `streamed_fused_attention`-style streaming
    recurrence) — XLA's GPU fusion pipeline keeps it memory-bounded,
    and a Triton kernel can slot into the same arm name later;
  * ``xla_ref``    — the pure-XLA reference arm: runs anywhere,
    bit-stable, the parity oracle every kernel arm is pinned against.

and resolution happens in ONE place (`resolve`): platform detection ->
shape gate -> env override. The override generalizes the tri-state
pattern that used to live in three hand-rolled copies
(ops/flash.py `kernel_dispatch`, ops/quant.py `quant_dispatch`,
ops/sparse.py's inline auto block):

  * a caller's ``use_kernel=True/False`` still forces the kernel/XLA arm
    (loud `ValueError` when forcing an unsupported shape — forcing must
    never silently fall back);
  * ``AF2_KERNEL_BACKEND=<arm>`` forces one arm globally,
    ``AF2_KERNEL_BACKEND_<OP>`` per op (op name upper-cased); ``off``
    means the op's ``xla_ref`` arm, ``auto``/unset keeps the heuristic
    (ops/knobs.py `kernel_backend_override`);
  * legacy per-op knobs (``AF2_QUANT_KERNEL=force/off``, the
    ``AF2_DISABLE_*_KERNEL`` kill-switches, ``AF2_FLASH_AUTO_MIN_J``)
    keep their documented meaning — they feed the same single resolver.

`flash_attention()` / `linear()` / `sparse_attention_apply()` /
`ring_attention()` call sites are unchanged: the op modules ask this
registry which arm to run and keep their own wiring. af2lint's
``dispatch`` pass enforces the monopoly: every registered op has an
``xla_ref`` arm and a registered chip-free parity test, no module
outside ``ops/`` imports a kernel module directly, and no module
outside ``ops/knobs.py`` parses an AF2_* env var.

Introspection: ``python -m alphafold2_tpu.ops.dispatch --check`` prints
the op x arm x resolved-on-this-host table (pinned by
tests/test_dispatch.py); `resolution_tag()` is the serving config-tag
fragment that keeps replicas on different arms out of one result-cache
keyspace (serving/engine.py).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

import jax

from alphafold2_tpu.ops import knobs

__all__ = [
    "ARM_GPU",
    "ARM_PALLAS_TPU",
    "ARM_XLA_REF",
    "Arm",
    "OpSpec",
    "get",
    "main",
    "ops",
    "pallas_triton_lowerable",
    "resolution_table",
    "resolution_tag",
    "resolve",
]

ARM_PALLAS_TPU = "pallas_tpu"
ARM_GPU = "gpu"
ARM_XLA_REF = "xla_ref"

# platforms jax reports for the GPU backends
_GPU_PLATFORMS = ("gpu", "cuda", "rocm")

# measured crossover for the block-sparse kernel (v5e @ block=128:
# kernel 2.2x faster at n=8192, XLA ~1.3x faster at n=2048 — ops/sparse.py)
_SPARSE_KERNEL_MIN_N = 4096


def pallas_triton_lowerable() -> bool:
    """Whether this host can LOWER the flash-family kernels through
    Pallas-Triton. The jax 0.4.x build in this image has no GPU client,
    so the probe is honest-but-static: False until a CUDA/ROCm backend
    is present. When it flips, a Triton kernel can register under the
    existing ``gpu`` arm name — dispatch, env overrides, bench legs, and
    the parity tier all apply unchanged."""
    try:
        return any(d.platform in _GPU_PLATFORMS for d in jax.devices())
    except RuntimeError:  # no backend at all
        return False


@dataclasses.dataclass(frozen=True)
class Arm:
    """One backend arm of one op.

    `supported(platform, **shapes) -> bool` is the shape/dtype gate —
    pure host arithmetic (no tracing), so resolution is free and works
    under `jax.eval_shape`."""

    name: str
    supported: Callable[..., bool]
    note: str = ""


@dataclasses.dataclass(frozen=True)
class OpSpec:
    """One hot op's dispatch contract.

    `auto(platform, shapes) -> arm name` is the heuristic used when
    nothing forces an arm; `probe` is the representative shape set the
    introspection table / serving tag resolve at; `parity_test` names
    the chip-free parity test function in tests/test_dispatch.py that
    pins kernel-arm == xla_ref (af2lint's dispatch pass fails CI when
    the op has none); `legacy_override` adapts a pre-registry env knob
    (e.g. AF2_QUANT_KERNEL) into the common override channel."""

    name: str
    arms: Tuple[Arm, ...]
    auto: Callable[[str, dict], str]
    probe: Dict[str, object]
    parity_test: str
    kernel_arm: str = ARM_PALLAS_TPU
    legacy_override: Optional[Callable[[], Optional[str]]] = None
    unsupported_msg: Optional[Callable[[str, dict], str]] = None

    def arm(self, name: str) -> Optional[Arm]:
        for a in self.arms:
            if a.name == name:
                return a
        return None

    def arm_names(self) -> Tuple[str, ...]:
        return tuple(a.name for a in self.arms)


_REGISTRY: Dict[str, OpSpec] = {}


def register(spec: OpSpec) -> OpSpec:
    if spec.name in _REGISTRY:
        raise ValueError(f"op {spec.name!r} already registered")
    if spec.arm(ARM_XLA_REF) is None:
        # the invariant the dispatch lint enforces repo-wide; refuse to
        # construct a registry that could not pass it
        raise ValueError(
            f"op {spec.name!r} must register an {ARM_XLA_REF} arm"
        )
    _REGISTRY[spec.name] = spec
    return spec


def ops() -> Tuple[str, ...]:
    """Registered op names, in registration order."""
    return tuple(_REGISTRY)


def get(op: str) -> OpSpec:
    try:
        return _REGISTRY[op]
    except KeyError:
        raise ValueError(
            f"unknown dispatch op {op!r}; registered: {list(_REGISTRY)}"
        ) from None


def _platform() -> str:
    return jax.devices()[0].platform


def resolve(op: str, request="auto", platform: Optional[str] = None,
            **shapes) -> str:
    """THE resolution point: (op, shapes, platform, env) -> arm name.

    `request` is the call-site tri-state (the old `use_kernel`): True
    forces the op's kernel arm, False forces `xla_ref`, "auto" consults
    the env override (AF2_KERNEL_BACKEND_<OP> > AF2_KERNEL_BACKEND >
    the op's legacy knob) and then the platform/shape heuristic. Forcing
    an unknown arm or an unsupported shape raises — a forced arm that
    silently fell back would record one arm's numbers under another's
    name."""
    spec = get(op)
    if platform is None:
        platform = _platform()

    forced: Optional[str] = None
    if request is True:
        forced = spec.kernel_arm
    elif request is False:
        forced = ARM_XLA_REF
    elif request == "auto":
        override = knobs.kernel_backend_override(op)
        if override is None and spec.legacy_override is not None:
            override = spec.legacy_override()
        if override == "off":
            forced = ARM_XLA_REF
        elif override is not None:
            forced = override
    else:
        raise ValueError(
            f"use_kernel must be True/False/'auto', got {request!r}"
        )

    if forced is not None:
        arm = spec.arm(forced)
        if arm is None:
            raise ValueError(
                f"{op}: unknown backend arm {forced!r} "
                f"(registered: {list(spec.arm_names())}; set "
                f"AF2_KERNEL_BACKEND[_{op.upper()}] to one of these, "
                f"'off', or 'auto')"
            )
        if not arm.supported(platform, **shapes):
            if spec.unsupported_msg is not None:
                raise ValueError(spec.unsupported_msg(forced, shapes))
            raise ValueError(
                f"{op}: forced arm {forced!r} does not support "
                f"{shapes} on platform {platform!r}"
            )
        return forced

    arm_name = spec.auto(platform, shapes)
    assert spec.arm(arm_name) is not None, (op, arm_name)
    return arm_name


# ---------------------------------------------------------------------------
# registered ops
# ---------------------------------------------------------------------------


def _always(platform, **shapes) -> bool:
    return True


def _flash_supported(platform, *, i, j, dh, **_):
    from alphafold2_tpu.ops import flash_kernel

    return flash_kernel.supported(i, j, dh)


def _fused_supported(platform, *, i, j, dh, **_):
    from alphafold2_tpu.ops import flash_kernel

    return flash_kernel.supported_fused(i, j, dh)


def _flash_unsupported_msg(arm, s):
    return (
        f"flash kernel does not support shapes i={s.get('i')}, "
        f"j={s.get('j')}, dh={s.get('dh')} (row-vector VMEM bound / lane "
        f"alignment, see ops/flash_kernel.py supported)"
    )


def _flash_family_auto(supported):
    """The measured flash heuristic, shared by the dense, fused, and
    ring-hop ops: Pallas on TPU for supported shapes past the short-j
    crossover (AF2_FLASH_AUTO_MIN_J, kill-switch honored), the GPU arm
    on GPU platforms, XLA streaming elsewhere."""

    def auto(platform: str, s: dict) -> str:
        # knobs parse FIRST, unconditionally: a typo'd value must raise
        # on every host, not only where the knob would have mattered
        disabled = knobs.flash_kernel_disabled()
        min_j = knobs.flash_auto_min_j()
        if (
            platform == "tpu"
            and not disabled
            and s["j"] >= min_j
            and supported(platform, **s)
        ):
            return ARM_PALLAS_TPU
        if platform in _GPU_PLATFORMS:
            return ARM_GPU
        return ARM_XLA_REF

    return auto


register(OpSpec(
    name="flash_attention",
    arms=(
        Arm(ARM_PALLAS_TPU, _flash_supported,
            "ops/flash_kernel.py flash_attention_tpu (interpret off-TPU)"),
        Arm(ARM_GPU, _always,
            "XLA blockwise streaming (ops/flash.py blockwise_attention); "
            "Pallas-Triton slot when lowerable"),
        Arm(ARM_XLA_REF, _always,
            "ops/flash.py blockwise_attention — the parity oracle"),
    ),
    auto=_flash_family_auto(_flash_supported),
    probe={"i": 1152, "j": 4096, "dh": 64},
    parity_test="test_parity_flash_attention",
    unsupported_msg=_flash_unsupported_msg,
))

register(OpSpec(
    name="fused_attention",
    arms=(
        Arm(ARM_PALLAS_TPU, _fused_supported,
            "ops/flash_kernel.py flash_attention_fused (2-D pair bias + "
            "in-kernel gate)"),
        Arm(ARM_GPU, _always,
            "ops/flash.py streamed_fused_attention — the fusion-tuned "
            "blockwise path"),
        Arm(ARM_XLA_REF, _always,
            "ops/flash.py streamed_fused_attention / gate epilogue"),
    ),
    auto=_flash_family_auto(_fused_supported),
    probe={"i": 1152, "j": 4096, "dh": 64},
    parity_test="test_parity_fused_attention",
    unsupported_msg=_flash_unsupported_msg,
))


def _quant_supported(platform, *, m, k, n, x_dtype, **_):
    from alphafold2_tpu.ops.quant_kernel import supported_quant

    return supported_quant(m, k, n, x_dtype)


def _quant_auto(platform: str, s: dict) -> str:
    disabled = knobs.quant_kernel_disabled()  # parse on every host
    if (
        platform == "tpu"
        and not disabled
        and _quant_supported(platform, **s)
    ):
        return ARM_PALLAS_TPU
    if platform in _GPU_PLATFORMS:
        return ARM_GPU
    return ARM_XLA_REF


def _quant_legacy_override() -> Optional[str]:
    ov = knobs.quant_kernel_override()  # AF2_QUANT_KERNEL force/off/auto
    if ov is None:
        return None
    return ARM_PALLAS_TPU if ov else "off"


def _quant_unsupported_msg(arm, s):
    import jax.numpy as jnp

    return (
        f"quant kernel does not support m={s.get('m')}, k={s.get('k')}, "
        f"n={s.get('n')}, x_dtype={jnp.dtype(s.get('x_dtype')).name} "
        f"(f32/bf16 activations, dims <= 2^24 — see ops/quant_kernel.py "
        f"supported_quant)"
    )


register(OpSpec(
    name="quant_matmul",
    arms=(
        Arm(ARM_PALLAS_TPU, _quant_supported,
            "ops/quant_kernel.py quant_matmul_tpu — int8 tiles cross HBM, "
            "dequant in the epilogue"),
        Arm(ARM_GPU, _always,
            "ops/quant.py quant_matmul_xla (XLA fuses dequant+matmul on "
            "GPU; Triton slot when lowerable)"),
        Arm(ARM_XLA_REF, _always,
            "ops/quant.py quant_matmul_xla — materialized-dequant "
            "reference"),
    ),
    auto=_quant_auto,
    probe={"m": 4096, "k": 512, "n": 512, "x_dtype": "float32"},
    parity_test="test_parity_quant_matmul",
    legacy_override=_quant_legacy_override,
    unsupported_msg=_quant_unsupported_msg,
))


def _sparse_auto(platform: str, s: dict) -> str:
    disabled = knobs.flash_kernel_disabled()  # the shared kill-switch;
    # parsed on every host so a typo'd value raises everywhere
    if (
        platform == "tpu"
        and not disabled
        and s["n"] >= _SPARSE_KERNEL_MIN_N
    ):
        return ARM_PALLAS_TPU
    if platform in _GPU_PLATFORMS:
        return ARM_GPU
    return ARM_XLA_REF


register(OpSpec(
    name="sparse_attention",
    arms=(
        Arm(ARM_PALLAS_TPU, _always,
            "ops/sparse_kernel.py block_sparse_attention_tpu (blocks "
            "stream; no per-row residency bound)"),
        Arm(ARM_GPU, _always,
            "ops/sparse.py block_sparse_attention — XLA block-gather"),
        Arm(ARM_XLA_REF, _always,
            "ops/sparse.py block_sparse_attention — the parity oracle"),
    ),
    auto=_sparse_auto,
    probe={"n": 2048},
    parity_test="test_parity_sparse_attention",
))

register(OpSpec(
    name="merge_lse",
    arms=(
        Arm(ARM_PALLAS_TPU, _flash_supported,
            "ops/flash_kernel.py flash_attention_lse per hop, log-space "
            "merge (ops/flash.py merge_lse)"),
        Arm(ARM_GPU, _always,
            "XLA stream_block hop recurrence (the blockwise streaming "
            "path)"),
        Arm(ARM_XLA_REF, _always,
            "ops/flash.py stream_block hop recurrence"),
    ),
    auto=_flash_family_auto(_flash_supported),
    probe={"i": 512, "j": 512, "dh": 64},
    parity_test="test_parity_merge_lse",
    unsupported_msg=_flash_unsupported_msg,
))


# ---------------------------------------------------------------------------
# introspection: the op x arm x resolved table, the serving tag, the CLI
# ---------------------------------------------------------------------------


def resolution_table(platform: Optional[str] = None):
    """[(op, probe, {arm: supported@probe}, resolved-or-error)] for this
    host (or an explicit `platform`), honoring the live env overrides —
    exactly what `resolve` would do at each op's probe shapes."""
    if platform is None:
        platform = _platform()
    rows = []
    for name, spec in _REGISTRY.items():
        supp = {
            a.name: bool(a.supported(platform, **spec.probe))
            for a in spec.arms
        }
        try:
            resolved = resolve(name, request="auto", platform=platform,
                               **spec.probe)
        except ValueError as e:  # forced-unknown / forced-unsupported env
            resolved = f"ERROR: {e}"
        rows.append((name, dict(spec.probe), supp, resolved))
    return rows


def resolution_tag(platform: Optional[str] = None) -> str:
    """The backend-arm fragment of the serving config tag: which arm each
    registered op resolves to on this host under the live env. Two
    replicas whose envs force different arms get different tags, so the
    result LRU / AOT-executable keyspace never aliases across arms
    (rounding differs between a kernel and its XLA twin). A malformed
    override propagates as ValueError — an engine must not build with an
    unresolvable dispatch env."""
    if platform is None:
        platform = _platform()
    parts = []
    for name, spec in _REGISTRY.items():
        arm = resolve(name, request="auto", platform=platform, **spec.probe)
        parts.append(f"{name}={arm}")
    return f"dispatch[{platform}](" + ",".join(parts) + ")"


def resolved_arm(op: str, platform: Optional[str] = None) -> str:
    """The arm one op resolves to on this host under the live env, at
    its probe shapes — the per-op slice of `resolution_tag()`. The
    serving cost ledger labels its cells with the flash_attention arm
    (the headline hot op, the same convention bench rows use for
    `backend_arm`)."""
    if platform is None:
        platform = _platform()
    spec = get(op)
    return resolve(op, request="auto", platform=platform, **spec.probe)


def main(argv=None) -> int:
    """CLI: ``python -m alphafold2_tpu.ops.dispatch --check``."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m alphafold2_tpu.ops.dispatch",
        description="kernel dispatch registry introspection",
    )
    ap.add_argument("--check", action="store_true",
                    help="print the op x arm x resolved-on-this-host "
                         "table (the only mode; --check makes intent "
                         "explicit in runbooks)")
    ap.add_argument("--platform", default=None,
                    help="resolve for an explicit platform instead of "
                         "this host's (tpu/gpu/cpu)")
    args = ap.parse_args(argv)

    platform = args.platform or _platform()
    print(f"kernel dispatch registry @ platform={platform} "
          f"(pallas_triton_lowerable={pallas_triton_lowerable()})")
    for name, probe, supp, resolved in resolution_table(platform):
        probe_s = " ".join(f"{k}={v}" for k, v in probe.items())
        supp_s = " ".join(
            f"{arm}={'yes' if ok else 'no'}" for arm, ok in supp.items()
        )
        print(f"  {name:<17} probe[{probe_s}]  {supp_s}  -> {resolved}")
    print(f"  tag: {resolution_tag(platform)}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
