"""Ops layer: functional NN primitives, attention (dense / axial / tied-row /
KV-compressed / block-sparse), and feed-forward blocks.

Everything here is a pure function over explicit parameter pytrees — the
TPU-native answer to the reference's `torch.nn.Module` ops layer
(reference alphafold2_pytorch/alphafold2.py:30-286).

Hot ops (flash/fused attention, quant matmul, sparse attention, the
ring hop) resolve their backend arm — pallas_tpu / gpu / xla_ref —
through ONE registry, `ops/dispatch.py` (`resolve`), with every AF2_*
env knob defined once in `ops/knobs.py`.
"""

from alphafold2_tpu.ops.core import (
    linear_init,
    linear,
    layer_norm_init,
    layer_norm,
    embedding_init,
    embedding,
    dropout,
)
from alphafold2_tpu.ops.attention import (
    AttentionConfig,
    attention_init,
    attention_apply,
    axial_attention_init,
    axial_attention_apply,
)
from alphafold2_tpu.ops.feedforward import (
    feed_forward_init,
    feed_forward_apply,
)
from alphafold2_tpu.ops.dispatch import (
    resolution_table,
    resolution_tag,
    resolve,
)
from alphafold2_tpu.ops.flash import blockwise_attention, flash_attention
from alphafold2_tpu.ops.quant import (
    dequantize_tree,
    dequantize_weight,
    quant_matmul,
    quantize_tree,
    quantize_weight,
    reject_quant_training,
    tree_weight_bytes,
)

__all__ = [
    "resolution_table",
    "resolution_tag",
    "resolve",
    "dequantize_tree",
    "dequantize_weight",
    "quant_matmul",
    "quantize_tree",
    "quantize_weight",
    "reject_quant_training",
    "tree_weight_bytes",
    "linear_init",
    "linear",
    "layer_norm_init",
    "layer_norm",
    "embedding_init",
    "embedding",
    "dropout",
    "AttentionConfig",
    "attention_init",
    "attention_apply",
    "axial_attention_init",
    "axial_attention_apply",
    "feed_forward_init",
    "feed_forward_apply",
    "blockwise_attention",
    "flash_attention",
]
