"""Pallas TPU kernel for the int8-weight mixed-precision matmul.

The inference fast path under ops/quant.py's `quant_matmul`: activations
(f32/bf16) times PER-CHANNEL-quantized int8 weights, with the dequant
scale applied in the kernel EPILOGUE. The weight tensor crosses HBM as
int8 — a quarter of the f32 traffic on the trunk's dense layers, which
are memory-bound at serving batch sizes — and the int8 -> activation-dtype
cast happens on the VMEM-resident tile, so a dequantized weight copy is
never materialized in HBM (the traffic the pure-XLA reference arm,
ops/quant.py `quant_matmul_xla`, pays by construction).

Streaming layout mirrors ops/flash_kernel.py: a 3-D grid whose LAST
dimension walks the contraction (K) blocks sequentially (dimension
semantics "arbitrary") with an f32 accumulator in VMEM scratch, while
Mosaic's pipeline double-buffers the activation and weight tile fetches.
The per-output-channel scale rides as a (1, bn) row-vector block and
multiplies the accumulator once, in the finish step — f32 epilogue math,
one cast to the output dtype at the very end, exactly the contract the
XLA reference arm follows so the two arms are allclose (tier-1 parity
matrix in tests/test_quant.py; `supported_quant` gates auto-dispatch the
way `supported_fused` gates the fused attention kernel).

On non-TPU backends the kernel runs in interpreter mode (tests), keeping
one code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from alphafold2_tpu import compat
from alphafold2_tpu.compat import pallas as pl, pallas_tpu as pltpu
from alphafold2_tpu.ops.core import pallas_interpret as _interpret
from alphafold2_tpu.ops.flash_kernel import pick_block

# Activation dtypes the MXU path handles with exact int8 -> dtype casts
# (|q| <= 127 is exactly representable in both); everything else streams
# via the XLA reference arm.
_SUPPORTED_X_DTYPES = (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16))

# Per-grid-step VMEM working set is bounded by the fixed tile targets
# below (double-buffered (bm, bk) activations + (bk, bn) int8 weights +
# the (bm, bn) f32 accumulator scratch + a (1, bn) scale row); the only
# shape-dependent residency is the grid bookkeeping, so the supported
# range is wide. The dim caps below are a sanity bound, not a VMEM one.
_MAX_DIM = 1 << 24

# int8 tiles want >= (32, 128) sublane x lane granularity; 128-multiples
# satisfy every operand dtype in the kernel at once.
_BM_TARGET = 256
_BN_TARGET = 256
_BK_TARGET = 256


def supported_quant(m: int, k: int, n: int, x_dtype=jnp.float32) -> bool:
    """Shapes/dtypes the int8-weight kernel handles; everything else takes
    the XLA dequant reference arm (ops/quant.py `quant_matmul_xla`).

    Tiles stream through the grid's sequential dimension, so there is no
    per-row residency bound to enforce (unlike the flash kernels' row
    vectors) — the gate is activation dtype (f32/bf16 exact int8 casts)
    plus sane dimension bounds."""
    return (
        0 < m <= _MAX_DIM
        and 0 < k <= _MAX_DIM
        and 0 < n <= _MAX_DIM
        and jnp.dtype(x_dtype) in _SUPPORTED_X_DTYPES
    )


# first two grid dims parallel (each (mi, ni) pair owns a private output
# window), streamed contraction dim sequential — the flash backward's
# semantics (ops/flash_kernel.py _BWD_PARAMS)
_QMM_PARAMS = compat.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary")
)

_out_struct = compat.out_struct


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref, acc_scr, *, nkb):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    x = x_ref[...]                    # (bm, bk) activation dtype
    w = w_ref[...]                    # (bk, bn) int8
    # the ONLY dequant in the kernel: int8 -> activation dtype on the
    # VMEM tile (exact — |q| <= 127), so the MXU runs at the activation
    # dtype's peak and HBM only ever saw int8 weight bytes
    acc_scr[...] = acc_scr[...] + jax.lax.dot_general(
        x, w.astype(x.dtype),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ki == nkb - 1)
    def _finish():
        # per-channel scale epilogue in f32 on the f32 accumulator, one
        # cast at the very end — the exact math quant_matmul_xla runs, so
        # kernel-on and kernel-off arms differ only in rounding
        s = s_ref[...].astype(jnp.float32)      # (1, bn)
        o_ref[...] = (acc_scr[...] * s).astype(o_ref.dtype)


def quant_matmul_tpu(x, qw, scale, *, bm=None, bn=None, bk=None):
    """Fused-dequant matmul: x (m, k) f32/bf16 @ qw (k, n) int8, scaled
    per output channel by `scale` (n,) f32 in the kernel epilogue.
    Returns (m, n) in x.dtype. bm/bn/bk override the tile sizes (None =
    padding-aware pick_block)."""
    m, k = x.shape
    n = qw.shape[1]
    bm = pick_block(m, target=_BM_TARGET) if bm is None else bm
    bn = pick_block(n, target=_BN_TARGET) if bn is None else bn
    bk = pick_block(k, target=_BK_TARGET) if bk is None else bk

    pad_m, pad_k, pad_n = (-m) % bm, (-k) % bk, (-n) % bn
    if pad_m or pad_k:
        x = jnp.pad(x, ((0, pad_m), (0, pad_k)))
    if pad_k or pad_n:
        qw = jnp.pad(qw, ((0, pad_k), (0, pad_n)))
    scale2 = scale.reshape(1, n)
    if pad_n:
        scale2 = jnp.pad(scale2, ((0, 0), (0, pad_n)))
    mp, kp, np_ = m + pad_m, k + pad_k, n + pad_n
    nkb = kp // bk

    out = pl.pallas_call(
        functools.partial(_qmm_kernel, nkb=nkb),
        out_shape=_out_struct((mp, np_), x.dtype, x, qw, scale2),
        grid=(mp // bm, np_ // bn, nkb),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, ki: (i, ki)),
            pl.BlockSpec((bk, bn), lambda i, j, ki: (ki, j)),
            pl.BlockSpec((1, bn), lambda i, j, ki: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, ki: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_QMM_PARAMS,
        interpret=_interpret(),
    )(x, qw, scale2)
    return out[:m, :n]
