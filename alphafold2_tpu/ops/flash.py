"""Blockwise (flash-style) exact attention with bounded memory.

The reference materializes the full (i, j) attention matrix per head
(reference alphafold2_pytorch/alphafold2.py:152-174). At the north-star
scale (crop 384 -> 1152x1152 pair grid, the grid axis folded into batch for
axial attention) that matrix is tens of GB per layer — it cannot exist on a
16G chip. This module computes the same softmax(QK^T)V exactly but tiled:
query tiles stream over K/V blocks accumulating running-max / sum statistics
in float32 (the FlashAttention recurrence, shared with ring attention in
parallel/sequence.py and the Pallas block-sparse kernel in
ops/sparse_kernel.py). Peak live memory is one (q_tile, kv_block) logit tile
instead of the full matrix.

Each tile is wrapped in `jax.checkpoint`, so the backward pass recomputes
tile activations instead of storing them — the memory bound holds for
training. Tiles stay large and static-shaped so XLA maps them onto the MXU;
this is the portable (CPU-testable) sibling of a Pallas dense flash kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = float("-inf")


def stream_block(q, k_blk, v_blk, bias_blk, m, l, acc, scale,
                 logit_dtype=jnp.float32, bias2d_blk=None):
    """One flash-attention accumulation step against a K/V block.

    q: (b, nq, h, d); k_blk/v_blk: (b, nk, h, d); bias_blk: (b, nk) additive
    (-inf for masked keys). Running stats m, l: (b, h, nq); acc: (b, h, nq, d).
    bias2d_blk: optional (b, h, nq, nk) full pair-bias block added to the
    logits (the XLA twin of the fused kernel's streamed 2-D bias tiles);
    bias_blk may be None when it is given (fold masks into the 2-D bias).

    logit_dtype: dtype the (b, h, nq, nk) score/probability tiles are
    MATERIALIZED in. These tiles dominate the path's HBM traffic (the
    running stats and accumulator are f32 regardless, and the AV dot
    casts p to v's dtype anyway) — bf16 halves the dominant traffic at
    ~0.5% probability error, the same order as the bf16 activation
    quantization the model already carries. Running max/sum stay f32.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk).astype(logit_dtype) * scale
    if bias_blk is not None:
        s = s + bias_blk[:, None, None, :].astype(logit_dtype)
    if bias2d_blk is not None:
        s = s + bias2d_blk.astype(logit_dtype)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1).astype(jnp.float32))
    # alpha/p guards: -inf - -inf = nan. The exp ARGUMENT must be sanitized
    # too, not just the result: exp(nan) in the unselected where-branch has a
    # nan primal, and exp's vjp multiplies even a zero cotangent by it
    # (0 * nan = nan), poisoning dq/dk for fully-masked rows.
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    alpha = jnp.where(
        jnp.isneginf(m), 0.0, jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m) - m_safe)
    )
    p = jnp.where(
        jnp.isneginf(s),
        jnp.zeros((), logit_dtype),
        jnp.exp(jnp.where(jnp.isneginf(s), jnp.zeros((), logit_dtype), s)
                - m_safe[..., None].astype(logit_dtype)),
    )
    # f32 ACCUMULATION without materializing an f32 copy of p
    l_new = l * alpha + jnp.sum(p, axis=-1, dtype=jnp.float32)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p.astype(v_blk.dtype), v_blk
    ).astype(jnp.float32)
    return m_new, l_new, acc_new


def merge_lse(out_a, lse_a, out_b, lse_b):
    """Log-space merge of two NORMALIZED partial softmax results.

    The hop interface of kernel-path ring attention
    (parallel/sequence.py): each hop produces its block's normalized
    output plus the log-sum-exp of its logits (ops/flash_kernel.py
    `flash_attention_lse`), and blocks combine associatively:

        new_out = (e^lse_a * out_a + e^lse_b * out_b) / (e^lse_a + e^lse_b)
        new_lse = log(e^lse_a + e^lse_b)

    computed with the usual running-max stabilization. Zero-mass blocks
    (a fully-masked hop) must carry lse = -inf so they weigh ZERO — the
    kernel's +inf zero-mass convention is flipped before merging
    (parallel/sequence.py hop()). Both-empty rows return (0, -inf).

    out_*: (..., d) float32; lse_*: (...) float32. Returns (out, lse).
    """
    m = jnp.maximum(lse_a, lse_b)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)  # both-empty rows
    w_a = jnp.exp(lse_a - m_safe)
    w_b = jnp.exp(lse_b - m_safe)
    tot = w_a + w_b
    safe_tot = jnp.where(tot > 0, tot, 1.0)
    out = jnp.where(
        (tot > 0)[..., None],
        (out_a * w_a[..., None] + out_b * w_b[..., None]) / safe_tot[..., None],
        0.0,
    )
    lse = jnp.where(tot > 0, m_safe + jnp.log(safe_tot), _NEG_INF)
    return out, lse


def _largest_divisor_leq(n: int, cap: int) -> int:
    cap = max(1, min(n, cap))
    for c in range(cap, 0, -1):
        if n % c == 0:
            return c
    return 1


def _tile_attention(q, k, v, bias, scale, kv_block, logit_dtype=jnp.float32):
    """Exact attention for one query tile, streaming K/V blocks."""
    b, nq, h, dh = q.shape
    j = k.shape[1]
    m0 = jnp.full((b, h, nq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, nq), jnp.float32)
    acc0 = jnp.zeros((b, h, nq, dh), jnp.float32)

    if kv_block is None or j <= kv_block:
        m, l, acc = stream_block(q, k, v, bias, m0, l0, acc0, scale,
                                 logit_dtype)
    else:
        pad = (-j) % kv_block
        if pad:
            k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
            v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
            bias = jnp.pad(bias, ((0, 0), (0, pad)), constant_values=_NEG_INF)
        nb = (j + pad) // kv_block
        ks = k.reshape(b, nb, kv_block, h, dh).transpose(1, 0, 2, 3, 4)
        vs = v.reshape(b, nb, kv_block, h, dh).transpose(1, 0, 2, 3, 4)
        bs = bias.reshape(b, nb, kv_block).transpose(1, 0, 2)

        def body(carry, blk):
            mm, ll, aa = carry
            kb, vb, bb = blk
            return stream_block(q, kb, vb, bb, mm, ll, aa, scale,
                                logit_dtype), None

        (m, l, acc), _ = jax.lax.scan(body, (m0, l0, acc0), (ks, vs, bs))

    out = acc / jnp.where(l > 0, l, 1.0)[..., None]  # zeros for all-masked q
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def blockwise_attention(
    q,
    k,
    v,
    key_bias=None,
    *,
    scale=None,
    tile_elems: int = 1 << 25,
    kv_block: int = 2048,
    remat: bool = True,
    logit_dtype=None,
):
    """Exact softmax(QK^T * scale + bias)V with bounded-memory tiling.

    Args:
      q: (B, i, h, dh) queries — B may be a huge folded-batch axis (axial
        attention) or 1 with huge i (flat cross-attention); tiling adapts.
      k, v: (B, j, h, dh).
      key_bias: (B, j) additive float32, 0 for valid keys / -inf for masked
        (key-side masking only, matching the reference's key-padding
        semantics, alphafold2.py:156-161). Query-side masking is
        intentionally absent: masked query rows produce finite values that
        downstream masking discards — the same contract as the dense path,
        which gives those rows uniform-attention garbage instead.
      tile_elems: target max elements per (batch*h*q*kv) logit tile
        (default 2^25 = 128 MB in f32).
      kv_block: stream K/V in blocks of this length when j exceeds it.
      remat: jax.checkpoint each tile so backward recomputes instead of
        storing tile activations.
      logit_dtype: dtype the score/probability tiles are materialized in
        (None = float32). These tiles dominate HBM traffic; bf16 halves
        it at ~0.5% probability error (see stream_block).

    Returns: (B, i, h, dh) in q.dtype. Fully-masked query rows return zeros.
    """
    B, i, h, dh = q.shape
    j = k.shape[1]
    scale = dh ** -0.5 if scale is None else scale
    logit_dtype = jnp.float32 if logit_dtype is None else logit_dtype
    if key_bias is None:
        key_bias = jnp.zeros((B, j), jnp.float32)

    j_eff = min(j, kv_block) if kv_block else j
    per_q_row = max(1, h * j_eff)
    qb = max(1, min(i, tile_elems // per_q_row))
    bb = _largest_divisor_leq(B, max(1, tile_elems // (per_q_row * min(i, qb))))
    kvb = kv_block if (kv_block and j > kv_block) else None

    def tile(qt, kt, vt, bt):
        return _tile_attention(qt, kt, vt, bt, scale, kvb, logit_dtype)

    if remat:
        tile = jax.checkpoint(tile)

    if bb == B and qb >= i:
        return tile(q, k, v, key_bias)

    pad_i = (-i) % qb
    if pad_i:
        q = jnp.pad(q, ((0, 0), (0, pad_i), (0, 0), (0, 0)))
    nq = (i + pad_i) // qb

    def batch_chunk(args):
        qc, kc, vc, bc = args  # (bb, i_p, h, dh), (bb, j, h, dh), (bb, j)
        if nq == 1:
            return tile(qc, kc, vc, bc)
        qs = qc.reshape(bb, nq, qb, h, dh).transpose(1, 0, 2, 3, 4)
        out = jax.lax.map(lambda qt: tile(qt, kc, vc, bc), qs)
        return out.transpose(1, 0, 2, 3, 4).reshape(bb, nq * qb, h, dh)

    if bb == B:
        out = batch_chunk((q, k, v, key_bias))
    else:
        nb = B // bb

        def resh(t):
            return t.reshape((nb, bb) + t.shape[1:])

        out = jax.lax.map(batch_chunk, (resh(q), resh(k), resh(v), resh(key_bias)))
        out = out.reshape((B, nq * qb, h, dh))

    return out[:, :i] if pad_i else out


def apply_output_gate(out, gate):
    """The UNFUSED sigmoid output-gate epilogue: sigmoid in f32 on the
    f32 output, one cast at the end — the exact math the fused kernel's
    finish step runs in VMEM (ops/flash_kernel.py), so kernel-on and
    kernel-off arms of a gated model differ only in rounding. out / gate:
    (..., dh) matching shapes; gate holds pre-sigmoid logits."""
    return (
        out.astype(jnp.float32) * jax.nn.sigmoid(gate.astype(jnp.float32))
    ).astype(out.dtype)


def streamed_fused_attention(q, k, v, key_bias, pair_bias, gate, scale,
                             kv_block: int = 2048, remat: bool = True,
                             logit_dtype=None):
    """XLA twin of the fused-epilogue kernel: 2-D pair bias + output gate.

    q: (B, i, h, dh); k, v: (B, j, h, dh); pair_bias: (B, h, i, j) f32
    additive; key_bias: optional (B, j) mask bias folded in; gate:
    optional (B, i, h, dh) pre-sigmoid logits. K/V and bias stream in
    `kv_block` chunks with the flash recurrence, so the live logit tile is
    (B, h, i, kv_block) — bounded along j only (the 2-D bias itself is a
    caller-materialized (B, h, i, j) input, so there is no q-tiling win to
    chase here; the Pallas kernel is the production TPU path).
    logit_dtype: dtype of the live score/probability tiles (None = f32) —
    same knob as `blockwise_attention`, so the
    attn_flash_compute_dtype_logits A/B stays honest on this path too.
    Exact at f32; the parity oracle for the fused kernel's interpret-mode
    tests."""
    B, i, h, dh = q.shape
    j = k.shape[1]
    logit_dtype = jnp.float32 if logit_dtype is None else logit_dtype
    bias = pair_bias.astype(jnp.float32)
    if key_bias is not None:
        bias = bias + key_bias[:, None, None, :].astype(jnp.float32)

    def run(q, k, v, bias):
        m0 = jnp.full((B, h, i), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, h, i), jnp.float32)
        acc0 = jnp.zeros((B, h, i, dh), jnp.float32)
        if j <= kv_block:
            m, l, acc = stream_block(q, k, v, None, m0, l0, acc0, scale,
                                     logit_dtype=logit_dtype,
                                     bias2d_blk=bias)
        else:
            pad = (-j) % kv_block
            if pad:
                k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
                v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
                bias = jnp.pad(bias, ((0, 0), (0, 0), (0, 0), (0, pad)),
                               constant_values=_NEG_INF)
            nb = (j + pad) // kv_block
            ks = k.reshape(B, nb, kv_block, h, dh).transpose(1, 0, 2, 3, 4)
            vs = v.reshape(B, nb, kv_block, h, dh).transpose(1, 0, 2, 3, 4)
            bs = bias.reshape(B, h, i, nb, kv_block).transpose(3, 0, 1, 2, 4)

            def body(carry, blk):
                mm, ll, aa = carry
                kb, vb, bb = blk
                return stream_block(q, kb, vb, None, mm, ll, aa, scale,
                                    logit_dtype=logit_dtype,
                                    bias2d_blk=bb), None

            (m, l, acc), _ = jax.lax.scan(body, (m0, l0, acc0), (ks, vs, bs))
        out = acc / jnp.where(l > 0, l, 1.0)[..., None]
        return jnp.transpose(out, (0, 2, 1, 3))  # (B, i, h, dh) f32

    if remat:
        run = jax.checkpoint(run)
    out = run(q, k, v, bias)
    if gate is not None:
        out = out * jax.nn.sigmoid(gate.astype(jnp.float32))
    return out.astype(q.dtype)


# The env knobs this module used to parse inline live in ops/knobs.py
# now (one validated definition per knob); the names are re-exported for
# existing importers (ops/sparse.py, tests). No env logic here — the
# af2lint `dispatch` pass enforces that.
from alphafold2_tpu.ops.knobs import (  # noqa: E402
    FLASH_AUTO_MIN_J_DEFAULT as _AUTO_MIN_J,
    flash_auto_min_j as auto_min_j,
    flash_kernel_disabled as kernel_env_disabled,
    gate_epilogue_unfused,
)


def kernel_dispatch(i: int, j: int, dh: int, use_kernel,
                    fused: bool = False) -> bool:
    """Resolve the tri-state `use_kernel` into a concrete kernel decision.

    Thin adapter over the ONE resolution point, ops/dispatch.py
    `resolve` — flash_attention and ring_attention
    (parallel/sequence.py) both route here, so the
    AF2_DISABLE_FLASH_KERNEL escape hatch, the AF2_KERNEL_BACKEND[_<OP>]
    overrides, and the loud unsupported-shape error hold everywhere.
    True forces the kernel (ValueError on unsupported shapes — forcing
    must not silently fall back), False forces XLA streaming, "auto" =
    the registry heuristic (kernel on TPU for supported shapes with
    j >= auto_min_j(), the measured short-j crossover). `fused` selects
    the fused-epilogue op (its shape gate is `supported_fused`:
    2-D pair bias / in-kernel gating, ops/flash_kernel.py).
    """
    from alphafold2_tpu.ops import dispatch

    op = "fused_attention" if fused else "flash_attention"
    return (
        dispatch.resolve(op, request=use_kernel, i=i, j=j, dh=dh)
        == dispatch.ARM_PALLAS_TPU
    )


def hop_attention_lse(qf, kf, vf, bias, scale):
    """One ring hop's normalized (out, lse) through the Pallas kernel —
    the `merge_lse` op's kernel arm, wrapped here so
    parallel/sequence.py never imports a kernel module directly (the
    dispatch lint's import monopoly).

    qf/kf/vf: (BH, n, dh) folded layout; bias: (BH, nk) additive f32.
    The kernel marks zero-mass rows with +inf lse (its backward
    convention); for cross-hop combination zero mass must weigh ZERO —
    flipped to -inf here (the `merge_lse` contract). Returns
    (out f32, lse f32)."""
    from alphafold2_tpu.ops import flash_kernel

    out_h, lse_h = flash_kernel.flash_attention_lse(qf, kf, vf, bias, scale)
    lse_h = jnp.where(jnp.isposinf(lse_h), _NEG_INF, lse_h)
    return out_h.astype(jnp.float32), lse_h


def flash_attention(q, k, v, key_bias=None, *, pair_bias=None, gate=None,
                    scale=None, use_kernel="auto",
                    kernel_qb=None, kernel_kb=None, **blockwise_kwargs):
    """Exact attention: fused Pallas kernel on TPU, XLA blockwise otherwise.

    Same contract as `blockwise_attention` (q (B, i, h, dh); k, v
    (B, j, h, dh); key-side (B, j) additive bias). use_kernel: True forces
    the kernel (interpret mode off-TPU — for tests), False forces XLA
    streaming, "auto" uses the kernel on TPU for supported shapes
    (ops/flash_kernel.py `supported`) with j >= auto_min_j() — below the
    measured short-j crossover XLA streaming is faster end-to-end
    (PERF.md session 4), so "auto" prefers it there. kernel_qb/kernel_kb override the
    kernel's query/key block sizes (None = padding-aware pick_block) —
    kernel path only, used for block tuning (scripts/bench_kernels.py).

    Fused epilogue: `pair_bias` (B, h, i, j) f32 full 2-D additive bias
    tiles and/or `gate` (B, i, h, dh) pre-sigmoid output-gate logits.
    On the kernel path both fuse INTO the Pallas kernel
    (ops/flash_kernel.py `flash_attention_fused` — the bias-add and the
    gate-multiply stop costing separate HBM logit/output passes); off
    kernel, the gate applies as an exact epilogue over the blockwise
    result and pair-bias streams through `streamed_fused_attention`.
    """
    from alphafold2_tpu.ops import flash_kernel

    B, i, h, dh = q.shape
    j = k.shape[1]
    scale = dh ** -0.5 if scale is None else scale
    fused = pair_bias is not None or gate is not None

    if gate is not None and pair_bias is None and gate_epilogue_unfused():
        # control arm (AF2_UNFUSE_GATE_EPILOGUE): same use_kernel policy
        # for the core, gate as an exact XLA epilogue — identical math to
        # the fused path, one extra HBM out-read/multiply/write pass
        out = flash_attention(
            q, k, v, key_bias, scale=scale, use_kernel=use_kernel,
            kernel_qb=kernel_qb, kernel_kb=kernel_kb, **blockwise_kwargs,
        )
        return apply_output_gate(out, gate)

    if fused and kernel_dispatch(i, j, dh, use_kernel, fused=True):
        ldt = blockwise_kwargs.get("logit_dtype")
        if ldt is not None and ldt != jnp.float32:
            raise ValueError(
                "logit_dtype (flash_compute_dtype_logits) applies only "
                "to the XLA streaming path, but the fused Pallas kernel "
                f"dispatched here (i={i}, j={j}, use_kernel="
                f"{use_kernel!r}); disable the kernel for this A/B"
            )

        def fold(t):
            return t.transpose(0, 2, 1, 3).reshape(B * h, t.shape[1], dh)

        if pair_bias is not None:
            bias = pair_bias.astype(jnp.float32)
            if key_bias is not None:
                bias = bias + jnp.broadcast_to(
                    key_bias, (B, j)
                ).astype(jnp.float32)[:, None, None, :]
            bias = jnp.broadcast_to(bias, (B, h, i, j)).reshape(B * h, i, j)
        else:
            bias = (
                jnp.zeros((B, j), jnp.float32)
                if key_bias is None
                else jnp.broadcast_to(key_bias, (B, j)).astype(jnp.float32)
            )
            bias = jnp.repeat(bias, h, axis=0)
        gate_folded = fold(gate) if gate is not None else None
        out = flash_kernel.flash_attention_fused(
            fold(q), fold(k), fold(v), bias, scale,
            gate=gate_folded, qb=kernel_qb, kb=kernel_kb,
        )
        return out.reshape(B, h, i, dh).transpose(0, 2, 1, 3)

    if pair_bias is not None:
        # XLA twin of the 2-D-bias mode: j-streamed, exact at f32.
        # logit_dtype threads through (the bf16-logits A/B must not
        # silently record f32 math here — the kernel branch above raises
        # for the same knob); tile_elems is structurally inapplicable
        # (the 2-D bias is a caller-materialized (B, h, i, j) input, so
        # there is no q-tiling win — see streamed_fused_attention).
        return streamed_fused_attention(
            q, k, v, key_bias, pair_bias, gate, scale,
            kv_block=blockwise_kwargs.get("kv_block", 2048),
            logit_dtype=blockwise_kwargs.get("logit_dtype"),
        )
    if gate is not None:
        # gate-only: the plain blockwise path plus the exact epilogue
        out = flash_attention(
            q, k, v, key_bias, scale=scale, use_kernel=False,
            **blockwise_kwargs,
        )
        return apply_output_gate(out, gate)

    if kernel_dispatch(i, j, dh, use_kernel):
        ldt = blockwise_kwargs.get("logit_dtype")
        if ldt is not None and ldt != jnp.float32:
            # the Pallas kernel keeps its logit tiles in VMEM (no HBM
            # materialization to halve) and computes them f32: recording
            # a "bf16-logits" measurement that actually ran the kernel
            # would misattribute the A/B — fail loudly instead
            raise ValueError(
                "logit_dtype (flash_compute_dtype_logits) applies only "
                "to the XLA streaming path, but the Pallas kernel "
                f"dispatched here (i={i}, j={j}, use_kernel="
                f"{use_kernel!r}); disable the kernel for this A/B"
            )

        def fold(t):
            return t.transpose(0, 2, 1, 3).reshape(B * h, t.shape[1], dh)

        bias = (
            jnp.zeros((B, j), jnp.float32)
            if key_bias is None
            else jnp.broadcast_to(key_bias, (B, j)).astype(jnp.float32)
        )
        bias = jnp.repeat(bias, h, axis=0)  # per (batch, head) grid row
        out = flash_kernel.flash_attention_tpu(
            fold(q), fold(k), fold(v), bias, scale,
            qb=kernel_qb, kb=kernel_kb,
        )
        return out.reshape(B, h, i, dh).transpose(0, 2, 1, 3)

    return blockwise_attention(
        q, k, v, key_bias, scale=scale, **blockwise_kwargs
    )
