"""Functional NN primitives.

Parameters are plain pytrees (nested dicts of jnp arrays); every op is a pure
function `f(params, x, ...) -> y`. This replaces the reference's torch.nn
primitives (Linear / LayerNorm / Embedding / Dropout) with a functional core
that composes cleanly with jit / pjit / scan / custom_vjp.

Initialization follows torch defaults so training dynamics are comparable to
the reference:
  - Linear: U(-1/sqrt(fan_in), 1/sqrt(fan_in)) for weight and bias
  - Embedding: N(0, 1)
  - LayerNorm: scale=1, bias=0

Parameters are stored in float32; `dtype` arguments select the compute dtype
(bfloat16 on TPU for the MXU path). LayerNorm statistics and softmax are
always accumulated in float32.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def _uniform(key, shape, bound, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


# --- linear -----------------------------------------------------------------


def linear_init(key, d_in: int, d_out: int, bias: bool = True):
    """Params for a dense layer; weight layout (d_in, d_out)."""
    kw, kb = jax.random.split(key)
    bound = 1.0 / math.sqrt(d_in)
    params = {"w": _uniform(kw, (d_in, d_out), bound)}
    if bias:
        params["b"] = _uniform(kb, (d_out,), bound)
    return params


def linear(params, x, dtype=None):
    """y = x @ w (+ b). Computes in `dtype` if given (params are cast).

    Quantized params (the PTQ tree rewrite `{"qw": int8, "scale": f32}`
    from ops/quant.py quantize_tree) dispatch to the mixed-precision
    matmul instead — this is THE chokepoint every dense/projection layer
    flows through, so the int8 inference arm needs no per-layer wiring."""
    if "qw" in params:
        from alphafold2_tpu.ops.quant import quant_matmul

        y = quant_matmul(x, params["qw"], params["scale"], dtype=dtype)
        if "b" in params:
            y = y + params["b"].astype(y.dtype)
        return y
    w = params["w"]
    if dtype is not None:
        w = w.astype(dtype)
        x = x.astype(dtype)
    y = x @ w
    if "b" in params:
        y = y + params["b"].astype(y.dtype)
    return y


# --- layer norm -------------------------------------------------------------


def layer_norm_init(dim: int):
    return {"scale": jnp.ones((dim,), jnp.float32), "bias": jnp.zeros((dim,), jnp.float32)}


def layer_norm(params, x, eps: float = 1e-5):
    """LayerNorm over the last axis; statistics in float32, output in x.dtype."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    y = y * params["scale"] + params["bias"]
    return y.astype(x.dtype)


# --- embedding --------------------------------------------------------------


def embedding_init(key, num_embeddings: int, dim: int):
    return {"table": jax.random.normal(key, (num_embeddings, dim), jnp.float32)}


def embedding(params, ids, dtype=None):
    table = params["table"]
    if dtype is not None:
        table = table.astype(dtype)
    return jnp.take(table, ids, axis=0)


# --- dropout ----------------------------------------------------------------


def dropout(rng, x, rate: float, deterministic: bool = False):
    """Inverted dropout. `rng is None` or `deterministic` means identity.

    JAX's explicit keys give the determinism the reference needs RNG
    state capture/replay for (reference reversible.py:26-56) for free: the
    reversible backward simply folds in the same key again.
    """
    if deterministic or rate == 0.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))


def pallas_interpret() -> bool:
    """Run Pallas kernels in interpreter mode off-TPU (one code path for
    CPU tests and TPU execution; shared by ops/sparse_kernel.py and
    ops/flash_kernel.py).

    AF2_PALLAS_INTERPRET overrides the platform default both ways:
    "0"/"false" forces compiled-mode tracing (used by
    scripts/check_mosaic_lowering.py to run the Pallas -> Mosaic lowering
    for the TPU target on a CPU host via jax.export, surfacing
    BlockSpec/layout errors without a chip); "1"/"true" forces interpret
    mode (kernel debugging on a TPU host); ""/unset falls through to the
    platform default (so `AF2_PALLAS_INTERPRET= cmd` blanks an inherited
    value); anything else raises (parsed in ops/knobs.py — the one home
    for every AF2_* knob).
    """
    import jax

    from alphafold2_tpu.ops.knobs import pallas_interpret_override

    forced = pallas_interpret_override()
    if forced is not None:
        return forced
    return jax.devices()[0].platform != "tpu"
