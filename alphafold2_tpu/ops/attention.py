"""Dense, tied-row, KV-compressed, and axial attention.

TPU-native re-design of the reference attention stack
(reference alphafold2_pytorch/alphafold2.py:77-286):

  * `attention_apply` — multi-head attention with the reference's three fused
    modes: self/cross (optional `context`), memory-compressed KV (grouped
    strided conv over keys/values + sum-pooled mask,
    reference alphafold2.py:99-101,116-136), and tied-row attention (logits
    contracted over MSA rows with an extra r^-0.5 scale,
    reference alphafold2.py:142-150).
  * `axial_attention_apply` — factorized 2D attention over a (b, h, w, d)
    grid: one pass along each axis with the other folded into batch, results
    summed (reference alphafold2.py:240-286). The fold-into-batch axis is the
    natural sharding axis for sequence parallelism (see parallel/).

Everything is expressed as einsums over static shapes so XLA can tile the
contractions onto the MXU; softmax runs in float32 regardless of the compute
dtype.

Deliberate divergences from the reference (documented, not accidental):
  * KV compression always applies when compress_ratio > 1. The reference
    skips it entirely when the key length is an exact multiple of the ratio
    (`padding < ratio` guard, reference alphafold2.py:122) — a bug we do not
    reproduce.
  * Tied-row attention accepts a mask: columns masked in *any* row are
    masked for the shared logits (the reference hard-errors on any padding,
    reference alphafold2.py:147).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

from alphafold2_tpu.ops.core import _uniform, linear, linear_init, dropout
from alphafold2_tpu.ops.flash import flash_attention

# switch to the blockwise path when the full logit tensor (B*h*i*j) would
# exceed this many elements (2^27 f32 = 512 MB)
_FLASH_AUTO_THRESHOLD = 1 << 27


@dataclasses.dataclass(frozen=True)
class AttentionConfig:
    """Static attention hyper-parameters (hashable; safe as a jit static arg)."""

    dim: int
    heads: int = 8
    dim_head: int = 64
    dropout: float = 0.0
    compress_ratio: int = 1  # KV compression for cross-attention, 1 = off
    dtype: Any = jnp.float32  # compute dtype (use bfloat16 on TPU)
    # blockwise (flash-style) streaming instead of materializing the full
    # logit tensor: True / False / "auto" (stream only when the logits would
    # exceed _FLASH_AUTO_THRESHOLD elements). Streaming is exact but skips
    # attention-probability dropout, so it is bypassed while attn dropout is
    # active. Not used for tied-row attention (its logits are already
    # row-contracted and small).
    flash: Union[bool, str] = "auto"
    # XLA streaming-path tile knobs (ignored by the Pallas kernel): target
    # logit-tile elements and K/V streaming block. Bigger tiles = better
    # MXU utilization, more live memory — tune per chip generation
    flash_tile_elems: int = 1 << 25
    flash_kv_block: int = 2048
    # Pallas-kernel QUERY block-size target (None = auto). The actual
    # block is pick_block(i, target=this) per attention shape, so short
    # axes are never padded up: at target 1152, a 1152-long axis gets
    # whole-row blocks (grid collapsed 3x vs the default 512 cap) while
    # 384/128-long axes keep their unpadded blocks. Key blocks stay auto
    # (a (1152, 384) f32 logit tile fits VMEM headroom; qb=kb=1152 would
    # not). Surfaced up to Alphafold2Config for the e2e sweep.
    flash_qb_target: Optional[int] = None
    # materialize the XLA streaming path's score/probability tiles in the
    # COMPUTE dtype instead of f32 (ops/flash.py stream_block): those
    # tiles dominate the path's HBM traffic, and the AV dot consumes p in
    # the compute dtype anyway — bf16 halves the dominant traffic at
    # ~0.5% probability error (running max/sum stats stay f32). Off by
    # default pending the on-chip A/B (sweep leg e2e_logit_bf16).
    flash_compute_dtype_logits: bool = False
    # process the (folded) batch axis in chunks of this many elements under
    # jax.checkpoint (0 = off). Flash tiling bounds the LOGITS, but the
    # QKV/output projections still materialize over the whole folded batch —
    # at crop 384 the pair stream is 1.3M tokens, whose (tokens, 512)
    # projections are 1.3 GB each, and the reversible backward holds several
    # at once. Chunking the whole op (proj -> attend -> out-proj per chunk)
    # bounds all of them. Skipped for tied-row attention (chunks would split
    # tie groups) and while attention dropout is active (per-chunk keys
    # would change the mask pattern).
    batch_chunk: int = 0
    # sigmoid output gating (the AF2-style gate): out = sigmoid(W_g x + b_g)
    # * attention(x) before the output projection, gate weights initialized
    # (w=0, b=1) so a fresh gate starts nearly open. On the TPU kernel path
    # the gate is fused into the Pallas flash kernel's finish step
    # (ops/flash_kernel.py); elsewhere it is an exact epilogue.
    gate: bool = False

    @property
    def inner_dim(self) -> int:
        return self.heads * self.dim_head


# --- init -------------------------------------------------------------------


def attention_init(key, cfg: AttentionConfig):
    inner = cfg.inner_dim
    kq, kkv, ko, kc = jax.random.split(key, 4)
    params = {
        "to_q": linear_init(kq, cfg.dim, inner, bias=False),
        "to_kv": linear_init(kkv, cfg.dim, 2 * inner, bias=False),
        "to_out": linear_init(ko, inner, cfg.dim),
    }
    if cfg.gate:
        # near-open init (w=0, b=1 -> sigmoid(1) ~ 0.73): a freshly gated
        # model starts close to its ungated twin, so enabling the gate is
        # a benign fine-tune, not a re-initialization
        params["to_gate"] = {
            "w": jnp.zeros((cfg.dim, inner)),
            "b": jnp.ones((inner,)),
        }
    if cfg.compress_ratio > 1:
        # grouped strided conv over the key/value sequence, one group per head
        # (torch Conv1d(inner, inner, ratio, stride=ratio, groups=heads),
        # reference alphafold2.py:101). Kernel layout WIO for lax.conv.
        in_per_group = inner // cfg.heads
        bound = 1.0 / math.sqrt(in_per_group * cfg.compress_ratio)
        kw, kb = jax.random.split(kc)
        params["compress"] = {
            "w": _uniform(kw, (cfg.compress_ratio, in_per_group, inner), bound),
            "b": _uniform(kb, (inner,), bound),
        }
    return params


def axial_attention_init(key, cfg: AttentionConfig):
    k1, k2 = jax.random.split(key)
    return {
        "attn_width": attention_init(k1, cfg),
        "attn_height": attention_init(k2, cfg),
    }


# --- apply ------------------------------------------------------------------


def _compress_conv(params, cfg: AttentionConfig, t):
    """The grouped strided conv the compression paths share: stride-`ratio`
    windows, one feature group per head (torch Conv1d(inner, inner, ratio,
    stride=ratio, groups=heads), reference alphafold2.py:101). Also used by
    the sequence-parallel halo-exchange compression
    (parallel/sp_trunk.py `_compress_kv_sharded`) — the two paths must
    convolve identically or SP parity breaks."""
    w = params["compress"]["w"].astype(t.dtype)
    b = params["compress"]["b"].astype(t.dtype)
    out = jax.lax.conv_general_dilated(
        t,
        w,
        window_strides=(cfg.compress_ratio,),
        padding="VALID",
        dimension_numbers=("NWC", "WIO", "NWC"),
        feature_group_count=cfg.heads,
    )
    return out + b


def _compress_kv(params, cfg: AttentionConfig, k, v, context_mask):
    """Downsample keys/values along the sequence with a grouped strided conv.

    k, v: (b, j, inner). Pads j up to a multiple of the ratio, then applies a
    stride-`ratio` conv with one feature group per head. The key mask is
    sum-pooled: a compressed position is valid if any source position was
    (reference alphafold2.py:116-136).
    """
    ratio = cfg.compress_ratio
    j = k.shape[-2]
    pad = (-j) % ratio
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0)))
        if context_mask is not None:
            context_mask = jnp.pad(context_mask, ((0, 0), (0, pad)))

    k = _compress_conv(params, cfg, k)
    v = _compress_conv(params, cfg, v)
    if context_mask is not None:
        pooled = jnp.sum(
            context_mask.astype(jnp.float32).reshape(context_mask.shape[0], -1, ratio),
            axis=-1,
        )
        context_mask = pooled > 0
    return k, v, context_mask


def attention_apply(
    params,
    cfg: AttentionConfig,
    x,
    *,
    context=None,
    mask=None,
    context_mask=None,
    tie_dim: Optional[int] = None,
    rng=None,
):
    """Multi-head attention.

    Args:
      x: queries, (b, i, dim).
      context: keys/values source, (b, j, dim); self-attention when None.
      mask: (b, i) bool query validity.
      context_mask: (b, j) bool key validity (defaults to `mask` for
        self-attention, all-valid for cross-attention —
        reference alphafold2.py:156-158).
      tie_dim: if given, x is (b*tie_dim, i, dim) and attention logits are
        shared across the tie_dim groups (MSA tied-row attention).
      rng: dropout key (None = deterministic).

    Returns: (b, i, dim) in cfg.dtype.
    """
    has_context = context is not None
    dropout_live = rng is not None and cfg.dropout > 0.0
    if (
        cfg.batch_chunk
        and x.shape[0] > cfg.batch_chunk
        and tie_dim is None
        and not dropout_live
    ):
        return _batch_chunked_attention(
            params, cfg, x, context=context, mask=mask, context_mask=context_mask
        )
    ctx = context if has_context else x
    dtype = cfg.dtype

    q = linear(params["to_q"], x, dtype=dtype)
    kv = linear(params["to_kv"], ctx, dtype=dtype)
    k, v = jnp.split(kv, 2, axis=-1)

    if cfg.compress_ratio > 1 and has_context:
        k, v, context_mask = _compress_kv(params, cfg, k, v, context_mask)

    h, dh = cfg.heads, cfg.dim_head
    scale = dh ** -0.5

    def split_heads(t):
        b, n, _ = t.shape
        return t.reshape(b, n, h, dh)

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    i, j = q.shape[1], k.shape[1]
    # pre-sigmoid output-gate logits from the QUERY stream (cfg.gate):
    # fused into the Pallas kernel on the flash path, exact epilogue on
    # the dense/tied paths — both multiply sigmoid(gate) into the head
    # outputs before to_out
    gate_logits = (
        linear(params["to_gate"], x, dtype=dtype) if cfg.gate else None
    )

    # blockwise streaming path: same math, bounded memory (see ops/flash.py).
    # Key-side masking only — masked query rows yield finite garbage masked
    # downstream, exactly like the dense path's uniform-attention rows.
    use_flash = cfg.flash is True or (
        cfg.flash == "auto" and q.shape[0] * h * i * j > _FLASH_AUTO_THRESHOLD
    )
    if use_flash and tie_dim is None and not dropout_live:
        if context_mask is None and mask is not None and not has_context:
            context_mask = mask
        key_bias = (
            None
            if context_mask is None
            else jnp.where(
                jnp.broadcast_to(context_mask, (k.shape[0], j)),
                0.0,
                float("-inf"),
            ).astype(jnp.float32)
        )
        # Pallas fused kernel on TPU (supported shapes), XLA streaming
        # otherwise (ops/flash.py dispatch)
        if cfg.flash_qb_target is None:
            qb = None
        else:
            from alphafold2_tpu.ops.flash_kernel import pick_block

            qb = pick_block(i, target=cfg.flash_qb_target)
        out = flash_attention(
            q, k, v, key_bias, scale=scale,
            gate=(
                gate_logits.reshape(gate_logits.shape[0], i, h, dh)
                if gate_logits is not None else None
            ),
            tile_elems=cfg.flash_tile_elems, kv_block=cfg.flash_kv_block,
            kernel_qb=qb,
            logit_dtype=dtype if cfg.flash_compute_dtype_logits else None,
        )
        out = out.reshape(out.shape[0], i, h * dh)
        return linear(params["to_out"], out, dtype=dtype)

    if tie_dim is not None:
        # (b*r, n, h, dh) -> (b, r, n, h, dh); share logits across rows r with
        # the extra r^-0.5 scale (reference alphafold2.py:142-150).
        r = tie_dim
        q, k, v = (t.reshape(-1, r, t.shape[1], h, dh) for t in (q, k, v))
        logits = jnp.einsum("brihd,brjhd->bhij", q, k) * (scale * r ** -0.5)
        # collapse per-row masks to the tied batch: a position is valid only
        # if valid in every row (generalizes the reference's all-valid
        # requirement, reference alphafold2.py:147).
        if mask is not None:
            mask = jnp.all(mask.reshape(-1, r, mask.shape[-1]), axis=1)
        if context_mask is not None and context_mask.shape[0] == r * logits.shape[0]:
            context_mask = jnp.all(
                context_mask.reshape(-1, r, context_mask.shape[-1]), axis=1
            )
    else:
        logits = jnp.einsum("bihd,bjhd->bhij", q, k) * scale

    if mask is not None or context_mask is not None:
        if mask is None:
            mask = jnp.ones((1, i), dtype=bool)
        if context_mask is None:
            context_mask = mask if not has_context else jnp.ones((1, j), dtype=bool)
        pair_mask = mask[:, None, :, None] & context_mask[:, None, None, :]
        logits = jnp.where(pair_mask, logits, jnp.finfo(jnp.float32).min)

    attn = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(dtype)
    attn = dropout(rng, attn, cfg.dropout)

    if tie_dim is not None:
        out = jnp.einsum("bhij,brjhd->brihd", attn, v)
        out = out.reshape(-1, i, h * dh)
    else:
        out = jnp.einsum("bhij,bjhd->bihd", attn, v)
        out = out.reshape(out.shape[0], i, h * dh)

    if gate_logits is not None:
        from alphafold2_tpu.ops.flash import apply_output_gate

        out = apply_output_gate(out, gate_logits)
    return linear(params["to_out"], out, dtype=dtype)


def _batch_chunked_attention(params, cfg: AttentionConfig, x, *, context, mask, context_mask):
    """Run attention_apply in chunks over the (folded) batch axis.

    Each chunk re-runs the full op (QKV projection, attention, output
    projection) under jax.checkpoint, so no projection ever materializes
    over the whole folded batch — the memory bound that lets the crop-384
    pair stream (1.3M tokens) run on one chip. Deterministic (no-dropout)
    path only; the caller gates on that.
    """
    B = x.shape[0]
    chunk = cfg.batch_chunk
    inner_cfg = dataclasses.replace(cfg, batch_chunk=0)

    pad = (-B) % chunk
    arrays = {"x": x, "context": context, "mask": mask, "context_mask": context_mask}
    padded = {}
    for name, t in arrays.items():
        if t is None:
            padded[name] = None
            continue
        if t.shape[0] == 1 and B > 1:  # broadcast batch: share across chunks
            padded[name] = t
            continue
        if pad:
            t = jnp.pad(t, ((0, pad),) + ((0, 0),) * (t.ndim - 1))
        padded[name] = t.reshape((-1, chunk) + t.shape[1:])

    def body(i):
        def pick(name):
            t = padded[name]
            if t is None or t.shape[0] != (B + pad) // chunk:
                return t  # None or broadcast
            return t[i]

        return attention_apply(
            params,
            inner_cfg,
            pick("x"),
            context=pick("context"),
            mask=pick("mask"),
            context_mask=pick("context_mask"),
        )

    nb = (B + pad) // chunk
    out = jax.lax.map(jax.checkpoint(body), jnp.arange(nb))
    out = out.reshape((nb * chunk,) + out.shape[2:])
    return out[:B] if pad else out


def axial_attention_apply(
    params,
    cfg: AttentionConfig,
    x,
    *,
    mask=None,
    context=None,
    context_mask=None,
    tie_row: bool = False,
    rng=None,
    attention_fn=None,
):
    """Factorized 2D attention over a grid.

    Args:
      x: (b, h, w, d) grid — the pair representation (i, j) or MSA
        (rows, cols).
      mask: (b, h, w) bool.
      context / context_mask: optional cross-attention source (b, n, d) /
        (b, n), broadcast to every folded row/column
        (reference alphafold2.py:269-273).
      tie_row: tie attention across the h axis on the width pass (MSA
        tied-row attention; reference alphafold2.py:280-282).
      attention_fn: override the inner attention (e.g. block-sparse); called
        as `attention_fn(axis_params, x, *, axis, mask, tie_dim, rng,
        [context, context_mask])` where `axis` is "width" (column pass) or
        "height" (row pass) and `axis_params` is that pass's parameter
        subtree.

    Two passes, summed:
      * column pass — attend along h, w folded into batch;
      * row pass — attend along w, h folded into batch (tied over h if
        tie_row).
    """
    inner = attention_fn
    b, hh, ww, d = x.shape

    rng_col, rng_row = (jax.random.split(rng) if rng is not None else (None, None))

    def run(p, t, m, cm_ctx, tie_dim, r, axis):
        if inner is not None:
            return inner(p, t, axis=axis, mask=m, tie_dim=tie_dim, rng=r, **cm_ctx)
        return attention_apply(p, cfg, t, mask=m, tie_dim=tie_dim, rng=r, **cm_ctx)

    # column pass: fold w into batch, attend along h
    col_x = jnp.swapaxes(x, 1, 2).reshape(b * ww, hh, d)
    col_mask = (
        jnp.swapaxes(mask, 1, 2).reshape(b * ww, hh) if mask is not None else None
    )
    ctx_kwargs_col = {}
    if context is not None:
        ctx_kwargs_col = {
            "context": jnp.repeat(context, ww, axis=0),
            "context_mask": (
                jnp.repeat(context_mask, ww, axis=0) if context_mask is not None else None
            ),
        }
    col_out = run(
        params["attn_width"], col_x, col_mask, ctx_kwargs_col, None, rng_col, "width"
    )
    col_out = jnp.swapaxes(col_out.reshape(b, ww, hh, d), 1, 2)

    # row pass: fold h into batch, attend along w (optionally tied across h)
    row_x = x.reshape(b * hh, ww, d)
    row_mask = mask.reshape(b * hh, ww) if mask is not None else None
    ctx_kwargs_row = {}
    if context is not None:
        ctx_kwargs_row = {
            "context": jnp.repeat(context, hh, axis=0),
            "context_mask": (
                jnp.repeat(context_mask, hh, axis=0) if context_mask is not None else None
            ),
        }
    tie_dim = hh if tie_row else None
    row_out = run(
        params["attn_height"], row_x, row_mask, ctx_kwargs_row, tie_dim, rng_row, "height"
    )
    row_out = row_out.reshape(b, hh, ww, d)

    return col_out + row_out
