"""Pallas TPU kernel for DENSE flash attention (forward + backward).

The fused fast path under ops/flash.py's blockwise streaming: QK^T ->
streaming softmax -> AV runs entirely in VMEM per (query-block, key-block)
tile, so logits never round-trip HBM between accumulation steps — the HBM
traffic the XLA-level `stream_block` scan pays. Sibling of the block-sparse
kernel (ops/sparse_kernel.py), without the index table, and supporting
CROSS attention (query and key lengths differ) — the shape the aligned
cross-attention mode produces (models/trunk.py).

Layout and numerics follow ops/sparse_kernel.py: (b*h, n, dh) flattened
heads, float32 streaming statistics with -inf masking (fully-masked rows
return zeros; +inf lse makes the backward's recomputed p vanish for them),
key-side additive bias only (ops/flash.py contract). Backward recomputes
tile logits from the saved lse: a dq kernel loops key blocks per query
block; a dk/dv kernel loops query blocks per key block.

Keys/values are VMEM-resident per (batch*head) row, which bounds the
supported key length (see `supported`); longer contexts fall back to the
XLA streaming path in ops/flash.py. On non-TPU backends the kernels run in
interpreter mode (tests), keeping one code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from alphafold2_tpu.ops.core import pallas_interpret as _interpret

_NEG = float("-inf")
# finite running-max sentinel: keeps the streaming-softmax recurrence free
# of (-inf) - (-inf) = nan without per-tile isneginf/where passes. Logits
# below this are treated as fully masked (the standard flash-kernel trade).
_M0 = -1e30
# K/V-block loops with a static trip count at or below this unroll into
# straight-line code (Mosaic software-pipelines across blocks); longer
# loops fall back to fori_loop to bound code size
_UNROLL_MAX = 8


def _block_loop(n, body, init):
    """fori_loop over blocks, unrolled to straight-line code when short."""
    if n <= _UNROLL_MAX:
        carry = init
        for a in range(n):
            carry = body(a, carry)
        return carry
    return jax.lax.fori_loop(0, n, body, init)

# VMEM budget for the resident operands of the worst kernel: the dk/dv
# backward keeps the FULL Q and G f32 copies per grid row, the forward/dq
# kernels the full K and V — so both i and j bound residency jointly.
# ~12 MB leaves headroom under the ~16 MB/core VMEM for tiles and spills.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def supported(i: int, j: int, dh: int) -> bool:
    """Shapes the kernel handles; everything else streams via XLA.

    Joint (i + j) * dh byte bound: each kernel keeps two full f32 copies of
    either the query-side (Q, G in dk/dv) or key-side (K, V in fwd/dq)
    arrays VMEM-resident per (batch*head) grid row.
    """
    resident = 2 * 4 * dh * (i + j)
    return resident <= _VMEM_BUDGET_BYTES and dh % 8 == 0 and dh <= 512


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, out_ref, lse_ref,
                *, kb, dh, nkb, scale):
    qb_idx = pl.program_id(1)
    # dots take operands in the INPUT dtype with f32 accumulation
    # (preferred_element_type): bf16 operands keep the MXU at its bf16 peak
    # (~4x the f32-operand rate on v5e) while statistics stay f32
    q = q_ref[0]  # (qb, dh)

    def body(a, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(a * kb, kb), :]  # (kb, dh)
        v = v_ref[0, pl.ds(a * kb, kb), :]
        b = bias_ref[0, a]  # (kb,)
        s = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale + b[None, :]
        # the running max starts at a FINITE sentinel (_M0), so m - m_new is
        # never (-inf) - (-inf): masked logits (s = -inf from the bias)
        # reach exp as -inf and underflow to an exact 0 with no nan guard
        # passes over the (qb, kb) tile
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    qb = q.shape[0]
    m0 = jnp.full((qb, 1), _M0, jnp.float32)
    l0 = jnp.zeros((qb, 1), jnp.float32)
    acc0 = jnp.zeros((qb, dh), jnp.float32)
    m, l, acc = _block_loop(nkb, body, (m0, l0, acc0))

    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    out_ref[0] = out.astype(out_ref.dtype)
    # +inf for rows with no active mass: exp(s - inf) = 0 zeroes every
    # recomputed p in the backward (lse travels as (1, nQB, qb) blocks —
    # Mosaic rejects (1, qb) row blocks over 2-D arrays)
    lse = jnp.where(l > 0, m + jnp.log(jnp.where(l > 0, l, 1.0)), jnp.inf)
    lse_ref[0, qb_idx] = lse[:, 0]


def _pad_args(q, k, v, bias, qb, kb):
    """Pad query/key lengths to block multiples (-inf bias on padded keys)."""
    BH, i, dh = q.shape
    j = k.shape[1]
    pad_i = (-i) % qb
    pad_j = (-j) % kb
    if pad_i:
        q = jnp.pad(q, ((0, 0), (0, pad_i), (0, 0)))
    if pad_j:
        k = jnp.pad(k, ((0, 0), (0, pad_j), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_j), (0, 0)))
        bias = jnp.pad(bias, ((0, 0), (0, pad_j)), constant_values=_NEG)
    return q, k, v, bias, i + pad_i, j + pad_j


def _forward(q, k, v, bias, scale, qb, kb):
    """q: (BH, i, dh); k, v: (BH, j, dh); bias: (BHB, j) where BHB is BH or
    a broadcastable batch dim handled by the caller (here: exactly BH)."""
    BH, i0, dh = q.shape
    j0 = k.shape[1]
    q, k, v, bias, i, j = _pad_args(q, k, v, bias, qb, kb)
    nqb, nkb = i // qb, j // kb
    bias3 = bias.reshape(BH, nkb, kb)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, kb=kb, dh=dh, nkb=nkb, scale=scale),
        out_shape=[
            jax.ShapeDtypeStruct((BH, i, dh), q.dtype),
            jax.ShapeDtypeStruct((BH, nqb, qb), jnp.float32),
        ],
        grid=(BH, nqb),
        in_specs=[
            pl.BlockSpec((1, qb, dh), lambda b, qi: (b, qi, 0)),
            pl.BlockSpec((1, j, dh), lambda b, qi: (b, 0, 0)),
            pl.BlockSpec((1, j, dh), lambda b, qi: (b, 0, 0)),
            pl.BlockSpec((1, nkb, kb), lambda b, qi: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, qb, dh), lambda b, qi: (b, qi, 0)),
            pl.BlockSpec((1, nqb, qb), lambda b, qi: (b, 0, 0)),
        ],
        interpret=_interpret(),
    )(q, k, v, bias3)
    return out[:, :i0], (q, k, v, bias3, lse, i0, j0)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref, delta_ref,
               dq_ref, *, kb, dh, nkb, scale):
    qb_idx = pl.program_id(1)
    q = q_ref[0]
    g = g_ref[0]
    lse = lse_ref[0, qb_idx][:, None]
    delta = delta_ref[0, qb_idx][:, None]

    def body(a, dq):
        k = k_ref[0, pl.ds(a * kb, kb), :]
        v = v_ref[0, pl.ds(a * kb, kb), :]
        b = bias_ref[0, a]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale + b[None, :]
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            g, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # ds in the operand dtype: bf16 ds @ k on the MXU bf16 path — the
        # standard flash-backward precision trade (f32 accumulate)
        ds = (p * (dp - delta)).astype(k.dtype)
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    qb = q.shape[0]
    dq = _block_loop(nkb, body, jnp.zeros((qb, dh), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, qb, dh, nqb, scale):
    kb_idx = pl.program_id(1)
    k = k_ref[0]  # (kb, dh)
    v = v_ref[0]
    b = bias_ref[0, kb_idx]            # (kb,)

    def body(a, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(a * qb, qb), :]
        g = g_ref[0, pl.ds(a * qb, qb), :]
        lse = lse_ref[0, a][:, None]
        delta = delta_ref[0, a][:, None]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale + b[None, :]
        p = jnp.exp(s - lse)           # (qb, kb) f32
        dv = dv + jax.lax.dot_general(
            p.astype(g.dtype), g, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            g, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta)).astype(q.dtype)
        dk = dk + jax.lax.dot_general(
            ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    kbs = k.shape[0]
    zero = jnp.zeros((kbs, dh), jnp.float32)
    dk, dv = _block_loop(nqb, body, (zero, zero))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def pick_block(n: int, target: int = 512, mult: int = 128, tol: float = 0.15) -> int:
    """Pick a Pallas block size for a length-n axis.

    Among multiples of `mult` (MXU-friendly) up to `target`, take the
    LARGEST block whose padded length is within `tol` of the minimum
    achievable — large blocks amortize grid/loop overhead, but gross
    padding waste is real FLOPs: n=1152 picks 384 (zero padding) where a
    fixed 512 pads to 1536 (+33%), while n=896 keeps 512 (+14% padding
    beats 7x the grid steps of 128). The tol knob is a heuristic pending
    on-chip measurement (PERF.md)."""
    if n <= mult:
        return mult
    padded = {b: ((n + b - 1) // b) * b for b in range(mult, target + 1, mult)}
    best = min(padded.values())
    return max(b for b, p in padded.items() if p <= best * (1 + tol))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_core(q, k, v, key_bias, scale, qb, kb):
    out, _ = _forward(q, k, v, key_bias, scale, qb, kb)
    return out


def _block_target(dh: int) -> int:
    """Cap block size so per-grid-step tiles fit the VMEM headroom left by
    `supported`'s 12 MB resident budget (~4 MB): the worst kernel holds ~6
    f32 tiles of (block, dh) plus a (qb, kb) logit tile per step. dh=64
    (the framework's head dim) keeps the full 512; dh=512 drops to 256."""
    return max(128, min(512, (4 << 20) // (24 * dh) // 128 * 128))


def flash_attention_tpu(q, k, v, key_bias, scale, qb=None, kb=None):
    """Fused dense flash attention. q: (BH, i, dh); k, v: (BH, j, dh);
    key_bias: (BH, j) additive f32 (0 valid / -inf masked). Returns
    (BH, i, dh). The bias cotangent is not computed (masks are data, not
    parameters). qb/kb: query/key block sizes (None = padding-aware pick)."""
    dh = q.shape[-1]
    qb = pick_block(q.shape[1], target=_block_target(dh)) if qb is None else qb
    kb = pick_block(k.shape[1], target=_block_target(dh)) if kb is None else kb
    return _flash_core(q, k, v, key_bias, scale, qb, kb)


def _fwd(q, k, v, key_bias, scale, qb, kb):
    out, (qp, kp, vp, bias3, lse, i0, j0) = _forward(q, k, v, key_bias, scale, qb, kb)
    return out, (qp, kp, vp, bias3, lse, out, i0, j0)


def _bwd(scale, qb, kb, res, g):
    qp, kp, vp, bias3, lse, out, i0, j0 = res
    BH, i, dh = qp.shape
    j = kp.shape[1]
    nqb, nkb = i // qb, j // kb

    pad_i = i - i0
    if pad_i:
        g = jnp.pad(g, ((0, 0), (0, pad_i), (0, 0)))
        out = jnp.pad(out, ((0, 0), (0, pad_i), (0, 0)))

    # delta_i = rowsum(dO_i * O_i), the softmax-jacobian diagonal term
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).reshape(BH, nqb, qb)

    blk_q = pl.BlockSpec((1, qb, dh), lambda b, qi: (b, qi, 0))
    blk_k = pl.BlockSpec((1, kb, dh), lambda b, ki: (b, ki, 0))
    full_q = pl.BlockSpec((1, i, dh), lambda b, x: (b, 0, 0))
    full_k = pl.BlockSpec((1, j, dh), lambda b, x: (b, 0, 0))
    rows_q = pl.BlockSpec((1, nqb, qb), lambda b, x: (b, 0, 0))
    rows_k = pl.BlockSpec((1, nkb, kb), lambda b, x: (b, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, kb=kb, dh=dh, nkb=nkb, scale=scale),
        out_shape=jax.ShapeDtypeStruct((BH, i, dh), qp.dtype),
        grid=(BH, nqb),
        in_specs=[blk_q, full_k, full_k, rows_k, blk_q, rows_q, rows_q],
        out_specs=blk_q,
        interpret=_interpret(),
    )(qp, kp, vp, bias3, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, qb=qb, dh=dh, nqb=nqb, scale=scale),
        out_shape=[
            jax.ShapeDtypeStruct((BH, j, dh), kp.dtype),
            jax.ShapeDtypeStruct((BH, j, dh), vp.dtype),
        ],
        grid=(BH, nkb),
        in_specs=[full_q, blk_k, blk_k, rows_k, full_q, rows_q, rows_q],
        out_specs=[blk_k, blk_k],
        interpret=_interpret(),
    )(qp, kp, vp, bias3, g, lse, delta)

    # cotangents must match the ORIGINAL (unpadded) primal shapes; the bias
    # is a mask, not a parameter — its cotangent is declared zero
    return (
        dq[:, :i0],
        dk[:, :j0],
        dv[:, :j0],
        jnp.zeros((qp.shape[0], j0), jnp.float32),
    )


_flash_core.defvjp(_fwd, _bwd)
