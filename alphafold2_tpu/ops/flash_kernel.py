"""Pallas TPU kernel for DENSE flash attention (forward + backward).

The fused fast path under ops/flash.py's blockwise streaming: QK^T ->
streaming softmax -> AV runs entirely in VMEM per (query-block, key-block)
tile, so logits never round-trip HBM — the traffic the XLA-level
`stream_block` scan pays between accumulation steps. Sibling of the
block-sparse kernel (ops/sparse_kernel.py), without the index table, and
supporting CROSS attention (query and key lengths differ) — the shape the
aligned cross-attention mode produces (models/trunk.py).

Streaming layout: each kernel runs a 3-D grid whose LAST dimension walks
the contraction blocks sequentially (dimension_semantics "arbitrary") with
running statistics in VMEM scratch, while Mosaic's pipeline double-buffers
the K/V (or Q/G) block fetches. Nothing is ever fully VMEM-resident per
grid row — unlike the previous design (whole K/V held per (batch*head)
row), the supported length is bounded only by the f32 row vectors (bias,
lse, delta) at 4 bytes per position, so the kernel also covers the long-j
flat cross-attention shapes that previously fell back to XLA streaming.

Layout and numerics follow ops/sparse_kernel.py: (b*h, n, dh) flattened
heads, float32 streaming statistics, finite running-max sentinel (_M0) so
masked logits (-inf bias) underflow to exact 0 with no nan-guard passes,
key-side additive bias only (ops/flash.py contract; fully-masked rows
return zeros, +inf lse makes the backward's recomputed p vanish). Dots
take operands in the INPUT dtype with f32 accumulation
(preferred_element_type): bf16 operands keep the MXU at its bf16 peak.
Backward recomputes tile logits from the saved lse: a dq kernel streams
key blocks per query block; a dk/dv kernel streams query blocks per key
block. On non-TPU backends the kernels run in interpreter mode (tests),
keeping one code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from alphafold2_tpu import compat
from alphafold2_tpu.compat import pallas as pl, pallas_tpu as pltpu
from alphafold2_tpu.ops.core import pallas_interpret as _interpret

_NEG = float("-inf")
# finite running-max sentinel: keeps the streaming-softmax recurrence free
# of (-inf) - (-inf) = nan without per-tile isneginf/where passes. Logits
# below this are treated as fully masked (the standard flash-kernel trade).
_M0 = -1e30

# VMEM budget for the per-grid-row RESIDENT operands: the f32 row vectors
# only (key bias at 4 B/key; lse + delta at 8 B/query in the backward).
# Blocks stream; ~12 MB leaves headroom under the ~16 MB/core VMEM for the
# double-buffered tiles and scratch.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def supported(i: int, j: int, dh: int) -> bool:
    """Shapes the kernel handles; everything else streams via XLA.

    Only the f32 row vectors are VMEM-resident per (batch*head) grid row
    (bias: 4j bytes; lse + delta: 8i bytes in the backward) — K/V and Q/G
    blocks stream through the grid's sequential dimension.
    """
    resident = 4 * j + 8 * i
    return resident <= _VMEM_BUDGET_BYTES and dh % 8 == 0 and dh <= 512


def pick_block(n: int, target: int = 512, mult: int = 128, tol: float = 0.15) -> int:
    """Pick a Pallas block size for a length-n axis.

    Among multiples of `mult` (MXU-friendly) up to `target`, take the
    LARGEST block whose padded length is within `tol` of the minimum
    achievable — large blocks amortize grid/loop overhead, but gross
    padding waste is real FLOPs: n=1152 picks 384 (zero padding) where a
    fixed 512 pads to 1536 (+33%), while n=896 keeps 512 (+14% padding
    beats 7x the grid steps of 128). The tol knob is a heuristic pending
    on-chip measurement (PERF.md)."""
    if n <= mult:
        return mult
    padded = {b: ((n + b - 1) // b) * b for b in range(mult, target + 1, mult)}
    best = min(padded.values())
    return max(b for b, p in padded.items() if p <= best * (1 + tol))


def _block_target(dh: int) -> int:
    """Cap block size so per-grid-step tiles fit VMEM: the worst kernel
    step holds ~6 f32 tiles of (block, dh) plus a (qb, kb) logit tile,
    double-buffered. dh=64 (the framework's head dim) keeps the full 512;
    dh=512 drops to 256."""
    return max(128, min(512, (4 << 20) // (24 * dh) // 128 * 128))


# vma-aware ShapeDtypeStruct (union of the operands' varying-across-mesh-
# axes sets) — required for pallas_call under shard_map with vma checking
# (e.g. the ring-attention hops); plain struct on pre-vma JAX.
_out_struct = compat.out_struct


def _pad_args(q, k, v, bias, qb, kb):
    """Pad query/key lengths to block multiples (-inf bias on padded keys)."""
    BH, i, dh = q.shape
    j = k.shape[1]
    pad_i = (-i) % qb
    pad_j = (-j) % kb
    if pad_i:
        q = jnp.pad(q, ((0, 0), (0, pad_i), (0, 0)))
    if pad_j:
        k = jnp.pad(k, ((0, 0), (0, pad_j), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_j), (0, 0)))
        bias = jnp.pad(bias, ((0, 0), (0, pad_j)), constant_values=_NEG)
    return q, k, v, bias, i + pad_i, j + pad_j


# Backward kernels: first two grid dims parallel (their output windows are
# private per (b, block) pair), streamed contraction dim sequential.
_BWD_PARAMS = compat.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary")
)
# Forward: the lse output window (1, nqb, qb) is SHARED across qi, so qi
# must not be split across megacore TPU cores (each core's private copy of
# the whole window would clobber the other's rows on write-back) — qi runs
# sequentially; the (batch*head) dim carries all the parallelism.
_FWD_PARAMS = compat.CompilerParams(
    dimension_semantics=("parallel", "arbitrary", "arbitrary")
)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, out_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, nkb, scale):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _M0, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    q = q_ref[0]          # (qb, dh), input dtype
    k = k_ref[0]          # (kb, dh)
    v = v_ref[0]
    b = bias_ref[0, ki]   # (kb,) f32, resident row vector
    s = jax.lax.dot_general(
        q, k,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale + b[None, :]

    m = m_scr[...]
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
        p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    m_scr[...] = m_new

    @pl.when(ki == nkb - 1)
    def _finish():
        l = l_scr[...]
        safe = jnp.where(l > 0, l, 1.0)
        out_ref[0] = jnp.where(l > 0, acc_scr[...] / safe, 0.0).astype(
            out_ref.dtype
        )
        # +inf for rows with no active mass: exp(s - inf) = 0 zeroes every
        # recomputed p in the backward (lse rides as a resident
        # (1, nQB, qb) block — Mosaic rejects (1, qb) row blocks)
        lse = jnp.where(l > 0, m_scr[...] + jnp.log(safe), jnp.inf)
        lse_ref[0, qi] = lse[:, 0]


def _forward(q, k, v, bias, scale, qb, kb):
    """q: (BH, i, dh); k, v: (BH, j, dh); bias: (BH, j) additive f32."""
    BH, i0, dh = q.shape
    j0 = k.shape[1]
    q, k, v, bias, i, j = _pad_args(q, k, v, bias, qb, kb)
    nqb, nkb = i // qb, j // kb
    bias3 = bias.reshape(BH, nkb, kb)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, nkb=nkb, scale=scale),
        out_shape=[
            _out_struct((BH, i, dh), q.dtype, q, k, v, bias3),
            _out_struct((BH, nqb, qb), jnp.float32, q, k, v, bias3),
        ],
        grid=(BH, nqb, nkb),
        in_specs=[
            pl.BlockSpec((1, qb, dh), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, kb, dh), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, kb, dh), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, nkb, kb), lambda b, qi, ki: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, qb, dh), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, nqb, qb), lambda b, qi, ki: (b, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((qb, 1), jnp.float32),
            pltpu.VMEM((qb, 1), jnp.float32),
            pltpu.VMEM((qb, dh), jnp.float32),
        ],
        compiler_params=_FWD_PARAMS,
        interpret=_interpret(),
    )(q, k, v, bias3)
    return out[:, :i0], (q, k, v, bias3, lse, i0, j0)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref, delta_ref,
               dq_ref, dq_scr, *, nkb, scale):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    q = q_ref[0]
    g = g_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    b = bias_ref[0, ki]
    lse = lse_ref[0, qi][:, None]
    delta = delta_ref[0, qi][:, None]

    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale + b[None, :]
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(
        g, v, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # ds in the operand dtype: bf16 ds @ k on the MXU bf16 path — the
    # standard flash-backward precision trade (f32 accumulate)
    ds = (p * (dp - delta)).astype(k.dtype)
    dq_scr[...] = dq_scr[...] + jnp.dot(
        ds, k, preferred_element_type=jnp.float32
    )

    @pl.when(ki == nkb - 1)
    def _finish():
        dq_ref[0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, nqb, scale):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    k = k_ref[0]                      # (kb, dh)
    v = v_ref[0]
    q = q_ref[0]                      # (qb, dh)
    g = g_ref[0]
    b = bias_ref[0, ki]               # (kb,)
    lse = lse_ref[0, qi][:, None]
    delta = delta_ref[0, qi][:, None]

    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale + b[None, :]
    p = jnp.exp(s - lse)              # (qb, kb) f32
    dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
        p.astype(g.dtype), g, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(
        g, v, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = (p * (dp - delta)).astype(q.dtype)
    dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
        ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(qi == nqb - 1)
    def _finish():
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_core(q, k, v, key_bias, scale, qb, kb):
    out, _ = _forward(q, k, v, key_bias, scale, qb, kb)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_core_lse(q, k, v, key_bias, scale, qb, kb):
    out, (_, _, _, _, lse, i0, _) = _forward(q, k, v, key_bias, scale, qb, kb)
    return out, lse.reshape(lse.shape[0], -1)[:, :i0]


def flash_attention_lse(q, k, v, key_bias, scale, qb=None, kb=None):
    """`flash_attention_tpu` that ALSO returns the per-row log-sum-exp.

    Returns (out (BH, i, dh), lse (BH, i) f32). lse is +inf for rows with
    no unmasked keys (zero attention mass — note the INVERTED convention
    vs the usual -inf-for-empty: +inf makes the backward's recomputed
    p = exp(s - lse) vanish). Differentiable in q/k/v including through
    lse — the lse cotangent folds into the softmax-jacobian diagonal
    (delta_eff = delta - g_lse), so the backward kernels are shared with
    the plain path. This is the building block for cross-chip softmax
    combination (ring attention, parallel/sequence.py).
    """
    dh = q.shape[-1]
    qb = pick_block(q.shape[1], target=_block_target(dh)) if qb is None else qb
    kb = pick_block(k.shape[1], target=_block_target(dh)) if kb is None else kb
    return _flash_core_lse(q, k, v, key_bias, scale, qb, kb)


def flash_attention_tpu(q, k, v, key_bias, scale, qb=None, kb=None):
    """Fused dense flash attention. q: (BH, i, dh); k, v: (BH, j, dh);
    key_bias: (BH, j) additive f32 (0 valid / -inf masked). Returns
    (BH, i, dh). The bias cotangent is not computed (masks are data, not
    parameters). qb/kb: query/key block sizes (None = padding-aware pick)."""
    dh = q.shape[-1]
    qb = pick_block(q.shape[1], target=_block_target(dh)) if qb is None else qb
    kb = pick_block(k.shape[1], target=_block_target(dh)) if kb is None else kb
    return _flash_core(q, k, v, key_bias, scale, qb, kb)


def _fwd(q, k, v, key_bias, scale, qb, kb):
    out, (qp, kp, vp, bias3, lse, i0, j0) = _forward(q, k, v, key_bias, scale, qb, kb)
    return out, (qp, kp, vp, bias3, lse, out, i0, j0)


def _bwd_impl(scale, qb, kb, res, g, g_lse=None):
    qp, kp, vp, bias3, lse, out, i0, j0 = res
    BH, i, dh = qp.shape
    j = kp.shape[1]
    nqb, nkb = i // qb, j // kb

    pad_i = i - i0
    if pad_i:
        g = jnp.pad(g, ((0, 0), (0, pad_i), (0, 0)))
        out = jnp.pad(out, ((0, 0), (0, pad_i), (0, 0)))

    # delta_i = rowsum(dO_i * O_i), the softmax-jacobian diagonal term.
    # An lse cotangent folds in here: d lse_i / d s_ij = p_ij, so
    # ds_ij = p_ij * (dp_ij - (delta_i - glse_i)) — same kernels, shifted
    # diagonal
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    if g_lse is not None:
        glse = g_lse.astype(jnp.float32)
        if pad_i:
            glse = jnp.pad(glse, ((0, 0), (0, pad_i)))
        delta = delta - glse
    delta = delta.reshape(BH, nqb, qb)

    blk_q = pl.BlockSpec((1, qb, dh), lambda b, x, y: (b, x, 0))
    blk_q_inner = pl.BlockSpec((1, qb, dh), lambda b, x, y: (b, y, 0))
    blk_k = pl.BlockSpec((1, kb, dh), lambda b, x, y: (b, x, 0))
    blk_k_inner = pl.BlockSpec((1, kb, dh), lambda b, x, y: (b, y, 0))
    rows_q = pl.BlockSpec((1, nqb, qb), lambda b, x, y: (b, 0, 0))
    rows_k = pl.BlockSpec((1, nkb, kb), lambda b, x, y: (b, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, nkb=nkb, scale=scale),
        out_shape=_out_struct((BH, i, dh), qp.dtype, qp, kp, vp, g),
        grid=(BH, nqb, nkb),
        in_specs=[blk_q, blk_k_inner, blk_k_inner, rows_k, blk_q,
                  rows_q, rows_q],
        out_specs=blk_q,
        scratch_shapes=[pltpu.VMEM((qb, dh), jnp.float32)],
        compiler_params=_BWD_PARAMS,
        interpret=_interpret(),
    )(qp, kp, vp, bias3, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, nqb=nqb, scale=scale),
        out_shape=[
            _out_struct((BH, j, dh), kp.dtype, qp, kp, vp, g),
            _out_struct((BH, j, dh), vp.dtype, qp, kp, vp, g),
        ],
        grid=(BH, nkb, nqb),
        in_specs=[blk_q_inner, blk_k, blk_k, rows_k, blk_q_inner,
                  rows_q, rows_q],
        out_specs=[blk_k, blk_k],
        scratch_shapes=[
            pltpu.VMEM((kb, dh), jnp.float32),
            pltpu.VMEM((kb, dh), jnp.float32),
        ],
        compiler_params=_BWD_PARAMS,
        interpret=_interpret(),
    )(qp, kp, vp, bias3, g, lse, delta)

    # cotangents must match the ORIGINAL (unpadded) primal shapes; the bias
    # is a mask, not a parameter — its cotangent is declared zero
    return (
        dq[:, :i0],
        dk[:, :j0],
        dv[:, :j0],
        jnp.zeros((qp.shape[0], j0), jnp.float32),
    )


def _bwd(scale, qb, kb, res, g):
    return _bwd_impl(scale, qb, kb, res, g)


_flash_core.defvjp(_fwd, _bwd)


def _fwd_lse(q, k, v, key_bias, scale, qb, kb):
    out, (qp, kp, vp, bias3, lse, i0, j0) = _forward(q, k, v, key_bias, scale, qb, kb)
    lse_flat = lse.reshape(lse.shape[0], -1)[:, :i0]
    return (out, lse_flat), (qp, kp, vp, bias3, lse, out, i0, j0)


def _bwd_lse(scale, qb, kb, res, gs):
    g, g_lse = gs
    return _bwd_impl(scale, qb, kb, res, g, g_lse=g_lse)


_flash_core_lse.defvjp(_fwd_lse, _bwd_lse)
