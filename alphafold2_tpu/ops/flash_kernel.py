"""Pallas TPU kernel for DENSE flash attention (forward + backward).

The fused fast path under ops/flash.py's blockwise streaming: QK^T ->
streaming softmax -> AV runs entirely in VMEM per (query-block, key-block)
tile, so logits never round-trip HBM — the traffic the XLA-level
`stream_block` scan pays between accumulation steps. Sibling of the
block-sparse kernel (ops/sparse_kernel.py), without the index table, and
supporting CROSS attention (query and key lengths differ) — the shape the
aligned cross-attention mode produces (models/trunk.py).

Streaming layout: each kernel runs a 3-D grid whose LAST dimension walks
the contraction blocks sequentially (dimension_semantics "arbitrary") with
running statistics in VMEM scratch, while Mosaic's pipeline double-buffers
the K/V (or Q/G) block fetches. Nothing is ever fully VMEM-resident per
grid row — unlike the previous design (whole K/V held per (batch*head)
row), the supported length is bounded only by the f32 row vectors (bias,
lse, delta) at 4 bytes per position, so the kernel also covers the long-j
flat cross-attention shapes that previously fell back to XLA streaming.

Layout and numerics follow ops/sparse_kernel.py: (b*h, n, dh) flattened
heads, float32 streaming statistics, finite running-max sentinel (_M0) so
masked logits (-inf bias) underflow to exact 0 with no nan-guard passes,
key-side additive bias only (ops/flash.py contract; fully-masked rows
return zeros, +inf lse makes the backward's recomputed p vanish). Dots
take operands in the INPUT dtype with f32 accumulation
(preferred_element_type): bf16 operands keep the MXU at its bf16 peak.
Backward recomputes tile logits from the saved lse: a dq kernel streams
key blocks per query block; a dk/dv kernel streams query blocks per key
block. On non-TPU backends the kernels run in interpreter mode (tests),
keeping one code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from alphafold2_tpu import compat
from alphafold2_tpu.compat import pallas as pl, pallas_tpu as pltpu
from alphafold2_tpu.ops.core import pallas_interpret as _interpret

_NEG = float("-inf")
# finite running-max sentinel: keeps the streaming-softmax recurrence free
# of (-inf) - (-inf) = nan without per-tile isneginf/where passes. Logits
# below this are treated as fully masked (the standard flash-kernel trade).
_M0 = -1e30

# VMEM budget for the per-grid-row RESIDENT operands: the f32 row vectors
# only (key bias at 4 B/key; lse + delta at 8 B/query in the backward).
# Blocks stream; ~12 MB leaves headroom under the ~16 MB/core VMEM for the
# double-buffered tiles and scratch.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def supported(i: int, j: int, dh: int) -> bool:
    """Shapes the kernel handles; everything else streams via XLA.

    Only the f32 row vectors are VMEM-resident per (batch*head) grid row
    (bias: 4j bytes; lse + delta: 8i bytes in the backward) — K/V and Q/G
    blocks stream through the grid's sequential dimension.
    """
    resident = 4 * j + 8 * i
    return resident <= _VMEM_BUDGET_BYTES and dh % 8 == 0 and dh <= 512


def supported_fused(i: int, j: int, dh: int) -> bool:
    """Shapes the FUSED-epilogue kernel handles (`flash_attention_fused`:
    2-D pair-bias tiles and/or in-kernel sigmoid output gating).

    The 2-D bias streams block-by-block like K/V (never row-resident) and
    the gate streams with the query block, so the VMEM residency bound is
    the same row-vector budget as the plain kernel — kept identical so
    one `supported` story covers both dispatch gates."""
    return supported(i, j, dh)


def pick_block(n: int, target: int = 512, mult: int = 128, tol: float = 0.15) -> int:
    """Pick a Pallas block size for a length-n axis.

    Among multiples of `mult` (MXU-friendly) up to `target`, take the
    LARGEST block whose padded length is within `tol` of the minimum
    achievable — large blocks amortize grid/loop overhead, but gross
    padding waste is real FLOPs: n=1152 picks 384 (zero padding) where a
    fixed 512 pads to 1536 (+33%), while n=896 keeps 512 (+14% padding
    beats 7x the grid steps of 128). The tol knob is a heuristic pending
    on-chip measurement (PERF.md)."""
    if n <= mult:
        return mult
    padded = {b: ((n + b - 1) // b) * b for b in range(mult, target + 1, mult)}
    best = min(padded.values())
    return max(b for b, p in padded.items() if p <= best * (1 + tol))


def _block_target(dh: int) -> int:
    """Cap block size so per-grid-step tiles fit VMEM: the worst kernel
    step holds ~6 f32 tiles of (block, dh) plus a (qb, kb) logit tile,
    double-buffered. dh=64 (the framework's head dim) keeps the full 512;
    dh=512 drops to 256."""
    return max(128, min(512, (4 << 20) // (24 * dh) // 128 * 128))


# vma-aware ShapeDtypeStruct (union of the operands' varying-across-mesh-
# axes sets) — required for pallas_call under shard_map with vma checking
# (e.g. the ring-attention hops); plain struct on pre-vma JAX.
_out_struct = compat.out_struct


def _pad_args(q, k, v, bias, qb, kb):
    """Pad query/key lengths to block multiples (-inf bias on padded keys)."""
    BH, i, dh = q.shape
    j = k.shape[1]
    pad_i = (-i) % qb
    pad_j = (-j) % kb
    if pad_i:
        q = jnp.pad(q, ((0, 0), (0, pad_i), (0, 0)))
    if pad_j:
        k = jnp.pad(k, ((0, 0), (0, pad_j), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_j), (0, 0)))
        bias = jnp.pad(bias, ((0, 0), (0, pad_j)), constant_values=_NEG)
    return q, k, v, bias, i + pad_i, j + pad_j


# Backward kernels: first two grid dims parallel (their output windows are
# private per (b, block) pair), streamed contraction dim sequential.
_BWD_PARAMS = compat.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary")
)
# Forward: the lse output window (1, nqb, qb) is SHARED across qi, so qi
# must not be split across megacore TPU cores (each core's private copy of
# the whole window would clobber the other's rows on write-back) — qi runs
# sequentially; the (batch*head) dim carries all the parallelism.
_FWD_PARAMS = compat.CompilerParams(
    dimension_semantics=("parallel", "arbitrary", "arbitrary")
)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, out_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, nkb, scale):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _M0, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    q = q_ref[0]          # (qb, dh), input dtype
    k = k_ref[0]          # (kb, dh)
    v = v_ref[0]
    b = bias_ref[0, ki]   # (kb,) f32, resident row vector
    s = jax.lax.dot_general(
        q, k,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale + b[None, :]

    m = m_scr[...]
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
        p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    m_scr[...] = m_new

    @pl.when(ki == nkb - 1)
    def _finish():
        l = l_scr[...]
        safe = jnp.where(l > 0, l, 1.0)
        out_ref[0] = jnp.where(l > 0, acc_scr[...] / safe, 0.0).astype(
            out_ref.dtype
        )
        # +inf for rows with no active mass: exp(s - inf) = 0 zeroes every
        # recomputed p in the backward (lse rides as a resident
        # (1, nQB, qb) block — Mosaic rejects (1, qb) row blocks)
        lse = jnp.where(l > 0, m_scr[...] + jnp.log(safe), jnp.inf)
        lse_ref[0, qi] = lse[:, 0]


def _forward(q, k, v, bias, scale, qb, kb):
    """q: (BH, i, dh); k, v: (BH, j, dh); bias: (BH, j) additive f32."""
    BH, i0, dh = q.shape
    j0 = k.shape[1]
    q, k, v, bias, i, j = _pad_args(q, k, v, bias, qb, kb)
    nqb, nkb = i // qb, j // kb
    bias3 = bias.reshape(BH, nkb, kb)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, nkb=nkb, scale=scale),
        out_shape=[
            _out_struct((BH, i, dh), q.dtype, q, k, v, bias3),
            _out_struct((BH, nqb, qb), jnp.float32, q, k, v, bias3),
        ],
        grid=(BH, nqb, nkb),
        in_specs=[
            pl.BlockSpec((1, qb, dh), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, kb, dh), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, kb, dh), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, nkb, kb), lambda b, qi, ki: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, qb, dh), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, nqb, qb), lambda b, qi, ki: (b, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((qb, 1), jnp.float32),
            pltpu.VMEM((qb, 1), jnp.float32),
            pltpu.VMEM((qb, dh), jnp.float32),
        ],
        compiler_params=_FWD_PARAMS,
        interpret=_interpret(),
    )(q, k, v, bias3)
    return out[:, :i0], (q, k, v, bias3, lse, i0, j0)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref, delta_ref,
               dq_ref, dq_scr, *, nkb, scale):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    q = q_ref[0]
    g = g_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    b = bias_ref[0, ki]
    lse = lse_ref[0, qi][:, None]
    delta = delta_ref[0, qi][:, None]

    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale + b[None, :]
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(
        g, v, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # ds in the operand dtype: bf16 ds @ k on the MXU bf16 path — the
    # standard flash-backward precision trade (f32 accumulate)
    ds = (p * (dp - delta)).astype(k.dtype)
    dq_scr[...] = dq_scr[...] + jnp.dot(
        ds, k, preferred_element_type=jnp.float32
    )

    @pl.when(ki == nkb - 1)
    def _finish():
        dq_ref[0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, nqb, scale):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    k = k_ref[0]                      # (kb, dh)
    v = v_ref[0]
    q = q_ref[0]                      # (qb, dh)
    g = g_ref[0]
    b = bias_ref[0, ki]               # (kb,)
    lse = lse_ref[0, qi][:, None]
    delta = delta_ref[0, qi][:, None]

    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale + b[None, :]
    p = jnp.exp(s - lse)              # (qb, kb) f32
    dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
        p.astype(g.dtype), g, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(
        g, v, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = (p * (dp - delta)).astype(q.dtype)
    dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
        ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(qi == nqb - 1)
    def _finish():
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_core(q, k, v, key_bias, scale, qb, kb):
    out, _ = _forward(q, k, v, key_bias, scale, qb, kb)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_core_lse(q, k, v, key_bias, scale, qb, kb):
    out, (_, _, _, _, lse, i0, _) = _forward(q, k, v, key_bias, scale, qb, kb)
    return out, lse.reshape(lse.shape[0], -1)[:, :i0]


def flash_attention_lse(q, k, v, key_bias, scale, qb=None, kb=None):
    """`flash_attention_tpu` that ALSO returns the per-row log-sum-exp.

    Returns (out (BH, i, dh), lse (BH, i) f32). lse is +inf for rows with
    no unmasked keys (zero attention mass — note the INVERTED convention
    vs the usual -inf-for-empty: +inf makes the backward's recomputed
    p = exp(s - lse) vanish). Differentiable in q/k/v including through
    lse — the lse cotangent folds into the softmax-jacobian diagonal
    (delta_eff = delta - g_lse), so the backward kernels are shared with
    the plain path. This is the building block for cross-chip softmax
    combination (ring attention, parallel/sequence.py).
    """
    dh = q.shape[-1]
    qb = pick_block(q.shape[1], target=_block_target(dh)) if qb is None else qb
    kb = pick_block(k.shape[1], target=_block_target(dh)) if kb is None else kb
    return _flash_core_lse(q, k, v, key_bias, scale, qb, kb)


def flash_attention_tpu(q, k, v, key_bias, scale, qb=None, kb=None):
    """Fused dense flash attention. q: (BH, i, dh); k, v: (BH, j, dh);
    key_bias: (BH, j) additive f32 (0 valid / -inf masked). Returns
    (BH, i, dh). The bias cotangent is not computed (masks are data, not
    parameters). qb/kb: query/key block sizes (None = padding-aware pick)."""
    dh = q.shape[-1]
    qb = pick_block(q.shape[1], target=_block_target(dh)) if qb is None else qb
    kb = pick_block(k.shape[1], target=_block_target(dh)) if kb is None else kb
    return _flash_core(q, k, v, key_bias, scale, qb, kb)


def _fwd(q, k, v, key_bias, scale, qb, kb):
    out, (qp, kp, vp, bias3, lse, i0, j0) = _forward(q, k, v, key_bias, scale, qb, kb)
    return out, (qp, kp, vp, bias3, lse, out, i0, j0)


def _bwd_impl(scale, qb, kb, res, g, g_lse=None):
    qp, kp, vp, bias3, lse, out, i0, j0 = res
    BH, i, dh = qp.shape
    j = kp.shape[1]
    nqb, nkb = i // qb, j // kb

    pad_i = i - i0
    if pad_i:
        g = jnp.pad(g, ((0, 0), (0, pad_i), (0, 0)))
        out = jnp.pad(out, ((0, 0), (0, pad_i), (0, 0)))

    # delta_i = rowsum(dO_i * O_i), the softmax-jacobian diagonal term.
    # An lse cotangent folds in here: d lse_i / d s_ij = p_ij, so
    # ds_ij = p_ij * (dp_ij - (delta_i - glse_i)) — same kernels, shifted
    # diagonal
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    if g_lse is not None:
        glse = g_lse.astype(jnp.float32)
        if pad_i:
            glse = jnp.pad(glse, ((0, 0), (0, pad_i)))
        delta = delta - glse
    delta = delta.reshape(BH, nqb, qb)

    blk_q = pl.BlockSpec((1, qb, dh), lambda b, x, y: (b, x, 0))
    blk_q_inner = pl.BlockSpec((1, qb, dh), lambda b, x, y: (b, y, 0))
    blk_k = pl.BlockSpec((1, kb, dh), lambda b, x, y: (b, x, 0))
    blk_k_inner = pl.BlockSpec((1, kb, dh), lambda b, x, y: (b, y, 0))
    rows_q = pl.BlockSpec((1, nqb, qb), lambda b, x, y: (b, 0, 0))
    rows_k = pl.BlockSpec((1, nkb, kb), lambda b, x, y: (b, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, nkb=nkb, scale=scale),
        out_shape=_out_struct((BH, i, dh), qp.dtype, qp, kp, vp, g),
        grid=(BH, nqb, nkb),
        in_specs=[blk_q, blk_k_inner, blk_k_inner, rows_k, blk_q,
                  rows_q, rows_q],
        out_specs=blk_q,
        scratch_shapes=[pltpu.VMEM((qb, dh), jnp.float32)],
        compiler_params=_BWD_PARAMS,
        interpret=_interpret(),
    )(qp, kp, vp, bias3, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, nqb=nqb, scale=scale),
        out_shape=[
            _out_struct((BH, j, dh), kp.dtype, qp, kp, vp, g),
            _out_struct((BH, j, dh), vp.dtype, qp, kp, vp, g),
        ],
        grid=(BH, nkb, nqb),
        in_specs=[blk_q_inner, blk_k, blk_k, rows_k, blk_q_inner,
                  rows_q, rows_q],
        out_specs=[blk_k, blk_k],
        scratch_shapes=[
            pltpu.VMEM((kb, dh), jnp.float32),
            pltpu.VMEM((kb, dh), jnp.float32),
        ],
        compiler_params=_BWD_PARAMS,
        interpret=_interpret(),
    )(qp, kp, vp, bias3, g, lse, delta)

    # cotangents must match the ORIGINAL (unpadded) primal shapes; the bias
    # is a mask, not a parameter — its cotangent is declared zero
    return (
        dq[:, :i0],
        dk[:, :j0],
        dv[:, :j0],
        jnp.zeros((qp.shape[0], j0), jnp.float32),
    )


def _bwd(scale, qb, kb, res, g):
    return _bwd_impl(scale, qb, kb, res, g)


_flash_core.defvjp(_fwd, _bwd)


def _fwd_lse(q, k, v, key_bias, scale, qb, kb):
    out, (qp, kp, vp, bias3, lse, i0, j0) = _forward(q, k, v, key_bias, scale, qb, kb)
    lse_flat = lse.reshape(lse.shape[0], -1)[:, :i0]
    return (out, lse_flat), (qp, kp, vp, bias3, lse, out, i0, j0)


def _bwd_lse(scale, qb, kb, res, gs):
    g, g_lse = gs
    return _bwd_impl(scale, qb, kb, res, g, g_lse=g_lse)


_flash_core_lse.defvjp(_fwd_lse, _bwd_lse)


# ---------------------------------------------------------------------------
# fused-epilogue kernel: full 2-D pair-bias tiles + sigmoid output gating
# ---------------------------------------------------------------------------
#
# The plain kernel above takes a key-side (BH, j) additive bias — a mask.
# The fused family generalizes the contract two ways (static flags, so
# each combination compiles its own minimal kernel):
#
#   * bias2d — the bias is a full (BH, i, j) f32 tile (pair bias + mask
#     folded together). It streams through the grid's sequential dimension
#     in (qb, kb) blocks exactly like K/V: the bias is never materialized
#     as a separate XLA add over an HBM logit tensor — one of the two HBM
#     round-trips the epilogue fusion removes. The bias cotangent is real
#     (pair biases are projections of learned state, not masks): the dq
#     kernel emits the per-tile ds as a d_bias output.
#   * gated — a (BH, i, dh) pre-sigmoid gate streams with the query block
#     and the finish step writes sigmoid(gate) * out directly, removing
#     the separate out-read/gate-multiply/out-write HBM pass. The gate
#     cotangent needs no kernel: d_gate = g * out_gated * (1 - sigmoid)
#     and the q/k/v backward sees g_eff = g * sigmoid(gate) — all
#     elementwise on tensors already in HBM (see _fused_bwd).
#
# The key-side-only contract stays the plain kernel's fast path; the
# (bias2d=False, gated=False) combination is the plain kernel and callers
# (ops/flash.py) dispatch it there.


def _make_fused_fwd_kernel(nkb, scale, bias2d, gated):
    def kernel(q_ref, k_ref, v_ref, bias_ref, *rest):
        if gated:
            gate_ref, out_ref, lse_ref, m_scr, l_scr, acc_scr = rest
        else:
            out_ref, lse_ref, m_scr, l_scr, acc_scr = rest
        qi = pl.program_id(1)
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            m_scr[...] = jnp.full(m_scr.shape, _M0, jnp.float32)
            l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
            acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if bias2d:
            s = s + bias_ref[0]             # (qb, kb) streamed tile
        else:
            s = s + bias_ref[0, ki][None, :]  # (kb,) resident row vector

        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_scr[...] = m_new

        @pl.when(ki == nkb - 1)
        def _finish():
            l = l_scr[...]
            safe = jnp.where(l > 0, l, 1.0)
            out = jnp.where(l > 0, acc_scr[...] / safe, 0.0)
            if gated:
                # sigmoid in f32 on the f32 accumulator: ONE cast at the
                # very end, matching the XLA epilogue's f32 math
                out = out * jax.nn.sigmoid(gate_ref[0].astype(jnp.float32))
            out_ref[0] = out.astype(out_ref.dtype)
            lse = jnp.where(l > 0, m_scr[...] + jnp.log(safe), jnp.inf)
            lse_ref[0, qi] = lse[:, 0]

    return kernel


def _pad_fused_args(q, k, v, bias, gate, qb, kb, bias2d, gated):
    """Pad to block multiples: -inf bias on padded keys AND padded query
    rows (2-D mode — padded rows become zero-mass, out 0 / lse +inf),
    zero gate rows (sigmoid of anything times a zero row is zero)."""
    BH, i, dh = q.shape
    j = k.shape[1]
    pad_i = (-i) % qb
    pad_j = (-j) % kb
    if pad_i:
        q = jnp.pad(q, ((0, 0), (0, pad_i), (0, 0)))
        if gated:
            gate = jnp.pad(gate, ((0, 0), (0, pad_i), (0, 0)))
    if pad_j:
        k = jnp.pad(k, ((0, 0), (0, pad_j), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_j), (0, 0)))
    if bias2d:
        if pad_i or pad_j:
            bias = jnp.pad(bias, ((0, 0), (0, pad_i), (0, pad_j)),
                           constant_values=_NEG)
    elif pad_j:
        bias = jnp.pad(bias, ((0, 0), (0, pad_j)), constant_values=_NEG)
    return q, k, v, bias, gate, i + pad_i, j + pad_j


def _forward_fused(q, k, v, bias, gate, scale, qb, kb, bias2d, gated):
    """q: (BH, i, dh); k, v: (BH, j, dh); bias: (BH, i, j) f32 when bias2d
    else (BH, j) f32; gate: (BH, i, dh) pre-sigmoid logits (gated only)."""
    BH, i0, dh = q.shape
    j0 = k.shape[1]
    q, k, v, bias, gate, i, j = _pad_fused_args(
        q, k, v, bias, gate, qb, kb, bias2d, gated
    )
    nqb, nkb = i // qb, j // kb
    biask = bias if bias2d else bias.reshape(BH, nkb, kb)

    in_specs = [
        pl.BlockSpec((1, qb, dh), lambda b, qi, ki: (b, qi, 0)),
        pl.BlockSpec((1, kb, dh), lambda b, qi, ki: (b, ki, 0)),
        pl.BlockSpec((1, kb, dh), lambda b, qi, ki: (b, ki, 0)),
        pl.BlockSpec((1, qb, kb), lambda b, qi, ki: (b, qi, ki))
        if bias2d
        else pl.BlockSpec((1, nkb, kb), lambda b, qi, ki: (b, 0, 0)),
    ]
    operands = [q, k, v, biask]
    if gated:
        in_specs.append(pl.BlockSpec((1, qb, dh), lambda b, qi, ki: (b, qi, 0)))
        operands.append(gate)

    out, lse = pl.pallas_call(
        _make_fused_fwd_kernel(nkb, scale, bias2d, gated),
        out_shape=[
            _out_struct((BH, i, dh), q.dtype, *operands),
            _out_struct((BH, nqb, qb), jnp.float32, *operands),
        ],
        grid=(BH, nqb, nkb),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, qb, dh), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, nqb, qb), lambda b, qi, ki: (b, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((qb, 1), jnp.float32),
            pltpu.VMEM((qb, 1), jnp.float32),
            pltpu.VMEM((qb, dh), jnp.float32),
        ],
        compiler_params=_FWD_PARAMS,
        interpret=_interpret(),
    )(*operands)
    return out[:, :i0], (q, k, v, biask, gate, lse, i0, j0)


def _make_fused_dq_kernel(nkb, scale, bias2d):
    def kernel(q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref, delta_ref,
               *rest):
        if bias2d:
            dq_ref, db_ref, dq_scr = rest
        else:
            dq_ref, dq_scr = rest
        qi = pl.program_id(1)
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

        q = q_ref[0]
        g = g_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        lse = lse_ref[0, qi][:, None]
        delta = delta_ref[0, qi][:, None]

        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        s = s + (bias_ref[0] if bias2d else bias_ref[0, ki][None, :])
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            g, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds_f32 = p * (dp - delta)
        if bias2d:
            # d s / d bias = 1: the unscaled ds tile IS the bias cotangent
            db_ref[0] = ds_f32
        ds = ds_f32.astype(k.dtype)
        dq_scr[...] = dq_scr[...] + jnp.dot(
            ds, k, preferred_element_type=jnp.float32
        )

        @pl.when(ki == nkb - 1)
        def _finish():
            dq_ref[0] = (dq_scr[...] * scale).astype(dq_ref.dtype)

    return kernel


def _make_fused_dkv_kernel(nqb, scale, bias2d):
    def kernel(q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref, delta_ref,
               dk_ref, dv_ref, dk_scr, dv_scr):
        ki = pl.program_id(1)
        qi = pl.program_id(2)

        @pl.when(qi == 0)
        def _init():
            dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
            dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

        k = k_ref[0]
        v = v_ref[0]
        q = q_ref[0]
        g = g_ref[0]
        lse = lse_ref[0, qi][:, None]
        delta = delta_ref[0, qi][:, None]

        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        s = s + (bias_ref[0] if bias2d else bias_ref[0, ki][None, :])
        p = jnp.exp(s - lse)
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p.astype(g.dtype), g, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            g, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        @pl.when(qi == nqb - 1)
        def _finish():
            dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
            dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)

    return kernel


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _fused_core(q, k, v, bias, gate, scale, qb, kb, bias2d, gated):
    out, _ = _forward_fused(q, k, v, bias, gate, scale, qb, kb, bias2d, gated)
    return out


def _fused_fwd(q, k, v, bias, gate, scale, qb, kb, bias2d, gated):
    out, res = _forward_fused(q, k, v, bias, gate, scale, qb, kb, bias2d, gated)
    qp, kp, vp, biask, gatep, lse, i0, j0 = res
    return out, (qp, kp, vp, biask, gatep, lse, out, i0, j0)


def _fused_bwd(scale, qb, kb, bias2d, gated, res, g):
    qp, kp, vp, biask, gatep, lse, out, i0, j0 = res
    BH, i, dh = qp.shape
    j = kp.shape[1]
    nqb, nkb = i // qb, j // kb

    pad_i = i - i0
    if pad_i:
        g = jnp.pad(g, ((0, 0), (0, pad_i), (0, 0)))
        out = jnp.pad(out, ((0, 0), (0, pad_i), (0, 0)))

    g32 = g.astype(jnp.float32)
    out32 = out.astype(jnp.float32)
    # delta = rowsum(dO_eff * O_pre). With gating, dO_eff = g * sig and
    # O_pre = O_gated / sig, so the product collapses to g * O_gated —
    # delta computes from the SAVED gated output with the RAW cotangent
    delta = jnp.sum(g32 * out32, axis=-1).reshape(BH, nqb, qb)
    d_gate = None
    if gated:
        sig = jax.nn.sigmoid(gatep.astype(jnp.float32))
        # d gate = g * O_pre * sig' = g * O_gated * (1 - sig); elementwise
        # on tensors already in HBM, so no backward kernel change
        d_gate = (g32 * out32 * (1.0 - sig)).astype(gatep.dtype)[:, :i0]
        g = (g32 * sig).astype(g.dtype)

    blk_q = pl.BlockSpec((1, qb, dh), lambda b, x, y: (b, x, 0))
    blk_q_inner = pl.BlockSpec((1, qb, dh), lambda b, x, y: (b, y, 0))
    blk_k = pl.BlockSpec((1, kb, dh), lambda b, x, y: (b, x, 0))
    blk_k_inner = pl.BlockSpec((1, kb, dh), lambda b, x, y: (b, y, 0))
    rows_q = pl.BlockSpec((1, nqb, qb), lambda b, x, y: (b, 0, 0))
    rows_k = pl.BlockSpec((1, nkb, kb), lambda b, x, y: (b, 0, 0))
    bias_dq = (
        pl.BlockSpec((1, qb, kb), lambda b, x, y: (b, x, y))
        if bias2d else rows_k
    )
    bias_dkv = (
        pl.BlockSpec((1, qb, kb), lambda b, x, y: (b, y, x))
        if bias2d else rows_k
    )

    dq_outs = [_out_struct((BH, i, dh), qp.dtype, qp, kp, vp, g)]
    dq_specs = [blk_q]
    scratch = [pltpu.VMEM((qb, dh), jnp.float32)]
    if bias2d:
        dq_outs.append(_out_struct((BH, i, j), jnp.float32, qp, kp, vp, g))
        dq_specs.append(pl.BlockSpec((1, qb, kb), lambda b, x, y: (b, x, y)))
    dq_res = pl.pallas_call(
        _make_fused_dq_kernel(nkb, scale, bias2d),
        out_shape=dq_outs,
        grid=(BH, nqb, nkb),
        in_specs=[blk_q, blk_k_inner, blk_k_inner, bias_dq, blk_q,
                  rows_q, rows_q],
        out_specs=dq_specs,
        scratch_shapes=scratch,
        compiler_params=_BWD_PARAMS,
        interpret=_interpret(),
    )(qp, kp, vp, biask, g, lse, delta)
    if bias2d:
        dq, db = dq_res
        d_bias = db[:, :i0, :j0]
    else:
        dq = dq_res[0] if isinstance(dq_res, (list, tuple)) else dq_res
        # key-side bias is a mask, not a parameter: cotangent declared zero
        d_bias = jnp.zeros((BH, j0), jnp.float32)

    dk, dv = pl.pallas_call(
        _make_fused_dkv_kernel(nqb, scale, bias2d),
        out_shape=[
            _out_struct((BH, j, dh), kp.dtype, qp, kp, vp, g),
            _out_struct((BH, j, dh), vp.dtype, qp, kp, vp, g),
        ],
        grid=(BH, nkb, nqb),
        in_specs=[blk_q_inner, blk_k, blk_k, bias_dkv, blk_q_inner,
                  rows_q, rows_q],
        out_specs=[blk_k, blk_k],
        scratch_shapes=[
            pltpu.VMEM((kb, dh), jnp.float32),
            pltpu.VMEM((kb, dh), jnp.float32),
        ],
        compiler_params=_BWD_PARAMS,
        interpret=_interpret(),
    )(qp, kp, vp, biask, g, lse, delta)

    if d_gate is None:
        d_gate = jnp.zeros(
            (BH, 1, dh), gatep.dtype if hasattr(gatep, "dtype") else jnp.float32
        )
    return (dq[:, :i0], dk[:, :j0], dv[:, :j0], d_bias, d_gate)


_fused_core.defvjp(_fused_fwd, _fused_bwd)


def flash_attention_fused(q, k, v, bias, scale, *, gate=None, qb=None,
                          kb=None):
    """Fused-epilogue dense flash attention.

    q: (BH, i, dh); k, v: (BH, j, dh). bias: additive f32, either the
    plain key-side (BH, j) contract or a full 2-D (BH, i, j) pair-bias
    tile (masks folded in as -inf) — the 2-D tiles stream through the
    kernel in (qb, kb) blocks, so the bias-add never costs a separate
    HBM logit pass. gate: optional (BH, i, dh) pre-sigmoid output-gate
    logits applied INSIDE the kernel's finish step
    (out = sigmoid(gate) * softmax(s) V). Returns (BH, i, dh).

    Differentiable in q/k/v, the 2-D bias (real cotangent — pair biases
    are learned projections), and the gate; the key-side bias cotangent
    stays declared-zero (masks are data). Shape support:
    `supported_fused`."""
    dh = q.shape[-1]
    bias2d = bias.ndim == 3
    gated = gate is not None
    # the 2-D bias adds a streamed (qb, kb) f32 tile plus the backward's
    # d_bias tile to each grid step's VMEM footprint: cap the block target
    # so the double-buffered working set keeps headroom
    target = min(256, _block_target(dh)) if bias2d else _block_target(dh)
    qb = pick_block(q.shape[1], target=target) if qb is None else qb
    kb = pick_block(k.shape[1], target=target) if kb is None else kb
    if not gated:
        gate = jnp.zeros((q.shape[0], 1, dh), q.dtype)
    return _fused_core(q, k, v, bias, gate, scale, qb, kb, bias2d, gated)
