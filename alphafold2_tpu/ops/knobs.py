"""One validated home for every AF2_* environment knob.

Before this module, each env knob was parsed where it was consumed —
`ops/flash.py` grew three parsers, `ops/quant.py` two more,
`parallel/overlap.py` and `parallel/distributed.py` their own — with
three different ideas of what "0"/"false"/"off" mean and silent
acceptance of typos (`AF2_DISABLE_FLASH_KERNEL=flase` disabled the
kernel). This module is the single registry:

  * every knob has exactly ONE definition (`KNOBS`) carrying its type,
    default, accepted values, and the module that consumes it;
  * every parse is strict — an unrecognized value raises `ValueError`
    naming the knob and the accepted spellings, instead of silently
    defaulting (a mistyped A/B-sweep env var must fail the leg, not
    quietly measure the wrong arm);
  * the env-var reference table in docs/OPERATIONS.md is GENERATED from
    the registry (`python -m alphafold2_tpu.ops.knobs`, pinned in sync
    by tests/test_dispatch.py), so docs cannot drift from code.

Values are read from `os.environ` at every call (not cached): A/B
harnesses and tests flip knobs mid-process, and jitted programs bake the
result in at trace time — the same contract the scattered parsers had.

This module imports nothing from the package (and no jax), so any layer
— ops, parallel, serving, analysis — can read knobs without cycles.
af2lint's `dispatch` pass enforces that no other module under
`alphafold2_tpu/` reads an AF2_* variable directly.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple

__all__ = [
    "KNOBS",
    "Knob",
    "auto_init",
    "comm_overlap_enabled",
    "coordinator",
    "flag",
    "flash_auto_min_j",
    "flash_kernel_disabled",
    "gate_epilogue_unfused",
    "generate_table",
    "kernel_backend_override",
    "num_processes",
    "pallas_interpret_override",
    "process_id",
    "quant_kernel_disabled",
    "quant_kernel_override",
]

_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")

#: default Pallas auto-dispatch key-length threshold — measured on-chip
#: (PERF_SWEEP.jsonl 2026-07-31): blanket kernel dispatch costs 14% e2e
#: at the short-axis shapes, while the long-j streaming shapes need the
#: kernel (XLA streaming compile >550 s there, PERF.md).
FLASH_AUTO_MIN_J_DEFAULT = 4096


@dataclasses.dataclass(frozen=True)
class Knob:
    """One env knob's single source of truth (name, contract, consumer)."""

    name: str
    values: str          # human-readable accepted values
    default: str         # human-readable default
    read_by: str         # the module whose behavior it changes
    help: str            # one-line description for the generated table


def _raw(name: str) -> str:
    return os.environ.get(name, "")


def flag(name: str, default: bool = False) -> bool:
    """Strict boolean knob: 1/true/yes/on vs 0/false/no/off ("" = unset
    -> default). Anything else raises — a typo must not silently pick a
    measurement arm."""
    raw = _raw(name).lower()
    if raw == "":
        return default
    if raw in _TRUE:
        return True
    if raw in _FALSE:
        return False
    raise ValueError(
        f"{name} must be one of {_TRUE + _FALSE} (or unset), got {raw!r}"
    )


def env_int(name: str, default: int) -> int:
    raw = _raw(name)
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"{name} must be an integer, got {raw!r}"
        ) from None


# --- kernel-dispatch knobs ---------------------------------------------------


def flash_kernel_disabled() -> bool:
    """AF2_DISABLE_FLASH_KERNEL kill-switch, shared by BOTH flash-family
    Pallas kernels (dense in ops/flash.py, block-sparse in ops/sparse.py):
    bench.py's kernel-off retry must leave no Pallas in the program.
    Auto-mode only; explicit forcing wins."""
    return flag("AF2_DISABLE_FLASH_KERNEL")


def quant_kernel_disabled() -> bool:
    """AF2_DISABLE_QUANT_KERNEL kill-switch (auto mode only), same
    contract as AF2_DISABLE_FLASH_KERNEL."""
    return flag("AF2_DISABLE_QUANT_KERNEL")


def gate_epilogue_unfused() -> bool:
    """AF2_UNFUSE_GATE_EPILOGUE: keep the Pallas kernel for the attention
    CORE but apply the sigmoid output gate as a separate XLA epilogue —
    the control arm that isolates the epilogue fusion (ops/flash.py)."""
    return flag("AF2_UNFUSE_GATE_EPILOGUE")


def flash_auto_min_j() -> int:
    """AF2_FLASH_AUTO_MIN_J: minimum key length for the Pallas kernel in
    "auto" mode (0 force-prefers the kernel everywhere supported — the
    sweep's kernel-on legs)."""
    return env_int("AF2_FLASH_AUTO_MIN_J", FLASH_AUTO_MIN_J_DEFAULT)


def quant_kernel_override() -> Optional[bool]:
    """AF2_QUANT_KERNEL legacy sweep override for auto-mode dispatch:
    "force" -> kernel everywhere (loud error on unsupported shapes),
    "off" -> XLA reference arm, ""/"auto" -> the platform/shape
    heuristic. Superseded by AF2_KERNEL_BACKEND_QUANT_MATMUL but kept —
    recorded sweep rows and runbooks use it."""
    raw = _raw("AF2_QUANT_KERNEL").lower()
    if raw in ("", "auto"):
        return None
    if raw == "force":
        return True
    if raw == "off":
        return False
    raise ValueError(
        f"AF2_QUANT_KERNEL must be force, off, or auto/empty, got {raw!r}"
    )


def comm_overlap_enabled() -> bool:
    """AF2_COMM_OVERLAP: communication-compute overlap schedules
    (double-buffered ring attention, backward-overlapped DP reduction).
    Default ON; read at trace time (parallel/overlap.py)."""
    return flag("AF2_COMM_OVERLAP", default=True)


def pallas_interpret_override() -> Optional[bool]:
    """AF2_PALLAS_INTERPRET: force Pallas interpret mode on (1/true) or
    off (0/false); ""/unset -> None (platform default, resolved by
    ops/core.py pallas_interpret)."""
    raw = _raw("AF2_PALLAS_INTERPRET")
    if not raw:  # empty string = unset, like the kill-switches
        return None
    if raw.lower() in ("0", "false"):
        return False
    if raw.lower() in ("1", "true"):
        return True
    raise ValueError(
        f"AF2_PALLAS_INTERPRET must be 0/false or 1/true, got {raw!r}"
    )


def kernel_backend_override(op: str) -> Optional[str]:
    """The dispatch-registry backend override (ops/dispatch.py).

    Per-op `AF2_KERNEL_BACKEND_<OP>` (op name upper-cased) wins over the
    global `AF2_KERNEL_BACKEND` — including an explicit per-op "auto",
    which restores the heuristic for that op UNDER a global override
    (the one combination per-op-wins exists for). Values: "" -> fall
    through (per-op) / None (global), "auto" -> None (heuristic),
    "off" -> the op's `xla_ref` arm, anything else -> returned verbatim
    as a FORCED arm name — ops/dispatch.py validates it against the
    op's registered arms and raises loudly on unknown arms or
    unsupported shapes (forcing must not silently fall back)."""
    for name in (f"AF2_KERNEL_BACKEND_{op.upper()}", "AF2_KERNEL_BACKEND"):
        raw = _raw(name).strip().lower()
        if raw == "auto":
            return None  # explicitly set: do NOT fall through to global
        if raw:
            return raw
    return None


# --- multi-host launch contract (parallel/distributed.py) --------------------


def coordinator() -> Optional[str]:
    """AF2_COORDINATOR: host:port of process 0's coordination service."""
    return _raw("AF2_COORDINATOR") or None


def num_processes() -> int:
    """AF2_NUM_PROCESSES: pod process count (0/unset = single process)."""
    return env_int("AF2_NUM_PROCESSES", 0)


def process_id() -> Optional[int]:
    """AF2_PROCESS_ID: this host's process index (None when unset)."""
    raw = _raw("AF2_PROCESS_ID")
    return int(raw) if raw else None


def auto_init() -> bool:
    """AF2_AUTO_INIT: opt into jax.distributed.initialize() TPU-pod
    topology auto-detection."""
    return flag("AF2_AUTO_INIT")


# --- the registry ------------------------------------------------------------

_BOOL = "1/true/yes/on, 0/false/no/off"

KNOBS: Tuple[Knob, ...] = (
    Knob("AF2_KERNEL_BACKEND",
         "auto, off, or an arm name (pallas_tpu, gpu, xla_ref)", "auto",
         "ops/dispatch.py",
         "Global backend-arm override for every registered hot op: an arm "
         "name forces it (loud error if unsupported), off forces xla_ref, "
         "auto/unset keeps the platform/shape heuristic."),
    Knob("AF2_KERNEL_BACKEND_<OP>",
         "auto, off, or an arm name (per-op)", "auto",
         "ops/dispatch.py",
         "Per-op override (OP = registered op name upper-cased, e.g. "
         "AF2_KERNEL_BACKEND_QUANT_MATMUL); wins over the global knob."),
    Knob("AF2_DISABLE_FLASH_KERNEL", _BOOL, "0", "ops/dispatch.py",
         "Kill-switch: auto-mode dispatch never picks a flash-family "
         "Pallas arm (dense, fused, sparse, ring hop). Forcing wins."),
    Knob("AF2_DISABLE_QUANT_KERNEL", _BOOL, "0", "ops/dispatch.py",
         "Kill-switch: auto-mode dispatch never picks the int8 "
         "fused-dequant Pallas arm."),
    Knob("AF2_FLASH_AUTO_MIN_J", "integer",
         str(FLASH_AUTO_MIN_J_DEFAULT), "ops/dispatch.py",
         "Minimum key length for flash-family Pallas arms in auto mode "
         "(measured short-j crossover; 0 = kernel everywhere supported)."),
    Knob("AF2_QUANT_KERNEL", "force, off, auto", "auto",
         "ops/dispatch.py",
         "Legacy quant_matmul arm override (recorded sweep rows use it); "
         "superseded by AF2_KERNEL_BACKEND_QUANT_MATMUL."),
    Knob("AF2_UNFUSE_GATE_EPILOGUE", _BOOL, "0", "ops/flash.py",
         "A/B control arm: Pallas attention core, sigmoid output gate as "
         "a separate XLA epilogue (isolates the epilogue fusion)."),
    Knob("AF2_PALLAS_INTERPRET", "1/true, 0/false", "platform default",
         "ops/core.py",
         "Force Pallas interpret mode on or off (default: interpret "
         "off-TPU, compiled on TPU)."),
    Knob("AF2_COMM_OVERLAP", _BOOL, "1", "parallel/overlap.py",
         "Communication-compute overlap schedules (double-buffered ring, "
         "backward-overlapped DP psum); baked in at trace time."),
    Knob("AF2_COORDINATOR", "host:port", "unset",
         "parallel/distributed.py",
         "Multi-host launch contract: process 0's coordination address."),
    Knob("AF2_NUM_PROCESSES", "integer", "0",
         "parallel/distributed.py",
         "Multi-host launch contract: pod process count."),
    Knob("AF2_PROCESS_ID", "integer", "unset",
         "parallel/distributed.py",
         "Multi-host launch contract: this host's process index."),
    Knob("AF2_AUTO_INIT", _BOOL, "0", "parallel/distributed.py",
         "Opt into TPU-pod topology auto-detection "
         "(jax.distributed.initialize with no arguments)."),
)


def generate_table() -> str:
    """The docs/OPERATIONS.md env-knob reference table, generated from
    the registry (one definition per knob — the docs block between the
    af2knobs markers must equal this string; pinned by
    tests/test_dispatch.py)."""
    lines = [
        "| Knob | Values | Default | Read by | What it does |",
        "| --- | --- | --- | --- | --- |",
    ]
    for k in KNOBS:
        lines.append(
            f"| `{k.name}` | {k.values} | {k.default} | `{k.read_by}` "
            f"| {k.help} |"
        )
    return "\n".join(lines)


if __name__ == "__main__":
    print(generate_table())
