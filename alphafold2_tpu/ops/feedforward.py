"""GEGLU feed-forward block (reference alphafold2_pytorch/alphafold2.py:52-73).

Linear(d -> 2*mult*d) -> GEGLU (value * gelu(gate)) -> dropout ->
Linear(mult*d -> d). Uses exact (erf) GELU to match torch.nn.functional.gelu.
The two matmuls dominate; XLA fuses the gating elementwise into them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from alphafold2_tpu.ops.core import dropout, linear, linear_init


def feed_forward_init(key, dim: int, mult: int = 4):
    k_in, k_out = jax.random.split(key)
    return {
        "proj_in": linear_init(k_in, dim, dim * mult * 2),
        "proj_out": linear_init(k_out, dim * mult, dim),
    }


def feed_forward_apply(params, x, *, dropout_rate: float = 0.0, rng=None, dtype=None):
    y = linear(params["proj_in"], x, dtype=dtype)
    value, gate = jnp.split(y, 2, axis=-1)
    y = value * jax.nn.gelu(gate, approximate=False)
    y = dropout(rng, y, dropout_rate)
    return linear(params["proj_out"], y, dtype=dtype)
