"""GEGLU feed-forward block (reference alphafold2_pytorch/alphafold2.py:52-73).

Linear(d -> 2*mult*d) -> GEGLU (value * gelu(gate)) -> dropout ->
Linear(mult*d -> d). Uses exact (erf) GELU to match torch.nn.functional.gelu.
The two matmuls dominate; XLA fuses the gating elementwise into them.

`chunk`: when set, the token axes are flattened and processed in blocks of
that many tokens under `jax.checkpoint`, bounding the 8*dim GEGLU
intermediate — at crop 384 the pair stream has 1.3M tokens, whose 2048-wide
intermediate would otherwise be the largest single activation in the trunk.
Chunked dropout draws an independent key per block (fold_in of the block
index); the unchunked mask pattern is not reproduced — set chunk=0 for
bit-identical dropout.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from alphafold2_tpu.ops.core import dropout, linear, linear_init


def feed_forward_init(key, dim: int, mult: int = 4):
    k_in, k_out = jax.random.split(key)
    return {
        "proj_in": linear_init(k_in, dim, dim * mult * 2),
        "proj_out": linear_init(k_out, dim * mult, dim),
    }


def _ff_core(params, x, dropout_rate, rng, dtype):
    y = linear(params["proj_in"], x, dtype=dtype)
    value, gate = jnp.split(y, 2, axis=-1)
    y = value * jax.nn.gelu(gate, approximate=False)
    y = dropout(rng, y, dropout_rate)
    return linear(params["proj_out"], y, dtype=dtype)


def feed_forward_apply(
    params, x, *, dropout_rate: float = 0.0, rng=None, dtype=None, chunk: int = 0
):
    d = x.shape[-1]
    tokens = 1
    for s in x.shape[:-1]:
        tokens *= s
    if not chunk or tokens <= chunk:
        return _ff_core(params, x, dropout_rate, rng, dtype)

    xf = x.reshape(tokens, d)
    pad = (-tokens) % chunk
    if pad:
        xf = jnp.pad(xf, ((0, pad), (0, 0)))
    nb = (tokens + pad) // chunk

    def body(args):
        xi, idx = args
        r = jax.random.fold_in(rng, idx) if rng is not None else None
        return _ff_core(params, xi, dropout_rate, r, dtype)

    out = jax.lax.map(
        jax.checkpoint(body), (xf.reshape(nb, chunk, d), jnp.arange(nb))
    )
    out = out.reshape(nb * chunk, -1)[:tokens]
    return out.reshape(x.shape[:-1] + (out.shape[-1],))
