"""Post-training int8 weight quantization for the inference arm.

Serving inference (serving/engine.py) runs the same fp32 weights as
training, so every replica pays full HBM for weight residency and full
memory bandwidth on the trunk's dense layers. The efficiency-
implementation line of work (HelixFold, arxiv 2207.05477; FastFold,
arxiv 2203.00854) shows AlphaFold2's trunk tolerates reduced-precision
arms when parity is pinned per-op; this module arms the int8 lever:

  * **Per-channel symmetric PTQ** — `quantize_weight` maps an fp32
    (d_in, d_out) dense weight to (int8 values, f32 per-output-channel
    scale): scale_c = max|w[:, c]| / 127, q = round(w / scale). Symmetric
    (no zero point), so dequant is one multiply; per-channel, so one
    saturated channel cannot flatten the rest of the layer's resolution.
  * **Tree transforms** — `quantize_tree` / `dequantize_tree` walk a
    model parameter pytree by NAMED path and rewrite selected linear
    weights `{"w": ...}` to `{"qw": int8, "scale": f32}` (bias and every
    unselected leaf untouched). The fp32 master tree is never mutated —
    PTQ produces a NEW inference tree; training keeps the master.
    The default selection (`default_quant_select`) is the trunk's dense/
    projection weights: every 2-D (or reversible-trunk depth-stacked
    3-D) "w" under a "trunk" path. Embedding tables (gather, not
    matmul), LayerNorm, the KV-compress conv (a real 3-D conv kernel,
    excluded by name), and the distogram head stay fp32.
  * **Mixed-precision matmul dispatch** — `quant_matmul` runs activations
    (f32/bf16) against int8 weights: the Pallas fused-dequant kernel
    (ops/quant_kernel.py — int8 tiles cross HBM, per-channel scale in
    the kernel epilogue) on TPU for supported shapes, the pure-XLA
    dequant reference arm (`quant_matmul_xla` — materializes the
    dequantized weight, the baseline the kernel exists to beat)
    elsewhere. Auto-dispatch mirrors ops/flash.py `kernel_dispatch`:
    tri-state use_kernel, loud error on forced-unsupported,
    AF2_DISABLE_QUANT_KERNEL kill-switch, AF2_QUANT_KERNEL=force/off
    sweep override.

Quantized weights are INFERENCE-ONLY: `quant_matmul` installs a
custom-vjp backward that raises, and the training entry points
(training/harness.py, training/e2e.py) reject `weight_dtype="int8"`
configs before any tracing via `reject_quant_training` — a silently
straight-through-estimated training run would be a wrong-numbers
generator, not a feature.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "quantize_weight",
    "dequantize_weight",
    "quantize_tree",
    "dequantize_tree",
    "default_quant_select",
    "is_quantized_linear",
    "quant_matmul",
    "quant_matmul_xla",
    "quant_dispatch",
    "tree_weight_bytes",
    "quantized_path_bytes",
    "reject_quant_training",
]

_QMAX = 127.0  # symmetric int8 range; -128 is never produced


# ---------------------------------------------------------------------------
# per-channel symmetric PTQ
# ---------------------------------------------------------------------------


def quantize_weight(w, *, per_channel: bool = True):
    """fp32 (..., d_in, d_out) -> (int8 same shape, f32 scale).

    scale is (..., d_out) per output channel (the matmul's N axis, so the
    dequant commutes past the contraction and can apply in the kernel
    epilogue), or (...,) when per_channel=False — a scalar for a plain
    2-D weight. Leading axes are a STACK (the reversible trunk stores
    every layer's weights stacked (depth, d_in, d_out), lax.scan-sliced
    back to 2-D inside the layer body): each stacked slice quantizes
    independently, so scan slicing a quantized tree hands `linear` the
    exact (d_in, d_out)/(d_out,) pair `quant_matmul` takes. All-zero
    channels get scale 0 and values 0 — dequant reproduces exact zeros
    (the near-open gate init `w=0` round-trips bit-exactly)."""
    wf = jnp.asarray(w, jnp.float32)
    if wf.ndim < 2:
        raise ValueError(
            f"quantize_weight expects a (stacked) 2-D dense weight, "
            f"got {wf.shape}"
        )
    amax = (
        jnp.max(jnp.abs(wf), axis=-2) if per_channel
        else jnp.max(jnp.abs(wf), axis=(-2, -1))
    )
    scale = amax / _QMAX
    safe = jnp.where(scale > 0, scale, 1.0)
    safe = safe[..., None, :] if per_channel else safe[..., None, None]
    q = jnp.clip(jnp.round(wf / safe), -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def dequantize_weight(qw, scale):
    """(int8, scale) -> f32 weight. Exact inverse of the rounding grid:
    |w_deq - w| <= scale/2 per element. Accepts per-channel scales
    (qw.ndim - 1 dims) and per-tensor scales (qw.ndim - 2 dims),
    stacked or plain."""
    s = jnp.asarray(scale, jnp.float32)
    if s.ndim == qw.ndim - 1:        # per output channel
        s = s[..., None, :]
    elif s.ndim == qw.ndim - 2:      # per tensor (per stacked slice)
        s = s[..., None, None]
    else:
        raise ValueError(
            f"scale shape {s.shape} does not match weight shape {qw.shape}"
        )
    return qw.astype(jnp.float32) * s


def is_quantized_linear(d) -> bool:
    """True for a linear-param dict rewritten by `quantize_tree`."""
    return isinstance(d, dict) and "qw" in d and "scale" in d


def default_quant_select(path: str, w) -> bool:
    """The trunk's dense/projection weights: every 2-D linear weight (or
    depth-STACKED 3-D weight — the reversible trunk's layout) on a path
    through the trunk layer stack. Embeddings/LayerNorm never reach here
    (no "w" leaf of rank >= 2); the KV-compress conv is excluded BY NAME
    (its "w" is a genuine 3-D (ratio, in_per_group, inner) conv kernel
    that `linear` never sees, ops/attention.py:158 reads it directly);
    the distogram head (`head_out`) and front-end projections are
    deliberately excluded — output quality-sensitive, and a
    rounding-error share of total bytes."""
    parts = path.split("/")
    return (
        "trunk" in parts
        and "compress" not in parts
        and getattr(w, "ndim", 0) in (2, 3)
    )


def _walk(tree, path, fn):
    """Rebuild a dict/list/tuple pytree, giving `fn(path, subtree)` first
    right of refusal at every dict node (return None = recurse)."""
    if isinstance(tree, dict):
        replaced = fn(path, tree)
        if replaced is not None:
            return replaced
        return {
            k: _walk(v, f"{path}/{k}" if path else str(k), fn)
            for k, v in tree.items()
        }
    if isinstance(tree, (list, tuple)):
        seq = [
            _walk(v, f"{path}/{i}" if path else str(i), fn)
            for i, v in enumerate(tree)
        ]
        return type(tree)(seq) if isinstance(tree, tuple) else seq
    return tree


def quantize_tree(
    params,
    select: Optional[Callable[[str, object], bool]] = None,
    *,
    per_channel: bool = True,
):
    """PTQ a parameter pytree: rewrite every selected linear-param dict
    `{"w": (d_in, d_out), ...}` to `{"qw": int8, "scale": f32, ...}`.

    `select(path, w) -> bool` picks weights by named path (default:
    `default_quant_select` — the trunk's dense/projection weights).
    Returns a NEW tree; the fp32 master is untouched. Pure jnp — safe
    under `jax.eval_shape` for chip-free residency accounting."""
    select = default_quant_select if select is None else select

    def visit(path, d):
        w = d.get("w")
        if w is None or getattr(w, "ndim", 0) < 2:
            return None
        if not select(path, w):
            return None
        qw, scale = quantize_weight(w, per_channel=per_channel)
        out = {k: v for k, v in d.items() if k != "w"}
        out["qw"], out["scale"] = qw, scale
        return out

    return _walk(params, "", visit)


def dequantize_tree(params):
    """Inverse structure transform: every `{"qw", "scale", ...}` dict back
    to `{"w": dequantized fp32, ...}` — the pure-XLA reference arm's tree
    (and the restore path for tooling that expects fp32 weights)."""

    def visit(path, d):
        if not is_quantized_linear(d):
            return None
        out = {k: v for k, v in d.items() if k not in ("qw", "scale")}
        out["w"] = dequantize_weight(d["qw"], d["scale"])
        return out

    return _walk(params, "", visit)


# ---------------------------------------------------------------------------
# residency accounting (chip-free: works on ShapeDtypeStructs)
# ---------------------------------------------------------------------------


def tree_weight_bytes(params) -> int:
    """Total resident bytes of a parameter pytree — the weight side of the
    HBM budget a serving replica pays per config tag. Works on concrete
    arrays AND abstract ShapeDtypeStructs (`jax.eval_shape` trees), so
    bench legs can record it with the TPU unreachable."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        size = 1
        for s in leaf.shape:
            size *= int(s)
        total += size * jnp.dtype(leaf.dtype).itemsize
    return total


def quantized_path_bytes(params) -> Tuple[int, int]:
    """(fp32 bytes of the quantizable weights, bytes after PTQ) over the
    DEFAULT selection — the per-tensor residency ratio the acceptance
    gate pins (>= 3.5x on the north-star preset: 4x from int8 minus the
    per-channel scale overhead of 4/d_in)."""
    before = after = 0
    for path, d in iter_linear_dicts(params):
        w = d.get("w")
        if w is not None and getattr(w, "ndim", 0) >= 2 \
                and default_quant_select(path, w):
            n = 1
            for s in w.shape:
                n *= int(s)
            stack = n // (int(w.shape[-2]) * int(w.shape[-1]))
            before += n * jnp.dtype(w.dtype).itemsize
            # int8 values + f32 per-(slice, out-channel) scales
            after += n + stack * int(w.shape[-1]) * 4
        elif is_quantized_linear(d):
            n = 1
            for s in d["qw"].shape:
                n *= int(s)
            before += n * 4
            after += tree_weight_bytes({"qw": d["qw"], "scale": d["scale"]})
    return before, after


def iter_linear_dicts(params, path: str = ""):
    """Yield (path, dict) for every dict node holding a "w" or "qw" leaf."""
    if isinstance(params, dict):
        if "w" in params or "qw" in params:
            yield path, params
            return
        for k, v in params.items():
            yield from iter_linear_dicts(v, f"{path}/{k}" if path else str(k))
    elif isinstance(params, (list, tuple)):
        for i, v in enumerate(params):
            yield from iter_linear_dicts(v, f"{path}/{i}" if path else str(i))


# ---------------------------------------------------------------------------
# mixed-precision matmul: dispatch + XLA reference arm
# ---------------------------------------------------------------------------


# env parsing lives in ops/knobs.py now (one validated definition per
# knob); re-exported for existing importers. No env logic here — the
# af2lint `dispatch` pass enforces that.
from alphafold2_tpu.ops.knobs import (  # noqa: E402
    quant_kernel_disabled as quant_kernel_env_disabled,
    quant_kernel_override,
)


def quant_dispatch(m: int, k: int, n: int, x_dtype, use_kernel) -> bool:
    """Resolve tri-state `use_kernel` into a concrete kernel decision —
    a thin adapter over the ONE resolution point (ops/dispatch.py
    `resolve`, op "quant_matmul"). True forces the kernel (ValueError on
    unsupported shapes/dtypes — forcing must not silently fall back),
    False forces the XLA dequant arm, "auto" = the registry heuristic
    (kernel on TPU for supported shapes), honoring the env kill-switch,
    the legacy AF2_QUANT_KERNEL sweep override, and the
    AF2_KERNEL_BACKEND[_QUANT_MATMUL] overrides."""
    from alphafold2_tpu.ops import dispatch

    return (
        dispatch.resolve("quant_matmul", request=use_kernel,
                         m=m, k=k, n=n, x_dtype=x_dtype)
        == dispatch.ARM_PALLAS_TPU
    )


def quant_matmul_xla(x, qw, scale):
    """Pure-XLA dequant reference arm: materialize the dequantized f32
    weight, matmul with f32 accumulation, cast once at the end — the
    same epilogue math as the kernel (scale in f32 on the f32
    accumulator), paid for with a full fp32 weight copy in HBM. x is 2-D
    (m, k); qw (k, n) int8; scale (n,) f32."""
    w = dequantize_weight(qw, scale)
    y = jax.lax.dot_general(
        x, w,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return y.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _quant_core(x, qw, scale, kernel: bool):
    if kernel:
        from alphafold2_tpu.ops.quant_kernel import quant_matmul_tpu

        return quant_matmul_tpu(x, qw, scale)
    return quant_matmul_xla(x, qw, scale)


def _quant_core_fwd(x, qw, scale, kernel):
    return _quant_core(x, qw, scale, kernel), None


def _quant_core_bwd(kernel, res, g):
    raise NotImplementedError(
        "int8 weight-quantized matmuls are inference-only: differentiating "
        "through quant_matmul would silently train on straight-through "
        "rounding noise. Train on the fp32 master weights "
        "(Alphafold2Config.weight_dtype='f32') and re-quantize post-training."
    )


_quant_core.defvjp(_quant_core_fwd, _quant_core_bwd)


def quant_matmul(x, qw, scale, *, use_kernel="auto", dtype=None):
    """y = x @ dequant(qw, scale), without dequantizing in HBM on the
    kernel path.

    x: (..., d_in) f32/bf16 activations (leading dims flattened for the
    kernel); qw: (d_in, d_out) int8; scale: per-output-channel (d_out,)
    f32, or a scalar per-tensor scale (broadcast). `dtype` casts the
    activations first (the `linear` compute-dtype contract); the output
    is in the activation compute dtype. use_kernel: True / False /
    "auto" (see `quant_dispatch`). Inference-only — the backward raises."""
    if dtype is not None:
        x = x.astype(dtype)
    if qw.ndim != 2:
        raise ValueError(
            f"quant_matmul takes one (d_in, d_out) weight slice, got "
            f"{qw.shape} — stacked (depth, ...) quantized trees are sliced "
            f"by the trunk's lax.scan before reaching the matmul"
        )
    d_in, d_out = qw.shape
    if x.shape[-1] != d_in:
        raise ValueError(
            f"activation feature dim {x.shape[-1]} != weight d_in {d_in}"
        )
    scale = jnp.broadcast_to(
        jnp.asarray(scale, jnp.float32).reshape(-1), (d_out,)
    )
    lead = x.shape[:-1]
    m = 1
    for s in lead:
        m *= int(s)
    x2 = x.reshape(m, d_in)
    kernel = quant_dispatch(m, d_in, d_out, x2.dtype, use_kernel)
    y = _quant_core(x2, qw, scale, kernel)
    return y.reshape(lead + (d_out,))


# ---------------------------------------------------------------------------
# training-side guard
# ---------------------------------------------------------------------------


def reject_quant_training(model_cfg, where: str) -> None:
    """Loudly refuse to build a training path over an int8-weight config.
    Called by every train-state/step constructor (training/harness.py,
    training/e2e.py) BEFORE any tracing, so the failure names the entry
    point instead of surfacing as a custom-vjp error deep in a scan.
    Accepts an Alphafold2Config OR a wrapper carrying one as `.model`
    (E2EConfig) — the harness builders take either."""
    model_cfg = getattr(model_cfg, "model", model_cfg)
    if getattr(model_cfg, "weight_dtype", "f32") == "int8":
        raise ValueError(
            f"{where}: weight_dtype='int8' is the inference-only serving "
            f"arm (per-channel PTQ over frozen weights, non-differentiable "
            f"by construction); train with weight_dtype='f32' and quantize "
            f"post-training (ops/quant.py quantize_tree)"
        )
