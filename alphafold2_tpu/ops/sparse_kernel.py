"""Pallas TPU kernel for block-sparse attention.

The performance path for ops/sparse.py's variable-sparsity attention —
the TPU replacement for DeepSpeed's CUDA/Triton block-sparse kernels
(reference alphafold2_pytorch/alphafold2.py:194-208). FlashAttention-style
streaming softmax over only the ACTIVE key blocks of each query block:
logits never materialize in HBM, VMEM holds one (block x block) tile at a
time, and the active-block index table rides in SMEM via scalar prefetch.

Forward is the Pallas kernel; backward currently reuses the XLA
block-gather path's gradient (ops/sparse.py) through jax.custom_vjp — the
two compute identical math, so gradients are exact. A native Pallas
backward (dq / dkv kernels exploiting the layout's symmetry) is the
planned optimization.

On non-TPU backends the kernel runs in interpreter mode (tests), keeping
one code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from alphafold2_tpu.ops.sparse import (
    SparseConfig,
    block_sparse_attention,
    layout_block_indices,
)

_NEG = -1e9  # additive mask value (attn_mask_mode='add', reference :208)


def _kernel(idx_ref, q_ref, k_ref, v_ref, bias_ref, out_ref, *, bs, dh, A, scale):
    qb = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # (bs, dh)

    def body(a, carry):
        m, l, acc = carry
        kidx = idx_ref[qb, a]

        def active(carry):
            m, l, acc = carry
            start = kidx * bs
            k = k_ref[0, pl.ds(start, bs), :].astype(jnp.float32)  # (bs, dh)
            v = v_ref[0, pl.ds(start, bs), :].astype(jnp.float32)
            b = bias_ref[0, pl.ds(start, bs)]  # (bs,)
            s = jax.lax.dot_general(
                q, k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale + b[None, :]
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * alpha + jnp.dot(
                p, v, preferred_element_type=jnp.float32
            )
            return m_new, l_new, acc_new

        return jax.lax.cond(kidx >= 0, active, lambda c: c, (m, l, acc))

    m0 = jnp.full((bs, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bs, 1), jnp.float32)
    acc0 = jnp.zeros((bs, dh), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, A, body, (m0, l0, acc0))

    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    out_ref[0] = out.astype(out_ref.dtype)


def _forward(q, k, v, scfg: SparseConfig, mask):
    b, n, h, dh = q.shape
    bs = scfg.block_size
    B = n // bs
    scale = dh ** -0.5

    idx_np, valid_np = layout_block_indices(B, scfg)
    idx = jnp.asarray(np.where(valid_np, idx_np, -1))
    A = idx.shape[1]

    # (b*h, n, dh) layout; bias (b, n) additive
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    if mask is None:
        bias = jnp.zeros((b, n), jnp.float32)
    else:
        bias = jnp.where(mask, 0.0, _NEG).astype(jnp.float32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * h, B),
        in_specs=[
            pl.BlockSpec((1, bs, dh), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, n, dh), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, n, dh), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, n), lambda i, j, *_: (i // h, 0)),
        ],
        out_specs=pl.BlockSpec((1, bs, dh), lambda i, j, *_: (i, j, 0)),
    )

    interpret = jax.devices()[0].platform != "tpu"
    out = pl.pallas_call(
        functools.partial(_kernel, bs=bs, dh=dh, A=A, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b * h, n, dh), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(idx, qh, kh, vh, bias)

    return out.reshape(b, h, n, dh).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def block_sparse_attention_tpu(q, k, v, scfg: SparseConfig, mask=None):
    """Same contract as ops.sparse.block_sparse_attention, Pallas forward."""
    return _forward(q, k, v, scfg, mask)


def _fwd(q, k, v, scfg, mask):
    return _forward(q, k, v, scfg, mask), (q, k, v, mask)


def _bwd(scfg, res, g):
    q, k, v, mask = res
    _, vjp = jax.vjp(
        lambda q, k, v: block_sparse_attention(q, k, v, scfg, mask=mask), q, k, v
    )
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


block_sparse_attention_tpu.defvjp(_fwd, _bwd)
