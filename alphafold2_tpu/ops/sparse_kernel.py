"""Pallas TPU kernels for block-sparse attention (forward + backward).

The performance path for ops/sparse.py's variable-sparsity attention —
the TPU replacement for DeepSpeed's CUDA/Triton block-sparse kernels
(reference alphafold2_pytorch/alphafold2.py:194-208). FlashAttention-style
streaming softmax over only the ACTIVE key blocks of each query block:
logits never materialize in HBM, VMEM holds one (block x block) tile at a
time, and the active-block index table rides in SMEM via scalar prefetch.

Streaming layout (same design as the dense kernel, ops/flash_kernel.py):
a 3-D grid whose LAST dimension walks the active-slot table sequentially
with running statistics in VMEM scratch — and the scalar-prefetched index
table drives the K/V (or Q/G) BLOCK FETCHES THEMSELVES through the
BlockSpec index maps, so Mosaic's pipeline double-buffers exactly the
blocks the sparsity pattern touches. Inactive (padded) slots fetch block
0 and are skipped under `pl.when`. Nothing is fully VMEM-resident per
grid row except the f32 row vectors (bias, lse, delta).

Backward is also Pallas: the forward additionally emits the per-row
log-sum-exp, and two kernels recompute tile logits to accumulate dq (over
a query block's active key blocks) and dk/dv (over a key block's active
query blocks). The dk/dv kernel reuses the SAME index table by exploiting
the layout's bidirectional symmetry, which sparsity_layout guarantees by
construction (ops/sparse.py `layout |= layout.T`; the reference sparsity
config is likewise bidirectional, alphafold2.py:204).

Numerics follow ops/flash_kernel.py: finite running-max sentinel (_M0) so
masked logits underflow to exact 0 with no nan-guard passes; dots take
operands in the INPUT dtype with f32 accumulation (bf16 MXU peak). On
non-TPU backends the kernels run in interpreter mode (tests), keeping one
code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu import compat
from alphafold2_tpu.compat import pallas as pl, pallas_tpu as pltpu
from alphafold2_tpu.ops.core import pallas_interpret as _interpret
from alphafold2_tpu.ops.sparse import (
    SparseConfig,
    layout_block_indices,
)

# masked keys are -inf (exact zero attention after exp); the reference's
# DeepSpeed config used additive -1e9 (attn_mask_mode='add', reference :208),
# which leaks O(ulp) attention to masked keys at float32 — we don't copy that
_NEG = float("-inf")
# finite running-max sentinel (see ops/flash_kernel.py _M0)
_M0 = -1e30

# Backward kernels: outputs are private per (row, block) pair — first two
# grid dims parallel, streamed slot dim sequential.
_BWD_PARAMS = compat.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary")
)
# Forward: the lse output window (1, B, bs) is SHARED across the
# query-block dim, so it must not split across megacore cores (see
# ops/flash_kernel.py _FWD_PARAMS).
_FWD_PARAMS = compat.CompilerParams(
    dimension_semantics=("parallel", "arbitrary", "arbitrary")
)


def _active_block(idx_ref, r, a):
    """BlockSpec index helper: the a-th active block of row r (block 0 for
    padded slots — the kernel body skips them under pl.when)."""
    return jnp.maximum(idx_ref[r, a], 0)


def _specs(bs: int, dh: int, B: int, h: int):
    """The four BlockSpec shapes shared by all three kernels: a row's OWN
    block, the table-driven ACTIVE block, a resident (1, B, bs) row
    vector, and the per-batch bias (bias has no head axis -> i // h)."""
    own = pl.BlockSpec((1, bs, dh), lambda i, j, a, idx: (i, j, 0))
    active = pl.BlockSpec(
        (1, bs, dh), lambda i, j, a, idx: (i, _active_block(idx, j, a), 0)
    )
    row_full = pl.BlockSpec((1, B, bs), lambda i, j, a, idx: (i, 0, 0))
    bias_full = pl.BlockSpec((1, B, bs), lambda i, j, a, idx: (i // h, 0, 0))
    return own, active, row_full, bias_full


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(idx_ref, q_ref, k_ref, v_ref, bias_ref, out_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, A, scale):
    qi = pl.program_id(1)
    a = pl.program_id(2)
    kidx = idx_ref[qi, a]

    @pl.when(a == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _M0, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    @pl.when(kidx >= 0)
    def _active():
        q = q_ref[0]          # (bs, dh), input dtype
        k = k_ref[0]          # the a-th active key block, fetched by the
        v = v_ref[0]          # index map from the prefetched table
        b = bias_ref[0, kidx]  # (bs,)
        s = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale + b[None, :]
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_scr[...] = m_new

    @pl.when(a == A - 1)
    def _finish():
        l = l_scr[...]
        safe = jnp.where(l > 0, l, 1.0)
        out_ref[0] = jnp.where(l > 0, acc_scr[...] / safe, 0.0).astype(
            out_ref.dtype
        )
        # +inf for rows with no active mass: exp(s - inf) = 0 zeroes every
        # recomputed p in the backward. lse rides in a resident (1, B, bs)
        # block (Mosaic rejects (1, bs) row blocks); each qi writes its slot
        lse = jnp.where(l > 0, m_scr[...] + jnp.log(safe), jnp.inf)
        lse_ref[0, qi] = lse[:, 0]


def _forward(q, k, v, scfg: SparseConfig, mask):
    b, n, h, dh = q.shape
    bs = scfg.block_size
    B = n // bs
    scale = dh ** -0.5

    idx_np, valid_np = layout_block_indices(B, scfg)
    idx = jnp.asarray(np.where(valid_np, idx_np, -1))
    A = idx.shape[1]

    # (b*h, n, dh) layout; bias (b, n) additive
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    if mask is None:
        bias = jnp.zeros((b, B, bs), jnp.float32)
    else:
        bias = jnp.where(mask, 0.0, _NEG).astype(jnp.float32).reshape(b, B, bs)

    own, active, row_full, bias_full = _specs(bs, dh, B, h)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * h, B, A),
        in_specs=[own, active, active, bias_full],
        out_specs=[own, row_full],
        scratch_shapes=[
            pltpu.VMEM((bs, 1), jnp.float32),
            pltpu.VMEM((bs, 1), jnp.float32),
            pltpu.VMEM((bs, dh), jnp.float32),
        ],
    )

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, A=A, scale=scale),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, n, dh), q.dtype),
            jax.ShapeDtypeStruct((b * h, B, bs), jnp.float32),
        ],
        grid_spec=grid_spec,
        compiler_params=_FWD_PARAMS,
        interpret=_interpret(),
    )(idx, qh, kh, vh, bias)

    return out.reshape(b, h, n, dh).transpose(0, 2, 1, 3), (out, lse)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(idx_ref, q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref,
               delta_ref, dq_ref, dq_scr, *, A, scale):
    qi = pl.program_id(1)
    a = pl.program_id(2)
    kidx = idx_ref[qi, a]

    @pl.when(a == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    @pl.when(kidx >= 0)
    def _active():
        q = q_ref[0]
        g = g_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        b = bias_ref[0, kidx]
        lse = lse_ref[0, qi][:, None]
        delta = delta_ref[0, qi][:, None]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale + b[None, :]
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            g, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta)).astype(k.dtype)
        dq_scr[...] = dq_scr[...] + jnp.dot(
            ds, k, preferred_element_type=jnp.float32
        )

    @pl.when(a == A - 1)
    def _finish():
        dq_ref[0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(idx_ref, q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, A, scale):
    # grid position 1 indexes a KEY block; by layout symmetry idx[kb] lists
    # exactly the query blocks attending to it, and the index maps fetch
    # the a-th such Q/G block
    kb = pl.program_id(1)
    a = pl.program_id(2)
    qidx = idx_ref[kb, a]

    @pl.when(a == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    @pl.when(qidx >= 0)
    def _active():
        k = k_ref[0]                      # (bs, dh)
        v = v_ref[0]
        q = q_ref[0]                      # the a-th active query block
        g = g_ref[0]
        b = bias_ref[0, kb]               # (bs,)
        lse = lse_ref[0, qidx][:, None]
        delta = delta_ref[0, qidx][:, None]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale + b[None, :]
        p = jnp.exp(s - lse)              # (bs_q, bs_k) f32
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p.astype(g.dtype), g, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            g, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(a == A - 1)
    def _finish():
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _backward_pallas(q, k, v, scfg, mask, out_flat, lse, g):
    b, n, h, dh = q.shape
    bs = scfg.block_size
    B = n // bs
    scale = dh ** -0.5

    idx_np, valid_np = layout_block_indices(B, scfg)
    idx = jnp.asarray(np.where(valid_np, idx_np, -1))
    A = idx.shape[1]

    qh = q.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    gh = g.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    if mask is None:
        bias = jnp.zeros((b, B, bs), jnp.float32)
    else:
        bias = jnp.where(mask, 0.0, _NEG).astype(jnp.float32).reshape(b, B, bs)

    # delta_i = rowsum(dO_i * O_i), the softmax-jacobian diagonal term
    delta = jnp.sum(
        gh.astype(jnp.float32) * out_flat.astype(jnp.float32), axis=-1
    ).reshape(b * h, B, bs)

    own, active, row_full, bias_full = _specs(bs, dh, B, h)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, A=A, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b * h, n, dh), q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h, B, A),
            in_specs=[own, active, active, bias_full, own, row_full, row_full],
            out_specs=own,
            scratch_shapes=[pltpu.VMEM((bs, dh), jnp.float32)],
        ),
        compiler_params=_BWD_PARAMS,
        interpret=_interpret(),
    )(idx, qh, kh, vh, bias, gh, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, A=A, scale=scale),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, n, dh), k.dtype),
            jax.ShapeDtypeStruct((b * h, n, dh), v.dtype),
        ],
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h, B, A),
            in_specs=[active, own, own, bias_full, active, row_full, row_full],
            out_specs=[own, own],
            scratch_shapes=[
                pltpu.VMEM((bs, dh), jnp.float32),
                pltpu.VMEM((bs, dh), jnp.float32),
            ],
        ),
        compiler_params=_BWD_PARAMS,
        interpret=_interpret(),
    )(idx, qh, kh, vh, bias, gh, lse, delta)

    def unflat(t):
        return t.reshape(b, h, n, dh).transpose(0, 2, 1, 3)

    return unflat(dq), unflat(dk), unflat(dv)


# ---------------------------------------------------------------------------
# custom_vjp wiring
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def block_sparse_attention_tpu(q, k, v, scfg: SparseConfig, mask=None):
    """Same contract as ops.sparse.block_sparse_attention, Pallas kernels."""
    out, _ = _forward(q, k, v, scfg, mask)
    return out


def _fwd(q, k, v, scfg, mask):
    out, (out_flat, lse) = _forward(q, k, v, scfg, mask)
    return out, (q, k, v, mask, out_flat, lse)


def _bwd(scfg, res, g):
    # the dkv kernel's index-table reuse relies on the layout being
    # symmetric, which sparsity_layout guarantees unconditionally
    # (ops/sparse.py symmetrizes with `layout |= layout.T`)
    q, k, v, mask, out_flat, lse = res
    dq, dk, dv = _backward_pallas(q, k, v, scfg, mask, out_flat, lse, g)
    return dq, dk, dv, None


block_sparse_attention_tpu.defvjp(_fwd, _bwd)
