"""Pallas TPU kernels for block-sparse attention (forward + backward).

The performance path for ops/sparse.py's variable-sparsity attention —
the TPU replacement for DeepSpeed's CUDA/Triton block-sparse kernels
(reference alphafold2_pytorch/alphafold2.py:194-208). FlashAttention-style
streaming softmax over only the ACTIVE key blocks of each query block:
logits never materialize in HBM, VMEM holds one (block x block) tile at a
time, and the active-block index table rides in SMEM via scalar prefetch.

Backward is also Pallas: the forward additionally emits the per-row
log-sum-exp, and two kernels recompute tile logits to accumulate dq (over
a query block's active key blocks) and dk/dv (over a key block's active
query blocks). The dk/dv kernel reuses the SAME index table by exploiting
the layout's bidirectional symmetry, which sparsity_layout guarantees by
construction (ops/sparse.py `layout |= layout.T`; the reference sparsity
config is likewise bidirectional, alphafold2.py:204).

On non-TPU backends the kernels run in interpreter mode (tests), keeping
one code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from alphafold2_tpu.ops.core import pallas_interpret as _interpret
from alphafold2_tpu.ops.sparse import (
    SparseConfig,
    layout_block_indices,
)

# masked keys are -inf (exact zero attention after exp); the reference's
# DeepSpeed config used additive -1e9 (attn_mask_mode='add', reference :208),
# which leaks O(ulp) attention to masked keys at float32 — we don't copy that
_NEG = float("-inf")
# finite running-max sentinel (see ops/flash_kernel.py _M0)
_M0 = -1e30





# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(idx_ref, q_ref, k_ref, v_ref, bias_ref, out_ref, lse_ref,
                *, bs, dh, A, scale):
    qb = pl.program_id(1)
    # operands stay in the input dtype; dots accumulate f32 via
    # preferred_element_type — bf16 operands keep the MXU bf16 peak
    q = q_ref[0]  # (bs, dh)

    def body(a, carry):
        m, l, acc = carry
        kidx = idx_ref[qb, a]

        def active(carry):
            m, l, acc = carry
            start = kidx * bs
            k = k_ref[0, pl.ds(start, bs), :]  # (bs, dh)
            v = v_ref[0, pl.ds(start, bs), :]
            b = bias_ref[0, kidx]  # (bs,)
            s = jax.lax.dot_general(
                q, k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale + b[None, :]
            # finite running-max sentinel (_M0): m - m_new is never
            # (-inf) - (-inf), masked logits reach exp as -inf and
            # underflow to exact 0 — no per-tile isneginf/where passes
            # (same recurrence as ops/flash_kernel.py)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * alpha + jnp.dot(
                p.astype(v.dtype), v, preferred_element_type=jnp.float32
            )
            return m_new, l_new, acc_new

        return jax.lax.cond(kidx >= 0, active, lambda c: c, (m, l, acc))

    m0 = jnp.full((bs, 1), _M0, jnp.float32)
    l0 = jnp.zeros((bs, 1), jnp.float32)
    acc0 = jnp.zeros((bs, dh), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, A, body, (m0, l0, acc0))

    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    out_ref[0] = out.astype(out_ref.dtype)
    # +inf for rows with no active mass: exp(s - inf) = 0 zeroes every
    # recomputed p in the backward, matching the zeroed forward output.
    # lse rides in a (1, B, bs) block fully covering its last two dims
    # (Mosaic tiling forbids (1, bs) row blocks); each grid step writes
    # its own B-slot
    lse = jnp.where(l > 0, m + jnp.log(jnp.where(l > 0, l, 1.0)), jnp.inf)
    lse_ref[0, qb] = lse[:, 0]


def _forward(q, k, v, scfg: SparseConfig, mask):
    b, n, h, dh = q.shape
    bs = scfg.block_size
    B = n // bs
    scale = dh ** -0.5

    idx_np, valid_np = layout_block_indices(B, scfg)
    idx = jnp.asarray(np.where(valid_np, idx_np, -1))
    A = idx.shape[1]

    # (b*h, n, dh) layout; bias (b, n) additive
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    if mask is None:
        bias = jnp.zeros((b, B, bs), jnp.float32)
    else:
        bias = jnp.where(mask, 0.0, _NEG).astype(jnp.float32).reshape(b, B, bs)

    # row vectors (bias, lse) travel as (.., B, bs) 3-D views whose last two
    # dims are fully covered by their blocks — Mosaic's tiling constraint
    # rejects (1, bs) / (1, n) row blocks over 2-D arrays
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * h, B),
        in_specs=[
            pl.BlockSpec((1, bs, dh), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, n, dh), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, n, dh), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, B, bs), lambda i, j, *_: (i // h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bs, dh), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, B, bs), lambda i, j, *_: (i, 0, 0)),
        ],
    )

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, bs=bs, dh=dh, A=A, scale=scale),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, n, dh), q.dtype),
            jax.ShapeDtypeStruct((b * h, B, bs), jnp.float32),
        ],
        grid_spec=grid_spec,
        interpret=_interpret(),
    )(idx, qh, kh, vh, bias)

    return out.reshape(b, h, n, dh).transpose(0, 2, 1, 3), (out, lse)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(idx_ref, q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref,
               delta_ref, dq_ref, *, bs, dh, A, scale):
    qb = pl.program_id(1)
    q = q_ref[0]                               # (bs, dh)
    g = g_ref[0]                               # (bs, dh)
    lse = lse_ref[0, qb][:, None]             # (bs, 1)
    delta = delta_ref[0, qb][:, None]         # (bs, 1)

    def body(a, dq):
        kidx = idx_ref[qb, a]

        def active(dq):
            start = kidx * bs
            k = k_ref[0, pl.ds(start, bs), :]
            v = v_ref[0, pl.ds(start, bs), :]
            b = bias_ref[0, kidx]
            s = jax.lax.dot_general(
                q, k, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale + b[None, :]
            p = jnp.exp(s - lse)               # (bs_q, bs_k)
            dp = jax.lax.dot_general(
                g, v, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                   # (bs_q, bs_k)
            ds = (p * (dp - delta)).astype(k.dtype)
            return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

        return jax.lax.cond(kidx >= 0, active, lambda d: d, dq)

    dq = jax.lax.fori_loop(0, A, body, jnp.zeros((bs, dh), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(idx_ref, q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, *, bs, dh, A, scale):
    # grid position j indexes a KEY block; by layout symmetry idx[j] lists
    # exactly the query blocks attending to it
    jb = pl.program_id(1)
    k = k_ref[0]                               # (bs, dh)
    v = v_ref[0]                               # (bs, dh)
    b = bias_ref[0, jb]                        # (bs,)

    def body(a, carry):
        dk, dv = carry
        qidx = idx_ref[jb, a]

        def active(carry):
            dk, dv = carry
            start = qidx * bs
            q = q_ref[0, pl.ds(start, bs), :]
            g = g_ref[0, pl.ds(start, bs), :]
            lse = lse_ref[0, qidx][:, None]
            delta = delta_ref[0, qidx][:, None]
            s = jax.lax.dot_general(
                q, k, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale + b[None, :]
            p = jnp.exp(s - lse)               # (bs_q, bs_k)
            dv_new = dv + jax.lax.dot_general(
                p.astype(g.dtype), g,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                   # (bs_k, dh)
            dp = jax.lax.dot_general(
                g, v, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = (p * (dp - delta)).astype(q.dtype)  # (bs_q, bs_k)
            dk_new = dk + jax.lax.dot_general(
                ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                   # (bs_k, dh)
            return dk_new, dv_new

        return jax.lax.cond(qidx >= 0, active, lambda c: c, carry)

    zero = jnp.zeros((bs, dh), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, A, body, (zero, zero))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _backward_pallas(q, k, v, scfg, mask, out_flat, lse, g):
    b, n, h, dh = q.shape
    bs = scfg.block_size
    B = n // bs
    scale = dh ** -0.5

    idx_np, valid_np = layout_block_indices(B, scfg)
    idx = jnp.asarray(np.where(valid_np, idx_np, -1))
    A = idx.shape[1]

    qh = q.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    gh = g.transpose(0, 2, 1, 3).reshape(b * h, n, dh)
    if mask is None:
        bias = jnp.zeros((b, B, bs), jnp.float32)
    else:
        bias = jnp.where(mask, 0.0, _NEG).astype(jnp.float32).reshape(b, B, bs)

    # delta_i = rowsum(dO_i * O_i), the softmax-jacobian diagonal term
    delta = jnp.sum(
        gh.astype(jnp.float32) * out_flat.astype(jnp.float32), axis=-1
    ).reshape(b * h, B, bs)

    full = pl.BlockSpec((1, n, dh), lambda i, j, *_: (i, 0, 0))
    blk = pl.BlockSpec((1, bs, dh), lambda i, j, *_: (i, j, 0))
    row_full = pl.BlockSpec((1, B, bs), lambda i, j, *_: (i, 0, 0))
    bias_full = pl.BlockSpec((1, B, bs), lambda i, j, *_: (i // h, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, bs=bs, dh=dh, A=A, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b * h, n, dh), q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h, B),
            in_specs=[blk, full, full, bias_full, blk, row_full, row_full],
            out_specs=blk,
        ),
        interpret=_interpret(),
    )(idx, qh, kh, vh, bias, gh, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, bs=bs, dh=dh, A=A, scale=scale),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, n, dh), k.dtype),
            jax.ShapeDtypeStruct((b * h, n, dh), v.dtype),
        ],
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h, B),
            in_specs=[full, blk, blk, bias_full, full, row_full, row_full],
            out_specs=[blk, blk],
        ),
        interpret=_interpret(),
    )(idx, qh, kh, vh, bias, gh, lse, delta)

    def unflat(t):
        return t.reshape(b, h, n, dh).transpose(0, 2, 1, 3)

    return unflat(dq), unflat(dk), unflat(dv)


# ---------------------------------------------------------------------------
# custom_vjp wiring
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def block_sparse_attention_tpu(q, k, v, scfg: SparseConfig, mask=None):
    """Same contract as ops.sparse.block_sparse_attention, Pallas kernels."""
    out, _ = _forward(q, k, v, scfg, mask)
    return out


def _fwd(q, k, v, scfg, mask):
    out, (out_flat, lse) = _forward(q, k, v, scfg, mask)
    return out, (q, k, v, mask, out_flat, lse)


def _bwd(scfg, res, g):
    # the dkv kernel's index-table reuse relies on the layout being
    # symmetric, which sparsity_layout guarantees unconditionally
    # (ops/sparse.py symmetrizes with `layout |= layout.T`)
    q, k, v, mask, out_flat, lse = res
    dq, dk, dv = _backward_pallas(q, k, v, scfg, mask, out_flat, lse, g)
    return dq, dk, dv, None


block_sparse_attention_tpu.defvjp(_fwd, _bwd)
