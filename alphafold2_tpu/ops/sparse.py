"""Block-sparse self-attention (variable sparsity layout).

TPU-native replacement for the reference's DeepSpeed `SparseSelfAttention`
with `VariableSparsityConfig` (reference alphafold2_pytorch/alphafold2.py:
183-238): block size 16, bidirectional, random blocks defaulting to
`max_seq_len // block // 4`, additive key-padding mask. The CUDA/Triton
kernels DeepSpeed builds (reference install_deepspeed.sh) are replaced by:

  * a static block LAYOUT (local group + global + random blocks, mirroring
    the structure of DeepSpeed's VariableSparsityConfig defaults:
    num_local_blocks=4, num_global_blocks=1) computed host-side;
  * a block-GATHER attention in pure XLA: per query block, only its active
    key blocks are gathered and attended — compute/memory O(n · A · block)
    instead of O(n²), static shapes, fully differentiable (no custom
    kernel needed for the bwd: XLA differentiates the gather);
  * a Pallas TPU kernel fast path for the same computation
    (ops/sparse_kernel.py).

Deliberate divergences from the reference (documented):
  * the reference DISCARDS the user's mask whenever padding is needed
    (it rebuilds an all-ones mask, reference alphafold2.py:218-221) — we
    honor the caller's mask and extend it with padding;
  * the reference also computes full dense attention logits that are never
    used (dead compute, reference alphafold2.py:227) — not reproduced;
  * DeepSpeed samples random blocks per head with torch's global RNG; our
    random blocks are deterministic per (layout_seed, row).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from alphafold2_tpu.ops.core import dropout, linear


@dataclasses.dataclass(frozen=True)
class SparseConfig:
    """Static sparsity hyper-parameters (hashable, jit-static)."""

    block_size: int = 16  # reference alphafold2.py:187
    num_random_blocks: Optional[int] = None  # None: max_seq_len//block//4
    num_local_blocks: int = 4  # DeepSpeed VariableSparsityConfig default
    num_global_blocks: int = 1  # DeepSpeed VariableSparsityConfig default
    layout_seed: int = 0
    max_seq_len: int = 2048  # reference alphafold2.py:333


@functools.lru_cache(maxsize=64)
def sparsity_layout(num_blocks: int, scfg: SparseConfig) -> np.ndarray:
    """(num_blocks, num_blocks) bool block-connectivity, bidirectional.

    Local: blocks attend within their group of `num_local_blocks`.
    Global: the first `num_global_blocks` blocks attend everywhere and are
    attended by everyone. Random: `num_random_blocks` extra key blocks per
    query row (symmetrized for bidirectionality).
    """
    B = num_blocks
    nl = scfg.num_local_blocks
    ng = min(scfg.num_global_blocks, B)
    nr = scfg.num_random_blocks
    if nr is None:
        nr = scfg.max_seq_len // scfg.block_size // 4  # reference :197
    nr = min(nr, B)

    layout = np.zeros((B, B), dtype=bool)
    for g in range(0, B, nl):
        layout[g : g + nl, g : g + nl] = True
    layout[:, :ng] = True
    layout[:ng, :] = True
    rng = np.random.RandomState(scfg.layout_seed)
    for i in range(B):
        cols = rng.choice(B, size=nr, replace=False)
        layout[i, cols] = True
    # bidirectional symmetry
    layout |= layout.T
    return layout


@functools.lru_cache(maxsize=64)
def layout_block_indices(num_blocks: int, scfg: SparseConfig):
    """Per-row active key-block indices, padded to the max row population.

    Returns (idx, valid): int32 (B, A) and bool (B, A). Cached per
    (num_blocks, config) — static at trace time.
    """
    layout = sparsity_layout(num_blocks, scfg)
    counts = layout.sum(axis=1)
    A = int(counts.max())
    idx = np.zeros((num_blocks, A), np.int32)
    valid = np.zeros((num_blocks, A), bool)
    for i in range(num_blocks):
        cols = np.nonzero(layout[i])[0]
        idx[i, : len(cols)] = cols
        valid[i, : len(cols)] = True
    return idx, valid


def block_sparse_attention(
    q,
    k,
    v,
    scfg: SparseConfig,
    *,
    mask=None,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    rng=None,
):
    """Block-sparse attention over pre-projected q/k/v.

    Args:
      q, k, v: (b, n, h, dh) with n a multiple of scfg.block_size.
      mask: (b, n) bool key validity (additive -inf semantics, matching
        DeepSpeed attn_mask_mode='add', reference alphafold2.py:208).

    Returns: (b, n, h, dh).
    """
    b, n, h, dh = q.shape
    bs = scfg.block_size
    assert n % bs == 0, f"sequence {n} not a multiple of block {bs}"
    B = n // bs
    scale = dh ** -0.5 if scale is None else scale

    idx_np, valid_np = layout_block_indices(B, scfg)
    idx = jnp.asarray(idx_np)
    valid = jnp.asarray(valid_np)
    A = idx.shape[1]

    # blocked views: (b, B, bs, h, dh)
    qb = q.reshape(b, B, bs, h, dh)
    kb = k.reshape(b, B, bs, h, dh)
    vb = v.reshape(b, B, bs, h, dh)

    # gather active key/value blocks per query row: (b, B, A, bs, h, dh)
    kg = jnp.take(kb, idx, axis=1)
    vg = jnp.take(vb, idx, axis=1)

    logits = jnp.einsum("bqihd,bqajhd->bhqiaj", qb, kg) * scale

    # key-validity: padded active slots + caller's key padding mask
    key_ok = valid[None, None, :, None, :, None]  # (1,1,B,1,A,1)
    if mask is not None:
        mb = mask.reshape(b, B, bs)
        mg = jnp.take(mb, idx, axis=1)  # (b, B, A, bs)
        key_ok = key_ok & mg[:, None, :, None, :, :]
    neg = jnp.finfo(jnp.float32).min
    logits = jnp.where(key_ok, logits, neg)

    attn = jax.nn.softmax(logits.astype(jnp.float32), axis=(-2, -1)).astype(q.dtype)
    attn = dropout(rng, attn, dropout_rate)
    out = jnp.einsum("bhqiaj,bqajhd->bqihd", attn, vg)
    out = out.reshape(b, n, h, dh)

    # query rows with NO valid key anywhere return zeros (not an arbitrary
    # uniform average over gathered slots) — the same contract as the
    # sequence-parallel primitives (parallel/sequence.py) and the Pallas
    # kernel, giving exact zero gradients for fully-padded rows
    if mask is not None:
        row_ok = jnp.any(
            valid[None, :, :, None] & jnp.take(mask.reshape(b, B, bs), idx, axis=1),
            axis=(-2, -1),
        )  # (b, B)
        row_ok = jnp.repeat(row_ok, bs, axis=1)  # (b, n)
        out = jnp.where(row_ok[:, :, None, None], out, 0.0)
    return out


def sparse_attention_apply(
    params,
    cfg,
    scfg: SparseConfig,
    x,
    *,
    mask=None,
    rng=None,
    use_kernel="auto",
):
    """Drop-in sparse counterpart of `attention_apply` for SELF-attention.

    Shares the dense attention's parameters (to_q / to_kv / to_out) — the
    sparsity only changes the attention pattern, exactly as the reference's
    SparseAttention subclasses Attention (reference alphafold2.py:183).
    Pads to a block multiple and unpads on exit (reference :216-222, but
    honoring the caller's mask — see module docstring).

    use_kernel: True / False / "auto". "auto" picks the Pallas kernel for
    long sequences, where it avoids materializing the gathered K/V blocks
    (measured on v5e @ block=128: kernel 2.2x faster at n=8192, XLA path
    ~1.3x faster at n=2048 — crossover around n=4096).
    """
    b, n, _ = x.shape
    # ONE resolution point (ops/dispatch.py, op "sparse_attention"):
    # the shared AF2_DISABLE_FLASH_KERNEL kill-switch covers every
    # flash-family Pallas arm, AF2_KERNEL_BACKEND[_SPARSE_ATTENTION]
    # forces an arm, and auto picks the kernel only on real TPUs past
    # the measured n >= 4096 crossover (off-TPU it would run in the
    # Pallas interpreter, orders of magnitude slower than the XLA path)
    from alphafold2_tpu.ops import dispatch

    use_kernel = (
        dispatch.resolve("sparse_attention", request=use_kernel, n=n)
        == dispatch.ARM_PALLAS_TPU
    )
    dtype = cfg.dtype
    bs = scfg.block_size

    q = linear(params["to_q"], x, dtype=dtype)
    kv = linear(params["to_kv"], x, dtype=dtype)
    k, v = jnp.split(kv, 2, axis=-1)

    h, dh = cfg.heads, cfg.dim_head

    pad = (-n) % bs
    if pad:
        q, k, v = (jnp.pad(t, ((0, 0), (0, pad), (0, 0))) for t in (q, k, v))
        if mask is None:
            mask = jnp.ones((b, n), bool)
        mask = jnp.pad(mask, ((0, 0), (0, pad)))

    def split_heads(t):
        return t.reshape(b, t.shape[1], h, dh)

    # the streaming kernel does not implement attention-weight dropout;
    # fall back to the XLA path when dropout is live so the two paths
    # always compute the same function
    if use_kernel and (rng is None or cfg.dropout == 0.0):
        from alphafold2_tpu.ops.sparse_kernel import block_sparse_attention_tpu

        out = block_sparse_attention_tpu(
            split_heads(q), split_heads(k), split_heads(v), scfg, mask
        )
    else:
        out = block_sparse_attention(
            split_heads(q),
            split_heads(k),
            split_heads(v),
            scfg,
            mask=mask,
            dropout_rate=cfg.dropout,
            rng=rng,
        )
    out = out.reshape(b, out.shape[1], h * dh)[:, :n]
    return linear(params["to_out"], out, dtype=dtype)
