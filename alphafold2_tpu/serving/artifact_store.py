"""Fleet-wide artifact store: content-addressed result/feature cache.

ISSUE 17's tentpole, the first tier that makes the FLEET — not a
replica — the unit of memoization. Every cache below this one is
process-local: the result LRU lives per engine (serving/cache.py),
coalescing happens per replica, and the featurize tier recomputes
features any replica has already seen. At millions of users the
traffic is heavily redundant (popular proteins, proteome sweeps,
retried submissions) and the cheapest request is the one that never
touches a chip, so redundancy absorbed HERE is chip capacity returned
to the fleet — measured directly by the PR 15 cost plane as a drop in
amortized chip-seconds per request.

Two levels, one content-addressed keyspace:

  * an in-memory HOT RING — an LRU bounded by entries AND bytes,
    shared by every pool of the fleet;
  * a DISK tier (optional: ``ArtifactStoreConfig.root``, deployed as a
    sibling of ``--flight-dir``) that survives restarts and is shared
    by every serving process pointed at it.

Keys are the existing ``request_key`` scheme (serving/cache.py)
extended with a STORE TAG that folds in the PR 13 dispatch
``resolution_tag`` and the deploy's ``params_tag`` (plus everything
else that moves the numerics: model config, MDS knobs, bucket ladder,
SP plan inputs) — so a rolling update or a kernel-resolution change
re-keys the whole tier and stale entries become unreachable rather
than wrong. On disk each tag gets its own directory
(``<root>/<kind>/<tag-digest>/<content-hash>.art``), which is what
lets the budget sweep garbage-collect a retired deploy's entries
wholesale (`sweep`).

Persistence is write-to-temp + ``os.replace`` (atomic on POSIX: a
reader never sees a half-written file under the final name) and every
payload carries a sha256 over its bytes, verified on read. Any
corruption — torn tail, truncation, poisoned bytes, a file evicted
mid-read by another process's sweep — counts into
``cache_corrupt_total``, deletes the bad entry, and reads as a MISS:
the degradation mode is recompute, never a wrong or partial answer.

Thread safety: one lock guards the hot ring and the counters; all
disk I/O and (de)serialization happen OUTSIDE it, so a slow disk can
never stall a reader that the ring could have served. ``_sweep_lock``
serializes sweeps and is never taken under ``_lock``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
import tempfile
import threading
from collections import OrderedDict
from typing import Iterable, Optional, Tuple

import numpy as np

from alphafold2_tpu.serving.engine import PredictionResult
from alphafold2_tpu.serving.featurize import FeatureBundle
from alphafold2_tpu.telemetry import MetricRegistry

#: on-disk entry framing: magic + 64 hex sha256 of the payload + "\n" + payload
_MAGIC = b"AF2ART1\n"
_HEADER_LEN = len(_MAGIC) + 64 + 1

#: artifact kinds (the first path segment on disk)
KIND_RESULT = "result"
KIND_FEATURES = "features"


class ArtifactCorruptError(Exception):
    """A disk entry failed framing/checksum/decode validation."""


def _read_bytes(path: str) -> bytes:
    """The read seam: module-level so the chaos suite can interpose a
    mid-read eviction (file deleted between the exists() check and the
    read) without monkeypatching builtins."""
    with open(path, "rb") as fh:
        return fh.read()


def tag_digest(tag: str) -> str:
    """Stable short digest of a store tag — the on-disk directory name
    (tags are long reprs; the digest keeps paths sane)."""
    return hashlib.sha256(tag.encode()).hexdigest()[:16]


# ------------------------------------------------------------- serialization

def _pack(arrays: dict, meta: dict) -> bytes:
    """Frame arrays + JSON meta as one checksummed blob. The meta rides
    inside the npz as a uint8 array (no pickle anywhere: `np.load` runs
    with allow_pickle=False, so a poisoned entry can corrupt a READ,
    never execute code)."""
    payload = {k: np.ascontiguousarray(v)
               for k, v in arrays.items() if v is not None}
    payload["__meta__"] = np.frombuffer(
        json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **payload)
    blob = buf.getvalue()
    digest = hashlib.sha256(blob).hexdigest().encode()
    return _MAGIC + digest + b"\n" + blob


def _unpack(data: bytes) -> Tuple[dict, dict]:
    """Inverse of `_pack`; raises ArtifactCorruptError on ANY framing,
    checksum, or decode problem (one failure class: recompute)."""
    if len(data) < _HEADER_LEN or not data.startswith(_MAGIC):
        raise ArtifactCorruptError("bad magic / truncated header")
    digest = data[len(_MAGIC):len(_MAGIC) + 64]
    if data[_HEADER_LEN - 1:_HEADER_LEN] != b"\n":
        raise ArtifactCorruptError("bad header framing")
    blob = data[_HEADER_LEN:]
    if hashlib.sha256(blob).hexdigest().encode() != digest:
        raise ArtifactCorruptError("payload checksum mismatch")
    try:
        with np.load(io.BytesIO(blob), allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
        meta = json.loads(bytes(arrays.pop("__meta__")).decode())
    except ArtifactCorruptError:
        raise
    except Exception as e:  # noqa: BLE001 — any decode failure is the
        # same operational fact: the entry cannot be trusted
        raise ArtifactCorruptError(f"payload decode failed: {e}") from None
    if not isinstance(meta, dict):
        raise ArtifactCorruptError("meta is not an object")
    return arrays, meta


def _encode_result(result: PredictionResult) -> Tuple[dict, dict]:
    return (
        {"coords": np.asarray(result.coords),
         "confidence": np.asarray(result.confidence)},
        {"kind": KIND_RESULT, "seq": result.seq,
         "stress": float(result.stress), "bucket": int(result.bucket)},
    )


def _decode_result(arrays: dict, meta: dict) -> PredictionResult:
    try:
        return PredictionResult(
            seq=str(meta["seq"]),
            coords=arrays["coords"],
            confidence=arrays["confidence"],
            stress=float(meta["stress"]),
            bucket=int(meta["bucket"]),
            from_cache=True,
            latency_s=0.0,
        )
    except KeyError as e:
        raise ArtifactCorruptError(f"result entry missing field {e}") from None


def _encode_features(bundle: FeatureBundle) -> Tuple[dict, dict]:
    return (
        {"tokens": np.asarray(bundle.tokens),
         "msa": bundle.msa, "msa_mask": bundle.msa_mask},
        {"kind": KIND_FEATURES, "seq": bundle.seq,
         "bucket": int(bundle.bucket),
         "has_msa": bundle.msa is not None,
         "has_msa_mask": bundle.msa_mask is not None},
    )


def _decode_features(arrays: dict, meta: dict) -> FeatureBundle:
    try:
        if bool(meta["has_msa"]) != ("msa" in arrays) or (
                bool(meta["has_msa_mask"]) != ("msa_mask" in arrays)):
            raise ArtifactCorruptError("feature entry meta/array mismatch")
        return FeatureBundle(
            seq=str(meta["seq"]),
            tokens=arrays["tokens"],
            msa=arrays.get("msa"),
            msa_mask=arrays.get("msa_mask"),
            bucket=int(meta["bucket"]),
        )
    except KeyError as e:
        raise ArtifactCorruptError(
            f"feature entry missing field {e}") from None


_CODECS = {
    KIND_RESULT: (_encode_result, _decode_result),
    KIND_FEATURES: (_encode_features, _decode_features),
}


def _entry_nbytes(arrays: dict, meta: dict) -> int:
    """Hot-ring accounting estimate: array payload + a small meta floor."""
    n = 256
    for v in arrays.values():
        if v is not None:
            n += np.asarray(v).nbytes
    return n


# --------------------------------------------------------------------- store

@dataclasses.dataclass(frozen=True)
class ArtifactStoreConfig:
    """Sizing/eviction knobs (docs/OPERATIONS.md "Artifact store")."""

    root: Optional[str] = None      # disk tier directory (None = memory-only)
    memory_entries: int = 256       # hot-ring entry cap (0 disables the ring)
    memory_bytes: int = 256 << 20   # hot-ring byte budget
    disk_bytes: int = 2 << 30       # disk budget the sweep enforces
    sweep_every_writes: int = 64    # opportunistic sweep cadence (disk puts)

    def __post_init__(self):
        if self.memory_entries < 0 or self.memory_bytes < 0:
            raise ValueError("memory budgets must be >= 0")
        if self.disk_bytes < 0:
            raise ValueError(f"disk_bytes must be >= 0, got {self.disk_bytes}")
        if self.sweep_every_writes < 1:
            raise ValueError("sweep_every_writes must be >= 1")


class ArtifactStore:
    """Content-addressed two-level cache over results and feature bundles.

    API surface the fleet uses:

      * ``lookup_result(tag, key)`` / ``put_result(tag, key, result)``
      * ``lookup_features(tag, key)`` / ``put_features(tag, key, bundle)``
      * ``set_current_tags(tags)`` — the tag lifecycle hook: the fleet
        declares which store tags are live after (re)configuration and
        every rolling update; ``sweep()`` garbage-collects everything
        else from both levels
      * ``sweep()`` — tag GC + disk byte-budget enforcement (oldest
        mtime first) + gauge refresh
      * ``snapshot()`` / ``publish_gauges()`` — the /statusz and
        /metrics views

    Lookups return ``(obj, level)`` with level ``"memory"`` or
    ``"disk"`` so callers can stamp cache provenance per flight, or
    ``None`` on a miss. A corrupt disk entry is counted, deleted, and
    reported as a miss — recompute, never a wrong answer.
    """

    def __init__(self, cfg: ArtifactStoreConfig = ArtifactStoreConfig(),
                 registry: Optional[MetricRegistry] = None):
        self.cfg = cfg
        self.registry = registry if registry is not None else MetricRegistry()
        self._lock = threading.Lock()
        self._sweep_lock = threading.Lock()
        # hot ring: (kind, tag, key) -> (obj, nbytes); tag kept verbatim
        # so sweep() can purge stale-tag entries without digest inversion
        self._ring: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._ring_bytes = 0
        self._current_tags = frozenset()        # tag strings
        self._current_digests = frozenset()     # their path digests
        self._disk_bytes_est = 0
        self._writes_since_sweep = 0
        # plain-int mirrors of the counters: snapshot() must not scrape
        # the registry to describe its own store
        self._stats = {
            "hits_memory": 0, "hits_disk": 0, "misses": 0, "corrupt": 0,
            "evictions_memory": 0, "evictions_disk": 0, "disk_writes": 0,
        }
        self._register_metrics()
        if cfg.root:
            os.makedirs(cfg.root, exist_ok=True)
            self._disk_bytes_est = self._scan_disk_usage()
            self._disk_bytes_g.set(self._disk_bytes_est)

    def _register_metrics(self):
        reg = self.registry
        self._hit_counters = {
            (kind, level): reg.counter(
                "artifact_store_hits_total",
                help="fleet artifact-store hits by kind and level",
                kind=kind, level=level)
            for kind in (KIND_RESULT, KIND_FEATURES)
            for level in ("memory", "disk")
        }
        self._miss_counters = {
            kind: reg.counter(
                "artifact_store_misses_total",
                help="fleet artifact-store misses by kind", kind=kind)
            for kind in (KIND_RESULT, KIND_FEATURES)
        }
        self._corrupt_counters = {
            kind: reg.counter(
                "cache_corrupt_total",
                help="disk entries that failed checksum/framing/decode "
                     "(or vanished mid-read) and fell through to "
                     "recompute", kind=kind)
            for kind in (KIND_RESULT, KIND_FEATURES)
        }
        self._evict_counters = {
            level: reg.counter(
                "artifact_store_evictions_total",
                help="entries evicted (memory ring LRU; disk sweep "
                     "tag-GC + byte budget)", level=level)
            for level in ("memory", "disk")
        }
        self._write_counter = reg.counter(
            "artifact_store_disk_writes_total",
            help="atomic write-then-rename persists to the disk tier")
        self._mem_bytes_g = reg.gauge(
            "artifact_store_memory_bytes",
            help="hot-ring resident bytes (estimate)")
        self._mem_entries_g = reg.gauge(
            "artifact_store_memory_entries", help="hot-ring entries")
        self._disk_bytes_g = reg.gauge(
            "artifact_store_disk_bytes",
            help="disk-tier bytes (exact after a sweep, estimated "
                 "between sweeps)")

    def bind_registry(self, registry: MetricRegistry):
        """Re-home the store's metric families into `registry`.

        The fleet calls this when attaching a store that was built
        standalone (serve.py constructs the store before the fleet — and
        its registry — exist), so ONE /metrics scrape carries the fleet
        and store families together. Counts carry over exactly: every
        re-registered counter is seeded from its predecessor's value, so
        a pre-warmed store loses no history at attach time."""
        if registry is self.registry:
            return
        old_maps = (self._hit_counters, self._miss_counters,
                    self._corrupt_counters, self._evict_counters)
        old_write = self._write_counter
        self.registry = registry
        self._register_metrics()
        for old, new in zip(old_maps,
                            (self._hit_counters, self._miss_counters,
                             self._corrupt_counters, self._evict_counters)):
            for labels, handle in old.items():
                if handle.value:
                    new[labels].inc(handle.value)
        if old_write.value:
            self._write_counter.inc(old_write.value)
        self.publish_gauges()

    # ------------------------------------------------------------ tag state

    def set_current_tags(self, tags: Iterable[str]):
        """Declare the live store tags (one per capability pool + the
        feature tag). Entries under any OTHER tag are unreachable by
        construction (the key embeds the tag) and become sweep fodder."""
        tags = frozenset(str(t) for t in tags)
        with self._lock:
            self._current_tags = tags
            self._current_digests = frozenset(tag_digest(t) for t in tags)

    # -------------------------------------------------------------- lookups

    def lookup_result(self, tag: str, key: str):
        return self._lookup(KIND_RESULT, tag, key)

    def lookup_features(self, tag: str, key: str):
        return self._lookup(KIND_FEATURES, tag, key)

    def put_result(self, tag: str, key: str, result: PredictionResult):
        # normalize BEFORE the hot ring sees it: a memory hit must read
        # exactly like a disk decode (from_cache=True, zero latency) —
        # callers re-stamp their own per-request provenance on delivery
        if not result.from_cache or result.latency_s:
            result = dataclasses.replace(result, from_cache=True,
                                         latency_s=0.0)
        self._put(KIND_RESULT, tag, key, result)

    def put_features(self, tag: str, key: str, bundle: FeatureBundle):
        self._put(KIND_FEATURES, tag, key, bundle)

    def _path(self, kind: str, tag: str, key: str) -> str:
        return os.path.join(self.cfg.root, kind, tag_digest(tag),
                            key + ".art")

    def _lookup(self, kind: str, tag: str, key: str):
        ring_key = (kind, tag, key)
        with self._lock:
            hit = self._ring.get(ring_key)
            if hit is not None:
                self._ring.move_to_end(ring_key)
                self._stats["hits_memory"] += 1
                self._hit_counters[(kind, "memory")].inc()
                return hit[0], "memory"
        obj = self._read_disk(kind, tag, key)
        if obj is None:
            with self._lock:
                self._stats["misses"] += 1
            self._miss_counters[kind].inc()
            return None
        self._ring_put(kind, tag, key, obj)
        with self._lock:
            self._stats["hits_disk"] += 1
        self._hit_counters[(kind, "disk")].inc()
        return obj, "disk"

    def _read_disk(self, kind: str, tag: str, key: str):
        if not self.cfg.root:
            return None
        path = self._path(kind, tag, key)
        if not os.path.exists(path):
            return None
        try:
            data = _read_bytes(path)
        except FileNotFoundError:
            # mid-read eviction: the entry existed an instant ago and a
            # concurrent sweep (this process or a sibling serving the
            # same disk tier) removed it — same degradation contract as
            # corruption: count it, recompute
            self._count_corrupt(kind)
            return None
        except OSError:
            self._count_corrupt(kind)
            return None
        try:
            arrays, meta = _unpack(data)
            if meta.get("kind") != kind:
                raise ArtifactCorruptError(
                    f"entry kind {meta.get('kind')!r} under {kind!r} path")
            obj = _CODECS[kind][1](arrays, meta)
        except ArtifactCorruptError:
            self._count_corrupt(kind)
            # a poisoned entry must not poison the next reader too
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        try:
            os.utime(path)  # refresh mtime: the sweep evicts oldest-first
        except OSError:
            pass
        return obj

    def _count_corrupt(self, kind: str):
        with self._lock:
            self._stats["corrupt"] += 1
        self._corrupt_counters[kind].inc()

    # ---------------------------------------------------------------- puts

    def _ring_put(self, kind: str, tag: str, key: str, obj):
        if self.cfg.memory_entries == 0:
            return
        nbytes = 0
        try:
            arrays, meta = _CODECS[kind][0](obj)
            nbytes = _entry_nbytes(arrays, meta)
        except Exception:  # noqa: BLE001 — sizing must never block caching
            nbytes = 4096
        evicted = 0
        with self._lock:
            ring_key = (kind, tag, key)
            old = self._ring.pop(ring_key, None)
            if old is not None:
                self._ring_bytes -= old[1]
            self._ring[ring_key] = (obj, nbytes)
            self._ring_bytes += nbytes
            while self._ring and (
                    len(self._ring) > self.cfg.memory_entries
                    or self._ring_bytes > self.cfg.memory_bytes):
                _, (_, n) = self._ring.popitem(last=False)
                self._ring_bytes -= n
                evicted += 1
            if evicted:
                self._stats["evictions_memory"] += evicted
            mem_bytes, mem_entries = self._ring_bytes, len(self._ring)
        if evicted:
            self._evict_counters["memory"].inc(evicted)
        self._mem_bytes_g.set(mem_bytes)
        self._mem_entries_g.set(mem_entries)

    def _put(self, kind: str, tag: str, key: str, obj):
        self._ring_put(kind, tag, key, obj)
        if not self.cfg.root:
            return
        try:
            arrays, meta = _CODECS[kind][0](obj)
            blob = _pack(arrays, meta)
        except Exception:  # noqa: BLE001 — an unserializable artifact
            # degrades to memory-only caching, never a failed request
            return
        path = self._path(kind, tag, key)
        d = os.path.dirname(path)
        try:
            os.makedirs(d, exist_ok=True)
            # atomic write-then-rename (the FlightRecorder idiom, but
            # with a unique temp name: two replicas persisting the same
            # key concurrently must not interleave into one .tmp)
            fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as fh:
                    fh.write(blob)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except OSError:
            return  # a full/readonly disk degrades to memory-only caching
        self._write_counter.inc()
        with self._lock:
            self._stats["disk_writes"] += 1
            self._disk_bytes_est += len(blob)
            self._writes_since_sweep += 1
            over = (self._disk_bytes_est > self.cfg.disk_bytes
                    or self._writes_since_sweep
                    >= self.cfg.sweep_every_writes)
        self._disk_bytes_g.set(self._disk_bytes_est)
        if over:
            self.sweep()

    # --------------------------------------------------------------- sweep

    def _scan_disk_usage(self) -> int:
        total = 0
        for dirpath, _dirnames, filenames in os.walk(self.cfg.root):
            for fn in filenames:
                try:
                    total += os.path.getsize(os.path.join(dirpath, fn))
                except OSError:
                    pass
        return total

    def sweep(self) -> dict:
        """The budget sweep: (1) GC every disk entry whose tag directory
        is not a CURRENT tag (a retired deploy's whole keyspace goes at
        once), (2) enforce the byte budget oldest-mtime-first over what
        remains, (3) purge stale-tag hot-ring entries, (4) refresh the
        gauges to exact numbers. Cheap enough to run inline on the put
        path (`sweep_every_writes`) and explicitly after a rolling
        update; concurrent calls serialize on `_sweep_lock`."""
        with self._lock:
            digests = self._current_digests
            tags = self._current_tags
        out = {"gc_files": 0, "gc_bytes": 0,
               "budget_files": 0, "budget_bytes": 0,
               "ring_purged": 0, "disk_bytes": 0}
        with self._sweep_lock:
            if self.cfg.root:
                files = []  # (mtime, size, path)
                for kind in (KIND_RESULT, KIND_FEATURES):
                    kdir = os.path.join(self.cfg.root, kind)
                    try:
                        tagdirs = os.listdir(kdir)
                    except OSError:
                        continue
                    for td in tagdirs:
                        tdir = os.path.join(kdir, td)
                        stale = digests and td not in digests
                        try:
                            names = os.listdir(tdir)
                        except OSError:
                            continue
                        for fn in names:
                            p = os.path.join(tdir, fn)
                            try:
                                st = os.stat(p)
                            except OSError:
                                continue
                            if stale or fn.endswith(".tmp"):
                                try:
                                    os.unlink(p)
                                    out["gc_files"] += 1
                                    out["gc_bytes"] += st.st_size
                                except OSError:
                                    pass
                            else:
                                files.append((st.st_mtime, st.st_size, p))
                        if stale:
                            try:
                                os.rmdir(tdir)
                            except OSError:
                                pass
                total = sum(size for _, size, _ in files)
                if total > self.cfg.disk_bytes:
                    for _, size, p in sorted(files):
                        try:
                            os.unlink(p)
                        except OSError:
                            continue
                        total -= size
                        out["budget_files"] += 1
                        out["budget_bytes"] += size
                        if total <= self.cfg.disk_bytes:
                            break
                out["disk_bytes"] = total
            evicted_disk = out["gc_files"] + out["budget_files"]
            with self._lock:
                if tags:
                    stale_keys = [k for k in self._ring if k[1] not in tags]
                    for k in stale_keys:
                        _, n = self._ring.pop(k)
                        self._ring_bytes -= n
                    out["ring_purged"] = len(stale_keys)
                self._disk_bytes_est = out["disk_bytes"]
                self._writes_since_sweep = 0
                if evicted_disk:
                    self._stats["evictions_disk"] += evicted_disk
                if out["ring_purged"]:
                    self._stats["evictions_memory"] += out["ring_purged"]
                mem_bytes, mem_entries = self._ring_bytes, len(self._ring)
            if evicted_disk:
                self._evict_counters["disk"].inc(evicted_disk)
            if out["ring_purged"]:
                self._evict_counters["memory"].inc(out["ring_purged"])
            self._disk_bytes_g.set(out["disk_bytes"])
            self._mem_bytes_g.set(mem_bytes)
            self._mem_entries_g.set(mem_entries)
        return out

    # ------------------------------------------------------------- reading

    def publish_gauges(self):
        with self._lock:
            mem_bytes, mem_entries = self._ring_bytes, len(self._ring)
            disk_bytes = self._disk_bytes_est
        self._mem_bytes_g.set(mem_bytes)
        self._mem_entries_g.set(mem_entries)
        if self.cfg.root:
            self._disk_bytes_g.set(disk_bytes)

    def snapshot(self) -> dict:
        """JSON-ready store view for /statusz and stats flushes."""
        with self._lock:
            stats = dict(self._stats)
            mem_bytes, mem_entries = self._ring_bytes, len(self._ring)
            disk_bytes = self._disk_bytes_est
            n_tags = len(self._current_tags)
        hits = stats["hits_memory"] + stats["hits_disk"]
        total = hits + stats["misses"]
        return {
            "memory": {
                "entries": mem_entries,
                "bytes": mem_bytes,
                "entry_capacity": self.cfg.memory_entries,
                "byte_budget": self.cfg.memory_bytes,
            },
            "disk": {
                "root": self.cfg.root,
                "bytes": disk_bytes,
                "byte_budget": self.cfg.disk_bytes,
                "writes": stats["disk_writes"],
            },
            "current_tags": n_tags,
            "hits_memory": stats["hits_memory"],
            "hits_disk": stats["hits_disk"],
            "misses": stats["misses"],
            "corrupt": stats["corrupt"],
            "evictions_memory": stats["evictions_memory"],
            "evictions_disk": stats["evictions_disk"],
            "hit_rate": (hits / total) if total else 0.0,
        }
