"""Request-level inference engine: AOT compile cache + micro-batching.

The one-shot `predict.py` CLI re-traces XLA for every new sequence length
and serves one request per process. This engine is the production front
end the ROADMAP north star needs (ParaFold, arxiv 2111.06340: batch many
predictions through one warm model; HelixFold, arxiv 2207.05477: fixed
padded shapes + executable reuse):

  * **Compiled-executable cache** — requests are padded onto a length
    bucket ladder (`bucketing.BucketLadder`) and each bucket is
    AOT-compiled ONCE via ``jax.jit(...).lower(...).compile()``; an
    arbitrary stream of lengths pays at most ``len(buckets)`` compiles
    (exposed as `compile_count` for tests and health checks).
  * **Dynamic micro-batching scheduler** — a bounded queue feeds a worker
    thread that assembles same-bucket batches: dispatch when a batch
    fills (`max_batch`) or its oldest request has waited `max_wait_s`.
    Queue-full is an explicit `QueueFullError` (never a silent block),
    per-request deadlines expire scheduler-side, and shutdown either
    drains or fails pending work.
  * **Result LRU cache** — keyed by (sequence, MSA hash, config tag); a
    hit completes at submit() without touching the queue or the model.
  * **Metrics** — queue depth, batch occupancy, p50/p95/p99 latency,
    cache hit rate, compile count (`serving/metrics.py`), surfaced as a
    JSON snapshot via `stats()`.

Thread model: clients call `submit()`/`result()` from any thread; all
model dispatch happens on the single worker thread, so device traffic is
serialized by construction. With **pipelined dispatch** armed
(`pipeline_depth > 0`, docs/SERVING.md "The dispatch pipeline") the
worker still issues every device call in order, but realization,
billing, and response move to a dedicated settle thread behind a bounded
in-flight window — batch N's device compute overlaps batch N±1's host
assembly and numpy conversion. The **batch-shape ladder**
(`batch_ladder`) compiles each bucket at power-of-two batch shapes so a
partial batch runs the smallest executable that fits instead of paying
phantom-row chip time at `max_batch`. Failure isolation: a model-call
exception fails only the requests of that batch — and a multi-request
batch is retried one request at a time first, so a single poison request
cannot take its batchmates down with it.

Self-protection (reliability layer, both off by default): a
consecutive-failure **circuit breaker** (`breaker_threshold` — open →
`CircuitOpenError` fast-reject → half-open probe → close) and a
**hung-batch watchdog** (`watchdog_timeout_s` — a wedged dispatch fails
its batch instead of the worker). Every terminal error is counted under
its stable code in `stats()["errors"]`; chaos tests inject faults via the
`fault_hook` seam (docs/OPERATIONS.md "Failure model & runbook").
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Optional, Tuple

import jax
import numpy as np

from alphafold2_tpu.serving.bucketing import (
    DEFAULT_BUCKETS,
    BucketLadder,
    batch_shape_ladder,
    pad_batch,
)
from alphafold2_tpu.ops.dispatch import (
    resolution_tag as dispatch_resolution_tag,
    resolved_arm as dispatch_resolved_arm,
)
from alphafold2_tpu.serving.cache import ResultCache, request_key
from alphafold2_tpu.reliability.breaker import CircuitBreaker
from alphafold2_tpu.serving.errors import (
    CircuitOpenError,
    EngineClosedError,
    HungBatchError,
    PredictionError,
    QueueFullError,
    RequestTimeoutError,
    ServingError,
)
from alphafold2_tpu.serving.metrics import ServingMetrics
from alphafold2_tpu.serving.pipeline import predict_structure
from alphafold2_tpu.serving.quant_residency import resident_params
from alphafold2_tpu.telemetry import NULL_TRACER, new_trace_id
from alphafold2_tpu.telemetry.costs import (
    ExecutableCostLedger,
    ServeGoodputLedger,
)


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Scheduler/cache knobs (model hyperparameters live in
    `Alphafold2Config`; see docs/SERVING.md for tuning guidance)."""

    buckets: Tuple[int, ...] = DEFAULT_BUCKETS
    max_batch: int = 4           # fixed batch dim of every executable
    max_queue: int = 64          # bounded request queue (backpressure)
    max_wait_s: float = 0.05     # batch-assembly deadline for partial batches
    request_timeout_s: Optional[float] = 60.0  # default per-request deadline
    cache_capacity: int = 256    # result LRU entries (0 disables)
    msa_rows: int = 0            # >0: executables take a fixed-row MSA stream
    mds_iters: int = 32
    mds_init: str = "classical"
    seed: int = 0
    precompile: bool = False     # AOT-compile every bucket at startup
    latency_window: int = 2048
    params_tag: str = ""         # checkpoint fingerprint for cache keys
    # self-protection (reliability layer; docs/OPERATIONS.md runbook):
    breaker_threshold: int = 0   # consecutive dispatch failures that open
    #                              the circuit (0 = breaker disabled)
    breaker_reset_s: float = 30.0  # open -> half-open probe window
    breaker_jitter: float = 0.0  # fraction of reset_s added as seeded
    #                              random spread per open window, so a
    #                              FLEET of breakers does not re-probe in
    #                              lockstep (0 = deterministic window)
    breaker_jitter_seed: int = 0  # per-replica seed for that spread; NOT
    #                              part of the numeric config tag
    watchdog_timeout_s: Optional[float] = None  # hung-batch watchdog: a
    #                              dispatch exceeding this fails its batch
    #                              instead of wedging the worker (None = off)
    # SP serving arm (serving/sp_arm.py; ROADMAP item 4a): >1 runs each
    # bucket's trunk over a model-axis mesh of this many devices, with a
    # per-bucket FastFold-style schedule (dense / sp_msa / sp_seq) picked
    # by the residency heuristic below. 0 = dense everywhere (the
    # pre-SP engine, bit-identical).
    sp_shards: int = 0
    sp_hbm_gb: float = 16.0      # per-chip HBM budget the schedule
    #                              heuristic prices buckets against
    #                              (planning estimate, not an allocator)
    sp_schedules: Tuple[Tuple[int, str], ...] = ()  # per-bucket overrides
    #                              ((bucket, schedule), ...) — win over
    #                              the heuristic, loud when infeasible
    # trunk-depth early exit (serving cascade's third lever; the pipeline
    # freezes a sample's distogram once consecutive checkpoint depths
    # agree to within early_exit_kl of masked-mean delta-KL). The first
    # depth is the delta-KL baseline, so arming requires >= 2 depths.
    # Priced per exit depth as distinct cost-ledger cells.
    early_exit_depths: Tuple[int, ...] = ()
    early_exit_kl: float = 0.0
    # batch-shape ladder (bucketing.batch_shape_ladder): compile each
    # bucket at power-of-two batch shapes {1, 2, ..., max_batch} and
    # assemble batches at the smallest shape >= live count, so a partial
    # batch stops paying phantom-row chip time. Off = the classic
    # single-shape engine (every executable at max_batch).
    batch_ladder: bool = False
    # pipelined dispatch: >0 splits the scheduler into an assembly/
    # dispatch thread and a settle thread with at most this many batches
    # enqueued-but-unsettled, so batch N's device compute overlaps batch
    # N±1's host assembly / numpy conversion / settle. 0 = synchronous
    # legacy path (dispatch realizes inline on the worker thread).
    pipeline_depth: int = 0

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.pipeline_depth < 0:
            raise ValueError(
                f"pipeline_depth must be >= 0, got {self.pipeline_depth}"
            )
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")
        if self.max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {self.max_wait_s}")
        if self.breaker_threshold < 0:
            raise ValueError(
                f"breaker_threshold must be >= 0, got {self.breaker_threshold}"
            )
        if self.breaker_jitter < 0:
            raise ValueError(
                f"breaker_jitter must be >= 0, got {self.breaker_jitter}"
            )
        if self.watchdog_timeout_s is not None and self.watchdog_timeout_s <= 0:
            raise ValueError(
                f"watchdog_timeout_s must be positive or None, got "
                f"{self.watchdog_timeout_s}"
            )
        if self.sp_shards < 0 or self.sp_shards == 1:
            raise ValueError(
                f"sp_shards must be 0 (dense) or >= 2, got {self.sp_shards}"
            )
        if self.sp_hbm_gb <= 0:
            raise ValueError(
                f"sp_hbm_gb must be positive, got {self.sp_hbm_gb}"
            )
        from alphafold2_tpu.serving.sp_arm import SP_SCHEDULES

        object.__setattr__(
            self, "sp_schedules",
            tuple(sorted((int(b), str(s)) for b, s in self.sp_schedules)))
        for _bucket, sched in self.sp_schedules:
            if sched not in SP_SCHEDULES:
                raise ValueError(
                    f"sp_schedules entry {sched!r} is not a schedule; "
                    f"known: {SP_SCHEDULES}"
                )
        if self.sp_schedules and not self.sp_shards:
            raise ValueError(
                "sp_schedules given but sp_shards=0 — per-bucket schedule "
                "overrides only apply to the SP arm"
            )
        object.__setattr__(
            self, "early_exit_depths",
            tuple(sorted({int(d) for d in self.early_exit_depths})))
        if self.early_exit_depths:
            if self.early_exit_depths[0] < 1:
                raise ValueError(
                    f"early_exit_depths must be >= 1, got "
                    f"{self.early_exit_depths}"
                )
            if len(self.early_exit_depths) < 2:
                raise ValueError(
                    "early_exit_depths needs >= 2 checkpoints: the first "
                    "is the delta-KL baseline and can never exit"
                )
            if self.early_exit_kl <= 0:
                raise ValueError(
                    f"early_exit_kl must be > 0 when early_exit_depths "
                    f"is set, got {self.early_exit_kl}"
                )
            if self.sp_shards:
                raise ValueError(
                    "early exit segments the dense sequential trunk and "
                    "cannot compose with the SP arm (sp_shards > 0)"
                )
        elif self.early_exit_kl:
            raise ValueError(
                "early_exit_kl set without early_exit_depths — the exit "
                "gate has no checkpoints to fire at"
            )
        if self.mds_init == "random" and self.cache_capacity:
            # random MDS inits draw from a per-dispatch key, so identical
            # requests served in different batches yield different
            # structures — a cached entry could not honor the cache's
            # equal-key == identical-computation contract (serving/cache.py)
            raise ValueError(
                "mds_init='random' is not reproducible across dispatches "
                "and cannot back the result cache; use mds_init="
                "'classical' (deterministic) or cache_capacity=0"
            )


@dataclasses.dataclass
class PredictionResult:
    """One served structure (host numpy, sliced to the true length).

    The last three fields are fleet-tier provenance (serving/fleet.py):
    which replica computed it, whether it was served by the degraded
    tier, and how many replica failovers it survived. Single-engine
    results keep the defaults."""

    seq: str
    coords: np.ndarray        # (L, 3) CA trace
    confidence: np.ndarray    # (L,) in [0, 1]
    stress: float             # final normalized MDS stress
    bucket: int
    from_cache: bool
    latency_s: float
    replica: str = ""         # fleet: serving replica name
    degraded: bool = False    # fleet: served by the degraded tier
    requeues: int = 0         # fleet: replica failovers survived
    trace_id: str = ""        # request trace id: grep it in span exports /
    #                           flight-recorder bundles to reconstruct this
    #                           request's whole cross-replica life
    mean_confidence: float = 0.0  # mean per-residue distogram confidence
    #                           over the true length — the cascade
    #                           scorer's primary signal (serving/cascade.py)
    exit_depth: int = 0       # trunk depth the distogram froze at when
    #                           early exit is armed (0 = early exit off)
    tier: str = ""            # cascade provenance: "" (no cascade) /
    #                           "draft" (accepted draft) / "escalated" /
    #                           "full"


class ServingRequest:
    """Client handle: a future resolved by the scheduler worker."""

    def __init__(self, seq: str, tokens: np.ndarray, msa, msa_mask,
                 cache_key: str, bucket: int, deadline: Optional[float],
                 trace_id: str = ""):
        self.seq = seq
        self.tokens = tokens
        self.msa = msa
        self.msa_mask = msa_mask
        self.cache_key = cache_key
        self.bucket = bucket
        self.deadline = deadline
        self.trace_id = trace_id or new_trace_id()
        self.submitted_at = time.monotonic()
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._result: Optional[PredictionResult] = None
        self._exc: Optional[BaseException] = None
        self._callbacks = []

    @property
    def length(self) -> int:
        return self.tokens.shape[0]

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (time.monotonic() if now is None else now) >= self.deadline

    def done(self) -> bool:
        return self._event.is_set()

    def _finish(self, result=None, exc=None) -> bool:
        """Resolve once; later resolutions (e.g. a drain racing a timeout)
        are dropped. Returns True when this call resolved the request.
        Done-callbacks fire outside the lock, on the resolving thread."""
        with self._lock:
            if self._event.is_set():
                return False
            self._result, self._exc = result, exc
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            try:
                fn(self)
            except Exception:  # noqa: BLE001 — a callback bug must not
                # poison the resolver (usually the engine worker thread)
                import traceback

                traceback.print_exc()
        return True

    def add_done_callback(self, fn):
        """Run `fn(request)` when the request resolves — immediately (on
        the calling thread) if it already has. Callbacks run on whatever
        thread resolves the request (typically the engine worker): keep
        them non-blocking. This is the fleet tier's completion seam."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def peek(self):
        """(result, exc) without blocking or copying; only valid after
        done(). The result may alias a cache entry — fleet/engine
        internals only; clients go through result()."""
        if not self._event.is_set():
            raise RuntimeError("peek() before the request resolved")
        return self._result, self._exc

    def result(self, timeout: Optional[float] = None) -> PredictionResult:
        """Block for the outcome. Raises the request's terminal
        ServingError, or builtin TimeoutError if the CALLER's wait budget
        expires first (the request itself may still complete later).

        Every call returns freshly copied arrays: a request can be shared
        (in-flight coalescing) and its resolved result can alias a cache
        entry — one caller's in-place edit must never reach another caller
        or a later cache hit."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request ({len(self.seq)} residues) not completed within "
                f"{timeout}s wait"
            )
        if self._exc is not None:
            raise self._exc
        return dataclasses.replace(
            self._result,
            coords=self._result.coords.copy(),
            confidence=self._result.confidence.copy(),
        )


_IDLE_POLL_S = 0.05  # worker wake cadence when nothing is staged

_SETTLE_STOP = object()  # settle-queue sentinel: enqueued LAST by the
#                          worker's final flush / abort, so every
#                          in-flight batch settles before the settle
#                          thread exits


@dataclasses.dataclass
class _InFlight:
    """One enqueued-but-unsettled pipelined batch (worker -> settle
    thread handoff). `out` holds unrealized device buffers; `enqueue_t`
    and `compile_s0` let the settle side bill enqueue->realized minus
    any concurrent compile."""

    bucket: int
    shape: int
    live: list
    out: dict
    idx: int
    enqueue_t: float
    compile_s0: float
    n_real: int


class ServingEngine:
    """Length-bucketed, micro-batching inference engine over
    `serving.pipeline.predict_structure`.

    Args:
      params: trunk parameter pytree (placed on device once).
      model_cfg: `Alphafold2Config`; `max_seq_len` must cover the ladder.
      cfg: `ServingConfig`.
      model_apply_fn: trunk-forward override threaded to the pipeline
        (e.g. a sequence-parallel wrapper).
      metrics_logger: optional `utils.MetricsLogger` receiving one record
        per dispatched batch.
      fault_hook: chaos-injection seam (reliability.FaultInjector
        .serving_hook()): called with (dispatch_index, bucket) at the top
        of every model dispatch, INSIDE the watchdog and failure-isolation
        guards — an injected fault travels the exact path an organic one
        would. None (production) costs nothing.
      tracer: optional `telemetry.Tracer` recording the request lifecycle
        as spans — serving.enqueue (client thread), serving.queue_wait,
        serving.batch / serving_compile / serving.execute /
        serving.respond (worker thread). None (production default) wires
        the no-op NULL_TRACER: one boolean test per phase, no records.
        Per-request spans carry `trace_id`; multi-request spans carry the
        `trace_ids` list (docs/OBSERVABILITY.md).
      replica_name: fleet identity stamped as a `replica` attribute on
        every serving span, so a shared fleet tracer attributes each span
        to the replica that recorded it ("" = single-engine, no tag).
      incident_hook: optional `fn(kind, **attrs)` called when a
        reliability seam trips — `breaker_open` (circuit transitioned to
        open) and `watchdog_fire` (hung-batch watchdog) — the flight
        recorder's `incident` method plugs in here
        (telemetry/ops_plane.py). Exceptions from the hook are swallowed
        with a traceback: observability must never take the engine down.
      pool_name: capability-pool label for the serving cost plane
        (telemetry/costs.py) — the fleet passes each replica's pool;
        single engines default to "default".
      cost_ledger: shared `ExecutableCostLedger` (the fleet passes its
        own so N replicas of a pool merge into one cell); None builds a
        private ledger over this engine's registry, so single-engine
        runs get the cost plane too. At build, one cell per bucket is
        registered with the analytic forward FLOPs and the priced
        residency; every successful dispatch feeds the measured EMA
        (compile time excluded).
      goodput: shared `ServeGoodputLedger`; None builds a private one.
        The engine accounts execute (successful dispatch), compile (AOT
        compiles), and requeue (device time burned by failed
        dispatches); the fleet layers probe/drain on the same ledger.
      flights: optional `telemetry.costs.FlightBook`. The FLEET keeps
        the book itself (it sees the whole cross-replica flight); a
        standalone engine given one records submit -> terminal exemplars
        so `/explainz` works in single-engine mode too.
    """

    def __init__(self, params, model_cfg, cfg: ServingConfig = ServingConfig(),
                 *, model_apply_fn=None, metrics_logger=None, fault_hook=None,
                 tracer=None, replica_name: str = "", incident_hook=None,
                 pool_name: str = "default", cost_ledger=None, goodput=None,
                 flights=None):
        self._ladder = BucketLadder(cfg.buckets)
        if self._ladder.max_len > model_cfg.max_seq_len:
            raise ValueError(
                f"largest bucket {self._ladder.max_len} exceeds the model's "
                f"max_seq_len {model_cfg.max_seq_len}"
            )
        if cfg.msa_rows > model_cfg.max_num_msa:
            raise ValueError(
                f"msa_rows {cfg.msa_rows} exceeds the model's max_num_msa "
                f"{model_cfg.max_num_msa}"
            )
        self.cfg = cfg
        self.model_cfg = model_cfg
        self._model_apply_fn = model_apply_fn
        # SP serving arm (serving/sp_arm.py): a model-axis mesh plus a
        # per-bucket schedule plan, priced chip-free at build. The plan is
        # part of the config tag below — schedules differ in float
        # association (ring/psum accumulation order), so results must
        # never alias across plans.
        self._sp_mesh = None
        self._sp_plan = {}
        if cfg.sp_shards:
            if model_apply_fn is not None:
                raise ValueError(
                    "sp_shards and model_apply_fn are mutually exclusive: "
                    "the SP arm builds its own per-bucket trunk override"
                )
            from alphafold2_tpu.serving import sp_arm

            self._sp_mesh = sp_arm.build_sp_mesh(cfg.sp_shards)
            self._sp_plan = sp_arm.plan_bucket_schedules(
                model_cfg,
                buckets=self._ladder.buckets,
                batch=cfg.max_batch,
                msa_rows=cfg.msa_rows,
                shards=cfg.sp_shards,
                hbm_bytes=cfg.sp_hbm_gb * (1 << 30),
                overrides=dict(cfg.sp_schedules),
            )
        # trunk-depth early exit (serving/pipeline.py _staged_trunk_logits;
        # the cascade's third lever): validated against the MODEL here so
        # a bad depth fails construction, not the first dispatch
        if cfg.early_exit_depths:
            if model_apply_fn is not None:
                raise ValueError(
                    "early_exit_depths and model_apply_fn are mutually "
                    "exclusive: early exit drives the trunk itself"
                )
            if model_cfg.reversible:
                raise ValueError(
                    "early exit segments the sequential layer list; the "
                    "reversible trunk is depth-stacked — set "
                    "reversible=False"
                )
            if cfg.early_exit_depths[-1] >= model_cfg.depth:
                raise ValueError(
                    f"early_exit_depths {cfg.early_exit_depths} must all "
                    f"be < model depth {model_cfg.depth} (the full-depth "
                    f"checkpoint is implicit)"
                )
            if len(set(model_cfg.layer_sparse)) > 1:
                raise ValueError(
                    "early exit requires uniform sparse_self_attn flags "
                    "across the trunk (layer slices re-index "
                    "cfg.layer_sparse from 0)"
                )
        # precision arm (serving/quant_residency.py): weight_dtype="int8"
        # places the per-channel-PTQ tree on device instead of the fp32
        # master — quantized once per residency tag process-wide, so a
        # fleet of replicas over one master tree shares the work
        params, self._weight_residency = resident_params(
            params, model_cfg, params_tag=cfg.params_tag
        )
        self._params = jax.device_put(params)
        self._base_key = jax.random.PRNGKey(cfg.seed)
        # the ladder is part of the numeric fingerprint: a sequence's
        # structure is a deterministic function of (sequence, bucket), and
        # bucket assignment follows the ladder (serving/bucketing.py).
        # repr(model_cfg) serializes EVERY Alphafold2Config field — in
        # particular trunk_schedule, attn_gate, and weight_dtype must be
        # (and are) in the tag: schedules may differ in fusion-level
        # float association, the gate changes the math outright, and the
        # int8 precision arm serves rounded weights — so the result LRU,
        # the AOT executables, and the fleet's shared-tag bit-exactness
        # pin must never alias results across them (tests/test_serving.py
        # pins all three)
        # ... and the RESOLVED kernel backend arms (ops/dispatch.py):
        # a kernel arm and its XLA twin agree only to rounding, so two
        # replicas whose envs force different arms (AF2_KERNEL_BACKEND*)
        # must never share one result-cache / executable keyspace.
        # Resolved once at build — the same trace-time-baked contract as
        # the env knobs themselves (tests/test_serving.py pins the
        # aliasing both ways).
        self._dispatch_tag = dispatch_resolution_tag()
        # ... and the SP plan: two engines whose buckets take different
        # schedules (dense vs ring-accumulated sp_seq vs psum-ordered
        # sp_msa) agree only to rounding — never one cache keyspace
        # ... and the early-exit knobs: an early-exited distogram is a
        # different function of the sequence than the full-depth one
        # batch-shape ladder: the smallest executable shape >= live count
        # serves each batch (perf only — per-sample outputs are batch-
        # composition independent, serving/pipeline.py). Still covered by
        # the config tag below when armed, so result-cache / artifact /
        # AOT keyspaces never alias across ladder configs; unarmed
        # engines keep the byte-identical legacy tag.
        self._batch_shapes = (
            batch_shape_ladder(cfg.max_batch) if cfg.batch_ladder
            else (cfg.max_batch,)
        )
        tag_fields = (
            model_cfg, cfg.mds_iters, cfg.mds_init, cfg.seed, cfg.msa_rows,
            cfg.params_tag, self._ladder.buckets, self._dispatch_tag,
            cfg.sp_shards,
            tuple((b, r.schedule) for b, r in sorted(self._sp_plan.items())),
            cfg.early_exit_depths, cfg.early_exit_kl,
        )
        if cfg.batch_ladder:
            tag_fields = tag_fields + (("batch_ladder", self._batch_shapes),)
        self._config_tag = repr(tag_fields)

        self._executables = {}
        self._compile_lock = threading.Lock()
        # guards _batch_counter: the worker and an abandoned watchdog
        # runner can reach _call_executable concurrently, and the RNG
        # fold must never hand two batches the same key
        self._counter_lock = threading.Lock()
        self._batch_counter = 0
        self._fault_hook = fault_hook
        self._dispatch_counter = 0  # the chaos clock; under _counter_lock
        #                             (worker + settle-thread retries)
        self.replica_name = replica_name
        self._span_tags = {"replica": replica_name} if replica_name else {}
        self._incident_hook = incident_hook
        self._breaker = (
            CircuitBreaker(cfg.breaker_threshold, cfg.breaker_reset_s,
                           jitter=cfg.breaker_jitter,
                           seed=cfg.breaker_jitter_seed,
                           on_open=self._on_breaker_open)
            if cfg.breaker_threshold else None
        )

        self._queue: "queue.Queue[ServingRequest]" = queue.Queue(
            maxsize=cfg.max_queue
        )
        self._cache = ResultCache(cfg.cache_capacity)
        # in-flight coalescing map: cache_key -> pending request, so a
        # thundering herd of identical queries shares ONE computation
        self._inflight = {}
        self._inflight_lock = threading.Lock()
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics = ServingMetrics(
            latency_window=cfg.latency_window, logger=metrics_logger,
            tracer=self._tracer,
        )
        # per-tag weight-bytes gauge: what THIS engine's config tag costs
        # in resident weight HBM (the int8 arm's headline residency win)
        self.metrics.set_weight_bytes(self._weight_residency)

        # ---- serving cost plane (telemetry/costs.py) ----
        # one cost-ledger cell per bucket: analytic forward FLOPs
        # (utils/flops.py at the bucket's padded shape) + priced per-chip
        # residency (the SAME sp_arm pricing the SP planner uses) join
        # the measured EMA the dispatch path feeds below. Ledgers are
        # shared when the fleet passes them (pool-wide cells / one
        # per-replica economy); private otherwise so a standalone engine
        # still answers "what does a request cost".
        self.pool_name = pool_name
        self._owns_costs = cost_ledger is None
        self.costs = (cost_ledger if cost_ledger is not None
                      else ExecutableCostLedger(self.metrics.registry))
        self.goodput = (goodput if goodput is not None
                        else ServeGoodputLedger(self.metrics.registry))
        self.flights = flights
        self._goodput_name = replica_name or "engine"
        self.goodput.register(self._goodput_name, pool_name)
        self._cost_cells = {}
        from alphafold2_tpu.serving import sp_arm
        from alphafold2_tpu.utils.flops import model_fwd_flops

        backend_arm = dispatch_resolved_arm("flash_attention")
        rows = cfg.msa_rows
        # one cell per (bucket, batch shape): the ladder leg compiles a
        # distinct executable per shape, and each shape's measured EMA
        # must never blend with another's (a 1-row batch and a 4-row
        # batch of the same bucket cost ~4x apart). Shape is encoded as
        # an `@b{B}` schedule suffix (same composition the cascade's
        # `dense@exit{d}` cells use) so the CellKey arity and label set
        # stay stable; unarmed engines keep the suffix-free legacy cells.
        for bucket in self._ladder.buckets:
            plan = self._sp_plan.get(bucket)
            schedule = plan.schedule if plan is not None else "dense"
            chips = cfg.sp_shards if schedule != "dense" else 1
            for shape in self._batch_shapes:
                residency = sp_arm.schedule_residency(
                    model_cfg, bucket=bucket, batch=shape,
                    msa_rows=rows, schedule=schedule, shards=max(1, chips),
                    weight_bytes=self._weight_residency["weight_bytes"],
                )
                sched_tag = (f"{schedule}@b{shape}" if cfg.batch_ladder
                             else schedule)
                self._cost_cells[(bucket, shape)] = self.costs.register_cell(
                    pool=pool_name, bucket=bucket, schedule=sched_tag,
                    backend_arm=backend_arm,
                    weight_dtype=model_cfg.weight_dtype,
                    forward_flops=model_fwd_flops(
                        model_cfg, n=bucket, r=rows, c=bucket),
                    residency_bytes=residency.total_bytes,
                    chips=max(1, chips), max_batch=shape,
                )

        # per-exit-depth cost cells: a request whose trunk froze at depth
        # d did ~flops(d)/flops(depth) of the full forward. Each exit
        # depth gets its OWN price-list cell (schedule "dense@exit{d}")
        # so the router optimizes against what shallow answers actually
        # cost; the dispatch path apportions the measured batch
        # device-seconds across cells flops-proportionally (_run_live),
        # preserving fleet_chip_seconds_total exactly.
        self._exit_cells = {}
        self._depth_flops = {}
        if cfg.early_exit_depths:
            # exits fire from the SECOND checkpoint on (the first is the
            # delta-KL baseline), so only depths[1:] get cells
            for bucket in self._ladder.buckets:
                for d in cfg.early_exit_depths[1:]:
                    sub_cfg = dataclasses.replace(model_cfg, depth=d)
                    flops_d = model_fwd_flops(
                        sub_cfg, n=bucket, r=rows, c=bucket)
                    self._depth_flops[(bucket, d)] = flops_d
                    # exit cells compose with the batch-shape ladder the
                    # same way the base cells do: one cell per (bucket,
                    # exit depth, shape), schedule `dense@exit{d}@b{B}`
                    for shape in self._batch_shapes:
                        sub_res = sp_arm.schedule_residency(
                            sub_cfg, bucket=bucket, batch=shape,
                            msa_rows=rows, schedule="dense", shards=1,
                            weight_bytes=self._weight_residency[
                                "weight_bytes"],
                        )
                        exit_tag = (f"dense@exit{d}@b{shape}"
                                    if cfg.batch_ladder else f"dense@exit{d}")
                        self._exit_cells[(bucket, d, shape)] = (
                            self.costs.register_cell(
                                pool=pool_name, bucket=bucket,
                                schedule=exit_tag,
                                backend_arm=backend_arm,
                                weight_dtype=model_cfg.weight_dtype,
                                forward_flops=flops_d,
                                residency_bytes=sub_res.total_bytes,
                                chips=1, max_batch=shape,
                            ))
                self._depth_flops[(bucket, model_cfg.depth)] = (
                    model_fwd_flops(model_cfg, n=bucket, r=rows, c=bucket))

        self._closed = False
        self._drain_on_stop = True
        self._stop = threading.Event()
        # ladder-aware drain-rate EMA (retry_after_estimate): seconds of
        # non-overlapped batch wall per settled request. Written from
        # whichever thread settles batches (worker in sync mode, settle
        # thread in pipelined mode) and read from client threads.
        self._rate_lock = threading.Lock()
        self._sec_per_req_ema = 0.0
        # ---- pipelined dispatch (cfg.pipeline_depth > 0) ----
        # the worker thread assembles and ENQUEUES batches; the settle
        # thread realizes device buffers, bills the cost plane, and
        # resolves requests. The semaphore bounds enqueued-but-unsettled
        # batches to the configured window; _last_realized_t is the
        # engine-wide realization watermark _billed_window clamps
        # against so concurrent in-flight spans never double-bill one
        # wall second of device time.
        self._settle_dead = False
        self._pipeline_lock = threading.Lock()
        self._last_realized_t = 0.0
        self._settle_queue: "queue.Queue" = queue.Queue()
        self._inflight_sem = threading.Semaphore(max(1, cfg.pipeline_depth))
        self._settle_thread = None
        # precompile BEFORE the worker thread exists: a failing compile
        # must abort construction cleanly, not strand a started worker
        # (and the device params it references) behind a raised __init__
        if cfg.precompile:
            for bucket in self._ladder.buckets:
                for shape in self._batch_shapes:
                    self._executable_for(bucket, shape)
        if cfg.pipeline_depth:
            self._settle_thread = threading.Thread(
                target=self._settle_loop,
                name=f"af2-settle-{replica_name or 'engine'}", daemon=True
            )
            self._settle_thread.start()
        self._worker = threading.Thread(
            target=self._worker_loop,
            name=f"af2-serve-{replica_name or 'engine'}", daemon=True
        )
        self._worker.start()

    # ------------------------------------------------------------------ API

    def submit(self, seq: str, *, msa=None, msa_mask=None,
               timeout: Optional[float] = None,
               trace_id: str = "", features=None) -> ServingRequest:
        """Enqueue one sequence; returns immediately with a future.

        `trace_id` correlates every span/result of this request; "" mints
        a fresh one (the fleet passes the id it minted at ITS front door,
        so a requeued request keeps one id across replicas).

        `features` is an optional pre-computed `featurize.FeatureBundle`
        (the fleet's CPU featurization tier, or a client that prepared
        its own): tokenization and MSA normalization are skipped — the
        bundle IS that work, produced by the same `featurize_request`
        function the inline path runs, so results are bit-identical
        either way (the tier moves work across threads, never changes
        it). `seq`/`msa`/`msa_mask` are ignored when given.

        Raises EngineClosedError / InvalidSequenceError /
        RequestTooLongError / QueueFullError / CircuitOpenError
        synchronously — a rejected request never occupies queue capacity.
        """
        trace_id = trace_id or new_trace_id()
        if features is not None:
            seq = features.seq
        # the span wraps validation + cache/coalesce lookup + enqueue; a
        # rejection exits it with an `error` attribute, so the trace shows
        # rejected submissions as first-class lifecycle events
        with self._tracer.span("serving.enqueue", cat="serving",
                               length=len(seq), trace_id=trace_id,
                               **self._span_tags) as sp:
            req = self._submit(seq, msa=msa, msa_mask=msa_mask,
                               timeout=timeout, trace_id=trace_id,
                               features=features)
            sp.set("bucket", req.bucket)
            if req.trace_id != trace_id:
                # coalesced onto an identical in-flight request: the
                # shared future keeps the FIRST submitter's id — record
                # the attachment so this submitter's id still resolves
                sp.set("coalesced_onto", req.trace_id)
            return req

    def _submit(self, seq: str, *, msa=None, msa_mask=None,
                timeout: Optional[float] = None,
                trace_id: str = "", features=None) -> ServingRequest:
        if self._closed:
            self._reject(EngineClosedError("engine is shut down"))
        if features is not None:
            # pre-featurized path (serving/featurize.py): the bundle was
            # produced by the SAME featurize_request function the inline
            # branch below delegates to, against the same ladder/msa_rows
            # — only cheap consistency guards remain (a bundle featurized
            # for a different deployment must not slip through)
            seq = features.seq
            tokens = features.tokens
            msa_arr, msa_mask = features.msa, features.msa_mask
            try:
                bucket = self._ladder.bucket_for(len(seq))
            except ServingError as e:
                self._reject(e)
            if msa_arr is not None and (
                    self.cfg.msa_rows == 0
                    or msa_arr.shape[0] > self.cfg.msa_rows):
                self._reject(ServingError(
                    f"pre-featurized msa has {msa_arr.shape[0]} rows; "
                    f"this engine serves msa_rows={self.cfg.msa_rows}"
                ))
            # a client-built bundle is untrusted input: a mask without
            # an alignment (or mis-shaped against it) would otherwise
            # first explode in batch assembly as a replica-attributed
            # PredictionError — which the fleet would requeue across
            # replicas and count as replica failure evidence
            if msa_arr is None and msa_mask is not None:
                self._reject(ServingError(
                    "pre-featurized msa_mask given without msa"))
            if (msa_arr is not None and msa_mask is not None
                    and msa_mask.shape != msa_arr.shape):
                self._reject(ServingError(
                    f"pre-featurized msa_mask shape {msa_mask.shape} "
                    f"does not match msa shape {msa_arr.shape}"))
        else:
            from alphafold2_tpu.serving.featurize import featurize_request

            try:
                bundle = featurize_request(
                    seq, msa, msa_mask,
                    ladder=self._ladder, msa_rows=self.cfg.msa_rows,
                )
            except ServingError as e:
                self._reject(e)
            seq, tokens = bundle.seq, bundle.tokens
            msa_arr, msa_mask = bundle.msa, bundle.msa_mask
            bucket = bundle.bucket

        key = request_key(seq, msa_arr, self._config_tag, msa_mask=msa_mask)

        if self.flights is not None:
            # cell_for carries pool/bucket/schedule/arm/dtype — the
            # whole cost-cell identity this request will bill to
            cell = self.cell_for(bucket) or {
                "pool": self.pool_name, "bucket": bucket}
            self.flights.begin(trace_id, length=len(seq), **cell)
        cached = self._cache.get(key)
        if cached is not None:
            # free path: never touches the queue, the scheduler, or the model
            self.metrics.inc("submitted")
            self.metrics.inc("cache_hits")
            self.metrics.inc("completed")
            self.metrics.latency.observe(0.0)
            if self.flights is not None:
                self.flights.finish(trace_id, "completed", from_cache=True,
                                     replica=self.replica_name)
            req = ServingRequest(seq, tokens, msa_arr, msa_mask, key, bucket,
                                 deadline=None, trace_id=trace_id)
            # array aliasing with the cache entry is fine here: result()
            # copies on every read, so clients can never reach it. The
            # trace id is THIS request's, not the computing request's —
            # a cache hit is a lifecycle event of the hitting request.
            req._finish(result=dataclasses.replace(
                cached, from_cache=True, latency_s=0.0, trace_id=trace_id,
            ))
            return req

        ttl = self.cfg.request_timeout_s if timeout is None else timeout
        deadline = (time.monotonic() + ttl) if ttl is not None else None
        with self._inflight_lock:
            existing = self._inflight.get(key)
            if existing is not None and not existing.done():
                # identical query already pending: share its future (the
                # shared request keeps the FIRST submitter's deadline).
                # THIS submitter's flight record seals here — only the
                # first submitter's id rides the shared future's resolve
                if self.flights is not None:
                    self.flights.finish(trace_id, "coalesced",
                                        onto=existing.trace_id)
                self.metrics.inc("coalesced")
                return existing
            if self._breaker is not None and not self._breaker.allow():
                # fast rejection, not queue time: the breaker has seen
                # enough consecutive dispatch failures that this request
                # would almost certainly burn a device call to fail. Cache
                # hits and coalesced attaches (above) stay free — they
                # cost no new dispatch.
                snap = self._breaker.snapshot()
                self._reject(CircuitOpenError(
                    f"circuit {snap['state']} after repeated dispatch "
                    f"failures (threshold {snap['threshold']}); retry "
                    f"after {self.cfg.breaker_reset_s}s"
                ), trace_id=trace_id)
            req = ServingRequest(seq, tokens, msa_arr, msa_mask, key, bucket,
                                 deadline, trace_id=trace_id)
            # count submitted BEFORE the worker can possibly complete the
            # request — counting after enqueue lets a stats() reader see
            # completed > submitted (negative in_flight) transiently
            self.metrics.inc("submitted")
            try:
                self._queue.put_nowait(req)
            except queue.Full:
                self.metrics.inc("submitted", -1)
                if self._breaker is not None:
                    # an admitted half-open probe that never enqueued must
                    # not leave the breaker waiting on it forever
                    self._breaker.abandon_probe()
                self.metrics.inc("rejected")
                self.metrics.inc_error("queue_full")
                if self.flights is not None:
                    self.flights.finish(trace_id, "rejected",
                                        code="queue_full")
                raise QueueFullError(
                    f"request queue at capacity ({self.cfg.max_queue}); "
                    f"retry with backoff or raise ServingConfig.max_queue",
                    retry_after_s=self.retry_after_estimate(),
                ) from None
            self._inflight[key] = req
        # close the TOCTOU window against shutdown(): if the closed flag
        # flipped after the entry check, the worker (and shutdown's
        # post-join drain) may already be past this request — resolve it
        # ourselves; _finish is resolve-once, so losing the race to a
        # draining worker is harmless
        if self._closed and self._resolve(req, exc=EngineClosedError(
                "engine shut down while the request was being submitted")):
            self.metrics.inc("failed")
            self.metrics.inc_error("engine_closed")
            raise EngineClosedError("engine is shut down")
        return req

    def _reject(self, exc: ServingError, trace_id: str = ""):
        """Count (terminal counter + stable per-code counter) and raise a
        submit-time rejection. `trace_id` seals the flight record for
        rejections that happen AFTER the record was born (breaker
        fast-rejects); for earlier ones no record exists and finish is a
        no-op."""
        self.metrics.inc("rejected")
        self.metrics.inc_error(exc)
        if self.flights is not None and trace_id:
            self.flights.finish(trace_id, "rejected", code=exc.code)
        raise exc from None

    def _incident(self, kind: str, **attrs):
        """Report one reliability incident to the hook (flight recorder).
        A raising hook is reported and swallowed: observability must
        never take the serving path down with it."""
        if self._incident_hook is None:
            return
        try:
            self._incident_hook(kind, replica=self.replica_name, **attrs)
        except Exception:  # noqa: BLE001 — see docstring
            import traceback

            traceback.print_exc()

    def _on_breaker_open(self, snapshot: dict):
        """CircuitBreaker on_open callback (called outside its lock)."""
        self._incident("breaker_open", **snapshot)

    def predict(self, seq: str, *, msa=None, msa_mask=None,
                timeout: Optional[float] = None) -> PredictionResult:
        """Synchronous convenience: submit + block for the result."""
        return self.submit(seq, msa=msa, msa_mask=msa_mask,
                           timeout=timeout).result()

    @property
    def compile_count(self) -> int:
        return self.metrics.compile_count

    @property
    def config_tag(self) -> str:
        """The numerics-identity tag this engine keys its result cache
        and executable table on. Public because the fleet artifact tier
        (serving/artifact_store.py) builds its per-pool store tags from
        the same inputs: the per-engine LRU and the fleet store are two
        TIERS of one memoization scheme, and both must re-key on exactly
        the knobs that move this engine's numerics (model config, MDS
        knobs, seed, params_tag, bucket ladder, kernel resolution tag,
        SP plan)."""
        return self._config_tag

    def capability(self) -> dict:
        """The replica capability tag (ROADMAP item 4b): what traffic this
        engine can physically serve — the fleet's length-adaptive router
        and `stats()["replicas"]` both read it, so an operator can see WHY
        a request landed where it did."""
        return {
            "weight_dtype": self.model_cfg.weight_dtype,
            "sp_shards": self.cfg.sp_shards,
            "max_len": self._ladder.max_len,
        }

    def cell_for(self, bucket: int, batch_shape: Optional[int] = None) -> dict:
        """The cost-ledger cell one bucket's executable bills to —
        flight records and operators use it to answer "this request ran
        WHICH executable, on which arm, at what precision". With the
        batch-shape ladder armed each (bucket, shape) has its own cell;
        `batch_shape=None` returns the top-rung cell (the shape a full
        batch runs at — the identity known at submit time, before batch
        assembly has picked a rung)."""
        if batch_shape is None:
            batch_shape = self._batch_shapes[-1]
        key = self._cost_cells.get((bucket, batch_shape))
        if key is None:
            return {}
        pool, b, schedule, arm, dtype = key
        return {"pool": pool, "bucket": b, "schedule": schedule,
                "backend_arm": arm, "weight_dtype": dtype}

    def retry_after_estimate(self) -> float:
        """Backoff advice for shed clients: batch-assembly wait plus the
        backlog drained at the measured per-request rate.

        The rate is an EMA of non-overlapped batch wall seconds per
        settled request, so it is ladder-aware by construction: partial
        batches served at small ladder rungs feed their real (cheaper)
        drain rate instead of the old assumption that every backlog
        batch is a full `max_batch` dispatch at batch p50. A cold engine
        (nothing settled yet) falls back to that p50 heuristic; both
        paths clamp to something actionable."""
        backlog = self._queue.qsize() + 1
        with self._rate_lock:
            sec_per_req = self._sec_per_req_ema
        if sec_per_req > 0.0:
            est = self.cfg.max_wait_s + sec_per_req * backlog
        else:
            lat = self.metrics.latency.snapshot()
            per_batch = lat.get("p50") or 0.1
            backlog_batches = 1 + self._queue.qsize() // self.cfg.max_batch
            est = self.cfg.max_wait_s + per_batch * backlog_batches
        return float(min(60.0, max(0.05, est)))

    def _note_drain(self, window_s: float, n: int):
        """Feed the drain-rate EMA one settled batch: `window_s` is the
        batch's NON-overlapped wall share (sync: dispatch wall), so in
        pipelined mode concurrently in-flight batches don't each claim
        the same second and overstate how slowly the engine drains."""
        if n <= 0:
            return
        sec_per_req = window_s / n
        with self._rate_lock:
            if self._sec_per_req_ema == 0.0:
                self._sec_per_req_ema = sec_per_req
            else:
                self._sec_per_req_ema = (
                    0.2 * sec_per_req + 0.8 * self._sec_per_req_ema
                )

    def health(self) -> dict:
        """Cheap liveness payload for `/healthz` (telemetry/ops_plane.py):
        no engine stats, no model touch. `status` is "ok" (serving),
        "degraded" (up but fast-rejecting: breaker not closed), or
        "down" (closed or worker dead — the HTTP layer maps it to 503)."""
        alive = self._worker.is_alive()
        if self._settle_thread is not None:
            alive = alive and self._settle_thread.is_alive()
        status = "ok" if (not self._closed and alive) else "down"
        out = {
            "status": status,
            "closed": self._closed,
            "worker_alive": alive,
            "queue_depth": self._queue.qsize(),
            "queue_capacity": self.cfg.max_queue,
        }
        if self._settle_thread is not None:
            out["settle_alive"] = self._settle_thread.is_alive()
        if self._breaker is not None:
            snap = self._breaker.snapshot()
            out["breaker"] = snap["state"]
            if status == "ok" and snap["state"] != "closed":
                out["status"] = "degraded"
        return out

    def sample_gauges(self):
        """Ticker hook (ops plane): publish the cost-plane gauges when
        this engine owns its ledgers (a fleet publishes the shared ones
        from ITS sample_gauges)."""
        if self._owns_costs:
            self.costs.publish()
            self.goodput.publish()

    def stats(self) -> dict:
        """JSON-ready health/stats snapshot."""
        self.sample_gauges()
        snap = self.metrics.snapshot(self.cfg.max_batch)
        snap["queue"] = {
            "depth": self._queue.qsize(),
            "capacity": self.cfg.max_queue,
        }
        snap["cache"] = self._cache.snapshot()
        snap["buckets"] = list(self._ladder.buckets)
        snap["max_batch"] = self.cfg.max_batch
        snap["batch_shapes"] = list(self._batch_shapes)
        if self.cfg.pipeline_depth:
            snap["pipeline"] = {
                "depth": self.cfg.pipeline_depth,
                **self.metrics.pipeline_snapshot(),
            }
        snap["closed"] = self._closed
        snap["weights"] = dict(self._weight_residency)
        # which backend arm each hot op resolved to at build (part of the
        # config tag — operators reading stats() can see WHY two replicas
        # refuse to share a cache keyspace)
        snap["dispatch"] = self._dispatch_tag
        snap["capability"] = self.capability()
        if self.cfg.sp_shards:
            # the per-bucket schedule plan + its chip-free residency
            # pricing: what the heuristic decided and what it priced
            snap["sp"] = {
                "shards": self.cfg.sp_shards,
                "hbm_budget_bytes": int(self.cfg.sp_hbm_gb * (1 << 30)),
                "schedules": {
                    str(b): r.as_dict()
                    for b, r in sorted(self._sp_plan.items())
                },
            }
        if self._breaker is not None:
            snap["breaker"] = self._breaker.snapshot()
        # the serving cost plane (telemetry/costs.py) — only when this
        # engine OWNS its ledgers: a fleet replica's cells/accounts live
        # in the FLEET's shared ledgers and its stats() would otherwise
        # show every sibling's rows as its own
        if self._owns_costs:
            snap["costs"] = self.costs.snapshot()
            snap["serve_goodput"] = self.goodput.snapshot()
        # the unified telemetry view: every registry metric (per-bucket
        # compile count/seconds gauges included) plus per-phase span
        # aggregates; empty-but-present under the no-op tracer so stats
        # consumers need no feature detection
        snap["telemetry"] = {
            "metrics": self.metrics.registry.snapshot(),
            "spans": self._tracer.summary(),
        }
        return snap

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop accepting work and stop the worker.

        drain=True: pending requests (queued + staged) are served first —
        batch-assembly deadlines are waived, expiry still honored.
        drain=False: pending requests fail with EngineClosedError; with
        pipelined dispatch, batches ALREADY enqueued on device are still
        settled either way (their device time is spent — abandoning them
        would only turn finished work into failures).
        Idempotent; safe to call from any thread except the worker.
        """
        # under the inflight lock: _abort_worker flips the same flag
        # from the worker thread (CONC001)
        with self._inflight_lock:
            self._closed = True
        self._drain_on_stop = drain
        self._stop.set()
        self._worker.join(timeout)
        # a submit() racing the close flag can strand a request in the
        # queue after the worker exited; nothing will serve it — fail it.
        # Only once the worker is actually DEAD: with a finite join
        # timeout the worker may still be draining, and popping its queue
        # here would fail requests drain=True promised to serve
        if self._worker.is_alive():
            return
        # the worker's final flush put the settle sentinel LAST, so by
        # the time the settle thread sees it every in-flight batch has
        # settled (drain=True's promise covers the pipeline window too)
        if self._settle_thread is not None:
            self._settle_thread.join(timeout)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if self._resolve(req, exc=EngineClosedError(
                    "engine shut down before request was served")):
                self.metrics.inc("failed")
                self.metrics.inc_error("engine_closed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=True)
        return False

    def _resolve(self, req: ServingRequest, *, result=None, exc=None) -> bool:
        """Finish a request and drop it from the coalescing map."""
        finished = req._finish(result=result, exc=exc)
        if finished:
            with self._inflight_lock:
                if self._inflight.get(req.cache_key) is req:
                    del self._inflight[req.cache_key]
            if self.flights is not None:
                # THE terminal chokepoint (worker, drain, abort, timeout
                # paths all resolve through here): seal the exemplar
                if exc is not None:
                    self.flights.finish(
                        req.trace_id, "failed",
                        code=getattr(exc, "code", type(exc).__name__),
                        replica=self.replica_name)
                else:
                    self.flights.finish(
                        req.trace_id, "completed",
                        replica=self.replica_name,
                        latency_s=result.latency_s,
                        batch_bucket=result.bucket)
        return finished

    # ------------------------------------------------- compile cache

    def _executable_for(self, bucket: int, batch_shape: Optional[int] = None):
        """AOT-compiled executable for (bucket, batch shape, engine
        config); compiled at most once per (bucket, shape), under a lock
        (precompile + worker can race). `batch_shape=None` compiles the
        top ladder rung (== max_batch; the only rung without the
        batch-shape ladder). Shapes never alias: the executable table is
        keyed on the pair, so a 2-row batch can never run — or clobber —
        the 4-row binary. The per-bucket compile gauges accumulate every
        shape's seconds under the bucket (`compile_count` stays the
        <= len(buckets) distinct-bucket invariant)."""
        if batch_shape is None:
            batch_shape = self._batch_shapes[-1]
        with self._compile_lock:
            exe = self._executables.get((bucket, batch_shape))
            if exe is not None:
                return exe
            B, rows = batch_shape, self.cfg.msa_rows
            mcfg, iters, init = self.model_cfg, self.cfg.mds_iters, self.cfg.mds_init
            apply_fn = self._model_apply_fn
            plan = self._sp_plan.get(bucket)
            if plan is not None and plan.schedule != "dense":
                # the SP arm: this bucket's trunk runs the planned
                # dynamic-axial cut over the model-axis mesh
                from alphafold2_tpu.serving import sp_arm

                apply_fn = sp_arm.make_sp_apply_fn(
                    self._sp_mesh, plan.schedule)

            ee_depths = self.cfg.early_exit_depths
            ee_kl = self.cfg.early_exit_kl

            def run(params, tokens, mask, key, msa=None, msa_mask=None):
                out = predict_structure(
                    params, mcfg, tokens, mask=mask, msa=msa,
                    msa_mask=msa_mask, rng=key, mds_iters=iters,
                    mds_init=init, model_apply_fn=apply_fn,
                    early_exit_depths=ee_depths, early_exit_kl=ee_kl,
                )
                # the (B, Lb, Lb, buckets) logits stay on device: at
                # bucket 512 they are ~150 MB per batch of host transfer
                # nothing in the serving path reads
                keep = ("coords", "confidence", "stress")
                if ee_depths:
                    keep = keep + ("exit_depth",)
                return {k: out[k] for k in keep}

            s_tok = jax.ShapeDtypeStruct((B, bucket), np.int32)
            s_mask = jax.ShapeDtypeStruct((B, bucket), np.bool_)
            s_key = jax.ShapeDtypeStruct(
                self._base_key.shape, self._base_key.dtype
            )
            # compile_span: per-bucket compile counter + wall-seconds
            # gauges in the registry, and one `serving_compile` span.
            # The goodput ledger gets the same wall under "compile" —
            # accounted HERE (not in the dispatch timing below, which
            # subtracts the compile tracker's delta) so precompile-at-
            # build and first-call compiles land in one bucket.
            t_compile = time.monotonic()
            with self.metrics.compile_span(bucket):
                if rows:
                    s_msa = jax.ShapeDtypeStruct((B, rows, bucket), np.int32)
                    s_msam = jax.ShapeDtypeStruct(
                        (B, rows, bucket), np.bool_
                    )
                    exe = (
                        jax.jit(run)
                        .lower(self._params, s_tok, s_mask, s_key, s_msa,
                               s_msam)
                        .compile()
                    )
                else:
                    exe = (
                        jax.jit(run)
                        .lower(self._params, s_tok, s_mask, s_key)
                        .compile()
                    )
            self.goodput.add(self._goodput_name, "compile",
                             time.monotonic() - t_compile)
            self._executables[(bucket, batch_shape)] = exe
            return exe

    def _call_executable(self, bucket: int, tokens, mask, msa=None,
                         msa_mask=None):
        """One device call. Overridable seam: tests substitute failure
        injection or fake outputs here without touching the scheduler.
        The batch shape rides in `tokens.shape[0]` — batch assembly
        already padded the rows to the chosen ladder rung."""
        exe = self._executable_for(bucket, tokens.shape[0])
        with self._counter_lock:
            self._batch_counter += 1
            batch_idx = self._batch_counter
        key = jax.random.fold_in(self._base_key, batch_idx)
        if self.cfg.msa_rows:
            return exe(self._params, tokens, mask, key, msa, msa_mask)
        return exe(self._params, tokens, mask, key)

    def _next_dispatch_idx(self) -> int:
        """Monotone dispatch index (the chaos clock) — under the counter
        lock: the worker's pipelined enqueues and a settle-thread
        poison-split retry can dispatch concurrently."""
        with self._counter_lock:
            idx = self._dispatch_counter
            self._dispatch_counter += 1
            return idx

    def _realize(self, out):
        """Block until a dispatch's output buffers are realized on host-
        visible memory. Overridable seam: tests simulating a wedged
        DEVICE computation (as opposed to a wedged dispatch call) block
        or raise here — it is the exact point the hung-batch watchdog
        guards in both dispatch modes."""
        return jax.block_until_ready(out)

    def _dispatch(self, bucket: int, tokens, mask, msa=None, msa_mask=None,
                  trace_ids=()):
        """One guarded dispatch: the chaos fault hook plus the optional
        hung-batch watchdog around `_call_executable`.

        With a watchdog configured, the call runs on a throwaway daemon
        thread; exceeding the timeout raises HungBatchError and ABANDONS
        the call (Python threads cannot be killed) — the orphan thread's
        late result is written into a container nobody reads, and the
        worker keeps serving instead of wedging. Without a watchdog the
        call runs inline (zero thread overhead, the production default
        when the runtime already bounds execution time).
        """
        idx = self._next_dispatch_idx()

        def call():
            if self._fault_hook is not None:
                self._fault_hook(idx, bucket)
            # the execute span covers device dispatch + (first-call)
            # compile; compile time is separately visible under the
            # nested `serving_compile` span, so execute-minus-compile is
            # readable straight off the trace. bind_trace stamps the
            # batch ids onto that nested span too (CompileTracker never
            # heard of requests) — on whichever thread call() runs, so a
            # bundle grep for a victim's id finds the 30s compile that
            # actually delayed it
            with self._tracer.bind_trace(list(trace_ids)), \
                    self._tracer.span("serving.execute", cat="serving",
                                      bucket=bucket, dispatch=idx,
                                      trace_ids=list(trace_ids),
                                      **self._span_tags):
                out = self._call_executable(
                    bucket, tokens, mask, msa, msa_mask
                )
                # realize the async device call INSIDE the span and the
                # watchdog window: executables return unrealized buffers,
                # so without this the execute span / cost-ledger timing
                # would end at enqueue (billing dispatch overhead as the
                # batch's device-seconds while the real compute lands in
                # the untimed np.asarray conversion) and a wedged device
                # computation would slip past the hung-batch watchdog
                return self._realize(out)

        timeout = self.cfg.watchdog_timeout_s
        if timeout is None:
            return call()
        box = {}
        done = threading.Event()

        def runner():
            try:
                box["out"] = call()
            except BaseException as e:  # noqa: BLE001 — relayed below
                box["exc"] = e
            finally:
                done.set()

        threading.Thread(
            target=runner, daemon=True,
            name=f"af2-dispatch-{self.replica_name or 'engine'}-{idx}"
        ).start()
        if not done.wait(timeout):
            self._incident("watchdog_fire", bucket=bucket, dispatch=idx,
                           timeout_s=timeout, trace_ids=list(trace_ids))
            raise HungBatchError(
                f"dispatch {idx} (bucket {bucket}) exceeded the {timeout}s "
                f"hung-batch watchdog; call abandoned"
            )
        if "exc" in box:
            raise box["exc"]
        return box["out"]

    # ------------------------------------------------- scheduler worker

    def _worker_loop(self):
        staged = {}  # bucket -> list[ServingRequest], FIFO
        try:
            while True:
                self._dispatch_ready(staged, force=False)
                if self._stop.is_set():
                    self._final_flush(staged)
                    return
                try:
                    req = self._queue.get(timeout=self._poll_timeout(staged))
                except queue.Empty:
                    continue
                self._stage(staged, req)
                # opportunistically drain whatever arrived with it, so a
                # burst becomes one batch instead of max_batch singleton
                # batches
                while True:
                    try:
                        self._stage(staged, self._queue.get_nowait())
                    except queue.Empty:
                        break
        except BaseException as e:  # noqa: BLE001 — last-resort guard
            # anything escaping the scheduler (host-side bookkeeping bugs,
            # a metrics sink hitting a full disk, ...) must not strand
            # pending requests behind a silently dead thread: fail
            # everything loudly (traceback included) and refuse further
            # traffic; no re-raise — the abort IS the report
            self._abort_worker(staged, e)

    def _abort_worker(self, staged, cause: BaseException):
        import traceback

        with self._inflight_lock:
            self._closed = True
        traceback.print_exc()
        err = PredictionError(
            f"serving worker crashed: {type(cause).__name__}: {cause}; "
            f"engine is closed"
        )
        err.__cause__ = cause
        while True:
            try:
                self._stage(staged, self._queue.get_nowait())
            except queue.Empty:
                break
        for reqs in staged.values():
            for req in reqs:
                if self._resolve(req, exc=err):
                    self.metrics.inc("failed")
                    self.metrics.inc_error(err)
        staged.clear()
        if self._settle_thread is not None:
            # in-flight batches (enqueued before the crash) still settle
            # FIFO ahead of the sentinel; nothing new can follow it
            self._settle_queue.put(_SETTLE_STOP)

    def _stage(self, staged, req: ServingRequest):
        staged.setdefault(req.bucket, []).append(req)

    def _poll_timeout(self, staged) -> float:
        """Sleep until the nearest batch-assembly deadline, capped so stop
        requests are noticed promptly."""
        if not staged:
            return _IDLE_POLL_S
        now = time.monotonic()
        nearest = min(
            reqs[0].submitted_at + self.cfg.max_wait_s
            for reqs in staged.values() if reqs
        )
        return min(_IDLE_POLL_S, max(1e-3, nearest - now))

    def _dispatch_ready(self, staged, force: bool):
        for bucket in list(staged):
            reqs = staged[bucket]
            while reqs and (
                force
                or len(reqs) >= self.cfg.max_batch
                or time.monotonic() - reqs[0].submitted_at
                >= self.cfg.max_wait_s
            ):
                batch = reqs[: self.cfg.max_batch]
                del reqs[: self.cfg.max_batch]
                self._run_batch(bucket, batch)
            if not reqs:
                staged.pop(bucket)

    def _final_flush(self, staged):
        """Stop path: drain the queue, then serve or fail everything."""
        while True:
            try:
                self._stage(staged, self._queue.get_nowait())
            except queue.Empty:
                break
        if self._drain_on_stop:
            self._dispatch_ready(staged, force=True)
        else:
            for reqs in staged.values():
                for req in reqs:
                    if self._resolve(req, exc=EngineClosedError(
                            "engine shut down before request was served")):
                        self.metrics.inc("failed")
                        self.metrics.inc_error("engine_closed")
            staged.clear()
        if self._settle_thread is not None:
            # sentinel LAST: batches the drain just enqueued (and any
            # still in flight from before the stop) settle first, so
            # shutdown(drain=True) means "every in-flight batch settled"
            self._settle_queue.put(_SETTLE_STOP)

    def _run_batch(self, bucket: int, reqs, allow_split: bool = True):
        now = time.monotonic()
        live = []
        for req in reqs:
            if req.expired(now):
                exc = RequestTimeoutError(
                    f"deadline passed after "
                    f"{now - req.submitted_at:.3f}s in queue",
                    retry_after_s=self.retry_after_estimate())
                if self._resolve(req, exc=exc):
                    self.metrics.inc("timed_out")
                    self.metrics.inc_error(exc)
            else:
                live.append(req)
        # an expired request may have been the breaker's half-open
        # probe; without a dispatch outcome the probe must be released
        # or the circuit would wait on it forever
        if len(live) < len(reqs) and self._breaker is not None:
            self._breaker.abandon_probe()
        if not live:
            return
        if not allow_split:
            # per-request poison-isolation retry: it re-enters here from
            # INSIDE the parent batch's serving.batch span — recording a
            # second queue_wait/batch span per request would double-count
            # the phase aggregates this subsystem exists to report
            self._run_live(bucket, live, allow_split)
            return
        if self._tracer.enabled:
            # queue phase, measured from each member's submit timestamp
            # (monotonic deltas; recorded as ending now on the tracer clock)
            for req in live:
                self._tracer.add("serving.queue_wait",
                                 now - req.submitted_at, cat="serving",
                                 bucket=bucket, trace_id=req.trace_id,
                                 **self._span_tags)
        with self._tracer.span("serving.batch", cat="serving", bucket=bucket,
                               n=len(live),
                               trace_ids=[r.trace_id for r in live],
                               **self._span_tags):
            self._run_live(bucket, live, allow_split)

    def _run_live(self, bucket: int, live, allow_split: bool):
        shape = self._batch_shape_for(len(live))
        if self.cfg.pipeline_depth and allow_split and not self._settle_dead:
            self._run_pipelined(bucket, shape, live)
        else:
            # sync path: pipeline off, or a poison-isolation single
            # retry (those run synchronously on whichever thread split
            # the batch — the worker in sync mode, the settle thread in
            # pipelined mode), or the settle thread died mid-flight
            self._run_sync(bucket, shape, live, allow_split)

    def _batch_shape_for(self, n: int) -> int:
        """Smallest ladder rung that fits n live rows (== max_batch when
        the batch-shape ladder is off)."""
        for s in self._batch_shapes:
            if n <= s:
                return s
        return self._batch_shapes[-1]

    def _billed_window(self, t0: float, t1: float, compile_s0: float):
        """(window_s, billed_s) for one dispatch realized over [t0, t1].

        window_s is the span clamped against the engine-wide realization
        watermark: with pipelined dispatch, concurrent in-flight spans
        each cover the same wall seconds, and billing every span in full
        would double-count device time (the PR 19 rule — bill what the
        device actually spent — must survive the split). Settles are
        FIFO, so the clamp partitions wall time exactly: the sum of
        windows never exceeds wall, which is what keeps the goodput
        ledger's sums-to-wall invariant intact. billed_s additionally
        subtracts the compile tracker's delta over the span (a
        first-call compile is accounted under "compile", never
        "execute"); a compile straddling the span boundary is subtracted
        in full — conservative under-billing, never double-billing.
        Sync mode (depth 0) keeps the legacy arithmetic: window == wall.
        """
        compile_delta = self.metrics.compile_seconds_total() - compile_s0
        if not self.cfg.pipeline_depth:
            window = max(0.0, t1 - t0)
        else:
            with self._pipeline_lock:
                start = max(t0, self._last_realized_t)
                if t1 > self._last_realized_t:
                    self._last_realized_t = t1
            window = max(0.0, t1 - start)
        return window, max(0.0, window - compile_delta)

    def _fail_live(self, bucket: int, live, e: Exception, allow_split: bool,
                   burned_s: float = 0.0):
        """Shared failure tail for a dispatched batch (sync dispatch,
        pipelined enqueue, or pipelined settle): bill the burned device
        time as requeue badput, poison-split multi-request batches, and
        otherwise resolve everything with the terminal error."""
        if burned_s > 0.0:
            # device time a FAILED dispatch burned: the failover bill
            # ("requeue" badput — its requests requeue onto another
            # replica or fail), never productive execute
            self.goodput.add(self._goodput_name, "requeue", burned_s)
        hung = isinstance(e, HungBatchError)
        if not hung and allow_split and len(live) > 1:
            # a poison request must not take its batchmates down: retry
            # one at a time so only the offender fails. A HUNG batch is
            # different — the device (not a request) is the suspect, and
            # each per-request retry would burn another full watchdog
            # window against a wedged call
            for req in live:
                self._run_batch(bucket, [req], allow_split=False)
            return
        # terminal dispatch outcome: the breaker counts it
        if self._breaker is not None:
            self._breaker.record_failure()
        if hung:
            err = e
        else:
            err = PredictionError(
                f"prediction failed for bucket {bucket}: "
                f"{type(e).__name__}: {e}"
            )
            err.__cause__ = e
        for req in live:
            if self._resolve(req, exc=err):
                self.metrics.inc("failed")
                self.metrics.inc_error(err)

    def _run_sync(self, bucket: int, shape: int, live, allow_split: bool):
        dispatch_t0 = None  # set iff the device call actually started
        compile_s0 = 0.0
        try:
            # batch assembly sits INSIDE the guard: a request that breaks
            # host-side padding must fail like one that breaks the model
            # call — isolated to its batch, never escalated to the
            # worker's last-resort abort
            tokens, mask, n_real = pad_batch(
                [r.tokens for r in live], bucket, shape
            )
            msa = msa_mask = None
            if self.cfg.msa_rows:
                msa, msa_mask = self._pad_msa_batch(live, bucket, shape)
            # cost-plane timing: dispatch wall minus the compile
            # tracker's delta = pure execute seconds — a bucket's first
            # batch (30s+ of XLA on real models) must not poison the
            # cost ledger's EMA or read as productive execute time
            # (_executable_for accounts the compile bucket itself)
            compile_s0 = self.metrics.compile_seconds_total()
            dispatch_t0 = time.monotonic()
            out = self._dispatch(bucket, tokens, mask, msa, msa_mask,
                                 trace_ids=[r.trace_id for r in live])
            window, exec_s = self._billed_window(
                dispatch_t0, time.monotonic(), compile_s0)
            coords = np.asarray(out["coords"])
            conf = np.asarray(out["confidence"])
            stress = np.asarray(out["stress"])
            exit_depth = (np.asarray(out["exit_depth"])
                          if "exit_depth" in out else None)
        except Exception as e:  # noqa: BLE001 — isolate, report, keep serving
            burned = 0.0
            if dispatch_t0 is not None:
                _, burned = self._billed_window(
                    dispatch_t0, time.monotonic(), compile_s0)
            self._fail_live(bucket, live, e, allow_split, burned_s=burned)
            return

        if self._breaker is not None:
            self._breaker.record_success()
        # the cost plane's measured column + the goodput execute bucket
        # (accounted BEFORE the requests resolve, so a probe blocking on
        # its result observes this accounting inside its probe_span)
        self.goodput.add(self._goodput_name, "execute", exec_s)
        self._bill_batch(bucket, shape, exec_s, live, exit_depth)
        self._note_drain(window, len(live))
        done_at = time.monotonic()
        with self._tracer.span("serving.respond", cat="serving",
                               bucket=bucket, n=len(live),
                               trace_ids=[r.trace_id for r in live],
                               **self._span_tags):
            self._respond(bucket, shape, live, coords, conf, stress, n_real,
                          done_at, exit_depth=exit_depth)

    # ------------------------------------------------- pipelined dispatch

    def _run_pipelined(self, bucket: int, shape: int, live):
        """Assemble + enqueue on the worker thread; realization, billing
        and response move to the settle thread (`_settle_loop`). At most
        `pipeline_depth` batches sit enqueued-but-unsettled, so batch
        N's device compute overlaps batch N±1's host work without
        letting the device queue grow unboundedly."""
        idx = self._next_dispatch_idx()
        acquired = False
        try:
            tokens, mask, n_real = pad_batch(
                [r.tokens for r in live], bucket, shape
            )
            msa = msa_mask = None
            if self.cfg.msa_rows:
                msa, msa_mask = self._pad_msa_batch(live, bucket, shape)
            # the chaos fault hook fires at the same point in the
            # request's life as the sync path: after assembly, before
            # the device call, inside the failure-isolation guard
            if self._fault_hook is not None:
                self._fault_hook(idx, bucket)
            # bound the in-flight window BEFORE touching the device. The
            # timeout loop keeps the worker responsive to a dead settle
            # thread, whose releases would otherwise never come.
            while not self._inflight_sem.acquire(timeout=0.1):
                if self._settle_dead:
                    raise PredictionError(
                        "settle thread died with the pipeline window "
                        "full; engine is closed")
            acquired = True
            # compile snapshot BEFORE the call — a first-use compile of
            # this (bucket, shape) happens inside _call_executable and
            # must be subtracted from the settle-side billing window
            compile_s0 = self.metrics.compile_seconds_total()
            enqueue_t = time.monotonic()
            out = self._call_executable(bucket, tokens, mask, msa, msa_mask)
        except Exception as e:  # noqa: BLE001 — same isolation as sync
            if acquired:
                self._inflight_sem.release()
            self._fail_live(bucket, live, e, allow_split=True)
            return
        self.metrics.pipeline_inflight_delta(+1)
        self._settle_queue.put(_InFlight(
            bucket=bucket, shape=shape, live=live, out=out, idx=idx,
            enqueue_t=enqueue_t, compile_s0=compile_s0, n_real=n_real,
        ))

    def _settle_loop(self):
        """Settle-thread main: realize each in-flight batch FIFO, bill
        the cost plane, resolve its requests. The worker enqueues the
        stop sentinel LAST (final flush / abort), so every in-flight
        batch settles before this thread exits."""
        try:
            while True:
                rec = self._settle_queue.get()
                if rec is _SETTLE_STOP:
                    return
                self._settle(rec)
        except BaseException as e:  # noqa: BLE001 — last-resort guard
            # mirror of _abort_worker: bookkeeping bugs on the settle
            # side must not strand in-flight requests behind a silently
            # dead thread
            self._abort_settle(e)

    def _settle(self, rec: "_InFlight"):
        try:
            out = self._wait_realized(rec)
            realized_t = time.monotonic()
            coords = np.asarray(out["coords"])
            conf = np.asarray(out["confidence"])
            stress = np.asarray(out["stress"])
            exit_depth = (np.asarray(out["exit_depth"])
                          if "exit_depth" in out else None)
        except Exception as e:  # noqa: BLE001 — isolate, keep settling
            realized_t = time.monotonic()
            _, burned = self._billed_window(
                rec.enqueue_t, realized_t, rec.compile_s0)
            # release the window slot BEFORE the poison-split retries:
            # those run synchronously here and the worker must be able
            # to keep enqueuing behind them
            self._inflight_sem.release()
            self.metrics.pipeline_inflight_delta(-1)
            self._fail_live(rec.bucket, rec.live, e, allow_split=True,
                            burned_s=burned)
            return
        self._inflight_sem.release()
        self.metrics.pipeline_inflight_delta(-1)
        span_s = realized_t - rec.enqueue_t
        window, exec_s = self._billed_window(
            rec.enqueue_t, realized_t, rec.compile_s0)
        # the execute span still brackets enqueue->realized per batch
        # (the PR 19 contract); the overlap gauge is cumulative
        # span/window — >1.0 exactly when in-flight batches overlapped
        self._tracer.add("serving.execute", span_s, cat="serving",
                         bucket=rec.bucket, dispatch=rec.idx,
                         trace_ids=[r.trace_id for r in rec.live],
                         **self._span_tags)
        self.metrics.observe_pipeline_settle(span_s, window)
        if self._breaker is not None:
            self._breaker.record_success()
        # accounted BEFORE the requests resolve (probe_span contract)
        self.goodput.add(self._goodput_name, "execute", exec_s)
        self._bill_batch(rec.bucket, rec.shape, exec_s, rec.live, exit_depth)
        self._note_drain(window, len(rec.live))
        done_at = time.monotonic()
        with self._tracer.span("serving.respond", cat="serving",
                               bucket=rec.bucket, n=len(rec.live),
                               trace_ids=[r.trace_id for r in rec.live],
                               **self._span_tags):
            self._respond(rec.bucket, rec.shape, rec.live, coords, conf,
                          stress, rec.n_real, done_at, exit_depth=exit_depth)

    def _wait_realized(self, rec: "_InFlight"):
        """Realize one in-flight batch under the hung-batch watchdog.

        Every in-flight dispatch gets a FULL watchdog window measured
        from when the settle thread reaches it (settles are FIFO): a
        wedged batch fires its own watchdog and is abandoned, and its
        pipelined neighbor then starts a fresh window — one wedged
        in-flight batch never takes its neighbor down with it. Without a
        watchdog the realization runs inline on the settle thread."""
        timeout = self.cfg.watchdog_timeout_s
        if timeout is None:
            return self._realize(rec.out)
        box = {}
        done = threading.Event()

        def runner():
            try:
                box["out"] = self._realize(rec.out)
            except BaseException as e:  # noqa: BLE001 — relayed below
                box["exc"] = e
            finally:
                done.set()

        threading.Thread(
            target=runner, daemon=True,
            name=f"af2-settle-wait-{self.replica_name or 'engine'}-{rec.idx}"
        ).start()
        if not done.wait(timeout):
            self._incident("watchdog_fire", bucket=rec.bucket,
                           dispatch=rec.idx, timeout_s=timeout,
                           trace_ids=[r.trace_id for r in rec.live])
            raise HungBatchError(
                f"dispatch {rec.idx} (bucket {rec.bucket}) exceeded the "
                f"{timeout}s hung-batch watchdog; in-flight realization "
                f"abandoned"
            )
        if "exc" in box:
            raise box["exc"]
        return box["out"]

    def _abort_settle(self, cause: BaseException):
        import traceback

        # _settle_dead FIRST: the worker's bounded semaphore acquire
        # polls it, and must stop waiting on releases that will never
        # come before it can observe the closed flag
        self._settle_dead = True
        with self._inflight_lock:
            self._closed = True
        traceback.print_exc()
        err = PredictionError(
            f"serving settle thread crashed: {type(cause).__name__}: "
            f"{cause}; engine is closed"
        )
        err.__cause__ = cause
        while True:
            try:
                rec = self._settle_queue.get_nowait()
            except queue.Empty:
                break
            if rec is _SETTLE_STOP:
                continue
            self._inflight_sem.release()
            self.metrics.pipeline_inflight_delta(-1)
            for req in rec.live:
                if self._resolve(req, exc=err):
                    self.metrics.inc("failed")
                    self.metrics.inc_error(err)

    def _bill_batch(self, bucket, shape, exec_s, live, exit_depth):
        """Charge the batch's measured device-seconds to cost cells.

        Cells are keyed per (bucket, batch shape): the ladder leg's
        whole point is that a 1-row dispatch is a different (cheaper)
        executable than the 4-row one, so their EMAs must never blend.
        Without early exit the whole batch bills that one cell. With it,
        requests grouped by exit depth split `exec_s`
        flops-proportionally across the per-exit-depth cells — the shares
        sum to exec_s exactly, so `fleet_chip_seconds_total` (the bench
        gate's headline) stays a faithful device-time integral."""
        if exit_depth is None or not self._exit_cells:
            self.costs.observe_batch(self._cost_cells[(bucket, shape)],
                                     device_seconds=exec_s,
                                     requests=len(live))
            return
        full_depth = self.model_cfg.depth
        full_flops = self._depth_flops[(bucket, full_depth)]
        groups = {}
        for i in range(len(live)):
            d = int(exit_depth[i])
            groups[d] = groups.get(d, 0) + 1
        total_w = sum(
            self._depth_flops.get((bucket, d), full_flops) * n
            for d, n in groups.items())
        for d, n in sorted(groups.items()):
            cell = self._exit_cells.get((bucket, d, shape),
                                        self._cost_cells[(bucket, shape)])
            w = self._depth_flops.get((bucket, d), full_flops) * n
            share = exec_s * (w / total_w) if total_w else 0.0
            self.costs.observe_batch(cell, device_seconds=share,
                                     requests=n)

    def _respond(self, bucket, shape, live, coords, conf, stress, n_real,
                 done_at, exit_depth=None):
        for i, req in enumerate(live):
            L = req.length
            # copies, not views: a view would both pin the whole
            # (max_batch, bucket, 3) batch array in the cache and let a
            # client's in-place edit corrupt later cache hits
            conf_i = conf[i, :L].copy()
            result = PredictionResult(
                seq=req.seq,
                coords=coords[i, :L].copy(),
                confidence=conf_i,
                stress=float(stress[i]),
                bucket=bucket,
                from_cache=False,
                latency_s=done_at - req.submitted_at,
                replica=self.replica_name,
                trace_id=req.trace_id,
                mean_confidence=float(conf_i.mean()) if L else 0.0,
                exit_depth=int(exit_depth[i]) if exit_depth is not None
                else 0,
            )
            # the cached entry and the resolved result may share arrays:
            # clients only ever see result() copies
            self._cache.put(req.cache_key, result)
            if self._resolve(req, result=result):
                self.metrics.inc("completed")
                self.metrics.latency.observe(result.latency_s)
        self.metrics.observe_batch(
            n_real, shape,
            latency_s=done_at - live[0].submitted_at,
        )

    def _pad_msa_batch(self, live, bucket: int, batch_shape: int):
        """(B, rows, bucket) MSA stream at the chosen batch shape. A
        request without an MSA gets its query as row 0 (an alignment
        always contains the query); unused rows duplicate row 0 under a
        False mask — finite values that masked attention zero-weights,
        never NaN-generating garbage."""
        B, rows = batch_shape, self.cfg.msa_rows
        from alphafold2_tpu.constants import PAD_TOKEN_ID

        msa = np.full((B, rows, bucket), PAD_TOKEN_ID, np.int32)
        msam = np.zeros((B, rows, bucket), bool)
        for i, req in enumerate(live):
            L = req.length
            src = req.msa if req.msa is not None else req.tokens[None]
            src_mask = (
                req.msa_mask if req.msa_mask is not None
                else np.ones(src.shape, bool)
            )
            r = src.shape[0]
            msa[i, :r, :L] = src
            msam[i, :r, :L] = src_mask
            for j in range(r, rows):
                msa[i, j] = msa[i, 0]  # finite filler, masked out
        for i in range(len(live), B):
            msa[i], msam[i] = msa[len(live) - 1], msam[len(live) - 1]
        return msa, msam
