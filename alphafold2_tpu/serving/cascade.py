"""Adaptive-fidelity cascade: confidence-gated draft -> verify escalation.

The fleet's fidelity ladder (int8 weights PR 8, reduced MDS iterations
PR 6, capability pools PR 14, per-executable chip prices PR 15) was
static per-pool config: every request paid full f32/deep chip cost
regardless of difficulty. This module makes fidelity DYNAMIC (ROADMAP
item 1, HelixFold arxiv 2207.05477 bounds what the cheap arm gets away
with; ParaFold arxiv 2111.06340 motivates spending expensive capacity
only where a cheap pass says it is needed):

  * every cascade-eligible request first runs on the DRAFT pool (a
    normal capability pool the operator points `CascadePolicy.
    draft_pool` at — typically int8 weights, fewer MDS iterations,
    reduced MSA rows, its own buckets/autoscaler);
  * a pluggable `ConfidenceScorer` scores the draft from the signals
    the pipeline already emits — per-residue distogram-entropy
    confidence (`geometry.distogram_confidence`) and the final
    normalized MDS stress — entirely host-side (no extra device work);
  * ACCEPTED drafts resolve the client future as-is (tier="draft");
    rejected drafts ESCALATE: the fleet re-queues the request onto the
    full-fidelity pool with the draft's `FeatureBundle` riding, so
    featurization is never repaid (tier="escalated").

The third lever — trunk-depth early exit (delta-KL-gated recycling that
stops when the distogram stabilizes) — lives in the serving pipeline
(`serving/pipeline.py` `early_exit_depths`/`early_exit_kl`) and is
priced per exit depth as distinct `ExecutableCostLedger` cells
(`serving/engine.py`), so the cost plane's price list reflects what a
shallow answer actually cost.

Cache-tier isolation (the PR 13 `resolution_tag` invariant family): the
fleet folds the cascade ROLE into each pool's `af2store:` tag, and only
ACCEPTED drafts persist under the draft tag — a draft-tier result can
never alias or serve a full-fidelity hit, and an escalated (rejected)
draft is never stored at all (tests/test_cascade.py pins both ways).

Thread-safety: `CascadeLedger` takes one LEAF lock for its EMA/count
dict ops — never held across a call out, never nested with the fleet
lock (af2lint pass 9 discipline).
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Optional

import numpy as np

_POLICY_KEYS = {
    "draft_pool", "min_confidence", "max_stress", "max_draft_length",
}


@dataclasses.dataclass(frozen=True)
class CascadePolicy:
    """Escalation thresholds — declarative, JSON-loadable like
    `ScalePolicy` (unknown keys reject loudly), validated eagerly.

    A draft is ACCEPTED when its mean per-residue distogram confidence
    reaches `min_confidence` AND (when `max_stress` > 0) its normalized
    MDS stress stays at or under `max_stress`; anything else escalates
    to the full-fidelity tier. `max_draft_length` > 0 sends longer
    sequences straight to the full tier (the draft pool's ladder
    ceiling bounds eligibility regardless)."""

    draft_pool: str = "draft"
    min_confidence: float = 0.5
    max_stress: float = 0.0       # 0 disables the stress leg
    max_draft_length: int = 0     # 0 = draft ladder ceiling decides

    def __post_init__(self):
        if not self.draft_pool:
            raise ValueError("draft_pool must name a capability pool")
        if self.draft_pool == "degraded":
            raise ValueError(
                "draft_pool must not be the reserved degraded tier — the "
                "draft tier is a first-class capability pool with health "
                "management and an autoscaler, not the outage fallback"
            )
        if not 0.0 <= self.min_confidence <= 1.0:
            raise ValueError(
                f"min_confidence must be in [0, 1], got "
                f"{self.min_confidence}"
            )
        if self.max_stress < 0:
            raise ValueError(
                f"max_stress must be >= 0 (0 disables the stress leg), "
                f"got {self.max_stress}"
            )
        if self.max_draft_length < 0:
            raise ValueError(
                f"max_draft_length must be >= 0 (0 defers to the draft "
                f"pool's ladder), got {self.max_draft_length}"
            )
        if self.min_confidence == 0.0 and self.max_stress == 0.0:
            # a gate that can never escalate silently serves every
            # request at draft fidelity — almost certainly a mis-set
            # policy file; demand an explicit threshold
            raise ValueError(
                "cascade policy has no active gate: set min_confidence "
                "> 0 and/or max_stress > 0"
            )

    @classmethod
    def from_dict(cls, d: dict) -> "CascadePolicy":
        unknown = set(d) - _POLICY_KEYS
        if unknown:
            # the faults --check stance: a typo'd knob must not silently
            # leave the default in force
            raise ValueError(
                f"unknown cascade-policy key(s) {sorted(unknown)}; "
                f"known: {sorted(_POLICY_KEYS)}"
            )
        return cls(**d)

    @classmethod
    def from_file(cls, path: str) -> "CascadePolicy":
        with open(path) as f:
            return cls.from_dict(json.load(f))


@dataclasses.dataclass(frozen=True)
class CascadeVerdict:
    """One draft's scoring outcome. `reason` is a stable label
    ("accepted" / "low_confidence" / "high_stress") — the escalation
    counter's dimension and the /explainz provenance field."""

    accept: bool
    confidence: float
    stress: float
    reason: str


class ConfidenceScorer:
    """Pluggable draft-quality gate: `score(result) -> CascadeVerdict`.

    Implementations must be cheap and host-side (they run on the
    replica completion callback) and must never raise — the fleet
    treats a scorer exception as an escalation (fail toward quality,
    never toward silently serving an unscored draft)."""

    def score(self, result) -> CascadeVerdict:
        raise NotImplementedError


class EntropyStressScorer(ConfidenceScorer):
    """The default gate: mean distogram-entropy confidence
    (`PredictionResult.confidence`, the pLDDT analog) + final
    normalized MDS stress, thresholded by a `CascadePolicy`.

    Scores from the result arrays directly rather than trusting any
    precomputed scalar, so custom engine factories / cache hits score
    identically."""

    def __init__(self, policy: CascadePolicy):
        self.policy = policy

    def score(self, result) -> CascadeVerdict:
        conf_arr = np.asarray(result.confidence, dtype=np.float64)
        conf = float(conf_arr.mean()) if conf_arr.size else 0.0
        stress = float(result.stress)
        if not np.isfinite(conf):
            conf = 0.0
        if conf < self.policy.min_confidence:
            return CascadeVerdict(False, conf, stress, "low_confidence")
        if 0.0 < self.policy.max_stress < stress:
            return CascadeVerdict(False, conf, stress, "high_stress")
        return CascadeVerdict(True, conf, stress, "accepted")


class _TierQuality:
    """Streaming per-tier quality: count + EMA confidence/stress."""

    __slots__ = ("count", "confidence_ema", "stress_ema")

    _ALPHA = 0.2

    def __init__(self):
        self.count = 0
        self.confidence_ema: Optional[float] = None
        self.stress_ema: Optional[float] = None

    def observe(self, confidence: float, stress: float):
        self.count += 1
        self.confidence_ema = (
            confidence if self.confidence_ema is None
            else self._ALPHA * confidence
            + (1 - self._ALPHA) * self.confidence_ema)
        self.stress_ema = (
            stress if self.stress_ema is None
            else self._ALPHA * stress + (1 - self._ALPHA) * self.stress_ema)

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "confidence_ema": (None if self.confidence_ema is None
                               else round(self.confidence_ema, 6)),
            "stress_ema": (None if self.stress_ema is None
                           else round(self.stress_ema, 6)),
        }


class CascadeLedger:
    """The cascade's observability plane: `cascade_*` metric families in
    the fleet registry + the `/statusz` `cascade` section (escalation
    rate and per-tier served quality — the acceptance surface).

    Families (docs/OBSERVABILITY.md inventory):
      cascade_requests_total{tier}     drafts scored / requests served
                                       per terminal tier
      cascade_escalations_total{reason} low_confidence / high_stress /
                                       scorer_error
      cascade_bypass_total{reason}     sent straight to the full tier
                                       (too_long / draft_unavailable)
      cascade_draft_confidence         histogram of draft mean confidence
      cascade_escalation_rate          escalations / scored drafts
      cascade_tier_confidence{tier}    served-quality EMA per tier
      cascade_tier_stress{tier}        served-stress EMA per tier
      cascade_early_exit_total{depth}  early-exited requests per trunk
                                       exit depth
    """

    def __init__(self, registry):
        self._registry = registry
        self._lock = threading.Lock()  # LEAF: dict/EMA ops only
        self._scored = 0
        self._escalated = 0
        self._tiers = {}          # tier -> _TierQuality
        self._served = {}         # tier -> counter (lazy)
        self._escalation = {}     # reason -> counter (lazy)
        self._bypass = {}         # reason -> counter (lazy)
        self._early_exit = {}     # depth -> counter (lazy)
        self._drafts_scored = registry.counter(
            "cascade_requests_total",
            help="cascade requests by tier outcome (draft = scored "
                 "drafts; draft_accepted / escalated / full = terminal "
                 "serves)", tier="draft")
        self._conf_hist = registry.histogram(
            "cascade_draft_confidence",
            help="draft-tier mean distogram confidence, sliding window "
                 "(the escalation gate's input distribution — watch it "
                 "drift when the draft arm regresses)")
        self._rate_gauge = registry.gauge(
            "cascade_escalation_rate",
            help="escalated / scored drafts, lifetime (pegged at 1.0 = "
                 "thresholds mis-set; a sudden spike = draft-quality "
                 "regression — docs/OPERATIONS.md runbook)")

    # ---------------------------------------------------- draft scoring

    def note_scored(self, verdict: CascadeVerdict):
        """One draft passed through the scorer (accept or escalate)."""
        self._drafts_scored.inc()
        self._conf_hist.observe(verdict.confidence)
        with self._lock:
            self._scored += 1
            if not verdict.accept:
                self._escalated += 1
        if not verdict.accept:
            # registry get-or-create is idempotent and takes its own
            # lock; kept OUTSIDE ours so the ledger lock stays a leaf
            counter = self._registry.counter(
                "cascade_escalations_total",
                help="drafts escalated to the full-fidelity tier, by "
                     "gate reason", reason=verdict.reason)
            with self._lock:
                self._escalation.setdefault(verdict.reason, counter)
            counter.inc()
        # the rate is a pure lifetime ratio — refresh the gauge here so a
        # run without the ops ticker (no --ops-port) still snapshots it
        self._rate_gauge.set(self.escalation_rate())

    def note_bypass(self, reason: str):
        """A request sent straight to the full tier without a draft leg
        (too_long: over the draft ladder/max_draft_length; draft_
        unavailable: no healthy draft replica — promoted, never
        starved)."""
        counter = self._registry.counter(
            "cascade_bypass_total",
            help="requests that skipped the draft tier, by reason",
            reason=reason)
        with self._lock:
            self._bypass.setdefault(reason, counter)
        counter.inc()

    def note_served(self, tier: str, *, confidence: float, stress: float,
                    exit_depth: int = 0):
        """One request reached a terminal result at `tier`
        ("draft" / "escalated" / "full"). The served-counter label for
        accepted drafts is "draft_accepted" — tier="draft" is the SCORED
        counter's cell, and sharing it would double-count accepts."""
        label = "draft_accepted" if tier == "draft" else tier
        # registry get-or-create is idempotent and takes its own lock;
        # keep it OUTSIDE ours so the ledger lock stays a true leaf
        counter = self._registry.counter(
            "cascade_requests_total",
            help="cascade requests by tier outcome (draft = scored "
                 "drafts; draft_accepted / escalated / full = terminal "
                 "serves)", tier=label)
        with self._lock:
            self._served.setdefault(label, counter)
            quality = self._tiers.get(tier)
            if quality is None:
                quality = self._tiers[tier] = _TierQuality()
            quality.observe(confidence, stress)
        counter.inc()
        if exit_depth:
            self.note_early_exit(exit_depth)

    def note_early_exit(self, depth: int):
        counter = self._registry.counter(
            "cascade_early_exit_total",
            help="requests whose trunk exited early at this depth "
                 "(delta-KL stabilized; priced as its own cost-ledger "
                 "cell)", depth=str(depth))
        with self._lock:
            self._early_exit.setdefault(depth, counter)
        counter.inc()

    # ------------------------------------------------------ observability

    def escalation_rate(self) -> float:
        with self._lock:
            return self._escalated / self._scored if self._scored else 0.0

    def publish(self):
        """Refresh the gauge families (the fleet's sample_gauges tick)."""
        self._rate_gauge.set(self.escalation_rate())
        with self._lock:
            tiers = {t: (q.confidence_ema, q.stress_ema)
                     for t, q in self._tiers.items()}
        for tier, (conf, stress) in tiers.items():
            if conf is not None:
                self._registry.gauge(
                    "cascade_tier_confidence",
                    help="EMA mean distogram confidence of results "
                         "served at this tier (the per-tier quality "
                         "half of /statusz)", tier=tier).set(conf)
            if stress is not None:
                self._registry.gauge(
                    "cascade_tier_stress",
                    help="EMA normalized MDS stress of results served "
                         "at this tier", tier=tier).set(stress)

    def snapshot(self) -> dict:
        with self._lock:
            tiers = {t: q.snapshot() for t, q in self._tiers.items()}
            scored, escalated = self._scored, self._escalated
            early = {d: int(c.value)
                     for d, c in self._early_exit.items()}
            bypass = {r: int(c.value) for r, c in self._bypass.items()}
            reasons = {r: int(c.value)
                       for r, c in self._escalation.items()}
        return {
            "drafts_scored": scored,
            "escalated": escalated,
            "escalation_rate": round(
                escalated / scored, 6) if scored else 0.0,
            "escalation_reasons": reasons,
            "bypass": bypass,
            "early_exits": early,
            "tiers": tiers,
        }
