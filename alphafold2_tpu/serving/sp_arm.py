"""The SP serving arm: schedule choice + residency pricing per bucket.

Serving was crop-bounded by construction: every bucket executable ran the
replicated trunk, so a request had to fit ONE chip's HBM and a sequence
past the largest bucket simply died. The sequence-parallel trunk and ring
attention (PRs 5/7) already existed — for training. This module wires
them into the serving path (ROADMAP item 4a): with
`ServingConfig.sp_shards > 1` the engine builds bucket executables whose
trunk runs over a model-axis mesh, and THIS module decides, per length
bucket, which FastFold-style dynamic-axial-parallelism cut to take
(arxiv 2203.00854 — shard whichever axis dominates):

  `"dense"`   the replicated trunk — no collectives, the right answer for
              every bucket that fits one chip;
  `"sp_msa"`  shard the MSA ROW axis only (`msa_sharded_trunk_apply`):
              MSA residency and attention FLOPs divide by the shard
              count, the pair grid stays whole — the deep-alignment cut,
              cheaper in communication than sp_seq (no pair-side
              all_to_all transposes, no ring);
  `"sp_seq"`  shard the SEQUENCE (pair rows + MSA rows, `sp_trunk_apply`
              with ring cross-attention resolving its hop merge through
              ops/dispatch.py like every other hot op): the O(L^2) pair
              grid divides by the shard count — the long-sequence cut.

The heuristic (`choose_schedule`) prices each candidate's per-chip
residency CHIP-FREE — every byte count comes from `jax.eval_shape`
structs, never a live allocation — and picks the cheapest-communication
schedule that fits the per-chip budget (`ServingConfig.sp_hbm_gb`):
dense < sp_msa < sp_seq. Per-bucket overrides
(`ServingConfig.sp_schedules`) win over the heuristic and fail LOUDLY
when infeasible (a non-dividing bucket must be a config error, not a
silent dense fallback that OOMs on chip).

The priced "residency" is the executable's dominant live set: the model
weight tree (int8-priced under the quantized arm), the trunk's two
residual streams at a documented live-copy multiplier, and the distogram
logits (the head runs replicated after the sharded trunk — counted
full-size on every chip, deliberately conservative). It is a routing/
planning estimate with the same contract as PR 8's weight-residency
pricing, not an allocator.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp

from alphafold2_tpu.models import Alphafold2Config

#: schedule names, in preference order (cheapest communication first) —
#: `choose_schedule` picks the first feasible one that fits the budget
SP_SCHEDULES = ("dense", "sp_msa", "sp_seq")

#: live copies of each residual stream priced per trunk position: the
#: stream itself, its pre-norm copy, the attention/FF block output, and
#: one workspace tile — the documented planning multiplier (residual
#: rematerialization and fusion change the exact number; 4 is the
#: conservative figure the A/B legs validate on chip)
LIVE_COPIES = 4


@dataclasses.dataclass(frozen=True)
class ScheduleResidency:
    """Per-chip priced residency of one (bucket, schedule) executable."""

    schedule: str
    weight_bytes: int
    pair_bytes: int      # pair residual stream x LIVE_COPIES, per chip
    msa_bytes: int       # MSA residual stream x LIVE_COPIES, per chip
    logits_bytes: int    # distogram head output (replicated; conservative)
    feasible: bool       # divisibility constraints hold for this shape

    @property
    def total_bytes(self) -> int:
        return (self.weight_bytes + self.pair_bytes + self.msa_bytes
                + self.logits_bytes)

    def as_dict(self) -> dict:
        return {
            "schedule": self.schedule,
            "weight_bytes": int(self.weight_bytes),
            "pair_bytes": int(self.pair_bytes),
            "msa_bytes": int(self.msa_bytes),
            "logits_bytes": int(self.logits_bytes),
            "total_bytes": int(self.total_bytes),
            "feasible": bool(self.feasible),
        }


def _struct_bytes(tree) -> int:
    return int(sum(
        leaf.size * jnp.dtype(leaf.dtype).itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
    ))


def weight_residency_bytes(model_cfg: Alphafold2Config) -> int:
    """Per-chip resident weight bytes, priced chip-free via eval_shape —
    the int8 arm prices the PTQ tree (serving/quant_residency.py places
    exactly that on device), f32 prices the master tree."""
    from alphafold2_tpu.models import alphafold2_init
    from alphafold2_tpu.ops.quant import quantize_tree, tree_weight_bytes

    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    f32_cfg = (dataclasses.replace(model_cfg, weight_dtype="f32")
               if model_cfg.weight_dtype != "f32" else model_cfg)
    tree = jax.eval_shape(lambda k: alphafold2_init(k, f32_cfg), key)
    if model_cfg.weight_dtype == "int8":
        tree = jax.eval_shape(quantize_tree, tree)
    return int(tree_weight_bytes(tree))


def _feasible(schedule: str, bucket: int, msa_rows: int, shards: int) -> bool:
    if schedule == "dense":
        return True
    if schedule == "sp_seq":
        # pair rows divide; MSA rows too when an MSA stream is served
        return bucket % shards == 0 and (
            msa_rows == 0 or msa_rows % shards == 0)
    if schedule == "sp_msa":
        # needs an MSA to shard; rows divide, and cols (= bucket) divide
        # for the along-rows transpose pass (msa_sharded_trunk_apply)
        return (msa_rows > 0 and msa_rows % shards == 0
                and bucket % shards == 0)
    raise ValueError(
        f"unknown SP schedule {schedule!r}; known: {SP_SCHEDULES}")


def schedule_residency(
    model_cfg: Alphafold2Config,
    *,
    bucket: int,
    batch: int,
    msa_rows: int,
    schedule: str,
    shards: int,
    weight_bytes: Optional[int] = None,
) -> ScheduleResidency:
    """Price one (bucket, schedule) executable's per-chip residency.

    Every byte count derives from `jax.eval_shape` structs (abstract
    zeros at the executable's real shapes/dtypes) — nothing allocates.
    `weight_bytes` can be passed in so a ladder-wide planning pass prices
    the tree once.
    """
    if schedule not in SP_SCHEDULES:
        raise ValueError(
            f"unknown SP schedule {schedule!r}; known: {SP_SCHEDULES}")
    s_pair = shards if schedule == "sp_seq" else 1
    s_msa = shards if schedule in ("sp_seq", "sp_msa") else 1
    dtype = model_cfg.dtype

    def streams():
        pair = jnp.zeros(
            (batch, max(1, bucket // s_pair), bucket, model_cfg.dim), dtype)
        msa = (jnp.zeros(
            (batch, max(1, msa_rows // s_msa), bucket, model_cfg.dim), dtype)
            if msa_rows else jnp.zeros((0,), dtype))
        logits = jnp.zeros(
            (batch, bucket, bucket, model_cfg.num_buckets), jnp.float32)
        return pair, msa, logits

    pair_s, msa_s, logits_s = jax.eval_shape(streams)
    if weight_bytes is None:
        weight_bytes = weight_residency_bytes(model_cfg)
    return ScheduleResidency(
        schedule=schedule,
        weight_bytes=weight_bytes,
        pair_bytes=_struct_bytes(pair_s) * LIVE_COPIES,
        msa_bytes=_struct_bytes(msa_s) * LIVE_COPIES,
        logits_bytes=_struct_bytes(logits_s),
        feasible=_feasible(schedule, bucket, msa_rows, shards),
    )


def choose_schedule(
    model_cfg: Alphafold2Config,
    *,
    bucket: int,
    batch: int,
    msa_rows: int,
    shards: int,
    hbm_bytes: float,
    weight_bytes: Optional[int] = None,
) -> ScheduleResidency:
    """The length/HBM heuristic: cheapest-communication schedule that fits.

    Candidates run in `SP_SCHEDULES` preference order (dense -> sp_msa ->
    sp_seq); infeasible cuts (non-dividing bucket/rows, no MSA to shard)
    are skipped. If NOTHING fits the budget the most-sharded feasible
    candidate is returned (`feasible` stays True but its total exceeds
    `hbm_bytes` — the engine surfaces the overage in `stats()["sp"]`
    rather than refusing to serve: the budget is a planning estimate).
    """
    if weight_bytes is None:
        weight_bytes = weight_residency_bytes(model_cfg)
    best = None
    for schedule in SP_SCHEDULES:
        res = schedule_residency(
            model_cfg, bucket=bucket, batch=batch, msa_rows=msa_rows,
            schedule=schedule, shards=shards, weight_bytes=weight_bytes,
        )
        if not res.feasible:
            continue
        if res.total_bytes <= hbm_bytes:
            return res
        best = res  # later candidates shard more: keep the last feasible
    # "dense" is unconditionally feasible, so best is always set: the
    # worst case is an over-budget plan, never an empty one
    assert best is not None
    return best


def plan_bucket_schedules(
    model_cfg: Alphafold2Config,
    *,
    buckets: Tuple[int, ...],
    batch: int,
    msa_rows: int,
    shards: int,
    hbm_bytes: float,
    overrides: Optional[Mapping[int, str]] = None,
) -> Dict[int, ScheduleResidency]:
    """bucket -> priced schedule for the whole ladder (engine build time).

    `overrides` (from `ServingConfig.sp_schedules`) win over the
    heuristic; an override naming an unknown bucket or an infeasible
    schedule raises — a mis-keyed override must never silently leave the
    heuristic's choice in force.
    """
    overrides = dict(overrides or {})
    unknown = set(overrides) - set(buckets)
    if unknown:
        raise ValueError(
            f"sp_schedules overrides name bucket(s) {sorted(unknown)} not "
            f"on the ladder {tuple(buckets)}"
        )
    weight_bytes = weight_residency_bytes(model_cfg)
    plan: Dict[int, ScheduleResidency] = {}
    for bucket in buckets:
        forced = overrides.get(bucket)
        if forced is not None:
            res = schedule_residency(
                model_cfg, bucket=bucket, batch=batch, msa_rows=msa_rows,
                schedule=forced, shards=shards, weight_bytes=weight_bytes,
            )
            if not res.feasible:
                raise ValueError(
                    f"sp_schedules forces {forced!r} for bucket {bucket}, "
                    f"but that cut is infeasible at msa_rows={msa_rows}, "
                    f"shards={shards} (divisibility)"
                )
            plan[bucket] = res
        else:
            plan[bucket] = choose_schedule(
                model_cfg, bucket=bucket, batch=batch, msa_rows=msa_rows,
                shards=shards, hbm_bytes=hbm_bytes,
                weight_bytes=weight_bytes,
            )
    return plan


def build_sp_mesh(shards: int, *, axis_name: str = "sp"):
    """The serving model-axis mesh: `shards` devices on one axis. Raises
    with sizing advice when the host exposes fewer devices."""
    from alphafold2_tpu.parallel import make_mesh

    n = len(jax.devices())
    if n < shards:
        raise ValueError(
            f"sp_shards={shards} needs {shards} devices, host exposes {n} "
            f"— size sp_shards to the accelerator count (or provision the "
            f"virtual CPU platform for chip-free work)"
        )
    return make_mesh({axis_name: shards})


def make_sp_apply_fn(mesh, schedule: str, *, axis_name: str = "sp",
                     overlap=None):
    """Trunk-forward override for `serving.pipeline.predict_structure`
    running the chosen SP cut over `mesh`. Returns None for "dense" (the
    pipeline's stock replicated apply)."""
    if schedule == "dense":
        return None
    if schedule not in SP_SCHEDULES:
        raise ValueError(
            f"unknown SP schedule {schedule!r}; known: {SP_SCHEDULES}")
    from alphafold2_tpu.parallel import alphafold2_apply_sp

    def apply_fn(params, cfg, tokens, msa, *, mask=None, msa_mask=None,
                 embedds=None, templates=None, templates_mask=None):
        if embedds is not None:
            raise ValueError(
                "the SP serving arm shards token/MSA row axes; the embedds "
                "substitute stream has none — serve embedds dense"
            )
        return alphafold2_apply_sp(
            params, cfg, tokens, msa, mesh,
            axis_name=axis_name, mask=mask, msa_mask=msa_mask,
            templates=templates, templates_mask=templates_mask,
            overlap=overlap, schedule=schedule,
        )

    return apply_fn
