"""Serving metrics: counters, batch occupancy, latency quantiles, compiles.

Rebuilt on the telemetry subsystem (`alphafold2_tpu.telemetry`): every
count lives in a `MetricRegistry` — Prometheus-exposable, uniformly
named — instead of the ad-hoc dicts this module used to keep:

  requests:  counter `serving_requests_total{outcome=...}`
  errors:    counter `serving_errors_total{code=...}`
  batches:   counters `serving_batches_total` /
             `serving_batch_requests_total`
  compiles:  counter `serving_compile_total{bucket=...}` + gauges
             `serving_compile_seconds_total` / `serving_compile_last_seconds`
             (via `telemetry.CompileTracker`)
  latency:   histogram `serving_request_latency_seconds`
             (sliding-window p50/p95/p99)
  padding:   gauge `serve_batch_pad_ratio` — cumulative padded rows /
             live rows across dispatches (batch-shape ladder waste)
  pipeline:  gauges `serve_pipeline_inflight` /
             `serve_pipeline_overlap_ratio` — the engine's pipelined
             dispatch (settle thread) feeds both

`snapshot()` keeps its pre-registry JSON shape — it is the engine's
health-check payload (`ServingEngine.stats()`) and the chaos suite
asserts on it — and additionally exposes the registry under
`stats()["telemetry"]` (engine-side). An optional `MetricsLogger`
streams one record per dispatched batch, same cadence contract as
training.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Optional

from alphafold2_tpu.telemetry import (
    NULL_TRACER,
    CompileTracker,
    MetricRegistry,
    MetricsLogger,
)

# request-terminal counter names; everything submitted eventually lands in
# exactly one of these (or stays in flight)
_COUNTERS = (
    "submitted",      # accepted by submit() (cache hits included)
    "completed",      # result delivered (cache hits included)
    "failed",         # PredictionError / EngineClosedError terminal
    "timed_out",      # scheduler-side deadline expiry
    "rejected",       # refused at submit(): queue full / too long / invalid
    "cache_hits",     # completed without touching the queue or the model
    "coalesced",      # submission attached to an identical in-flight request
    #                   — PER ENGINE. The fleet-level twin is
    #                   `fleet_coalesced_total` (serving/frontdoor.py):
    #                   identical requests collapsed ACROSS replicas and
    #                   pools before routing; artifact-store hit/corrupt
    #                   volume rides `artifact_store_*` / `cache_corrupt_total`
)


class ServingMetrics:
    """Thread-safe counters + histograms for one engine instance."""

    def __init__(self, latency_window: int = 2048,
                 logger: Optional[MetricsLogger] = None,
                 registry: Optional[MetricRegistry] = None,
                 tracer=NULL_TRACER):
        self.registry = registry if registry is not None else MetricRegistry(
            histogram_window=latency_window
        )
        # one lock over the terminal counters: a stats() reader must see a
        # CONSISTENT view (submit() counts `submitted` before enqueue so
        # in_flight can never read negative — per-counter locks alone
        # would reopen that window between two reads)
        self._counts_lock = threading.Lock()
        self._counts = {
            name: self.registry.counter(
                "serving_requests_total",
                help="request-terminal outcomes", outcome=name)
            for name in _COUNTERS
        }
        self._errors_lock = threading.Lock()
        self._errors = {}  # stable error code -> Counter (serving/errors.py)
        self.latency = self.registry.histogram(
            "serving_request_latency_seconds",
            help="submit->complete latency, sliding window",
        )
        self._batches = self.registry.counter(
            "serving_batches_total", help="dispatched batches")
        self._batch_requests = self.registry.counter(
            "serving_batch_requests_total",
            help="real requests across dispatched batches")
        self._recent_lock = threading.Lock()
        self._recent_batch_sizes = collections.deque(maxlen=256)
        # batch-shape ladder accounting (serving/bucketing.py
        # batch_shape_ladder): cumulative padded vs live rows across
        # dispatched batches — the waste the ladder deletes. Occupancy
        # is measured against the CHOSEN batch shape, not max_batch.
        self._shape_rows = 0   # sum of chosen batch shapes (row slots)
        self._live_rows = 0    # sum of real requests (live rows)
        self._pad_ratio_gauge = self.registry.gauge(
            "serve_batch_pad_ratio",
            help="cumulative padded rows / live rows across dispatched "
                 "batches (batch-shape ladder waste metric)")
        # pipelined-dispatch accounting (engine settle thread): span =
        # enqueue->realized per batch; window = the same span clamped
        # against previously realized batches (the non-double-billed
        # device seconds). span/window > 1 iff in-flight batches overlap.
        self._pipe_lock = threading.Lock()
        self._pipe_span_s = 0.0
        self._pipe_window_s = 0.0
        self._pipe_inflight = 0
        self._pipe_inflight_gauge = self.registry.gauge(
            "serve_pipeline_inflight",
            help="batches enqueued on device but not yet settled")
        self._pipe_overlap_gauge = self.registry.gauge(
            "serve_pipeline_overlap_ratio",
            help="sum(enqueue->realized spans) / union of those spans; "
                 "1.0 = synchronous dispatch, >1.0 = pipelined overlap")
        self._compiles_lock = threading.Lock()
        self._compile_seconds = {}  # bucket -> seconds gauge (snapshot view)
        # prefix "serving_compile": the tracker's `<prefix>_seconds_total`
        # gauge is the SAME registry object compile_span registers in
        # `_compile_seconds` (identity = name + labels), so the snapshot's
        # per-bucket seconds view and the exposition never diverge
        self.compile_tracker = CompileTracker(
            self.registry, tracer=tracer, prefix="serving_compile"
        )
        self._logger = logger
        self._t0 = time.monotonic()

    def inc(self, name: str, n: int = 1):
        with self._counts_lock:
            self._counts[name].inc(n)

    def inc_error(self, code_or_exc, n: int = 1):
        """Count one error by its stable code. Accepts a code string or a
        ServingError instance (its `code` attribute is used) — every
        terminal failure and submit-time rejection lands here, keyed the
        way ops dashboards and the circuit breaker see the world."""
        code = getattr(code_or_exc, "code", code_or_exc)
        with self._errors_lock:
            counter = self._errors.get(code)
            if counter is None:
                counter = self.registry.counter(
                    "serving_errors_total",
                    help="terminal failures and rejections by stable code",
                    code=code)
                self._errors[code] = counter
        counter.inc(n)

    def observe_batch(self, n_real: int, batch_shape: int, latency_s: float):
        """One dispatched batch: n_real real requests of `batch_shape`
        row slots (the CHOSEN executable shape — max_batch without the
        batch-shape ladder, the smallest ladder rung >= n_real with it);
        latency_s is the oldest member's submit->complete latency."""
        self._batches.inc()
        self._batch_requests.inc(n_real)
        with self._recent_lock:
            self._recent_batch_sizes.append(n_real)
            self._shape_rows += batch_shape
            self._live_rows += n_real
            live = self._live_rows
            pad = self._shape_rows - self._live_rows
        self._pad_ratio_gauge.set(pad / live if live else 0.0)
        step = int(self._batches.value)
        if self._logger is not None:
            self._logger.log(step, {
                "batch_requests": n_real,
                "batch_shape": batch_shape,
                "batch_occupancy": n_real / batch_shape,
                "batch_latency_s": latency_s,
            })

    def observe_pipeline_settle(self, span_s: float, window_s: float):
        """One settled pipelined batch: span = enqueue->realized wall,
        window = the span's non-overlapping share (engine's realized-
        watermark clamp). The published overlap ratio is cumulative
        span/window — exactly 1.0 when dispatch is synchronous."""
        with self._pipe_lock:
            self._pipe_span_s += span_s
            self._pipe_window_s += window_s
            span, window = self._pipe_span_s, self._pipe_window_s
        self._pipe_overlap_gauge.set(span / window if window > 0 else 0.0)

    def pipeline_inflight_delta(self, delta: int):
        """Track batches enqueued-but-unsettled (the in-flight window)."""
        with self._pipe_lock:
            self._pipe_inflight += delta
            n = self._pipe_inflight
        self._pipe_inflight_gauge.set(n)

    def pipeline_snapshot(self) -> dict:
        with self._pipe_lock:
            span, window = self._pipe_span_s, self._pipe_window_s
            inflight = self._pipe_inflight
        return {
            "inflight": inflight,
            "span_seconds": span,
            "window_seconds": window,
            "overlap_ratio": span / window if window > 0 else 0.0,
        }

    @contextlib.contextmanager
    def compile_span(self, bucket: int):
        """Context manager around one bucket compile: registry counters +
        gauges + a `serving_compile` span, and the per-bucket seconds
        view `snapshot()` reports. The bucket is registered in that view
        only AFTER the compile succeeds — a failed or still-in-flight
        compile must not read as a compiled bucket (`compile_count` backs
        the <= len(buckets) invariant)."""
        with self.compile_tracker.track(bucket=str(bucket)):
            yield
        gauge = self.registry.gauge(
            "serving_compile_seconds_total",
            help="cumulative compile wall seconds", bucket=str(bucket))
        with self._compiles_lock:
            self._compile_seconds[bucket] = gauge

    def set_weight_bytes(self, residency: dict):
        """Per-tag resident-weight gauge (serving/quant_residency.py):
        `serving_weight_bytes{tag, weight_dtype}` = bytes this engine's
        parameter tree keeps resident in device memory — the
        multi-precision serving story's capacity metric (an int8 tag
        costs ~4x less than its f32 twin, so more tags fit a replica)."""
        self.registry.gauge(
            "serving_weight_bytes",
            help="resident parameter-tree bytes for this engine's "
                 "residency tag",
            tag=residency["tag"], weight_dtype=residency["weight_dtype"],
        ).set(residency["weight_bytes"])

    def record_compile(self, bucket: int, seconds: float):
        """Back-compat direct recording (pre-tracker callers/tests)."""
        gauge = self.registry.gauge(
            "serving_compile_seconds_total",
            help="cumulative compile wall seconds", bucket=str(bucket))
        gauge.inc(seconds)
        self.registry.counter(
            "serving_compile_total", help="compile events",
            bucket=str(bucket)).inc()
        with self._compiles_lock:
            self._compile_seconds[bucket] = gauge

    @property
    def compile_count(self) -> int:
        """Distinct compiled buckets (the <= len(buckets) invariant)."""
        with self._compiles_lock:
            return len(self._compile_seconds)

    def compile_seconds_total(self) -> float:
        """Cumulative compile wall seconds across every bucket — the
        engine's dispatch timing reads this before/after a device call
        so a first-call compile is EXCLUDED from the cost ledger's
        execute EMA (telemetry/costs.py)."""
        with self._compiles_lock:
            return float(sum(g.value for g in self._compile_seconds.values()))

    def snapshot(self, max_batch: int) -> dict:
        with self._counts_lock:
            counts = {name: int(c.value) for name, c in self._counts.items()}
        batches = int(self._batches.value)
        batch_requests = int(self._batch_requests.value)
        with self._recent_lock:
            recent = list(self._recent_batch_sizes)
            shape_rows = self._shape_rows
            live_rows = self._live_rows
        with self._compiles_lock:
            compiles = {b: g.value for b, g in self._compile_seconds.items()}
        with self._errors_lock:
            errors = {code: int(c.value) for code, c in self._errors.items()}
        uptime = time.monotonic() - self._t0
        in_flight = (
            counts["submitted"] - counts["completed"]
            - counts["failed"] - counts["timed_out"]
        )
        latency = self.latency.snapshot()
        latency.pop("sum", None)      # lifetime sum and cumulative buckets
        latency.pop("buckets", None)  # are exposition detail (/metrics has
        #                               them), not health-check payload shape
        return {
            "uptime_s": uptime,
            "requests": {**counts, "in_flight": in_flight},
            "batches": {
                "count": batches,
                "mean_requests_per_batch": (
                    batch_requests / batches if batches else 0.0
                ),
                # occupancy vs the CHOSEN batch shape per dispatch (the
                # batch-shape ladder's view); falls back to max_batch
                # slots for direct-call paths that never observed a batch
                "mean_occupancy": (
                    batch_requests / shape_rows if shape_rows
                    else (batch_requests / (batches * max_batch)
                          if batches else 0.0)
                ),
                "pad_ratio": (
                    (shape_rows - live_rows) / live_rows if live_rows
                    else 0.0
                ),
                "recent_sizes": recent,
            },
            "compiles": {
                "count": len(compiles),
                "seconds_by_bucket": {str(k): v for k, v in compiles.items()},
            },
            "errors": errors,
            "latency": latency,
        }
