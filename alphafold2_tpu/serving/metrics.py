"""Serving metrics: counters, batch occupancy, latency quantiles, compiles.

Built on `utils.observability` — `LatencyHistogram` provides the
sliding-window p50/p95/p99, and an optional `MetricsLogger` streams one
record per dispatched batch to stdout/JSONL with the same cadence
contract training uses. `snapshot()` returns a plain-JSON dict, which is
the engine's health-check payload (`ServingEngine.stats()`).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional

from alphafold2_tpu.utils.observability import LatencyHistogram, MetricsLogger

# request-terminal counter names; everything submitted eventually lands in
# exactly one of these (or stays in flight)
_COUNTERS = (
    "submitted",      # accepted by submit() (cache hits included)
    "completed",      # result delivered (cache hits included)
    "failed",         # PredictionError / EngineClosedError terminal
    "timed_out",      # scheduler-side deadline expiry
    "rejected",       # refused at submit(): queue full / too long / invalid
    "cache_hits",     # completed without touching the queue or the model
    "coalesced",      # submission attached to an identical in-flight request
)


class ServingMetrics:
    """Thread-safe counters + histograms for one engine instance."""

    def __init__(self, latency_window: int = 2048,
                 logger: Optional[MetricsLogger] = None):
        self._lock = threading.Lock()
        self._counts = {name: 0 for name in _COUNTERS}
        self.latency = LatencyHistogram(window=latency_window)
        self._batches = 0
        self._batch_requests = 0
        self._recent_batch_sizes = collections.deque(maxlen=256)
        self._compiles = {}  # bucket -> seconds spent compiling
        self._errors = {}    # stable error code -> count (serving/errors.py)
        self._logger = logger
        self._t0 = time.monotonic()

    def inc(self, name: str, n: int = 1):
        with self._lock:
            self._counts[name] += n

    def inc_error(self, code_or_exc, n: int = 1):
        """Count one error by its stable code. Accepts a code string or a
        ServingError instance (its `code` attribute is used) — every
        terminal failure and submit-time rejection lands here, keyed the
        way ops dashboards and the circuit breaker see the world."""
        code = getattr(code_or_exc, "code", code_or_exc)
        with self._lock:
            self._errors[code] = self._errors.get(code, 0) + n

    def observe_batch(self, n_real: int, max_batch: int, latency_s: float):
        """One dispatched batch: n_real real requests of max_batch slots;
        latency_s is the oldest member's submit->complete latency."""
        with self._lock:
            self._batches += 1
            self._batch_requests += n_real
            self._recent_batch_sizes.append(n_real)
            step = self._batches
        if self._logger is not None:
            self._logger.log(step, {
                "batch_requests": n_real,
                "batch_occupancy": n_real / max_batch,
                "batch_latency_s": latency_s,
            })

    def record_compile(self, bucket: int, seconds: float):
        with self._lock:
            self._compiles[bucket] = self._compiles.get(bucket, 0.0) + seconds

    @property
    def compile_count(self) -> int:
        with self._lock:
            return len(self._compiles)

    def snapshot(self, max_batch: int) -> dict:
        with self._lock:
            counts = dict(self._counts)
            batches = self._batches
            batch_requests = self._batch_requests
            recent = list(self._recent_batch_sizes)
            compiles = dict(self._compiles)
            errors = dict(self._errors)
            uptime = time.monotonic() - self._t0
        in_flight = (
            counts["submitted"] - counts["completed"]
            - counts["failed"] - counts["timed_out"]
        )
        return {
            "uptime_s": uptime,
            "requests": {**counts, "in_flight": in_flight},
            "batches": {
                "count": batches,
                "mean_requests_per_batch": (
                    batch_requests / batches if batches else 0.0
                ),
                "mean_occupancy": (
                    batch_requests / (batches * max_batch) if batches else 0.0
                ),
                "recent_sizes": recent,
            },
            "compiles": {
                "count": len(compiles),
                "seconds_by_bucket": {str(k): v for k, v in compiles.items()},
            },
            "errors": errors,
            "latency": self.latency.snapshot(),
        }
