"""Fleet front-door coalescing: one dispatch per identical in-flight key.

The per-engine coalescing map (`ServingEngine._inflight`) collapses
identical submissions that land on the SAME replica. At fleet scale
that is the wrong unit: the router spreads identical requests across
the least-loaded replicas of a pool (and failovers move them between
pools), so a burst of N identical submissions costs up to N dispatches
even though one answer serves them all. This registry sits at the
fleet front door — after featurization, BEFORE pool routing — keyed by
the same content hash the artifact store uses, so the first submission
of a key becomes the LEADER (it proceeds through admission and routing
as always) and every subsequent identical submission attaches as a
FOLLOWER that never enters the admission queue.

The fleet settles the coalition at every leader-terminal path
(completion, shed, failure, shutdown): `settle` pops the followers and
the FLEET resolves them — success hands every follower the leader's
result (each `FleetRequest.result()` copy-stamps its own provenance),
failure propagates the leader's terminal error, exactly the
per-engine coalescing contract one level up. Followers carry their
leader's store key but never register one themselves, so a follower's
own terminal accounting can never pop a coalition it does not lead.

Lock discipline (af2lint CONC model): `_lock` guards only the waiter
dict and is never held while resolving a request or touching any other
lock — `register`/`settle` return immediately and the fleet does all
resolution outside it.
"""

from __future__ import annotations

import threading
from typing import Optional

from alphafold2_tpu.telemetry import MetricRegistry


class FrontDoor:
    """Waiter registry keyed by (store tag, content hash)."""

    def __init__(self, registry: Optional[MetricRegistry] = None):
        self.registry = registry if registry is not None else MetricRegistry()
        self._lock = threading.Lock()
        self._waiters = {}   # key -> [follower FleetRequest, ...]
        self._coalesced = 0  # lifetime followers attached (snapshot mirror)
        self._coalesced_counter = self.registry.counter(
            "fleet_coalesced_total",
            help="submissions attached to an identical in-flight request "
                 "at the fleet front door (one dispatch serves them all)")

    def register(self, key, entry) -> bool:
        """True: `entry` is the leader for `key` (caller admits it).
        False: `entry` was attached as a follower of the in-flight
        leader and must NOT be admitted — it resolves at settle."""
        with self._lock:
            group = self._waiters.get(key)
            if group is None:
                self._waiters[key] = []
                return True
            group.append(entry)
            self._coalesced += 1
        self._coalesced_counter.inc()
        return False

    def settle(self, key) -> list:
        """Pop and return `key`'s followers (empty if already settled or
        never registered). Pop-once: the caller that receives the list
        owns resolving every entry in it."""
        with self._lock:
            return self._waiters.pop(key, [])

    def drain(self) -> list:
        """Shutdown backstop: pop EVERY follower still attached (their
        leaders settle through the normal terminal paths; this catches
        any coalition whose leader can no longer reach one)."""
        with self._lock:
            groups = list(self._waiters.values())
            self._waiters.clear()
        return [entry for group in groups for entry in group]

    def depth(self) -> int:
        """Followers currently waiting (not counting leaders)."""
        with self._lock:
            return sum(len(g) for g in self._waiters.values())

    def snapshot(self) -> dict:
        with self._lock:
            keys = len(self._waiters)
            waiting = sum(len(g) for g in self._waiters.values())
            lifetime = self._coalesced
        return {
            "inflight_keys": keys,
            "waiting_followers": waiting,
            "coalesced_total": lifetime,
        }
