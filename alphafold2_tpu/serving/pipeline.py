"""The sequence→structure inference pipeline as one pure, jit-compilable
function.

This is the body of `predict.py`'s CA-trace path (trunk forward →
distogram softmax → centering → stress-majorization MDS → entropy
confidence) factored out of its 200-line `main()` so that

  * `predict.py` stays a thin CLI client (checkpoint restore, file I/O,
    argument plumbing — nothing numerical), and
  * the serving engine (`serving/engine.py`) can AOT-compile exactly this
    function once per length bucket and drive it with batched, padded
    request streams.

Everything here is traceable: no host I/O, no Python branching on traced
values, static knobs (`cfg`, `mds_iters`, `mds_init`) passed as Python
values closed over at jit time. Batch-capable end to end — `tokens` is
(b, L) and every output carries the batch axis.

Every numeric knob of this pipeline must be covered by the serving
config tag (serving/engine.py `_config_tag`, via repr of the full
Alphafold2Config plus the MDS/bucket knobs): the result LRU and the
fleet's bit-exactness pins key on it, so anything that can change a
served structure — including the trunk schedule (`trunk_schedule`) and
the fused output gate (`attn_gate`) — must never alias across configs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from alphafold2_tpu.geometry import (
    MDScaling,
    center_distogram,
    distogram_confidence,
)
from alphafold2_tpu.models import alphafold2_apply


def predict_structure(
    params,
    cfg,
    tokens,
    *,
    mask=None,
    msa=None,
    msa_mask=None,
    embedds=None,
    templates=None,
    templates_mask=None,
    rng=None,
    mds_iters: int = 200,
    mds_init: str = "classical",
    model_apply_fn=None,
):
    """Tokens (+ optional MSA/embedds/templates) → CA trace + confidence.

    Args:
      params: trunk parameter pytree (`alphafold2_init`).
      cfg: `Alphafold2Config` — static under jit.
      tokens: (b, L) int residue tokens. Padded positions carry
        PAD_TOKEN_ID and must be excluded via `mask`.
      mask: (b, L) bool residue validity. Padded pairs are zero-weighted
        in the MDS objective and masked residues score zero confidence,
        so a sequence's structure does not depend on how far its bucket
        over-pads it.
      msa / msa_mask: (b, rows, L) int tokens / bool validity, or None.
      embedds: (b, L, num_embedds) LM-embedding MSA substitute, or None.
      templates / templates_mask: (b, T, L, L) template conditioning.
      rng: PRNG key for the MDS random init (unused with
        mds_init="classical"); model forward is deterministic (eval).
      mds_iters / mds_init: static MDS knobs (see geometry/mds.py).
      model_apply_fn: trunk-forward override with the `alphafold2_apply`
        keyword signature — e.g. a sequence-parallel wrapper
        (parallel/sp_trunk.py). Geometry always runs replicated.

    Returns dict:
      coords: (b, L, 3) CA trace.
      confidence: (b, L) per-residue confidence in [0, 1]
        (distogram-entropy pLDDT analog).
      stress: (b,) final normalized MDS stress.
      distogram_logits: (b, L, L, buckets) float32.
    """
    apply_fn = model_apply_fn if model_apply_fn is not None else alphafold2_apply
    logits = apply_fn(
        params, cfg, tokens, msa,
        mask=mask, msa_mask=msa_mask, embedds=embedds,
        templates=templates, templates_mask=templates_mask,
    )  # (b, L, L, buckets)

    # geometry runs in float32 regardless of the trunk compute dtype: the
    # distogram -> MDS pipeline divides by pairwise distances and small
    # weights, which overflows/NaNs in bfloat16 (same stance as
    # training/e2e.py)
    logits = logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    distances, weights = center_distogram(probs)
    if mask is not None:
        pair_mask = (mask[:, :, None] & mask[:, None, :]).astype(weights.dtype)
        # zero BOTH channels for padded pairs: weights silence them in the
        # Guttman iterations, but the classical (Torgerson) init
        # double-centers the raw distance matrix with no weighting — junk
        # model distances for pad pairs would shift the real residues'
        # eigendecomposition start
        weights = weights * pair_mask
        distances = distances * pair_mask

    coords, stresses = MDScaling(
        distances,
        weights=weights,
        iters=mds_iters,
        # disable the convergence freeze: its trigger averages improvement
        # over the whole batch (geometry/mds.py), which would make one
        # request's iteration count — and thus its coordinates — depend on
        # its batchmates. Serving results must be batch-composition
        # independent (the result cache asserts equal key == identical
        # computation); Guttman steps past convergence are no-ops, so the
        # only cost is finishing the fixed iteration budget.
        tol=-jnp.inf,
        # single-atom-per-residue trace has no phi signal to decide
        # chirality from (same stance as predict.py's historical path)
        fix_mirror=False,
        key=rng,
        init=mds_init,
    )  # (b, 3, L), (iters, b)

    conf = distogram_confidence(probs, mask=mask)  # (b, L)
    return {
        "coords": jnp.transpose(coords, (0, 2, 1)),  # (b, L, 3)
        "confidence": conf,
        "stress": stresses[-1],
        "distogram_logits": logits,
    }
