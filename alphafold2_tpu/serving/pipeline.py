"""The sequence→structure inference pipeline as one pure, jit-compilable
function.

This is the body of `predict.py`'s CA-trace path (trunk forward →
distogram softmax → centering → stress-majorization MDS → entropy
confidence) factored out of its 200-line `main()` so that

  * `predict.py` stays a thin CLI client (checkpoint restore, file I/O,
    argument plumbing — nothing numerical), and
  * the serving engine (`serving/engine.py`) can AOT-compile exactly this
    function once per length bucket and drive it with batched, padded
    request streams.

Everything here is traceable: no host I/O, no Python branching on traced
values, static knobs (`cfg`, `mds_iters`, `mds_init`) passed as Python
values closed over at jit time. Batch-capable end to end — `tokens` is
(b, L) and every output carries the batch axis.

Every numeric knob of this pipeline must be covered by the serving
config tag (serving/engine.py `_config_tag`, via repr of the full
Alphafold2Config plus the MDS/bucket knobs): the result LRU and the
fleet's bit-exactness pins key on it, so anything that can change a
served structure — including the trunk schedule (`trunk_schedule`) and
the fused output gate (`attn_gate`) — must never alias across configs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from alphafold2_tpu.geometry import (
    MDScaling,
    center_distogram,
    distogram_confidence,
)
from alphafold2_tpu.models import alphafold2_apply


def _staged_trunk_logits(
    params,
    cfg,
    tokens,
    *,
    mask,
    msa,
    msa_mask,
    embedds,
    templates,
    templates_mask,
    exit_depths,
    exit_kl,
):
    """Trunk forward with confidence-gated depth early exit.

    Runs front -> trunk segment -> head at each checkpoint depth and
    freezes a sample's distogram once consecutive checkpoints agree
    (per-sample masked-mean KL(prev ‖ cur) <= `exit_kl`). The FIRST
    checkpoint is the delta-KL baseline — exits can fire from the second
    checkpoint on, which is why the serving config demands >= 2 depths.

    Per-sample outputs depend only on that sample's own tokens (the
    freeze is a per-sample `where` select, never a data-dependent shape),
    so the batch-composition-independence invariant the result cache
    keys on still holds — only the batch's COST is composition-dependent,
    exactly as micro-batching already makes it. Each checkpoint step is
    wrapped in `lax.cond(all frozen)` so once the whole batch has exited,
    the remaining trunk segments are skipped on device — that skipped
    work is the chip-seconds the per-exit-depth cost cells
    (serving/engine.py) price.

    Returns (logits (b, L, L, buckets) float32, exit_depth (b,) int32).
    """
    from alphafold2_tpu.models.alphafold2 import (
        alphafold2_front,
        alphafold2_head,
    )
    from alphafold2_tpu.models.trunk import sequential_trunk_apply

    if cfg.reversible:
        raise ValueError(
            "early exit segments the sequential layer list; the "
            "reversible trunk is depth-stacked — set reversible=False"
        )
    checkpoints = tuple(sorted({int(d) for d in exit_depths}))
    if len(checkpoints) < 2:
        raise ValueError(
            f"early exit needs >= 2 checkpoint depths (the first is the "
            f"delta-KL baseline and can never exit), got {checkpoints}"
        )
    if checkpoints[0] < 1 or checkpoints[-1] >= cfg.depth:
        raise ValueError(
            f"early-exit depths must satisfy 1 <= d < depth={cfg.depth}, "
            f"got {checkpoints}"
        )
    if len(set(cfg.layer_sparse)) > 1:
        # sequential_trunk_apply indexes cfg.layer_sparse by LOCAL layer
        # position; running a layer SLICE is only flag-correct when every
        # layer shares the same flag
        raise ValueError(
            "early exit requires uniform sparse_self_attn flags across "
            "the trunk (layer slices re-index cfg.layer_sparse from 0)"
        )
    if exit_kl <= 0:
        raise ValueError(f"early_exit_kl must be > 0, got {exit_kl}")
    checkpoints = checkpoints + (cfg.depth,)

    x, m, x_mask, m_mask, _rng_trunk = alphafold2_front(
        params, cfg, tokens, msa,
        mask=mask, msa_mask=msa_mask, templates=templates,
        templates_mask=templates_mask, embedds=embedds, rng=None,
    )
    layers = params["trunk"]
    b, n = tokens.shape
    if mask is not None:
        pm = (mask[:, :, None] & mask[:, None, :]).astype(jnp.float32)
    else:
        pm = jnp.ones((b, n, n), jnp.float32)
    denom = jnp.maximum(jnp.sum(pm, axis=(1, 2)), 1.0)

    def head_logp(x_cur):
        lg = alphafold2_head(params, cfg, x_cur).astype(jnp.float32)
        return lg, jax.nn.log_softmax(lg, axis=-1)

    # baseline segment: always runs, never exits
    x, m = sequential_trunk_apply(
        layers[: checkpoints[0]], cfg, x, m,
        x_mask=x_mask, msa_mask=m_mask, rng=None,
    )
    out_logits, prev_logp = head_logp(x)
    frozen = jnp.zeros((b,), bool)
    exit_depth = jnp.full((b,), checkpoints[-1], jnp.int32)

    start = checkpoints[0]
    for ck in checkpoints[1:]:
        seg = layers[start:ck]

        def step(operand, seg=seg, ck=ck):
            x_c, m_c, out_c, prev_c, frozen_c, depth_c = operand
            x_n, m_n = sequential_trunk_apply(
                seg, cfg, x_c, m_c,
                x_mask=x_mask, msa_mask=m_mask, rng=None,
            )
            lg, logp = head_logp(x_n)
            # per-sample masked-mean KL between consecutive checkpoint
            # distograms; log-space and f32 throughout, pad pairs zeroed
            kl = jnp.sum(jnp.exp(prev_c) * (prev_c - logp), axis=-1)
            kl = jnp.sum(kl * pm, axis=(1, 2)) / denom
            live = ~frozen_c
            out_n = jnp.where(live[:, None, None, None], lg, out_c)
            newly = live & (kl <= exit_kl)
            depth_n = jnp.where(newly, ck, depth_c)
            return (x_n, m_n, out_n, logp, frozen_c | newly, depth_n)

        operand = (x, m, out_logits, prev_logp, frozen, exit_depth)
        x, m, out_logits, prev_logp, frozen, exit_depth = jax.lax.cond(
            jnp.all(frozen), lambda op: op, step, operand
        )
        start = ck
    return out_logits, exit_depth


def predict_structure(
    params,
    cfg,
    tokens,
    *,
    mask=None,
    msa=None,
    msa_mask=None,
    embedds=None,
    templates=None,
    templates_mask=None,
    rng=None,
    mds_iters: int = 200,
    mds_init: str = "classical",
    model_apply_fn=None,
    early_exit_depths=(),
    early_exit_kl: float = 0.0,
):
    """Tokens (+ optional MSA/embedds/templates) → CA trace + confidence.

    Args:
      params: trunk parameter pytree (`alphafold2_init`).
      cfg: `Alphafold2Config` — static under jit.
      tokens: (b, L) int residue tokens. Padded positions carry
        PAD_TOKEN_ID and must be excluded via `mask`.
      mask: (b, L) bool residue validity. Padded pairs are zero-weighted
        in the MDS objective and masked residues score zero confidence,
        so a sequence's structure does not depend on how far its bucket
        over-pads it.
      msa / msa_mask: (b, rows, L) int tokens / bool validity, or None.
      embedds: (b, L, num_embedds) LM-embedding MSA substitute, or None.
      templates / templates_mask: (b, T, L, L) template conditioning.
      rng: PRNG key for the MDS random init (unused with
        mds_init="classical"); model forward is deterministic (eval).
      mds_iters / mds_init: static MDS knobs (see geometry/mds.py).
      model_apply_fn: trunk-forward override with the `alphafold2_apply`
        keyword signature — e.g. a sequence-parallel wrapper
        (parallel/sp_trunk.py). Geometry always runs replicated.
      early_exit_depths / early_exit_kl: static trunk-depth early-exit
        knobs (the serving cascade's third lever, serving/cascade.py).
        When `early_exit_depths` is non-empty the trunk runs in segments
        and a sample freezes its distogram at the first checkpoint depth
        whose masked-mean delta-KL from the previous checkpoint is
        <= `early_exit_kl` (first checkpoint = baseline, never exits);
        incompatible with `model_apply_fn` and `cfg.reversible`. Both
        knobs must be covered by the serving config tag.

    Returns dict:
      coords: (b, L, 3) CA trace.
      confidence: (b, L) per-residue confidence in [0, 1]
        (distogram-entropy pLDDT analog).
      stress: (b,) final normalized MDS stress.
      distogram_logits: (b, L, L, buckets) float32.
      exit_depth: (b,) int32 trunk depth each sample's distogram froze
        at — only when early exit is armed.
    """
    exit_depth = None
    if early_exit_depths:
        if model_apply_fn is not None:
            raise ValueError(
                "early exit drives the trunk itself (front/segments/"
                "head); it cannot compose with model_apply_fn overrides"
            )
        logits, exit_depth = _staged_trunk_logits(
            params, cfg, tokens,
            mask=mask, msa=msa, msa_mask=msa_mask, embedds=embedds,
            templates=templates, templates_mask=templates_mask,
            exit_depths=early_exit_depths, exit_kl=float(early_exit_kl),
        )  # (b, L, L, buckets) f32, (b,)
    else:
        apply_fn = (
            model_apply_fn if model_apply_fn is not None
            else alphafold2_apply
        )
        logits = apply_fn(
            params, cfg, tokens, msa,
            mask=mask, msa_mask=msa_mask, embedds=embedds,
            templates=templates, templates_mask=templates_mask,
        )  # (b, L, L, buckets)

    # geometry runs in float32 regardless of the trunk compute dtype: the
    # distogram -> MDS pipeline divides by pairwise distances and small
    # weights, which overflows/NaNs in bfloat16 (same stance as
    # training/e2e.py)
    logits = logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    distances, weights = center_distogram(probs)
    if mask is not None:
        pair_mask = (mask[:, :, None] & mask[:, None, :]).astype(weights.dtype)
        # zero BOTH channels for padded pairs: weights silence them in the
        # Guttman iterations, but the classical (Torgerson) init
        # double-centers the raw distance matrix with no weighting — junk
        # model distances for pad pairs would shift the real residues'
        # eigendecomposition start
        weights = weights * pair_mask
        distances = distances * pair_mask

    coords, stresses = MDScaling(
        distances,
        weights=weights,
        iters=mds_iters,
        # disable the convergence freeze: its trigger averages improvement
        # over the whole batch (geometry/mds.py), which would make one
        # request's iteration count — and thus its coordinates — depend on
        # its batchmates. Serving results must be batch-composition
        # independent (the result cache asserts equal key == identical
        # computation); Guttman steps past convergence are no-ops, so the
        # only cost is finishing the fixed iteration budget.
        tol=-jnp.inf,
        # single-atom-per-residue trace has no phi signal to decide
        # chirality from (same stance as predict.py's historical path)
        fix_mirror=False,
        key=rng,
        init=mds_init,
    )  # (b, 3, L), (iters, b)

    conf = distogram_confidence(probs, mask=mask)  # (b, L)
    out = {
        "coords": jnp.transpose(coords, (0, 2, 1)),  # (b, L, 3)
        "confidence": conf,
        "stress": stresses[-1],
        "distogram_logits": logits,
    }
    if exit_depth is not None:
        out["exit_depth"] = exit_depth
    return out
