"""Elastic replica autoscaler: close the loop from signals to capacity.

The ops plane (PR 9) made the fleet measurable while it runs — queue-wait
p95, per-replica occupancy, SLO burn rate all live in the registry — but
the replica count stayed a static `--replicas` flag: an operator reading
a burning queue-wait SLO still had to redeploy to add capacity. This
module is the missing actuator:

  `ScalePolicy`        declarative thresholds + hysteresis (JSON-loadable
                       like SLO configs and fault plans; unknown keys
                       reject loudly). Clock-invariant: the policy talks
                       thresholds and windows, never wall-clock now().
  `ReplicaAutoscaler`  a clock-injectable evaluator ticked periodically —
                       registerable as an `OpsTicker` hook (tick is
                       reentrancy-guarded), though serve.py runs it on
                       its OWN control thread so a scale-up's engine
                       build (seconds of XLA compile) cannot stall the
                       shared ticker's SLO/recorder/gauge work. Each
                       tick refreshes the live queue gauges, reads the
                       registry signals, runs the
                       sustain/hysteresis state machine, and grows or
                       shrinks the pool through `ServingFleet.add_replica`
                       / `remove_replica` — which retire capacity through
                       the SAME HealthMonitor drain path a sick replica
                       takes, so in-flight work requeues and nothing is
                       lost across a scale event.

Signals (all read from the fleet registry, so the autoscaler's inputs
are exactly what `/metrics` scrapes show an operator):

  * `fleet_queue_wait_seconds` p95 — the demand signal; sustained waits
    past `up_queue_wait_p95_s` with a non-empty queue mean the pool is
    underwater.
  * `slo_burn_rate{window="fast"}` — the SLO engine's verdict; burn past
    `up_burn` is the "users are noticing" trigger.
  * `fleet_occupancy` — dispatched work per slot of healthy capacity;
    high occupancy scales up before queue-wait degrades, low occupancy
    with an empty queue is the scale-DOWN signal (queue-wait p95 is a
    sliding window and stays high after a burst — it must never be the
    idle signal).

Hysteresis, the no-flap contract: an action needs its signal SUSTAINED
for `up_sustain`/`down_sustain` consecutive ticks, and any action starts
a cooldown (`up_cooldown_s`/`down_cooldown_s`, measured from the LAST
action in either direction) inside which the opposite decision is
suppressed — so the pool can never oscillate faster than its hysteresis
window, which the chaos suite drives directly with `scale_flap` faults
(forced alternating demands that bypass sustain but not the window).

Zero-downtime deploys ride the same machinery: `ServingFleet.
rolling_update` cycles each replica through the drain path one at a
time while the rest keep serving (docs/OPERATIONS.md runbook).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import threading
import time
import traceback
from typing import Optional

from alphafold2_tpu.serving.errors import ScaleRejectedError
from alphafold2_tpu.telemetry import MetricRegistry

_POLICY_KEYS = {
    "min_replicas", "max_replicas", "up_queue_wait_p95_s", "up_burn",
    "up_occupancy", "down_occupancy", "up_sustain", "down_sustain",
    "up_cooldown_s", "down_cooldown_s", "up_headroom",
}


@dataclasses.dataclass(frozen=True)
class ScalePolicy:
    """Autoscaling thresholds + hysteresis (module docstring)."""

    min_replicas: int = 1
    max_replicas: int = 4
    # scale-up triggers (any one, sustained `up_sustain` ticks):
    up_queue_wait_p95_s: float = 2.0   # queue-wait p95 with a live queue
    up_burn: float = 2.0               # fast-window SLO burn rate
    up_occupancy: float = 0.85         # dispatched work / healthy slots
    up_headroom: float = 0.15          # MODEL trigger: scale up when the
    #                                    cost-ledger capacity model says
    #                                    fleet_pool_headroom_ratio fell to
    #                                    this — a LEADING signal that fires
    #                                    before queue-wait p95 (a lagging
    #                                    symptom) crosses its threshold.
    #                                    Inert until the gauge exists
    #                                    (measured batches); 0 disables.
    # scale-down trigger (all, sustained `down_sustain` ticks):
    down_occupancy: float = 0.25       # ... with an EMPTY queue
    up_sustain: int = 2
    down_sustain: int = 5
    # cooldowns, both measured from the last action in EITHER direction —
    # down_cooldown_s is the hysteresis window that forbids up->down flap
    up_cooldown_s: float = 5.0
    down_cooldown_s: float = 30.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}"
            )
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})"
            )
        if self.up_sustain < 1 or self.down_sustain < 1:
            raise ValueError("sustain counts must be >= 1")
        if self.up_cooldown_s < 0 or self.down_cooldown_s < 0:
            raise ValueError("cooldowns must be >= 0")
        if (self.up_queue_wait_p95_s <= 0 or self.up_burn <= 0
                or not 0 < self.up_occupancy <= 1
                or not 0 <= self.down_occupancy < self.up_occupancy):
            raise ValueError(
                "thresholds must be positive, with "
                "0 <= down_occupancy < up_occupancy <= 1"
            )
        if not 0 <= self.up_headroom < 1:
            raise ValueError(
                f"up_headroom must be in [0, 1) (0 disables), got "
                f"{self.up_headroom}"
            )

    @classmethod
    def from_dict(cls, d: dict) -> "ScalePolicy":
        unknown = set(d) - _POLICY_KEYS
        if unknown:
            # the faults --check stance: a typo'd knob must not silently
            # leave the default in force
            raise ValueError(
                f"unknown scale-policy key(s) {sorted(unknown)}; known: "
                f"{sorted(_POLICY_KEYS)}"
            )
        return cls(**d)

    @classmethod
    def from_file(cls, path: str) -> "ScalePolicy":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))


class ReplicaAutoscaler:
    """Hysteresis autoscaler over one `ServingFleet` (module docstring).

    Args:
      fleet: the scaling target. Duck-typed surface: `registry`,
        `sample_gauges()`, `replica_count()`, `add_replica()`,
        `remove_replica()`, `attach_autoscaler(self)`, `_closed` — tests
        substitute a stub.
      policy: `ScalePolicy`.
      clock: injectable monotonic clock (the whole unit matrix runs
        without sleeping).
      incident_hook: optional `fn(kind, **attrs)` — scale events report
        as `scale_up` / `scale_down` (flight-recorder seam), so a bundle
        captures what the fleet looked like around the event.
      fault_hook: chaos seam (`FaultInjector.autoscale_hook()`): called
        with the tick index; a returned "up"/"down" is a FORCED demand
        (bypasses sustain, still subject to cooldown/min/max).
      pool: "" (default) scales the whole fleet off the fleet-wide
        signals — the homogeneous PR-11 behavior. A capability-pool name
        scopes EVERYTHING to that pool: signals read the pool-labeled
        families (`fleet_pool_queue_depth` / `fleet_pool_occupancy` /
        `fleet_pool_queue_wait_seconds` p95), actions call
        `add_replica(pool=)` / `remove_replica(pool=)`, and the size
        check uses `replica_count(pool)` — so a heterogeneous fleet runs
        one autoscaler per pool and a saturated SP pool grows while the
        idle dense pool shrinks, independently (ROADMAP item 4b). The
        SLO fast-burn trigger stays fleet-wide (objectives are
        fleet-level) but only fires a pool whose own queue is live.
    """

    def __init__(self, fleet, policy: ScalePolicy, *,
                 registry: Optional[MetricRegistry] = None,
                 clock=time.monotonic, incident_hook=None, fault_hook=None,
                 max_events: int = 256, pool: str = ""):
        self.fleet = fleet
        self.policy = policy
        self.pool = pool
        self.registry = registry if registry is not None else fleet.registry
        self._clock = clock
        self._incident_hook = incident_hook
        self._fault_hook = fault_hook
        self._lock = threading.Lock()
        self._ticks = 0
        self._up_streak = 0
        self._down_streak = 0
        self._last_action: Optional[str] = None
        self._last_action_at: Optional[float] = None
        self._events = collections.deque(maxlen=max_events)
        pool_label = {"pool": pool} if pool else {}
        self._decisions = {
            name: self.registry.counter(
                "autoscale_decisions_total",
                help="autoscaler decisions by outcome", action=name,
                **pool_label)
            for name in ("up", "down", "rejected", "suppressed")
        }
        # pool size itself is the fleet's gauge (fleet_replicas, set by
        # sample_gauges) — a second autoscaler-side copy would just be a
        # momentarily-disagreeing duplicate
        self._tick_gate = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        attach = getattr(fleet, "attach_autoscaler", None)
        if attach is not None:
            attach(self)

    # ------------------------------------------------------------- signals

    def _signals(self) -> dict:
        fams = self.registry.collect()

        def max_gauge(name, **want):
            fam = fams.get(name)
            if fam is None:
                return 0.0
            vals = [m.value for key, m in fam[1].items()
                    if all(dict(key).get(k) == v for k, v in want.items())]
            return max(vals, default=0.0)

        # pool-scoped: the pool-labeled families (ServingFleet
        # sample_gauges / _try_dispatch publish them) — never the global
        # ones, which mix every pool's traffic together
        if self.pool:
            depth_name, occ_name = ("fleet_pool_queue_depth",
                                    "fleet_pool_occupancy")
            wait_name, want = ("fleet_pool_queue_wait_seconds",
                               {"pool": self.pool})
        else:
            depth_name, occ_name = "fleet_queue_depth", "fleet_occupancy"
            wait_name, want = "fleet_queue_wait_seconds", {}

        p95 = 0.0
        fam = fams.get(wait_name)
        if fam is not None and fam[0] == "histogram":
            p95 = max((m.percentile(95.0) for key, m in fam[1].items()
                       if all(dict(key).get(k) == v
                              for k, v in want.items())),
                      default=0.0)
        # headroom (fleet_pool_headroom_ratio, the cost-ledger capacity
        # model): None while the gauge is ABSENT — the trigger must stay
        # inert until the pool has measured batches, and a
        # default-to-zero here would read "no data" as "no headroom"
        # and scale every cold fleet to max
        headroom = None
        fam = fams.get("fleet_pool_headroom_ratio")
        if fam is not None:
            pool_want = {"pool": self.pool} if self.pool else {}
            vals = [m.value for key, m in fam[1].items()
                    if all(dict(key).get(k) == v
                           for k, v in pool_want.items())]
            if vals:
                # fleet-wide scaler: the TIGHTEST pool is the signal
                headroom = min(vals)
        return {
            "queue_depth": max_gauge(depth_name, **want),
            "occupancy": max_gauge(occ_name, **want),
            "queue_wait_p95": p95,
            "burn_fast": max_gauge("slo_burn_rate", window="fast"),
            "headroom": headroom,
        }

    # ---------------------------------------------------------------- tick

    def tick(self, now: Optional[float] = None):
        """One evaluation pass. Never raises — rejected actions are
        decisions, not crashes. Reentrancy-guarded: a tick whose scale
        action is still building an engine (adds can XLA-compile for
        seconds) makes overlapping ticks no-ops instead of stacking.
        NOTE serve.py runs this on the autoscaler's OWN thread, not the
        shared OpsTicker — a slow engine build must not stall SLO
        evaluation / flight-recorder polling / gauge sampling during
        exactly the overload window that triggered the scale-up."""
        if not self._tick_gate.acquire(blocking=False):
            return
        try:
            self._tick(now)
        finally:
            self._tick_gate.release()

    def _tick(self, now: Optional[float]):
        if getattr(self.fleet, "_closed", False):
            return
        now = self._clock() if now is None else now
        with self._lock:
            idx = self._ticks
            self._ticks += 1
        forced = None
        if self._fault_hook is not None:
            try:
                forced = self._fault_hook(idx)
            except Exception:  # noqa: BLE001 — a chaos hook bug must not
                # kill the control loop it is testing
                traceback.print_exc()
        try:
            self.fleet.sample_gauges()
        except Exception:  # noqa: BLE001 — stale gauges beat a dead loop
            traceback.print_exc()
        sig = self._signals()
        with self._lock:
            live_queue = sig["queue_depth"] >= 1
            want_up = (
                (live_queue
                 and sig["queue_wait_p95"] >= self.policy.up_queue_wait_p95_s)
                or (live_queue and sig["burn_fast"] >= self.policy.up_burn)
                or sig["occupancy"] >= self.policy.up_occupancy
                # the capacity-MODEL trigger (deliberately queue-free:
                # the whole point is to fire before queue symptoms —
                # the gauge itself only exists once arrivals and
                # measured batches armed the model)
                or (self.policy.up_headroom > 0
                    and sig["headroom"] is not None
                    and sig["headroom"] <= self.policy.up_headroom)
            )
            # the idle test deliberately ignores queue-wait p95: it is a
            # sliding window and stays high long after a burst drains
            want_down = (
                sig["queue_depth"] == 0
                and sig["occupancy"] <= self.policy.down_occupancy
            )
            if want_up:
                self._up_streak += 1
                self._down_streak = 0
            elif want_down:
                self._down_streak += 1
                self._up_streak = 0
            else:
                self._up_streak = 0
                self._down_streak = 0
            action = None
            if forced == "up" or (want_up
                                  and self._up_streak
                                  >= self.policy.up_sustain):
                action = "up"
            elif forced == "down" or (want_down
                                      and self._down_streak
                                      >= self.policy.down_sustain):
                action = "down"
            if action is None:
                return
            # hysteresis window: cooldown measured from the last action
            # in EITHER direction — the no-flap contract
            cooldown = (self.policy.up_cooldown_s if action == "up"
                        else self.policy.down_cooldown_s)
            if (self._last_action_at is not None
                    and now - self._last_action_at < cooldown):
                self._decisions["suppressed"].inc()
                self._note(now, "suppressed", sig,
                           reason=f"{action} inside {cooldown}s cooldown",
                           forced=bool(forced))
                return
            n = (self.fleet.replica_count(self.pool) if self.pool
                 else self.fleet.replica_count())
            if action == "up" and n >= self.policy.max_replicas:
                self._decisions["suppressed"].inc()
                self._note(now, "suppressed", sig, reason="at_max",
                           forced=bool(forced))
                return
            if action == "down" and n <= self.policy.min_replicas:
                self._decisions["suppressed"].inc()
                self._note(now, "suppressed", sig, reason="at_min",
                           forced=bool(forced))
                return
        # act OUTSIDE the lock: add/remove take fleet locks and (remove)
        # wait on health machinery
        try:
            if action == "up":
                name = (self.fleet.add_replica(pool=self.pool)
                        if self.pool else self.fleet.add_replica())
            else:
                name = (self.fleet.remove_replica(pool=self.pool)
                        if self.pool else self.fleet.remove_replica())
        except ScaleRejectedError as e:
            self._decisions["rejected"].inc()
            count_err = getattr(self.fleet, "_count_error", None)
            if count_err is not None:
                count_err(e)
            with self._lock:
                self._note(now, "rejected", sig, reason=str(e),
                           forced=bool(forced))
            return
        with self._lock:
            self._last_action, self._last_action_at = action, now
            self._up_streak = self._down_streak = 0
            self._decisions[action].inc()
            n_after = (self.fleet.replica_count(self.pool) if self.pool
                       else self.fleet.replica_count())
            self._note(now, action, sig, replica=name, replicas=n_after,
                       forced=bool(forced))
        if self._incident_hook is not None:
            try:
                self._incident_hook(f"scale_{action}", replica=name,
                                    replicas=n_after,
                                    **({"pool": self.pool} if self.pool
                                       else {}), **sig)
            except Exception:  # noqa: BLE001 — observability must never
                # take the control loop down
                traceback.print_exc()

    def _note(self, now, action, sig, **extra):
        self._events.append({
            "ts": now, "action": action,
            # None = signal absent (headroom before the model arms);
            # recorded as-is so the event log distinguishes "no data"
            # from a measured zero
            "signals": {k: (round(float(v), 4) if v is not None else None)
                        for k, v in sig.items()},
            **extra,
        })

    # ------------------------------------------------------------- threads

    def start(self, interval_s: float = 1.0):
        """Fallback ticker for runs without an ops server (the OpsTicker
        hook is the production wiring — `ops.add_tick(scaler.tick)`)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — the control loop
                    # must survive its own bugs
                    traceback.print_exc()

        self._thread = threading.Thread(
            target=loop, name=f"af2-autoscale-{self.pool or 'fleet'}",
            daemon=True)
        self._thread.start()

    def stop(self, timeout: Optional[float] = 5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    # --------------------------------------------------------------- stats

    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def scale_events(self) -> list:
        """Only the acted up/down transitions (the acceptance assertions'
        view)."""
        return [e for e in self.events() if e["action"] in ("up", "down")]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "policy": dataclasses.asdict(self.policy),
                "pool": self.pool,
                "ticks": self._ticks,
                "replicas": (self.fleet.replica_count(self.pool)
                             if self.pool else self.fleet.replica_count()),
                "last_action": self._last_action,
                "last_action_age_s": (
                    None if self._last_action_at is None
                    else self._clock() - self._last_action_at
                ),
                "decisions": {k: int(c.value)
                              for k, c in self._decisions.items()},
                "events": list(self._events)[-32:],
            }
