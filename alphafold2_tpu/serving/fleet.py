"""Fleet tier: N engine replicas, one admission-controlled front door.

The PR 2 engine is one warm model in one process — a single hung batch,
poisoned executable, or slow compile stalls the whole tier. This module
is the robustness half of the ParaFold pool story (arxiv 2111.06340):
a replicated tier that keeps answering, degrades predictably, and treats
replica death as routine traffic management rather than an outage.

Architecture (three cooperating layers, each independently testable):

  `serving/admission.py`   the shared front door: priority classes,
                           per-request deadlines, structured shedding
                           with `retry_after_s`.
  this module              the router: a dispatcher thread pulls from
                           the admission queue and places requests on
                           the least-loaded HEALTHY replica; completion
                           callbacks (the `add_done_callback` seam on
                           `ServingRequest`) either resolve the client
                           future or REQUEUE the request onto another
                           replica (bounded by `requeue_limit`).
  `reliability/health.py`  the supervisor: dispatch-failure evidence and
                           heartbeat probes drain a sick replica (its
                           engine is shut down drain=False, which fails
                           its queued work back through the requeue
                           path — nothing is lost), and re-probes
                           reinstate it behind a fresh engine.

Requeue is IDEMPOTENT by construction: a structure is a deterministic
function of (sequence, bucket) under a shared config tag
(serving/cache.py), so replaying a request on a different replica
returns bit-identical results — pinned by tests against the
single-engine path. Fleet latency/cache stats count each request once,
at its terminal outcome.

Degraded mode: with `degraded_mds_iters` and/or `degraded_weight_dtype`
set, the fleet holds one extra engine at a cheaper config tag (fewer MDS
iterations, and/or int8 PTQ trunk weights — serving/quant_residency.py —
a second tenant of the result-cache keyspace at ~1/4 the weight
residency). It takes traffic only when every full replica is down or the
queue is past `degrade_depth`, and every response it serves is flagged
`degraded=True` — the client always knows which answer it got.

Every replica breaker gets seeded `breaker_jitter` with a per-replica
seed, so a fleet-wide dependency failure does not re-probe in lockstep.

Terminal outcomes are exhaustive: every accepted request ends exactly
one of served / served-degraded / shed-with-structured-error / failed —
the chaos suite drives kill/slow/flap plans through `serve.py
--replicas --fault-plan` and asserts zero lost requests.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import traceback
from typing import Optional

from alphafold2_tpu.constants import AA_ORDER, aa_to_tokens
from alphafold2_tpu.reliability.health import HealthMonitor, ReplicaState
from alphafold2_tpu.serving.admission import (
    AdmissionConfig,
    AdmissionController,
    resolve_priority,
)
from alphafold2_tpu.serving.bucketing import BucketLadder
from alphafold2_tpu.serving.engine import (
    PredictionResult,
    ServingConfig,
    ServingEngine,
)
from alphafold2_tpu.serving.errors import (
    CircuitOpenError,
    EngineClosedError,
    HungBatchError,
    InvalidSequenceError,
    NoHealthyReplicaError,
    PredictionError,
    QueueFullError,
    RequestTimeoutError,
    RequeueLimitError,
    ServingError,
)
from alphafold2_tpu.telemetry import NULL_TRACER, MetricRegistry, new_trace_id

#: replica errors that justify trying ANOTHER replica — the replica (not
#: the request) is the suspect. Everything else is terminal for the
#: request itself.
_REPLICA_FAULT_ERRORS = (
    PredictionError,
    HungBatchError,
    EngineClosedError,
    CircuitOpenError,
)

DEGRADED = "degraded"  # reserved tier name (not a health-managed replica)


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet-level knobs; per-replica scheduler knobs stay in
    `ServingConfig` (docs/OPERATIONS.md "Fleet runbook")."""

    replicas: int = 2
    queue_capacity: int = 64     # shared admission queue bound
    default_timeout_s: Optional[float] = 60.0  # fleet-level deadline
    requeue_limit: int = 2       # replica failovers per request
    degraded_mds_iters: int = 0  # >0: hold a cheaper-tag fallback engine
    degraded_weight_dtype: str = ""  # "int8": the degraded tier serves
    #                              per-channel-PTQ int8 trunk weights
    #                              (ops/quant.py) — a precision degrade
    #                              that composes with degraded_mds_iters;
    #                              ""/"f32" keeps full-precision weights
    degrade_depth: int = 0       # queue depth that routes NEW work to the
    #                              degraded tier (0 = only on total outage)
    probe_interval_s: float = 5.0    # heartbeat cadence, healthy replicas
    reprobe_interval_s: float = 0.5  # reinstatement probe cadence, down
    probe_timeout_s: float = 10.0
    fail_threshold: int = 2      # consecutive failures that drain
    drain_timeout_s: float = 5.0
    breaker_jitter: float = 0.25  # seeded reopen spread per replica
    dispatch_backoff_s: float = 0.01  # router sleep when every target is full
    tick_interval_s: float = 0.05     # health thread granularity

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {self.replicas}")
        if self.requeue_limit < 0:
            raise ValueError(
                f"requeue_limit must be >= 0, got {self.requeue_limit}"
            )
        if self.degraded_mds_iters < 0 or self.degrade_depth < 0:
            raise ValueError("degraded knobs must be >= 0")
        if self.degraded_weight_dtype not in ("", "f32", "int8"):
            raise ValueError(
                f"degraded_weight_dtype must be '', 'f32', or 'int8', "
                f"got {self.degraded_weight_dtype!r}"
            )


class FleetRequest:
    """Client handle: one future, resolved exactly once by the fleet.

    Duck-typed for the admission queue (`priority` / `deadline` /
    `enqueued_at`); `requeues` counts replica failovers survived."""

    def __init__(self, seq: str, msa, msa_mask, priority: int,
                 deadline: Optional[float], trace_id: str = ""):
        self.seq = seq
        self.msa = msa
        self.msa_mask = msa_mask
        self.priority = priority
        self.deadline = deadline
        self.enqueued_at = time.monotonic()
        # minted HERE (the fleet front door) and handed to every engine
        # submit this request makes — admission queueing, routing, and
        # requeues onto other replicas all carry ONE id
        self.trace_id = trace_id or new_trace_id()
        self.requeues = 0
        self.failed_on = set()   # replica names this request failed on
        self.last_error: Optional[BaseException] = None
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._result: Optional[PredictionResult] = None
        self._meta = {}
        self._exc: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def _finish(self, result=None, exc=None, **meta) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self._result, self._exc, self._meta = result, exc, meta
            self._event.set()
            return True

    def result(self, timeout: Optional[float] = None) -> PredictionResult:
        """Block for the outcome; raises the terminal ServingError, or
        builtin TimeoutError if the CALLER's wait budget expires first.
        Returns a fresh copy stamped with fleet provenance (replica,
        degraded, requeues) — the raw result may alias a replica cache
        entry and is never handed out."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"fleet request ({len(self.seq)} residues) not completed "
                f"within {timeout}s wait"
            )
        if self._exc is not None:
            raise self._exc
        return dataclasses.replace(
            self._result,
            coords=self._result.coords.copy(),
            confidence=self._result.confidence.copy(),
            latency_s=self._meta.get("latency_s", self._result.latency_s),
            replica=self._meta.get("replica", ""),
            degraded=self._meta.get("degraded", False),
            requeues=self.requeues,
            trace_id=self.trace_id,
        )


class _Replica:
    """One engine slot; the engine reference swaps across drain/restart
    cycles (guarded by the fleet lock)."""

    def __init__(self, name: str, factory):
        self.name = name
        self.factory = factory   # () -> ServingEngine
        self.engine: Optional[ServingEngine] = None
        self.in_flight = 0
        self.dispatches = 0
        self.probe_counter = 0
        self.restarts = 0


class ServingFleet:
    """N `ServingEngine` replicas behind one admission-controlled queue.

    Args:
      params / model_cfg / serving_cfg: as `ServingEngine` — every
        replica shares them (and therefore the cache-key config tag:
        the idempotency contract failover depends on).
      fleet_cfg: `FleetConfig`.
      engine_factory: override `(name, serving_cfg, fault_hook) ->
        ServingEngine` — tests substitute fake engines; the default
        builds real ones over `params`.
      injector: optional `reliability.FaultInjector`; each replica gets
        `injector.replica_hook(name)` so kill/slow/flap plans target
        replicas by name.
      tracer / registry: fleet-level telemetry (replica engines keep
        their own `ServingMetrics`; the fleet registry carries the
        fleet_* metric families).
      incident_hook: optional `fn(kind, **attrs)` — the flight-recorder
        seam (telemetry/ops_plane.py). The fleet reports
        `replica_drain` itself and threads the hook into every
        default-factory engine (breaker_open / watchdog_fire); custom
        `engine_factory` callers wire their own engines.
    """

    def __init__(self, params, model_cfg,
                 serving_cfg: ServingConfig = ServingConfig(),
                 fleet_cfg: FleetConfig = FleetConfig(), *,
                 engine_factory=None, model_apply_fn=None, injector=None,
                 tracer=None, registry: Optional[MetricRegistry] = None,
                 incident_hook=None):
        self.cfg = fleet_cfg
        self._params = params
        self._model_cfg = model_cfg
        self._serving_cfg = serving_cfg
        self._model_apply_fn = model_apply_fn
        self._injector = injector
        self._ladder = BucketLadder(serving_cfg.buckets)
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self.registry = registry if registry is not None else MetricRegistry()
        self._incident_hook = incident_hook
        self._factory = engine_factory or self._default_factory

        self._lock = threading.Lock()
        self._closed = False
        self._drain_on_stop = True
        self._stop = threading.Event()

        # ---- telemetry families (the acceptance surface) ----
        self._counts = {
            name: self.registry.counter(
                "fleet_requests_total", help="fleet request terminal outcomes",
                outcome=name)
            for name in ("submitted", "completed", "shed", "failed")
        }
        self._degraded_total = self.registry.counter(
            "fleet_degraded_total", help="responses served by the degraded tier")
        self._requeue_total = self.registry.counter(
            "fleet_requeue_total", help="replica-failover requeues")
        self._shed_reasons = {}   # reason -> counter (lazy)
        self._errors = {}         # stable code -> counter (lazy)
        self._queue_wait = self.registry.histogram(
            "fleet_queue_wait_seconds",
            help="admission-queue wait, sliding window (p95 is the "
                 "autoscaling signal)")
        self._latency = self.registry.histogram(
            "fleet_request_latency_seconds",
            help="fleet submit->terminal latency, sliding window")
        self._up_gauges = {}

        # ---- replicas + health ----
        self._admission = AdmissionController(
            AdmissionConfig(capacity=fleet_cfg.queue_capacity))
        self._health = HealthMonitor(
            probe_interval_s=fleet_cfg.probe_interval_s,
            reprobe_interval_s=fleet_cfg.reprobe_interval_s,
            fail_threshold=fleet_cfg.fail_threshold,
        )
        self._replicas = {}
        for i in range(fleet_cfg.replicas):
            name = f"r{i}"
            rcfg = dataclasses.replace(
                serving_cfg,
                breaker_jitter=(fleet_cfg.breaker_jitter
                                if serving_cfg.breaker_threshold else 0.0),
                breaker_jitter_seed=i,
            )
            rep = _Replica(name, self._make_factory(name, rcfg))
            rep.engine = rep.factory()
            self._replicas[name] = rep
            self._up_gauges[name] = self.registry.gauge(
                "fleet_replica_up", help="1 = taking traffic", replica=name)
            self._up_gauges[name].set(1)
            self._health.register(
                name,
                probe=lambda n=name: self._probe_replica(n),
                on_drain=self._drain_replica,
                on_reinstate=self._reinstate_replica,
            )

        self._degraded_rep: Optional[_Replica] = None
        # the degraded tier can be cheaper on MDS iterations, on weight
        # precision (int8 PTQ trunk), or both — either knob arms it. Its
        # model config diverges from the full replicas' exactly when the
        # precision knob is set, which moves it to its own config tag
        # (results can never alias the full-precision cache keyspace).
        self._degraded_model_cfg = self._model_cfg
        if fleet_cfg.degraded_weight_dtype == "int8":
            self._degraded_model_cfg = dataclasses.replace(
                model_cfg, weight_dtype="int8")
        if (fleet_cfg.degraded_mds_iters
                or fleet_cfg.degraded_weight_dtype == "int8"):
            dcfg = serving_cfg
            if fleet_cfg.degraded_mds_iters:
                dcfg = dataclasses.replace(
                    serving_cfg, mds_iters=fleet_cfg.degraded_mds_iters)
            self._degraded_rep = _Replica(
                DEGRADED, self._make_factory(DEGRADED, dcfg))
            self._degraded_rep.engine = self._degraded_rep.factory()

        self._health.start(fleet_cfg.tick_interval_s)
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="fleet-dispatcher", daemon=True)
        self._dispatcher.start()

    # ------------------------------------------------------------ factories

    def _default_factory(self, name, cfg, fault_hook):
        model_cfg = (self._degraded_model_cfg if name == DEGRADED
                     else self._model_cfg)
        return ServingEngine(
            self._params, model_cfg, cfg,
            model_apply_fn=self._model_apply_fn,
            fault_hook=fault_hook, tracer=self._tracer,
            replica_name=name, incident_hook=self._incident_hook,
        )

    def _make_factory(self, name, cfg):
        hook = (self._injector.replica_hook(name)
                if self._injector is not None else None)

        def build():
            try:
                return self._factory(name, cfg, hook)
            except Exception:  # noqa: BLE001 — a failing restart is a
                # failed probe, not a fleet crash
                traceback.print_exc()
                return None

        return build

    # ----------------------------------------------------------------- API

    def submit(self, seq: str, *, msa=None, msa_mask=None,
               timeout: Optional[float] = None,
               priority="normal", trace_id: str = "") -> FleetRequest:
        """Enqueue one sequence at the fleet front door; returns a future.

        `trace_id` ("" mints one) correlates every span this request
        touches — across the admission queue, the dispatcher, requeues,
        and every replica engine — and rides the result for log/trace
        cross-reference.

        Raises EngineClosedError / InvalidSequenceError /
        RequestTooLongError / QueueFullError(retry_after_s) synchronously.
        A lower-priority queued request may be EVICTED (resolved with a
        retry-after error) to admit a higher-priority one.
        """
        trace_id = trace_id or new_trace_id()
        with self._tracer.span("fleet.enqueue", cat="fleet",
                               length=len(seq), trace_id=trace_id):
            if self._closed:
                raise EngineClosedError("fleet is shut down")
            seq = seq.strip().upper()
            try:
                aa_to_tokens(seq, strict=True)
            except ValueError as e:
                self._count_error(InvalidSequenceError(str(e)))
                raise InvalidSequenceError(str(e)) from None
            try:
                self._ladder.bucket_for(len(seq))
            except ServingError as e:
                self._count_error(e)
                raise
            ttl = (self.cfg.default_timeout_s if timeout is None else timeout)
            deadline = (time.monotonic() + ttl) if ttl is not None else None
            entry = FleetRequest(seq, msa, msa_mask,
                                 resolve_priority(priority), deadline,
                                 trace_id=trace_id)
            self._counts["submitted"].inc()
            try:
                evicted = self._admission.offer(entry)
            except QueueFullError as e:
                # stays counted as submitted: shed is its terminal
                # outcome, so in_flight arithmetic balances
                self._shed_counter("queue_full").inc()
                self._counts["shed"].inc()
                self._count_error(e)
                raise
            if evicted is not None:
                self._resolve_shed(
                    evicted, "evicted",
                    QueueFullError(
                        "evicted by a higher-priority arrival under "
                        "overload; retry with backoff",
                        retry_after_s=self._admission.retry_after_s(),
                    ))
            # close the TOCTOU window against shutdown() (the engine's
            # stance, engine.py): if the closed flag flipped after the
            # entry check, shutdown's final drain may already be past
            # this entry — resolve it ourselves; _finish is resolve-once,
            # so losing the race to a still-draining dispatcher is
            # harmless
            if self._closed and self._resolve_failed(entry, EngineClosedError(
                    "fleet shut down while the request was being "
                    "submitted")):
                raise EngineClosedError("fleet is shut down")
            return entry

    def predict(self, seq: str, *, msa=None, msa_mask=None,
                timeout: Optional[float] = None,
                priority="normal") -> PredictionResult:
        """Synchronous convenience: submit + block for the result."""
        return self.submit(seq, msa=msa, msa_mask=msa_mask, timeout=timeout,
                           priority=priority).result()

    def health(self) -> dict:
        """Cheap liveness payload for `/healthz` (telemetry/ops_plane.py):
        HealthMonitor states + replica-up view, no engine stats. `status`
        is "ok" (all replicas healthy), "degraded" (reduced capacity:
        some replicas down, or only the degraded tier is serving), or
        "down" (closed, or nothing can serve — mapped to HTTP 503)."""
        snap = self._health.snapshot()
        states = {name: t["state"] for name, t in snap["targets"].items()}
        n_healthy = sum(1 for s in states.values() if s == "healthy")
        with self._lock:
            has_degraded = self._degraded_rep is not None
        if self._closed or (n_healthy == 0 and not has_degraded):
            status = "down"
        elif n_healthy < len(states):
            status = "degraded"
        else:
            status = "ok"
        return {
            "status": status,
            "closed": self._closed,
            "replicas": states,
            "healthy_replicas": n_healthy,
            "total_replicas": len(states),
            "degraded_tier": has_degraded,
            "queue_depth": self._admission.depth(),
            "queue_capacity": self.cfg.queue_capacity,
        }

    def stats(self) -> dict:
        """JSON-ready fleet snapshot: terminal counters, admission queue,
        per-replica state + engine stats, health, telemetry registry."""
        counts = {k: int(c.value) for k, c in self._counts.items()}
        counts["degraded"] = int(self._degraded_total.value)
        counts["requeued"] = int(self._requeue_total.value)
        counts["in_flight"] = (
            counts["submitted"] - counts["completed"] - counts["shed"]
            - counts["failed"]
        )
        with self._lock:
            reps = list(self._replicas.values())
            degraded = self._degraded_rep
            shed = {reason: int(c.value)
                    for reason, c in self._shed_reasons.items()}
            errors = {code: int(c.value)
                      for code, c in self._errors.items()}
        replicas = {}
        for rep in reps + ([degraded] if degraded else []):
            engine = rep.engine
            replicas[rep.name] = {
                "state": (DEGRADED if rep.name == DEGRADED
                          else self._health.state(rep.name).value),
                "in_flight": rep.in_flight,
                "dispatches": rep.dispatches,
                "restarts": rep.restarts,
                "engine": engine.stats() if engine is not None else None,
            }
        return {
            "closed": self._closed,
            "requests": counts,
            "shed": shed,
            "errors": errors,
            "queue_wait": self._queue_wait.snapshot(),
            "latency": self._latency.snapshot(),
            "admission": self._admission.snapshot(),
            "replicas": replicas,
            "health": self._health.snapshot(),
            "telemetry": {
                "metrics": self.registry.snapshot(),
                "spans": self._tracer.summary(),
            },
        }

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop the front door, the router, the supervisor, and every
        engine. drain=True serves what it still can (replica engines
        drain their queues); whatever cannot be served resolves with
        EngineClosedError — nothing is left unresolved. Idempotent."""
        self._closed = True
        self._drain_on_stop = drain
        self._stop.set()
        self._dispatcher.join(timeout)
        self._health.stop()
        with self._lock:
            reps = list(self._replicas.values())
            if self._degraded_rep is not None:
                reps.append(self._degraded_rep)
        for rep in reps:
            engine = rep.engine
            if engine is not None:
                engine.shutdown(drain=drain, timeout=self.cfg.drain_timeout_s)
        # engine shutdown callbacks may have requeued entries after the
        # dispatcher died; fail every remaining queued entry terminally
        for entry in self._admission.drain():
            self._resolve_failed(entry, EngineClosedError(
                "fleet shut down before the request was served"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=True)
        return False

    # ------------------------------------------------------------- router

    def _dispatch_loop(self):
        try:
            while True:
                if self._stop.is_set():
                    if not self._drain_on_stop:
                        return
                    entry, expired = self._admission.poll(timeout=0)
                    if entry is None and not expired:
                        return  # queue fully drained
                else:
                    entry, expired = self._admission.poll(timeout=0.05)
                for e in expired:
                    self._resolve_shed(e, "deadline", RequestTimeoutError(
                        f"deadline passed after "
                        f"{time.monotonic() - e.enqueued_at:.3f}s in the "
                        f"fleet queue",
                        retry_after_s=self._admission.retry_after_s()))
                if entry is not None:
                    self._route(entry)
        except BaseException:  # noqa: BLE001 — last-resort guard (engine
            # worker stance): fail queued work loudly, refuse new traffic
            self._closed = True
            traceback.print_exc()
            for entry in self._admission.drain():
                self._resolve_failed(entry, PredictionError(
                    "fleet dispatcher crashed; fleet is closed"))

    def _route(self, entry: FleetRequest):
        wait = time.monotonic() - entry.enqueued_at
        self._queue_wait.observe(wait)
        if self._tracer.enabled:
            self._tracer.add("fleet.queue_wait", wait, cat="fleet",
                             priority=entry.priority,
                             trace_id=entry.trace_id,
                             requeues=entry.requeues)
        overloaded = (self.cfg.degrade_depth > 0
                      and self._admission.depth() >= self.cfg.degrade_depth)
        healthy = self._health.healthy_targets()
        with self._lock:
            ranked = sorted(
                (self._replicas[n] for n in healthy),
                key=lambda r: r.in_flight,
            )
            degraded = self._degraded_rep
        # failover exclusion: a replica this request already FAILED on is
        # the worst candidate, not an equal one — prefer untried healthy
        # replicas, fall to the degraded tier when none remain, and only
        # then retry where it failed (better a retry than a starve)
        fresh = [r for r in ranked if r.name not in entry.failed_on]
        stale = [r for r in ranked if r.name in entry.failed_on]
        targets = fresh
        if degraded is not None and (overloaded or not fresh):
            # the cheap tier catches the overload spill the full replicas
            # reject, and is the first resort once the request has failed
            # on (or lost) every full replica — the response says so
            targets = targets + [degraded]
        targets = targets + stale
        if not targets:
            # every full replica is down and there is no degraded tier:
            # answer NOW with the re-probe horizon instead of letting the
            # request age out silently
            self._resolve_shed(
                entry, "no_healthy_replica",
                NoHealthyReplicaError(
                    "every replica is down and no degraded tier is "
                    "configured",
                    retry_after_s=self.cfg.reprobe_interval_s))
            return
        for rep in targets:
            if self._try_dispatch(entry, rep):
                return
        # nothing admitted it (queues full / engines mid-drain): the
        # entry stays accepted — requeue WITHOUT consuming failover
        # budget and let the router breathe. Exception: during shutdown
        # with every candidate engine already dead, nothing will ever
        # free up — resolve terminally instead of orbiting the queue.
        with self._lock:
            alive = any(
                r.engine is not None and not r.engine._closed
                for r in targets
            )
        if self._closed and not alive:
            self._resolve_failed(entry, EngineClosedError(
                "fleet shut down before the request was served"))
            return
        self._admission.requeue(entry)
        time.sleep(self.cfg.dispatch_backoff_s)

    def _try_dispatch(self, entry: FleetRequest, rep: _Replica) -> bool:
        engine = rep.engine
        if engine is None:
            return False
        now = time.monotonic()
        remaining = (None if entry.deadline is None
                     else entry.deadline - now)
        if remaining is not None and remaining <= 0:
            self._resolve_shed(entry, "deadline", RequestTimeoutError(
                "deadline passed at dispatch",
                retry_after_s=self._admission.retry_after_s()))
            return True
        try:
            # bind_trace: any span a helper records on the dispatcher
            # thread during THIS routing inherits the request's id
            with self._tracer.bind_trace(entry.trace_id):
                inner = engine.submit(
                    entry.seq, msa=entry.msa, msa_mask=entry.msa_mask,
                    # None would fall back to the ENGINE's default
                    # deadline; a deadline-less fleet request must stay
                    # deadline-less
                    timeout=remaining if remaining is not None else 1e9,
                    # the fleet's id, not a fresh engine-minted one: a
                    # requeued request keeps one id across replicas
                    trace_id=entry.trace_id,
                )
        except QueueFullError:
            return False
        except (CircuitOpenError, EngineClosedError) as e:
            if rep.name != DEGRADED:
                self._health.record_failure(rep.name, e.code)
            return False
        except ServingError as e:
            # semantic rejection (bad MSA shape etc.): the request is the
            # problem — terminal, no failover
            self._resolve_failed(entry, e)
            return True
        with self._lock:
            rep.in_flight += 1
            rep.dispatches += 1
        dispatched_at = now
        inner.add_done_callback(
            lambda r, e=entry, rp=rep, t=dispatched_at:
            self._on_replica_done(e, rp, r, t))
        return True

    # ---------------------------------------------------- completion path

    def _on_replica_done(self, entry: FleetRequest, rep: _Replica,
                         inner, dispatched_at: float):
        """Runs on the replica worker (or drain) thread: resolve, or
        requeue onto another replica. Never blocks, never raises."""
        with self._lock:
            rep.in_flight -= 1
        result, exc = inner.peek()
        degraded = rep.name == DEGRADED
        if exc is None:
            if not degraded:
                self._health.record_success(rep.name)
            self._admission.note_served(time.monotonic() - dispatched_at)
            if entry._finish(result=result, replica=rep.name,
                             degraded=degraded,
                             latency_s=time.monotonic() - entry.enqueued_at):
                self._counts["completed"].inc()
                self._latency.observe(time.monotonic() - entry.enqueued_at)
                if degraded:
                    self._degraded_total.inc()
            return
        if isinstance(exc, RequestTimeoutError):
            # the request's OWN deadline expired inside the replica —
            # failover could not have saved it
            self._resolve_shed(entry, "deadline", exc)
            return
        if isinstance(exc, _REPLICA_FAULT_ERRORS):
            if not degraded:
                self._health.record_failure(rep.name, exc.code)
            entry.failed_on.add(rep.name)
            entry.last_error = exc
            if not self._closed and entry.requeues < self.cfg.requeue_limit:
                entry.requeues += 1
                self._requeue_total.inc()
                self._admission.requeue(entry)
                return
            if entry.requeues >= self.cfg.requeue_limit > 0:
                err = RequeueLimitError(
                    f"failed on {entry.requeues + 1} replica(s) "
                    f"(requeue_limit {self.cfg.requeue_limit}); last: "
                    f"{type(exc).__name__}: {exc}")
                err.__cause__ = exc
                self._resolve_failed(entry, err)
                return
        self._resolve_failed(entry, exc)

    # ------------------------------------------------- terminal accounting

    def _shed_counter(self, reason: str):
        with self._lock:
            counter = self._shed_reasons.get(reason)
            if counter is None:
                counter = self.registry.counter(
                    "fleet_shed_total", help="load shed by reason",
                    reason=reason)
                self._shed_reasons[reason] = counter
            return counter

    def _count_error(self, exc):
        code = getattr(exc, "code", "serving_error")
        with self._lock:
            counter = self._errors.get(code)
            if counter is None:
                counter = self.registry.counter(
                    "fleet_errors_total",
                    help="terminal failures and rejections by stable code",
                    code=code)
                self._errors[code] = counter
        counter.inc()

    def _resolve_shed(self, entry: FleetRequest, reason: str,
                      exc: ServingError) -> bool:
        if entry._finish(exc=exc):
            self._counts["shed"].inc()
            self._shed_counter(reason).inc()
            self._count_error(exc)
            return True
        return False

    def _resolve_failed(self, entry: FleetRequest,
                        exc: BaseException) -> bool:
        if entry._finish(exc=exc):
            self._counts["failed"].inc()
            self._count_error(exc)
            return True
        return False

    # -------------------------------------------------- health callbacks

    def _probe_replica(self, name: str) -> bool:
        """End-to-end heartbeat: one tiny request through the replica's
        real dispatch path (unique sequence per probe so the result
        cache cannot vouch for a dead engine). Restarts the engine first
        if a drain tore it down. Runs on the health thread."""
        with self._lock:
            rep = self._replicas[name]
            engine = rep.engine
        if engine is None or getattr(engine, "_closed", False):
            engine = rep.factory()
            if engine is None:
                return False
            with self._lock:
                rep.engine = engine
                rep.restarts += 1
        rep.probe_counter += 1
        n, seq = rep.probe_counter, []
        for _ in range(4):  # base-len(AA_ORDER) counter encoding
            seq.append(AA_ORDER[n % len(AA_ORDER)])
            n //= len(AA_ORDER)
        try:
            req = engine.submit("".join(seq),
                                timeout=self.cfg.probe_timeout_s)
            req.result(timeout=self.cfg.probe_timeout_s)
            return True
        except (ServingError, TimeoutError):
            return False

    def _drain_replica(self, name: str, reason: str):
        """Health-thread callback: take the sick engine out of rotation
        and fail its queued work BACK through the requeue path (shutdown
        drain=False resolves everything pending with EngineClosedError,
        which `_on_replica_done` converts into requeues)."""
        with self._lock:
            rep = self._replicas[name]
            engine, rep.engine = rep.engine, None
        self._up_gauges[name].set(0)
        if self._incident_hook is not None:
            try:
                self._incident_hook("replica_drain", replica=name,
                                    reason=reason)
            except Exception:  # noqa: BLE001 — observability must never
                # take the supervisor down
                traceback.print_exc()
        if engine is not None:
            engine.shutdown(drain=False, timeout=self.cfg.drain_timeout_s)

    def _reinstate_replica(self, name: str):
        self._up_gauges[name].set(1)
