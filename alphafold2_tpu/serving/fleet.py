"""Fleet tier: N engine replicas, one admission-controlled front door.

The PR 2 engine is one warm model in one process — a single hung batch,
poisoned executable, or slow compile stalls the whole tier. This module
is the robustness half of the ParaFold pool story (arxiv 2111.06340):
a replicated tier that keeps answering, degrades predictably, and treats
replica death as routine traffic management rather than an outage.

Architecture (three cooperating layers, each independently testable):

  `serving/admission.py`   the shared front door: priority classes,
                           per-request deadlines, structured shedding
                           with `retry_after_s`.
  this module              the router: a dispatcher thread pulls from
                           the admission queue and places requests on
                           the least-loaded HEALTHY replica; completion
                           callbacks (the `add_done_callback` seam on
                           `ServingRequest`) either resolve the client
                           future or REQUEUE the request onto another
                           replica (bounded by `requeue_limit`).
  `reliability/health.py`  the supervisor: dispatch-failure evidence and
                           heartbeat probes drain a sick replica (its
                           engine is shut down drain=False, which fails
                           its queued work back through the requeue
                           path — nothing is lost), and re-probes
                           reinstate it behind a fresh engine.

Requeue is IDEMPOTENT by construction: a structure is a deterministic
function of (sequence, bucket) under a shared config tag
(serving/cache.py), so replaying a request on a different replica
returns bit-identical results — pinned by tests against the
single-engine path. Fleet latency/cache stats count each request once,
at its terminal outcome.

Degraded mode: with `degraded_mds_iters` and/or `degraded_weight_dtype`
set, the fleet holds one extra engine at a cheaper config tag (fewer MDS
iterations, and/or int8 PTQ trunk weights — serving/quant_residency.py —
a second tenant of the result-cache keyspace at ~1/4 the weight
residency). It takes traffic only when every full replica is down or the
queue is past `degrade_depth`, and every response it serves is flagged
`degraded=True` — the client always knows which answer it got.

Every replica breaker gets seeded `breaker_jitter` with a per-replica
seed, so a fleet-wide dependency failure does not re-probe in lockstep.

Terminal outcomes are exhaustive: every accepted request ends exactly
one of served / served-degraded / shed-with-structured-error / failed —
the chaos suite drives kill/slow/flap plans through `serve.py
--replicas --fault-plan` and asserts zero lost requests.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import traceback
from typing import Optional

from alphafold2_tpu.constants import AA_ORDER
from alphafold2_tpu.ops.dispatch import (
    resolution_tag as dispatch_resolution_tag,
)
from alphafold2_tpu.reliability.health import HealthMonitor, ReplicaState
from alphafold2_tpu.serving.admission import (
    AdmissionConfig,
    AdmissionController,
    resolve_priority,
)
from alphafold2_tpu.serving.artifact_store import ArtifactStore
from alphafold2_tpu.serving.bucketing import BucketLadder
from alphafold2_tpu.serving.cache import request_key
from alphafold2_tpu.serving.cascade import (
    CascadeLedger,
    CascadePolicy,
    CascadeVerdict,
    EntropyStressScorer,
)
from alphafold2_tpu.serving.engine import (
    PredictionResult,
    ServingConfig,
    ServingEngine,
)
from alphafold2_tpu.serving.frontdoor import FrontDoor
from alphafold2_tpu.serving.journal import IntakeJournal
from alphafold2_tpu.reliability.retry_budget import RetryBudget
from alphafold2_tpu.serving.errors import (
    CircuitOpenError,
    EngineClosedError,
    HungBatchError,
    NoHealthyReplicaError,
    PredictionError,
    QueueFullError,
    RequestTimeoutError,
    RequeueLimitError,
    RetryBudgetExhaustedError,
    ScaleRejectedError,
    SequenceTooLongError,
    ServingError,
)
from alphafold2_tpu.serving.featurize import (
    FeatureBundle,
    FeaturizeConfig,
    FeaturizePool,
    featurize_request,
)
from alphafold2_tpu.telemetry import NULL_TRACER, MetricRegistry, new_trace_id
from alphafold2_tpu.telemetry.costs import (
    ExecutableCostLedger,
    FlightBook,
    ServeGoodputLedger,
)

#: replica errors that justify trying ANOTHER replica — the replica (not
#: the request) is the suspect. Everything else is terminal for the
#: request itself.
_REPLICA_FAULT_ERRORS = (
    PredictionError,
    HungBatchError,
    EngineClosedError,
    CircuitOpenError,
)

DEGRADED = "degraded"  # reserved tier name (not a health-managed replica)

DEFAULT_POOL = "default"  # implicit pool name for homogeneous fleets


@dataclasses.dataclass(frozen=True)
class PoolSpec:
    """One capability pool: replicas sharing a (weight_dtype x sp_shards
    x bucket ceiling) capability tag (ROADMAP item 4b — the
    generalization of PR 8's multi-precision residency into
    heterogeneous-replica residency).

    The fleet routes each request to the CHEAPEST pool whose ceiling
    covers its length — pools are preferred in (bucket-ceiling ascending,
    declaration order), so short sequences land on dense/int8 replicas
    and only the lengths that need it reach the SP-sharded pool.
    `weight_dtype`/`buckets` left at their defaults inherit the fleet's
    base configs; the SP knobs are POOL-OWNED — with pools configured the
    base ServingConfig must keep sp_shards=0 (the fleet rejects the
    ambiguous combination loudly)."""

    name: str
    replicas: int = 1
    weight_dtype: str = ""       # "int8"/"f32"; "" inherits the model cfg
    sp_shards: int = 0           # >1: this pool's engines run the SP arm
    buckets: Optional[tuple] = None  # pool bucket ladder; None inherits
    sp_schedules: tuple = ()     # per-bucket SP overrides ((bucket,
    #                              schedule), ...); () defers to the base
    #                              config's overrides (ladder-filtered)
    #                              and the residency heuristic
    # per-pool fidelity knobs (the cascade's draft tier: int8 weights via
    # weight_dtype above, FEWER MDS ITERATIONS, REDUCED MSA ROWS, and
    # trunk-depth early exit — serving/cascade.py). Each knob also moves
    # the pool's store tag, so cheaper results never alias dearer ones.
    mds_iters: int = 0           # >0 overrides the base ServingConfig
    msa_rows: Optional[int] = None  # None inherits; 0 drops the MSA
    #                              stream entirely; >0 truncates riding
    #                              FeatureBundles to the top rows
    early_exit_depths: tuple = ()   # >= 2 checkpoint depths arm the
    early_exit_kl: float = 0.0      # delta-KL trunk early exit

    def __post_init__(self):
        if not self.name or self.name == DEGRADED:
            raise ValueError(
                f"pool name must be non-empty and not {DEGRADED!r}, "
                f"got {self.name!r}"
            )
        if self.replicas < 1:
            raise ValueError(
                f"pool {self.name!r}: replicas must be >= 1, "
                f"got {self.replicas}"
            )
        if self.weight_dtype not in ("", "f32", "int8"):
            raise ValueError(
                f"pool {self.name!r}: weight_dtype must be '', 'f32', or "
                f"'int8', got {self.weight_dtype!r}"
            )
        if self.sp_shards < 0 or self.sp_shards == 1:
            raise ValueError(
                f"pool {self.name!r}: sp_shards must be 0 or >= 2, "
                f"got {self.sp_shards}"
            )
        if self.buckets is not None:
            object.__setattr__(
                self, "buckets", tuple(int(b) for b in self.buckets))
            if not self.buckets:
                raise ValueError(
                    f"pool {self.name!r}: buckets must be None (inherit) "
                    f"or non-empty"
                )
        object.__setattr__(
            self, "sp_schedules",
            tuple((int(b), str(s)) for b, s in self.sp_schedules))
        if self.sp_schedules and not self.sp_shards:
            raise ValueError(
                f"pool {self.name!r}: sp_schedules without sp_shards"
            )
        if self.mds_iters < 0:
            raise ValueError(
                f"pool {self.name!r}: mds_iters must be >= 0 "
                f"(0 inherits), got {self.mds_iters}"
            )
        if self.msa_rows is not None and self.msa_rows < 0:
            raise ValueError(
                f"pool {self.name!r}: msa_rows must be None (inherit) "
                f"or >= 0, got {self.msa_rows}"
            )
        object.__setattr__(
            self, "early_exit_depths",
            tuple(int(d) for d in self.early_exit_depths))
        # depth/kl consistency is ServingConfig.__post_init__'s job —
        # _pool_serving_cfg replaces these into the pool's config, which
        # re-validates


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet-level knobs; per-replica scheduler knobs stay in
    `ServingConfig` (docs/OPERATIONS.md "Fleet runbook")."""

    replicas: int = 2
    queue_capacity: int = 64     # shared admission queue bound
    default_timeout_s: Optional[float] = 60.0  # fleet-level deadline
    requeue_limit: int = 2       # replica failovers per request
    degraded_mds_iters: int = 0  # >0: hold a cheaper-tag fallback engine
    degraded_weight_dtype: str = ""  # "int8": the degraded tier serves
    #                              per-channel-PTQ int8 trunk weights
    #                              (ops/quant.py) — a precision degrade
    #                              that composes with degraded_mds_iters;
    #                              ""/"f32" keeps full-precision weights
    degrade_depth: int = 0       # queue depth that routes NEW work to the
    #                              degraded tier (0 = only on total outage)
    probe_interval_s: float = 5.0    # heartbeat cadence, healthy replicas
    reprobe_interval_s: float = 0.5  # reinstatement probe cadence, down
    probe_timeout_s: float = 10.0
    fail_threshold: int = 2      # consecutive failures that drain
    drain_timeout_s: float = 5.0
    breaker_jitter: float = 0.25  # seeded reopen spread per replica
    dispatch_backoff_s: float = 0.01  # router sleep when every target is full
    tick_interval_s: float = 0.05     # health thread granularity
    # CPU featurization tier (serving/featurize.py): >0 workers puts a
    # separately-sized feature-prep pool in FRONT of the admission queue
    # — raw-sequence submissions featurize there; pre-featurized bundles
    # bypass it. 0 = featurize inline on the submit thread (the pre-tier
    # behavior, bit-identical results).
    featurize_workers: int = 0
    featurize_queue: int = 128
    featurize_retry_limit: int = 1    # worker-death requeues per job
    # Heterogeneous capability pools (ROADMAP item 4b): () = one implicit
    # pool of `replicas` base-config engines (the pre-pool fleet,
    # behavior-identical). Non-empty REPLACES `replicas`: each PoolSpec
    # sizes and capability-tags its own slice of the fleet, routing
    # prefers the cheapest capable pool, and the per-pool autoscalers
    # scale each pool off its own queue-wait signal.
    pools: tuple = ()
    # Fleet-wide retry budget (ISSUE 18): >0 arms a token bucket (one per
    # fleet, reliability/retry_budget.py) that featurize requeues,
    # replica-failover requeues, and hedged dispatches ALL draw from,
    # refilled `retry_budget_refill` tokens per successful completion. A
    # drained bucket degrades retries into fast typed
    # RetryBudgetExhaustedError sheds instead of a retry storm. 0 keeps
    # retries unmetered (the pre-budget fleet, behavior-identical).
    retry_budget_capacity: int = 0
    retry_budget_refill: float = 0.1
    # Hedged dispatch (ISSUE 18): >0 arms a hedge timer — a dispatch
    # outstanding longer than `hedge_p95_factor` x its pool's service-time
    # p95 (floored at `hedge_min_delay_s`, armed only after
    # `hedge_min_samples` completions have been measured) gets ONE
    # budgeted duplicate dispatch on another healthy capable replica;
    # first settle wins, the loser's chip-seconds count into
    # `hedge_wasted_chip_seconds_total`. Total hedges stay under
    # `hedge_rate_cap` x dispatches. 0 disables hedging entirely.
    hedge_p95_factor: float = 0.0
    hedge_min_delay_s: float = 0.05
    hedge_rate_cap: float = 0.1
    hedge_min_samples: int = 8
    # Adaptive-fidelity cascade (ISSUE 19; serving/cascade.py): a
    # CascadePolicy routes eligible requests through a DRAFT pool first
    # (named by policy.draft_pool — must be one of `pools`), scores the
    # draft with a ConfidenceScorer, and escalates only low-confidence
    # results to the remaining full-fidelity pools with the request's
    # FeatureBundle riding. None keeps static pool routing
    # (behavior-identical to the pre-cascade fleet).
    cascade_policy: Optional["CascadePolicy"] = None

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {self.replicas}")
        if self.pools:
            object.__setattr__(self, "pools", tuple(self.pools))
            names = [p.name for p in self.pools]
            if len(set(names)) != len(names):
                raise ValueError(f"duplicate pool name in {names}")
        if self.requeue_limit < 0:
            raise ValueError(
                f"requeue_limit must be >= 0, got {self.requeue_limit}"
            )
        if self.degraded_mds_iters < 0 or self.degrade_depth < 0:
            raise ValueError("degraded knobs must be >= 0")
        if self.degraded_weight_dtype not in ("", "f32", "int8"):
            raise ValueError(
                f"degraded_weight_dtype must be '', 'f32', or 'int8', "
                f"got {self.degraded_weight_dtype!r}"
            )
        if self.featurize_workers < 0 or self.featurize_queue < 1:
            raise ValueError(
                "featurize_workers must be >= 0 and featurize_queue >= 1, "
                f"got {self.featurize_workers}/{self.featurize_queue}"
            )
        if self.retry_budget_capacity < 0:
            raise ValueError(
                f"retry_budget_capacity must be >= 0, "
                f"got {self.retry_budget_capacity}"
            )
        if not (0.0 < self.retry_budget_refill <= 1.0):
            raise ValueError(
                f"retry_budget_refill must be in (0, 1], "
                f"got {self.retry_budget_refill}"
            )
        if self.hedge_p95_factor < 0:
            raise ValueError(
                f"hedge_p95_factor must be >= 0 (0 disables hedging), "
                f"got {self.hedge_p95_factor}"
            )
        if self.hedge_min_delay_s <= 0 or self.hedge_min_samples < 1:
            raise ValueError(
                "hedge_min_delay_s must be > 0 and hedge_min_samples >= 1, "
                f"got {self.hedge_min_delay_s}/{self.hedge_min_samples}"
            )
        if not (0.0 < self.hedge_rate_cap <= 1.0):
            raise ValueError(
                f"hedge_rate_cap must be in (0, 1], "
                f"got {self.hedge_rate_cap}"
            )
        if self.cascade_policy is not None:
            names = [p.name for p in self.pools]
            if not names:
                raise ValueError(
                    "cascade_policy requires explicit capability pools "
                    "(FleetConfig.pools) — the draft tier is a pool"
                )
            if self.cascade_policy.draft_pool not in names:
                raise ValueError(
                    f"cascade draft_pool "
                    f"{self.cascade_policy.draft_pool!r} is not a "
                    f"configured pool (pools: {names})"
                )
            if len(names) < 2:
                raise ValueError(
                    "the cascade needs at least one full-fidelity pool "
                    "besides the draft pool — escalations would have "
                    "nowhere to go"
                )


class FleetRequest:
    """Client handle: one future, resolved exactly once by the fleet.

    Duck-typed for the admission queue (`priority` / `deadline` /
    `enqueued_at`); `requeues` counts replica failovers survived."""

    def __init__(self, seq: str, msa, msa_mask, priority: int,
                 deadline: Optional[float], trace_id: str = "",
                 features: Optional[FeatureBundle] = None):
        self.seq = seq
        self.msa = msa
        self.msa_mask = msa_mask
        self.features = features   # set by the featurize tier (or caller)
        self.priority = priority
        self.deadline = deadline
        self.enqueued_at = time.monotonic()
        # minted HERE (the fleet front door) and handed to every engine
        # submit this request makes — admission queueing, routing, and
        # requeues onto other replicas all carry ONE id
        self.trace_id = trace_id or new_trace_id()
        self.requeues = 0
        self.pool = None         # preferred capability pool (set at admit)
        # artifact-store identity, stamped at the front door: (store tag,
        # content hash) — the waiter-registry key this request leads or
        # follows, and the address its result persists under
        self.store_key = None
        self.coalesced = False   # True: follower of an in-flight leader
        self.feat_store_key = None  # (tag, hash) to persist features under
        self.failed_on = set()   # replica names this request failed on
        self.last_error: Optional[BaseException] = None
        self.hedges = 0          # hedged duplicate dispatches issued
        # dispatches currently outstanding on replicas (fleet-lock
        # guarded): with hedging, a failed twin must defer to the one
        # still in flight instead of requeueing a request that may win
        self.inflight_dispatches = 0
        # cascade state (serving/cascade.py; "" when the cascade is off):
        # tier is "draft" while the draft leg is pending, "full" after
        # bypass/promotion/escalation; escalated marks a rejected draft;
        # draft_accepted gates what may persist under the draft store tag
        self.tier = ""
        self.escalated = False
        self.draft_accepted = False
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._result: Optional[PredictionResult] = None
        self._meta = {}
        self._exc: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def _finish(self, result=None, exc=None, **meta) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self._result, self._exc, self._meta = result, exc, meta
            self._event.set()
            return True

    def result(self, timeout: Optional[float] = None) -> PredictionResult:
        """Block for the outcome; raises the terminal ServingError, or
        builtin TimeoutError if the CALLER's wait budget expires first.
        Returns a fresh copy stamped with fleet provenance (replica,
        degraded, requeues) — the raw result may alias a replica cache
        entry and is never handed out."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"fleet request ({len(self.seq)} residues) not completed "
                f"within {timeout}s wait"
            )
        if self._exc is not None:
            raise self._exc
        return dataclasses.replace(
            self._result,
            coords=self._result.coords.copy(),
            confidence=self._result.confidence.copy(),
            latency_s=self._meta.get("latency_s", self._result.latency_s),
            replica=self._meta.get("replica", ""),
            degraded=self._meta.get("degraded", False),
            requeues=self.requeues,
            trace_id=self.trace_id,
            tier=self._meta.get("tier", ""),
        )


class _Replica:
    """One engine slot; the engine reference swaps across drain/restart
    cycles (guarded by the fleet lock)."""

    def __init__(self, name: str, index: int, cfg: ServingConfig,
                 pool: str = DEFAULT_POOL):
        self.name = name
        self.index = index       # monotone creation index (victim ranking)
        self.cfg = cfg           # live: rolling updates swap it in place
        self.pool = pool         # capability pool this slot belongs to
        self.factory = None      # () -> ServingEngine; reads self.cfg
        self.engine: Optional[ServingEngine] = None
        self.retiring = False    # deliberate removal in progress
        self.in_flight = 0
        self.dispatches = 0
        self.probe_counter = 0
        self.restarts = 0


class _Pool:
    """Runtime view of one capability pool (spec + derived capability)."""

    def __init__(self, spec: PoolSpec, rank: int, ladder: BucketLadder):
        self.spec = spec
        self.name = spec.name
        self.rank = rank          # routing preference (ceiling-ascending)
        self.ladder = ladder
        self.service_ema_s: Optional[float] = None  # drain-rate EMA

    @property
    def max_len(self) -> int:
        return self.ladder.max_len


class ServingFleet:
    """N `ServingEngine` replicas behind one admission-controlled queue.

    Args:
      params / model_cfg / serving_cfg: as `ServingEngine` — every
        replica shares them (and therefore the cache-key config tag:
        the idempotency contract failover depends on).
      fleet_cfg: `FleetConfig`.
      engine_factory: override `(name, serving_cfg, fault_hook) ->
        ServingEngine` — tests substitute fake engines; the default
        builds real ones over `params`.
      injector: optional `reliability.FaultInjector`; each replica gets
        `injector.replica_hook(name)` so kill/slow/flap plans target
        replicas by name.
      tracer / registry: fleet-level telemetry (replica engines keep
        their own `ServingMetrics`; the fleet registry carries the
        fleet_* metric families).
      incident_hook: optional `fn(kind, **attrs)` — the flight-recorder
        seam (telemetry/ops_plane.py). The fleet reports
        `replica_drain` itself and threads the hook into every
        default-factory engine (breaker_open / watchdog_fire); custom
        `engine_factory` callers wire their own engines.
    """

    def __init__(self, params, model_cfg,
                 serving_cfg: ServingConfig = ServingConfig(),
                 fleet_cfg: FleetConfig = FleetConfig(), *,
                 engine_factory=None, model_apply_fn=None, injector=None,
                 tracer=None, registry: Optional[MetricRegistry] = None,
                 incident_hook=None,
                 artifact_store: Optional[ArtifactStore] = None,
                 journal: Optional[IntakeJournal] = None,
                 cascade_scorer=None):
        self.cfg = fleet_cfg
        self._params = params
        self._model_cfg = model_cfg
        self._serving_cfg = serving_cfg
        self._model_apply_fn = model_apply_fn
        self._injector = injector
        # ---- capability pools (ROADMAP item 4b) ----
        # no explicit pools = ONE implicit pool of base-config replicas
        # (the pre-pool fleet, behavior-identical); explicit pools replace
        # `replicas` and give the router a capability table. Preference is
        # (bucket ceiling ascending, declaration order): short work lands
        # on the cheapest capable pool, the SP pool keeps its headroom.
        self._implicit_pools = not fleet_cfg.pools
        if fleet_cfg.pools and serving_cfg.sp_shards:
            # with pools configured, the SP knob belongs to the PoolSpecs
            # (each pool declares its own sp_shards/sp_schedules): a base
            # sp_shards would silently apply to the degraded tier but not
            # the pools — reject the ambiguity instead of guessing
            raise ValueError(
                "ServingConfig.sp_shards and FleetConfig.pools are "
                "mutually exclusive — declare sp_shards per PoolSpec"
            )
        specs = fleet_cfg.pools or (
            PoolSpec(DEFAULT_POOL, replicas=fleet_cfg.replicas),)
        base_buckets = serving_cfg.buckets
        ordered = sorted(
            enumerate(specs),
            key=lambda iv: (max(iv[1].buckets or base_buckets), iv[0]),
        )
        self._pools = {}
        for rank, (_, spec) in enumerate(ordered):
            self._pools[spec.name] = _Pool(
                spec, rank, BucketLadder(spec.buckets or base_buckets))
        # the union ladder: featurization + the too-long check run against
        # what the WHOLE fleet can serve — `bucket_for` past its top is the
        # sharp sequence_too_long signal (no capable pool exists)
        union = sorted({b for p in self._pools.values()
                        for b in p.ladder.buckets})
        self._ladder = BucketLadder(tuple(union))
        self._replica_pool = {}   # replica name -> pool name (never reused)
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self.registry = registry if registry is not None else MetricRegistry()
        self._incident_hook = incident_hook
        self._factory = engine_factory or self._default_factory

        # ---- adaptive-fidelity cascade (ISSUE 19; serving/cascade.py) --
        # None keeps static pool routing (behavior-identical). Armed, the
        # draft pool takes every eligible request first; the scorer's
        # verdict on each draft decides accept vs escalate in
        # _on_replica_done, and _route/_admit keep the tiers disjoint.
        self._cascade: Optional[CascadePolicy] = fleet_cfg.cascade_policy
        self._cascade_scorer = None
        self._cascade_ledger: Optional[CascadeLedger] = None
        if self._cascade is not None:
            self._cascade_scorer = (
                cascade_scorer if cascade_scorer is not None
                else EntropyStressScorer(self._cascade))
            self._cascade_ledger = CascadeLedger(self.registry)

        # ---- fleet-wide artifact store + front-door coalescing (ISSUE
        # 17) ---- None keeps the pre-store fleet behavior-identical;
        # with a store, submissions consult it (and register in the
        # coalescing waiter registry) at `_admit`, BEFORE pool routing.
        # The store's metric families land in the FLEET registry so one
        # /metrics scrape carries both.
        self._store = artifact_store
        self._frontdoor = (FrontDoor(self.registry)
                           if artifact_store is not None else None)
        if self._store is not None:
            self._store.bind_registry(self.registry)
            self._store.set_current_tags(self._current_store_tags())

        # ---- durable intake journal (ISSUE 18) ---- None keeps the
        # in-memory-only request plane. With a journal, every accepted
        # request is durably recorded at submit and settled (record
        # unlinked) at its terminal path — `replay_journal()` after a
        # restart pushes unsettled records back through submit, where
        # front-door coalescing + the artifact store make the replay
        # idempotent (at-least-once accepted->terminal, zero duplicate
        # chip dispatch).
        self._journal = journal
        if journal is not None:
            journal.bind_registry(self.registry)

        # ---- fleet-wide retry budget (ISSUE 18) ---- one bucket for
        # every internal retry kind; None = unmetered (pre-budget
        # behavior). Lives in the fleet registry so /metrics carries the
        # retry_budget_* families.
        self._budget: Optional[RetryBudget] = None
        if fleet_cfg.retry_budget_capacity > 0:
            self._budget = RetryBudget(
                fleet_cfg.retry_budget_capacity,
                refill_ratio=fleet_cfg.retry_budget_refill,
            ).bind_registry(self.registry)

        # ---- serving cost & profiling plane (telemetry/costs.py) ----
        # always on (dict bookkeeping, no model cost): the shared
        # per-executable cost ledger (every replica of a pool merges into
        # one cell), the per-replica goodput ledger (the fleet layers
        # probe/drain on what the engines account), and the exemplar
        # flight book behind /explainz
        self.costs = ExecutableCostLedger(self.registry)
        self.goodput = ServeGoodputLedger(self.registry)
        self.flights = FlightBook()
        # per-pool arrival tracking for the headroom model: counts at
        # _admit (preferred-pool key), rates derived in sample_gauges
        self._arrivals_lock = threading.Lock()
        self._arrivals = {name: 0 for name in self._pools}
        self._arrival_rate = {}   # pool -> {"count", "ts", "ema"}
        self._last_headroom = {}  # pool -> headroom model (sample_gauges)

        self._lock = threading.Lock()
        self._closed = False
        self._drain_on_stop = True
        self._stop = threading.Event()

        # ---- telemetry families (the acceptance surface) ----
        self._counts = {
            name: self.registry.counter(
                "fleet_requests_total", help="fleet request terminal outcomes",
                outcome=name)
            for name in ("submitted", "completed", "shed", "failed")
        }
        self._degraded_total = self.registry.counter(
            "fleet_degraded_total", help="responses served by the degraded tier")
        self._requeue_total = self.registry.counter(
            "fleet_requeue_total", help="replica-failover requeues")
        self._shed_reasons = {}   # reason -> counter (lazy)
        self._errors = {}         # stable code -> counter (lazy)
        self._queue_wait = self.registry.histogram(
            "fleet_queue_wait_seconds",
            help="admission-queue wait, sliding window (p95 is the "
                 "autoscaling signal)")
        self._latency = self.registry.histogram(
            "fleet_request_latency_seconds",
            help="fleet submit->terminal latency, sliding window")
        self._up_gauges = {}

        # ---- live queue/occupancy gauges (sample_gauges ticker hook) ----
        self._queue_depth_gauge = self.registry.gauge(
            "fleet_queue_depth",
            help="live admission-queue depth (sampled by the ops ticker "
                 "so scrapes see pressure between requests)")
        self._service_ema_gauge = self.registry.gauge(
            "fleet_service_ema_seconds",
            help="admission drain-rate EMA (per-request service seconds)")
        self._occupancy_gauge = self.registry.gauge(
            "fleet_occupancy",
            help="dispatched requests per slot of healthy replica "
                 "capacity (the autoscaler's load signal)")
        self._replicas_gauge = self.registry.gauge(
            "fleet_replicas", help="current (non-retiring) replica count")

        # ---- per-capability-pool telemetry (the length-adaptive router's
        # observability + the per-pool autoscalers' signals) ----
        self._routed = {}         # pool -> fleet_routed_total counter (lazy)
        self._pool_wait = {
            name: self.registry.histogram(
                "fleet_pool_queue_wait_seconds",
                help="admission wait of requests dispatched to this "
                     "capability pool (p95 is the per-pool autoscaling "
                     "signal)", pool=name)
            for name in self._pools
        }
        self._pool_depth_g = {
            name: self.registry.gauge(
                "fleet_pool_queue_depth",
                help="queued requests whose preferred capability pool is "
                     "this one (sampled each ops tick)", pool=name)
            for name in self._pools
        }
        self._pool_occ_g = {
            name: self.registry.gauge(
                "fleet_pool_occupancy",
                help="dispatched requests per slot of this pool's healthy "
                     "capacity", pool=name)
            for name in self._pools
        }
        self._pool_reps_g = {
            name: self.registry.gauge(
                "fleet_pool_replicas",
                help="current (non-retiring) replicas in this capability "
                     "pool", pool=name)
            for name in self._pools
        }

        # ---- hedged dispatch (ISSUE 18) ---- per-pool replica SERVICE
        # time (dispatch->completion, excludes queue wait: the hedge
        # delay must measure how long a dispatch should take, not how
        # long the queue was) + the outstanding-dispatch registry the
        # hedge timer scans. `_hedge_lock` is a LEAF lock: dict ops only,
        # never held across a call out, never nested with `_lock`.
        self._pool_service = {
            name: self.registry.histogram(
                "fleet_pool_service_seconds",
                help="replica service time (dispatch->completion) per "
                     "capability pool; its p95 derives the hedge delay",
                pool=name)
            for name in self._pools
        }
        self._hedge_lock = threading.Lock()
        self._outstanding = {}   # id(entry) -> primary-dispatch state
        self._hedges_issued = 0  # lifetime, under _hedge_lock
        self._hedge_denied = {}  # reason -> count, under _hedge_lock
        self._hedge_counters = {}  # pool -> fleet_hedge_total, under _lock
        self._dispatch_count = 0  # lifetime dispatches, under _lock
        self._hedge_waste = self.registry.counter(
            "hedge_wasted_chip_seconds_total",
            help="chip-seconds spent by the LOSING side of hedged "
                 "dispatch pairs (the price of the tail-latency cut)")

        # ---- replicas + health ----
        self._admission = AdmissionController(
            AdmissionConfig(capacity=fleet_cfg.queue_capacity))
        self._health = HealthMonitor(
            probe_interval_s=fleet_cfg.probe_interval_s,
            reprobe_interval_s=fleet_cfg.reprobe_interval_s,
            fail_threshold=fleet_cfg.fail_threshold,
        )
        self._replicas = {}
        self._replica_seq = 0
        self._autoscaler = None
        self._pool_autoscalers = {}
        self._last_gauge_sample = -1.0  # sample_gauges dedupe timestamp
        for pool in self._pools.values():
            for _ in range(pool.spec.replicas):
                self._spawn_replica(pool.name)

        # ---- CPU featurization tier (serving/featurize.py) ----
        self._featurize: Optional[FeaturizePool] = None
        if fleet_cfg.featurize_workers > 0:
            self._featurize = FeaturizePool(
                FeaturizeConfig(
                    workers=fleet_cfg.featurize_workers,
                    queue_capacity=fleet_cfg.featurize_queue,
                    retry_limit=fleet_cfg.featurize_retry_limit,
                ),
                self._ladder, msa_rows=serving_cfg.msa_rows,
                registry=self.registry, tracer=self._tracer,
                fault_hook=(injector.featurize_hook()
                            if injector is not None else None),
                incident_hook=self._incident_hook,
                retry_budget=self._budget,
            )

        self._degraded_rep: Optional[_Replica] = None
        # the degraded tier can be cheaper on MDS iterations, on weight
        # precision (int8 PTQ trunk), or both — either knob arms it. Its
        # model config diverges from the full replicas' exactly when the
        # precision knob is set, which moves it to its own config tag
        # (results can never alias the full-precision cache keyspace).
        self._degraded_model_cfg = self._model_cfg
        if fleet_cfg.degraded_weight_dtype == "int8":
            self._degraded_model_cfg = dataclasses.replace(
                model_cfg, weight_dtype="int8")
        # the degraded tier serves only lengths ITS ladder (the base
        # serving config's) covers — with wider capability pools
        # configured, a long request must shed rather than silently land
        # on a tier that cannot bucket it
        self._degraded_ladder = BucketLadder(serving_cfg.buckets)
        if (fleet_cfg.degraded_mds_iters
                or fleet_cfg.degraded_weight_dtype == "int8"):
            dcfg = serving_cfg
            if fleet_cfg.degraded_mds_iters:
                dcfg = dataclasses.replace(
                    serving_cfg, mds_iters=fleet_cfg.degraded_mds_iters)
            self._degraded_rep = _Replica(DEGRADED, -1, dcfg, pool=DEGRADED)
            self._degraded_rep.factory = self._make_factory(
                self._degraded_rep)
            self._degraded_rep.engine = self._degraded_rep.factory()

        self._health.start(fleet_cfg.tick_interval_s)
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="af2-fleet-dispatcher",
            daemon=True)
        self._dispatcher.start()
        self._hedger: Optional[threading.Thread] = None
        if fleet_cfg.hedge_p95_factor > 0:
            self._hedger = threading.Thread(
                target=self._hedge_loop, name="af2-fleet-hedger",
                daemon=True)
            self._hedger.start()

    # ------------------------------------------------------------ factories

    def _pool_serving_cfg(self, pool: "_Pool") -> ServingConfig:
        """The pool's ServingConfig, derived LIVE from the fleet template
        (so rolling updates that retag the template reach every pool).
        The implicit pool inherits the base config untouched."""
        base = self._serving_cfg
        if self._implicit_pools:
            return base
        spec = pool.spec
        buckets = spec.buckets or base.buckets
        # per-bucket SP overrides: the pool's own first, else the base
        # config's filtered to this pool's ladder; a dense pool carries
        # none (sp_schedules without sp_shards is a config error)
        if not spec.sp_shards:
            sp_scheds = ()
        elif spec.sp_schedules:
            sp_scheds = spec.sp_schedules
        else:
            sp_scheds = tuple((b, s) for b, s in base.sp_schedules
                              if b in buckets)
        return dataclasses.replace(
            base, buckets=buckets, sp_shards=spec.sp_shards,
            sp_schedules=sp_scheds,
            mds_iters=spec.mds_iters or base.mds_iters,
            msa_rows=(base.msa_rows if spec.msa_rows is None
                      else spec.msa_rows),
            early_exit_depths=(spec.early_exit_depths
                               or base.early_exit_depths),
            early_exit_kl=(spec.early_exit_kl if spec.early_exit_depths
                           else base.early_exit_kl))

    def _pool_model_cfg(self, pool: "_Pool"):
        """The pool's Alphafold2Config (weight-precision arm), derived
        LIVE from the fleet master config."""
        if self._implicit_pools or not pool.spec.weight_dtype:
            return self._model_cfg
        return dataclasses.replace(
            self._model_cfg, weight_dtype=pool.spec.weight_dtype)

    def _pool_capability(self, pool: "_Pool") -> dict:
        """The pool's capability tag (what its engines CAN serve) — the
        router's table, surfaced in stats()/statusz so an operator can
        see why a request went where it did."""
        cfg = self._pool_serving_cfg(pool)
        return {
            "weight_dtype": self._pool_model_cfg(pool).weight_dtype,
            "sp_shards": cfg.sp_shards,
            "max_len": pool.max_len,
        }

    # ------------------------------------------------- artifact-store tags

    def _store_tag(self, pool_name: str) -> str:
        """The fleet-level store tag for one capability pool: the
        `request_key` config tag extended (ISSUE 17) with the PR 13
        dispatch `resolution_tag` and the deploy's `params_tag`, plus
        every other knob that moves the numerics a pool's engines
        produce (model config incl. the pool's weight precision, MDS
        knobs, seed, the pool's bucket ladder, and the SP plan inputs).
        Derived LIVE from the fleet template, so `rolling_update`'s
        retag re-keys the whole fleet tier exactly like it re-keys the
        per-engine LRUs — old-tag entries become unreachable, never
        stale answers."""
        pool = self._pools[pool_name]
        cfg = self._pool_serving_cfg(pool)
        mcfg = self._pool_model_cfg(pool)
        parts = (
            mcfg, cfg.mds_iters, cfg.mds_init, cfg.seed, cfg.msa_rows,
            cfg.params_tag, tuple(pool.ladder.buckets),
            dispatch_resolution_tag(), cfg.sp_shards, cfg.sp_hbm_gb,
            tuple(sorted(cfg.sp_schedules)),
            cfg.early_exit_depths, cfg.early_exit_kl,
        )
        if self.cfg.cascade_policy is not None:
            # the cascade-tier component (ISSUE 19, the PR 13
            # resolution_tag invariant family): even if an operator arms
            # the cascade over numerically IDENTICAL pools, a draft-tier
            # result must never alias or serve a full-fidelity hit —
            # draft acceptance is a thresholded quality gate, not a
            # config equivalence
            role = ("cascade:draft"
                    if pool_name == self.cfg.cascade_policy.draft_pool
                    else "cascade:verify")
            parts = parts + (role,)
        return "af2store:" + repr(parts)

    def _feature_tag(self) -> str:
        """Feature bundles depend only on (union ladder, msa_rows) —
        deterministic host preprocessing, no params, no kernels — so
        their tag survives rolling updates: a redeploy invalidates
        results, not featurization."""
        return "af2feat:" + repr(
            (tuple(self._ladder.buckets), self._serving_cfg.msa_rows))

    def _current_store_tags(self) -> list:
        return ([self._store_tag(name) for name in self._pools]
                + [self._feature_tag()])

    def _default_factory(self, name, cfg, fault_hook):
        if name == DEGRADED:
            model_cfg = self._degraded_model_cfg
        else:
            model_cfg = self._pool_model_cfg(
                self._pools[self._replica_pool[name]])
        return ServingEngine(
            self._params, model_cfg, cfg,
            model_apply_fn=self._model_apply_fn,
            fault_hook=fault_hook, tracer=self._tracer,
            replica_name=name, incident_hook=self._incident_hook,
            # the shared cost plane: this replica's cells merge into its
            # pool's rows and its execute/compile/requeue seconds land in
            # the fleet-wide per-replica economy (the fleet itself adds
            # probe/drain). The flight book stays FLEET-owned — the
            # fleet sees the whole cross-replica flight.
            pool_name=(DEGRADED if name == DEGRADED
                       else self._replica_pool[name]),
            cost_ledger=self.costs, goodput=self.goodput,
        )

    def _make_factory(self, rep: _Replica):
        hook = (self._injector.replica_hook(rep.name)
                if self._injector is not None else None)

        def build():
            try:
                # rep.cfg is read at BUILD time, not closure time: a
                # rolling update swaps the cfg and cycles the replica
                # through the drain path — the reinstatement probe's
                # fresh engine picks up the new cfg (and the current
                # self._params master) automatically
                return self._factory(rep.name, rep.cfg, hook)
            except Exception:  # noqa: BLE001 — a failing restart is a
                # failed probe, not a fleet crash
                traceback.print_exc()
                return None

        return build

    def _spawn_replica(self, pool_name: str) -> _Replica:
        """Create, build, and register one replica in `pool_name`
        (ctor + add_replica). Builds the engine OUTSIDE the fleet lock
        (it may compile)."""
        with self._lock:
            pool = self._pools[pool_name]
            i = self._replica_seq
            self._replica_seq += 1
            name = f"r{i}"
            rcfg = dataclasses.replace(
                self._pool_serving_cfg(pool),
                breaker_jitter=(self.cfg.breaker_jitter
                                if self._serving_cfg.breaker_threshold
                                else 0.0),
                breaker_jitter_seed=i,
            )
            rep = _Replica(name, i, rcfg, pool=pool_name)
            # registered BEFORE the engine builds: the default factory
            # resolves the pool's model config through this map (names
            # are never reused, so entries never need removal)
            self._replica_pool[name] = pool_name
            rep.factory = self._make_factory(rep)
        # the goodput clock starts when the SLOT exists (engine build —
        # which may compile — is already on it); fleet-side so custom
        # engine_factory fleets keep per-replica accounts too
        self.goodput.register(name, pool_name)
        rep.engine = rep.factory()
        with self._lock:
            self._replicas[name] = rep
            gauge = self._up_gauges.get(name)
            if gauge is None:
                gauge = self.registry.gauge(
                    "fleet_replica_up", help="1 = taking traffic",
                    replica=name)
                self._up_gauges[name] = gauge
        gauge.set(1 if rep.engine is not None else 0)
        self._health.register(
            name,
            probe=lambda n=name: self._probe_replica(n),
            on_drain=self._drain_replica,
            on_reinstate=self._reinstate_replica,
        )
        return rep

    # ----------------------------------------------------------------- API

    def submit(self, seq: str, *, msa=None, msa_mask=None,
               timeout: Optional[float] = None,
               priority="normal", trace_id: str = "",
               features: Optional[FeatureBundle] = None) -> FleetRequest:
        """Enqueue one sequence at the fleet front door; returns a future.

        `trace_id` ("" mints one) correlates every span this request
        touches — across the featurize tier, the admission queue, the
        dispatcher, requeues, and every replica engine — and rides the
        result for log/trace cross-reference.

        With a featurize tier configured (`FleetConfig.featurize_workers`
        > 0) a RAW submission enters the CPU featurization pool first
        and reaches the admission queue from a pool worker — validation
        errors then resolve the returned future instead of raising here
        (the submit thread never blocks on feature prep). A
        pre-featurized `features` bundle BYPASSES the tier and keeps the
        fully-synchronous contract. Without a tier, featurization runs
        inline exactly as before.

        Raises EngineClosedError / InvalidSequenceError /
        RequestTooLongError / QueueFullError(retry_after_s) synchronously
        on the paths that validate synchronously (see above). A
        lower-priority queued request may be EVICTED (resolved with a
        retry-after error) to admit a higher-priority one.
        """
        trace_id = trace_id or new_trace_id()
        with self._tracer.span("fleet.enqueue", cat="fleet",
                               length=len(seq), trace_id=trace_id):
            if self._closed:
                raise EngineClosedError("fleet is shut down")
            ttl = (self.cfg.default_timeout_s if timeout is None else timeout)
            deadline = (time.monotonic() + ttl) if ttl is not None else None
            # exemplar flight record (telemetry/costs.py FlightBook —
            # the /explainz backing): born HERE, the fleet front door;
            # every hop below appends to it
            self.flights.begin(trace_id, length=len(seq),
                               priority=str(priority))

            # durable intake (ISSUE 18): record the request BEFORE any
            # work happens — validation included, so a crash mid-
            # featurize still replays (an invalid replay settles with
            # the same typed error it would have settled with now). The
            # journal stores the ABSOLUTE wall-clock deadline: a
            # relative one would silently extend across a restart.
            if self._journal is not None:
                self._journal.accept(
                    trace_id, seq, msa=msa, msa_mask=msa_mask,
                    priority=resolve_priority(priority),
                    deadline_unix=(time.time() + ttl
                                   if ttl is not None else None),
                    accepted_at_unix=time.time())

            # feature reuse from the artifact store (ISSUE 17): the
            # generalization of the `features` ride-along — a bundle any
            # replica (or a previous submission, retry, or process
            # sharing the disk tier) already computed is fetched instead
            # of re-featurized, bypassing the tier and the inline path
            # alike. Seq-only requests only: an MSA submission's raw
            # arrays are unvalidated before featurize_request, so their
            # content key is not yet well-defined.
            feat_key = None
            if features is None and self._store is not None and msa is None:
                ftag = self._feature_tag()
                feat_key = request_key(seq.strip().upper(), None, ftag)
                hit = self._store.lookup_features(ftag, feat_key)
                if hit is not None:
                    features, level = hit
                    self.flights.note(trace_id, "features_from_store",
                                      level=level)

            if features is None and self._featurize is None:
                # no tier: featurize inline on the submit thread (the
                # pre-tier contract — same function, same errors). The
                # ladder is the UNION over capability pools, so its
                # too-long rejection means NO pool can serve this length
                # — the sharp sequence_too_long shed, identical to the
                # single-engine ladder path.
                try:
                    features = featurize_request(
                        seq, msa, msa_mask,
                        ladder=self._ladder,
                        msa_rows=self._serving_cfg.msa_rows,
                    )
                except SequenceTooLongError as e:
                    self._shed_too_long(e)
                    self.flights.finish(trace_id, "shed", code=e.code)
                    self._journal_settle(trace_id)
                    raise
                except ServingError as e:
                    self._count_error(e)
                    self.flights.finish(trace_id, "failed", code=e.code)
                    self._journal_settle(trace_id)
                    raise
                if feat_key is not None:
                    self._store.put_features(ftag, feat_key, features)
            if features is not None:
                if features.length > self._ladder.max_len:
                    # a client-built bundle is untrusted: a length past
                    # every pool's ceiling must shed HERE with the sharp
                    # code, not die later as a replica-attributed
                    # dispatch failure
                    e = SequenceTooLongError(
                        f"sequence length {features.length} exceeds every "
                        f"capability pool's bucket ceiling "
                        f"({self._ladder.max_len})")
                    self._shed_too_long(e)
                    self.flights.finish(trace_id, "shed", code=e.code)
                    self._journal_settle(trace_id)
                    raise e
                entry = FleetRequest(features.seq, msa, msa_mask,
                                     resolve_priority(priority), deadline,
                                     trace_id=trace_id, features=features)
                self._counts["submitted"].inc()
                self._admit(entry, raise_on_full=True)
                return entry

            # featurize tier: the pool's bounded queue is the new first
            # backpressure point; queue-full there raises synchronously
            # like admission queue-full always has
            entry = FleetRequest(seq, msa, msa_mask,
                                 resolve_priority(priority), deadline,
                                 trace_id=trace_id)
            if feat_key is not None:
                entry.feat_store_key = (ftag, feat_key)
            self._counts["submitted"].inc()
            self.flights.note(trace_id, "featurize_enqueue")
            try:
                self._featurize.submit(
                    seq, msa, msa_mask, trace_id=trace_id,
                    # fleet deadline rides into the CPU tier: a job whose
                    # deadline passes while queued is dropped BEFORE
                    # featurizing (featurize_expired_total)
                    deadline=entry.deadline,
                    on_done=lambda bundle, exc, e=entry:
                    self._on_featurized(e, bundle, exc))
            except QueueFullError as e:
                # stays counted as submitted: shed is its terminal
                # outcome, so in_flight arithmetic balances
                self._shed_counter("featurize_queue_full").inc()
                self._counts["shed"].inc()
                self._count_error(e)
                self.flights.finish(trace_id, "shed", code=e.code)
                self._journal_settle(trace_id)
                raise
            except EngineClosedError as e:
                self._resolve_failed(entry, e)
                raise
            return entry

    def _shed_too_long(self, exc: SequenceTooLongError):
        """Synchronous-path accounting for the sharp too-long shed: the
        submission is counted submitted AND shed (terminal) so in_flight
        arithmetic balances, with the dedicated shed reason + error code
        an operator's dashboard keys on."""
        self._counts["submitted"].inc()
        self._counts["shed"].inc()
        self._shed_counter("too_long").inc()
        self._count_error(exc)

    def _on_featurized(self, entry: FleetRequest, bundle, exc):
        """Featurize-pool completion (pool worker thread): attach the
        features and offer the entry to the admission queue, or resolve
        it with the featurization error. Never raises."""
        if exc is not None:
            if isinstance(exc, SequenceTooLongError):
                # same sharp signal as the synchronous paths — the tier
                # moves featurization across threads, never the taxonomy
                self._resolve_shed(entry, "too_long", exc)
            elif isinstance(exc, RequestTimeoutError):
                # deadline passed while queued in the CPU tier — the
                # tier's pre-featurize check (featurize_expired_total)
                # dropped it before burning CPU
                self._resolve_shed(entry, "deadline", exc)
            elif isinstance(exc, RetryBudgetExhaustedError):
                # a worker-death requeue was denied by the fleet-wide
                # retry budget — brownout shed, not a request defect
                self._resolve_shed(entry, "retry_budget", exc)
            else:
                self._resolve_failed(entry, exc)
            return
        entry.features = bundle
        entry.seq = bundle.seq
        if entry.feat_store_key is not None and self._store is not None:
            self._store.put_features(*entry.feat_store_key, bundle)
        self.flights.note(entry.trace_id, "featurized",
                          bucket=bundle.bucket)
        self._admit(entry, raise_on_full=False)

    def _preferred_pool_name(self, length: int,
                             exclude=()) -> Optional[str]:
        """First capability pool (preference order: ceiling ascending,
        declaration order) whose bucket ceiling covers `length` — the
        router's primary target and the depth-accounting key. `exclude`
        skips pools by name (the cascade keeps full-tier work off the
        draft pool)."""
        for pool in sorted(self._pools.values(), key=lambda p: p.rank):
            if pool.name in exclude:
                continue
            if pool.max_len >= length:
                return pool.name
        return None

    def _route_tier(self, entry: FleetRequest, length: int) -> Optional[str]:
        """Pick the entry's preferred pool; with the cascade armed, also
        stamp its tier. Draft-eligible work (length within the draft
        pool's ladder and the policy's max_draft_length) goes to the
        draft pool first; everything else — and escalations — goes to
        the cheapest NON-draft pool."""
        if self._cascade is None:
            return self._preferred_pool_name(length)
        draft = self._cascade.draft_pool
        if entry.tier == "full" or entry.escalated:
            return self._preferred_pool_name(length, exclude=(draft,))
        eligible = (
            self._pools[draft].max_len >= length
            and (self._cascade.max_draft_length == 0
                 or length <= self._cascade.max_draft_length))
        if eligible:
            entry.tier = "draft"
            return draft
        entry.tier = "full"
        self._cascade_ledger.note_bypass("too_long")
        return self._preferred_pool_name(length, exclude=(draft,))

    def _pool_retry_after(self, pool_name: Optional[str],
                          depth: Optional[int] = None) -> float:
        """Backoff advice quoting the CAPABLE pool's backlog: depth of
        queued entries targeting that pool x its drain-rate EMA (same
        formula, cold default, and AdmissionConfig clamps as the global
        estimate — one tuning surface). The global estimate would lie
        whenever one pool is saturated and another idle — a
        long-sequence shed must quote the SP pool's horizon, not the
        idle dense pool's. `depth` lets a caller that already grouped
        the queue (stats) skip the per-pool scan."""
        pool = self._pools.get(pool_name) if pool_name else None
        if pool is None:
            return self._admission.retry_after_s()
        if depth is None:
            depth = sum(1 for e in self._admission.entries()
                        if getattr(e, "pool", None) == pool.name)
        acfg = self._admission.cfg
        est = (pool.service_ema_s or 1.0) * max(1, depth)
        return float(min(acfg.max_retry_after_s,
                         max(acfg.min_retry_after_s, est)))

    def _front_door(self, entry: FleetRequest) -> bool:
        """The fleet front door (ISSUE 17): artifact-store result lookup
        then cross-pool coalescing, after featurization but BEFORE pool
        routing. Returns True if the entry was fully handled here — hit
        served, or attached as a follower of an identical in-flight
        leader — and must not be admitted. Runs on the caller's thread
        (sync submit or featurize-tier callback); all store I/O is
        lock-free with respect to the fleet lock."""
        if (self._store is None or self._frontdoor is None
                or entry.pool is None or entry.features is None):
            return False
        f = entry.features
        tag = self._store_tag(entry.pool)
        key = request_key(f.seq, f.msa, tag, msa_mask=f.msa_mask)
        entry.store_key = (tag, key)
        lookups = [(tag, key)]
        if self._cascade is not None and entry.tier == "draft":
            # a FULL-fidelity result dominates a draft one: check the
            # escalation target's tag first so a previously-escalated
            # sequence is served at the better tier. The reverse never
            # happens — full-tier entries only consult their own tag, so
            # a draft result can never serve a full-fidelity lookup
            # (tests/test_cascade.py pins the asymmetry)
            full_pool = self._preferred_pool_name(
                f.length, exclude=(self._cascade.draft_pool,))
            if full_pool is not None:
                ftag = self._store_tag(full_pool)
                fkey = request_key(f.seq, f.msa, ftag,
                                   msa_mask=f.msa_mask)
                lookups.insert(0, (ftag, fkey))
        for ltag, lkey in lookups:
            hit = self._store.lookup_result(ltag, lkey)
            if hit is None:
                continue
            cached, level = hit
            latency = time.monotonic() - entry.enqueued_at
            if entry._finish(result=cached, replica="", degraded=False,
                             latency_s=latency):
                self._counts["completed"].inc()
                self._latency.observe(latency)
                self.flights.finish(
                    entry.trace_id, "completed", pool=entry.pool,
                    from_cache=True, cache_tier="artifact_store",
                    cache_level=level, bucket=cached.bucket,
                    latency_s=round(latency, 6))
                self._journal_settle(entry.trace_id)
            return True
        if not self._frontdoor.register((tag, key), entry):
            entry.coalesced = True
            self.flights.note(entry.trace_id, "coalesced", pool=entry.pool)
            return True
        return False

    def _admit(self, entry: FleetRequest, *, raise_on_full: bool):
        """Offer an accepted entry to the admission queue; shed/eviction
        accounting in one place for the sync and async entry paths."""
        # tag the preferred capability pool (features are always attached
        # by now — sync paths featurize before admitting, the tier admits
        # from its completion callback): per-pool depth gauges and
        # pool-quoted retry_after_s key on it
        length = (entry.features.length if entry.features is not None
                  else len(entry.seq))
        entry.pool = self._route_tier(entry, length)
        if self._front_door(entry):
            # served from the artifact store or attached to an identical
            # in-flight leader — the entry never reaches the admission
            # queue, and deliberately never counts as pool ARRIVAL: the
            # headroom model measures demand on CHIP capacity, and
            # cache-absorbed demand is exactly the demand that costs none
            return
        if entry.pool is not None:
            # the ARRIVAL half of the headroom model (sample_gauges
            # derives rates): demand is counted where it is admitted,
            # shed included — a shed request is still demand the pool
            # failed to absorb
            with self._arrivals_lock:
                self._arrivals[entry.pool] = (
                    self._arrivals.get(entry.pool, 0) + 1)
        self.flights.note(entry.trace_id, "admitted", pool=entry.pool)
        try:
            evicted = self._admission.offer(entry)
        except QueueFullError as e:
            # the entry stays counted as submitted: shed is its terminal
            # outcome, so in_flight arithmetic balances
            if not self._implicit_pools:
                e = QueueFullError(
                    f"{e} (capable pool {entry.pool!r})",
                    retry_after_s=self._pool_retry_after(entry.pool),
                )
            if raise_on_full:
                self._shed_counter("queue_full").inc()
                self._counts["shed"].inc()
                self._count_error(e)
                # the entry never resolves through _resolve_shed on this
                # synchronous path — seal its flight here or /explainz
                # would show an overload shed (the flight most worth
                # explaining) as forever in flight
                self.flights.finish(entry.trace_id, "shed",
                                    reason="queue_full", code=e.code)
                self._journal_settle(entry.trace_id)
                # a shed LEADER's followers must shed with it (the
                # raise skips _resolve_shed, so settle here)
                self._settle_waiters(entry, exc=e)
                raise e from None
            self._resolve_shed(entry, "queue_full", e)
            return
        if evicted is not None:
            self._resolve_shed(
                evicted, "evicted",
                QueueFullError(
                    "evicted by a higher-priority arrival under "
                    "overload; retry with backoff",
                    # the EVICTED entry's own capable pool, not the
                    # arrival's: its retry lands back in that pool's line
                    retry_after_s=(
                        self._pool_retry_after(evicted.pool)
                        if not self._implicit_pools
                        else self._admission.retry_after_s()),
                ))
        # close the TOCTOU window against shutdown() (the engine's
        # stance, engine.py): if the ROUTER is stopping (or crashed —
        # the crash guard closes the fleet with the stop event unset
        # but the thread dead), its final drain may already be past
        # this entry — resolve it ourselves; _finish is resolve-once,
        # so losing the race to a still-draining dispatcher is
        # harmless. The closed flag alone is NOT the test: during
        # shutdown(drain=True) the featurize tier drains THROUGH here
        # while the dispatcher is still serving ("serves what it still
        # can"), and failing those entries would break that promise.
        dispatcher_gone = (self._stop.is_set()
                           or not self._dispatcher.is_alive())
        if (self._closed and dispatcher_gone
                and self._resolve_failed(entry, EngineClosedError(
                    "fleet shut down while the request was being "
                    "submitted"))):
            if raise_on_full:
                raise EngineClosedError("fleet is shut down")

    def predict(self, seq: str, *, msa=None, msa_mask=None,
                timeout: Optional[float] = None,
                priority="normal") -> PredictionResult:
        """Synchronous convenience: submit + block for the result."""
        return self.submit(seq, msa=msa, msa_mask=msa_mask, timeout=timeout,
                           priority=priority).result()

    # -------------------------------------------------------- elasticity

    def _resolve_pool_name(self, pool: Optional[str]) -> str:
        """Default to the sole pool; with several, the caller must say
        which capability pool a scale action targets."""
        if pool is None:
            if len(self._pools) == 1:
                return next(iter(self._pools))
            raise ScaleRejectedError(
                f"fleet has capability pools {sorted(self._pools)} — "
                f"scale actions must name one (pool=...)")
        if pool not in self._pools:
            raise ScaleRejectedError(
                f"no capability pool named {pool!r}; known: "
                f"{sorted(self._pools)}")
        return pool

    def replica_count(self, pool: Optional[str] = None) -> int:
        """Non-retiring full replicas — fleet-wide, or one capability
        pool's slice (the per-pool autoscaler's pool size)."""
        with self._lock:
            return sum(1 for r in self._replicas.values()
                       if not r.retiring
                       and (pool is None or r.pool == pool))

    def add_replica(self, pool: Optional[str] = None) -> str:
        """Grow the pool by one replica (autoscale scale-up). `pool`
        names the capability pool to grow (optional with one pool).
        Returns the new replica's name. Raises ScaleRejectedError when
        the fleet is closed or the engine fails to build — a failed grow
        must be a visible decision outcome, not a zombie slot."""
        if self._closed:
            raise ScaleRejectedError("fleet is shut down")
        pool = self._resolve_pool_name(pool)
        rep = self._spawn_replica(pool)
        if rep.engine is None:
            # take the stillborn slot back out through the normal path
            rep.retiring = True
            self._health.retire(rep.name, "failed_to_build")
            raise ScaleRejectedError(
                f"replica {rep.name} engine failed to build")
        return rep.name

    def remove_replica(self, name: Optional[str] = None,
                       pool: Optional[str] = None) -> str:
        """Shrink the fleet by one replica through the HealthMonitor
        drain path (autoscale scale-down): the victim stops taking
        traffic immediately, its queued work fails back through the
        requeue path onto the survivors (nothing is lost), and the
        health tick unregisters it after the drain runs. `name=None`
        picks the least-loaded healthy replica (newest on ties) within
        `pool` (or fleet-wide with one pool).

        Raises ScaleRejectedError when: the fleet is closed; the victim's
        capability pool would drop below one replica (a pool emptied of
        capacity silently narrows what the FLEET can serve); `name` is
        unknown or already retiring; or (victim unspecified) any replica
        in the target pool is DOWN — draining on top of failure-drained
        capacity would amplify the outage."""
        with self._lock:
            if self._closed:
                raise ScaleRejectedError("fleet is shut down")
            if name is None:
                pool = self._resolve_pool_name(pool)
                live = [r for r in self._replicas.values()
                        if not r.retiring and r.pool == pool]
                if len(live) <= 1:
                    raise ScaleRejectedError(
                        f"refusing to shrink pool {pool!r} below one "
                        f"replica")
                healthy = set(self._health.healthy_targets())
                down = sorted(r.name for r in live if r.name not in healthy)
                if down:
                    raise ScaleRejectedError(
                        f"replica(s) {down} are down — refusing to shrink "
                        f"already-degraded capacity")
                victim = sorted(live,
                                key=lambda r: (r.in_flight, -r.index))[0]
            else:
                victim = self._replicas.get(name)
                if victim is None or victim.retiring:
                    raise ScaleRejectedError(
                        f"no live replica named {name!r}")
                peers = sum(1 for r in self._replicas.values()
                            if not r.retiring and r.pool == victim.pool)
                if peers <= 1:
                    raise ScaleRejectedError(
                        f"refusing to shrink pool {victim.pool!r} below "
                        f"one replica")
            victim.retiring = True
        self._health.retire(victim.name, "scale_down")
        return victim.name

    def attach_autoscaler(self, autoscaler):
        """Bind a ReplicaAutoscaler so `stats()` carries its snapshot
        (the acceptance surface) and shutdown() stops its ticker. A
        pool-scoped autoscaler (ReplicaAutoscaler(pool=...)) registers
        under its pool; the fleet holds one per capability pool plus at
        most one fleet-wide scaler."""
        pool = getattr(autoscaler, "pool", "") or ""
        if pool:
            self._pool_autoscalers[pool] = autoscaler
        else:
            self._autoscaler = autoscaler

    def sample_gauges(self):
        """Ticker hook (ops plane / autoscaler): publish the LIVE queue
        and occupancy signals as registry gauges — until this hook,
        queue depth and the drain-rate EMA were visible only inside
        `stats()` snapshots, so a `/metrics` scrape between requests
        never saw queue pressure.

        Cheap-dedupe guard: with per-pool autoscalers every pool's
        ticker calls this at the same cadence, and each pass takes the
        fleet lock + scans the admission queue — K pools must not mean
        K redundant sweeps per tick. Calls within 50 ms of the last
        full sample are no-ops (the signals cannot meaningfully change
        faster than the tick cadences that consume them)."""
        now = time.monotonic()
        with self._lock:
            # check-and-set under the lock: two pool tickers firing at
            # the same instant must not both pass the guard
            if now - self._last_gauge_sample < 0.05:
                return
            self._last_gauge_sample = now
        snap = self._admission.snapshot()
        self._queue_depth_gauge.set(snap["depth"])
        self._service_ema_gauge.set(snap["service_ema_s"] or 0.0)
        healthy = set(self._health.healthy_targets())
        depth_by_pool = {}
        for e in self._admission.entries():
            p = getattr(e, "pool", None)
            if p is not None:
                depth_by_pool[p] = depth_by_pool.get(p, 0) + 1
        with self._lock:
            live = [r for r in self._replicas.values() if not r.retiring]
            n_live = len(live)
            in_flight = sum(r.in_flight for r in live
                            if r.name in healthy)
            slots = sum(r.cfg.max_batch for r in live
                        if r.name in healthy)
            per_pool = {}
            for name in self._pools:
                p_live = [r for r in live if r.pool == name]
                per_pool[name] = (
                    len(p_live),
                    sum(1 for r in p_live if r.name in healthy),
                    sum(r.in_flight for r in p_live if r.name in healthy),
                    sum(r.cfg.max_batch for r in p_live
                        if r.name in healthy),
                )
        self._replicas_gauge.set(n_live)
        self._occupancy_gauge.set(in_flight / slots if slots else 0.0)
        # the per-capability-pool view: each pool autoscaler reads ITS
        # queue depth / occupancy / size, so a saturated SP pool scales
        # without the idle dense pool's signals diluting the decision
        for name, (n_p, _healthy_p, inf_p, slots_p) in per_pool.items():
            self._pool_reps_g[name].set(n_p)
            self._pool_occ_g[name].set(inf_p / slots_p if slots_p else 0.0)
            self._pool_depth_g[name].set(depth_by_pool.get(name, 0))
        self._sample_headroom(
            now, {name: h for name, (_n, h, _i, _s) in per_pool.items()})
        # the shared cost plane's gauges ride the same tick
        self.costs.publish()
        self.goodput.publish()
        if self._store is not None:
            self._store.publish_gauges()
        # the AMORTIZED fleet economy: cumulative chip-seconds over ALL
        # completed requests, cache/coalesce hits included. The per-cell
        # serve_chip_seconds_per_request gauge is an EMA over DISPATCHED
        # batches and cannot drop when a request never touches a chip —
        # this one is what the artifact store actually moves, and what
        # the ISSUE 17 telemetry.check gate reads from bench artifacts.
        completed = int(self._counts["completed"].value)
        if completed > 0:
            self.registry.gauge(
                "fleet_chip_seconds_per_request",
                help="cumulative device-seconds x chips across every "
                     "executable, amortized over completed requests "
                     "(artifact-store hits and coalesced followers "
                     "complete without spending chip time, so this "
                     "drops as the fleet memoizes)",
            ).set(self.costs.fleet_chip_seconds_total() / completed)
        if self._featurize is not None:
            self._featurize.sample_gauges()
        if self._cascade is not None:
            self._cascade_ledger.publish()

    def _sample_headroom(self, now: float, healthy_by_pool: dict):
        """The capacity model closing ROADMAP item 2's loop: per pool,
        arrival rate (EMA over `_admit` counts) vs modeled capacity
        (cost-ledger service rate x healthy replicas) published as
        `fleet_pool_headroom_ratio` — the autoscaler's new up-trigger
        reads it, so scale-up fires when the MODEL says the pool is
        running out, before queue-wait p95 (a lagging symptom) climbs.
        `fleet_pool_slo_burn_predicted` (arrival/capacity) is the burn
        predictor: >1 means the queue grows without bound and an SLO
        page is a matter of time. Gauges stay ABSENT until the pool has
        measured batches — a guessed capacity is worse than none."""
        snap = {}
        with self._arrivals_lock:
            counts = dict(self._arrivals)
            for name, count in counts.items():
                state = self._arrival_rate.get(name)
                if state is None:
                    self._arrival_rate[name] = {
                        "count": count, "ts": now, "ema": None}
                    continue
                dt = now - state["ts"]
                if dt <= 0:
                    continue
                inst = (count - state["count"]) / dt
                state["ema"] = (inst if state["ema"] is None
                                else 0.3 * inst + 0.7 * state["ema"])
                state["count"], state["ts"] = count, now
            rates = {name: (s["ema"] or 0.0)
                     for name, s in self._arrival_rate.items()}
        for name in self._pools:
            arrival = rates.get(name, 0.0)
            self.registry.gauge(
                "fleet_pool_arrival_per_sec",
                help="EMA request arrival rate whose preferred "
                     "capability pool is this one (sheds included — "
                     "demand, not throughput)", pool=name).set(arrival)
            per_replica = self.costs.pool_rate_rps(name)
            if per_replica is None:
                continue  # nothing measured yet: headroom stays absent
            capacity = per_replica * healthy_by_pool.get(name, 0)
            self.registry.gauge(
                "fleet_pool_capacity_per_sec",
                help="modeled service capacity: cost-ledger per-replica "
                     "rate x healthy replicas", pool=name).set(capacity)
            # capacity 0 = every replica of a measured pool is down:
            # publish WORST-case headroom rather than `continue` —
            # freezing the last pre-outage value would blind the
            # headroom up-trigger during exactly the outage it exists
            # for. Burn caps at a large finite ceiling (a gauge must
            # stay finite) and reads 0 only when demand is also 0.
            if capacity > 0:
                headroom = max(-1.0,
                               min(1.0, (capacity - arrival) / capacity))
                burn = min(1e6, arrival / capacity)
            else:
                headroom = -1.0
                burn = 1e6 if arrival > 0 else 0.0
            self.registry.gauge(
                "fleet_pool_headroom_ratio",
                help="(capacity - arrival) / capacity; the autoscaler "
                     "headroom up-trigger and the capacity runbook's "
                     "first signal (-1 when a measured pool has zero "
                     "healthy capacity)", pool=name).set(headroom)
            self.registry.gauge(
                "fleet_pool_slo_burn_predicted",
                help="arrival / capacity: >1 predicts unbounded queue "
                     "growth (an SLO page is a matter of time; capped "
                     "at 1e6 when capacity is zero)",
                pool=name).set(burn)
            snap[name] = {
                "arrival_per_sec": arrival,
                "capacity_per_sec": capacity,
                "per_replica_rps": per_replica,
                "healthy_replicas": healthy_by_pool.get(name, 0),
                "headroom_ratio": headroom,
                "burn_predicted": burn,
            }
        self._last_headroom = snap

    def rolling_update(self, *, params=None, model_cfg=None,
                       params_tag: Optional[str] = None,
                       timeout_s: float = 120.0) -> dict:
        """Zero-downtime deploy: swap the master weights and/or model
        config, then cycle each replica through the SAME HealthMonitor
        drain path a failure takes — one at a time, waiting for the
        re-probe to reinstate it behind a fresh engine (which reads the
        new masters) before touching the next, so the pool never drops
        more than one replica of capacity and in-flight work requeues
        onto the survivors.

        `params_tag` MUST change when `params` does: it is part of the
        result-cache key, and stale-tag cache entries would serve the
        OLD weights' structures after the update. Returns a summary dict
        ({replica: restarts}). Raises ScaleRejectedError if the fleet is
        closed or a replica fails to come back inside `timeout_s`."""
        if params is not None and params_tag is None:
            raise ValueError(
                "rolling_update(params=...) requires params_tag=: the "
                "result cache keys on it — reusing the old tag would "
                "serve stale structures from the previous weights"
            )
        if params is None and model_cfg is None and params_tag is None:
            raise ValueError("rolling_update: nothing to update")
        with self._lock:
            if self._closed:
                raise ScaleRejectedError("fleet is shut down")
            if params is not None:
                self._params = params
            if model_cfg is not None:
                self._model_cfg = model_cfg
                self._degraded_model_cfg = model_cfg
                if self.cfg.degraded_weight_dtype == "int8":
                    self._degraded_model_cfg = dataclasses.replace(
                        model_cfg, weight_dtype="int8")
            reps = sorted(
                (r for r in self._replicas.values() if not r.retiring),
                key=lambda r: r.index)
            if params_tag is not None:
                # the template too, not just live replicas: a replica
                # the autoscaler ADDS after this deploy is spawned from
                # self._serving_cfg and must carry the new tag — a fresh
                # engine serving the new weights under the old tag would
                # alias the old weights' result-cache keyspace
                self._serving_cfg = dataclasses.replace(
                    self._serving_cfg, params_tag=params_tag)
                for r in reps:
                    r.cfg = dataclasses.replace(r.cfg,
                                                params_tag=params_tag)
                if self._degraded_rep is not None:
                    self._degraded_rep.cfg = dataclasses.replace(
                        self._degraded_rep.cfg, params_tag=params_tag)
            degraded = self._degraded_rep
        if self._store is not None:
            # re-key the fleet artifact tier the moment the tags change —
            # BEFORE cycling replicas, so no window exists where a
            # new-weights replica could read an old-tag entry. In-flight
            # old-tag leaders still settle their coalitions (settle keys
            # on the entry's stamped store_key, not the current tags);
            # their put_result lands under a retired tag and the sweep
            # below (plus the periodic budget sweep) reclaims it.
            self._store.set_current_tags(self._current_store_tags())
        summary = {}
        for rep in reps:
            try:
                self._health.force_down(rep.name, "rolling_update")
            except KeyError:
                continue  # retired (autoscale) since we captured reps
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                try:
                    state = self._health.state(rep.name)
                except KeyError:
                    break  # retired mid-update: nothing left to cycle
                if (state is ReplicaState.HEALTHY
                        and rep.engine is not None):
                    break
                time.sleep(min(0.02, self.cfg.reprobe_interval_s))
            else:
                raise ScaleRejectedError(
                    f"rolling update stalled: {rep.name} not reinstated "
                    f"within {timeout_s}s")
            summary[rep.name] = rep.restarts
        if degraded is not None:
            # the degraded tier has no health-managed drain path; swap
            # its engine directly (it serves only overflow/outage)
            old, degraded.engine = degraded.engine, None
            if old is not None:
                old.shutdown(drain=False,
                             timeout=self.cfg.drain_timeout_s)
            degraded.engine = degraded.factory()
        if self._store is not None:
            # GC the retired deploy's keyspace from disk right away
            # rather than waiting for the next budget sweep
            self._store.sweep()
        return summary

    def health(self) -> dict:
        """Cheap liveness payload for `/healthz` (telemetry/ops_plane.py):
        HealthMonitor states + replica-up view, no engine stats. `status`
        is "ok" (all replicas healthy), "degraded" (reduced capacity:
        some replicas down, or only the degraded tier is serving), or
        "down" (closed, or nothing can serve — mapped to HTTP 503)."""
        snap = self._health.snapshot()
        # retiring replicas are deliberate removals mid-drain, not lost
        # capacity: they must not flip /healthz to "degraded"
        states = {name: t["state"] for name, t in snap["targets"].items()
                  if not t.get("retiring")}
        n_healthy = sum(1 for s in states.values() if s == "healthy")
        with self._lock:
            has_degraded = self._degraded_rep is not None
        if self._closed or (n_healthy == 0 and not has_degraded):
            status = "down"
        elif n_healthy < len(states):
            status = "degraded"
        else:
            status = "ok"
        return {
            "status": status,
            "closed": self._closed,
            "replicas": states,
            "healthy_replicas": n_healthy,
            "total_replicas": len(states),
            "degraded_tier": has_degraded,
            "queue_depth": self._admission.depth(),
            "queue_capacity": self.cfg.queue_capacity,
        }

    def stats(self) -> dict:
        """JSON-ready fleet snapshot: terminal counters, admission queue,
        per-replica state + engine stats, health, telemetry registry."""
        counts = {k: int(c.value) for k, c in self._counts.items()}
        counts["degraded"] = int(self._degraded_total.value)
        counts["requeued"] = int(self._requeue_total.value)
        counts["in_flight"] = (
            counts["submitted"] - counts["completed"] - counts["shed"]
            - counts["failed"]
        )
        with self._lock:
            reps = list(self._replicas.values())
            degraded = self._degraded_rep
            shed = {reason: int(c.value)
                    for reason, c in self._shed_reasons.items()}
            errors = {code: int(c.value)
                      for code, c in self._errors.items()}
        replicas = {}
        # one snapshot, not per-name state() lookups: a replica retired
        # between our reps copy and here has already left the health
        # registry, and indexing it would KeyError a /statusz scrape
        health_states = {name: t["state"] for name, t
                         in self._health.snapshot()["targets"].items()}
        for rep in reps + ([degraded] if degraded else []):
            engine = rep.engine
            pool = self._pools.get(rep.pool)
            # capability visibility (ISSUE 14 satellite): the live
            # engine's own tag when it exists, else the pool's derived
            # one — so /statusz always shows WHY the router considers
            # this replica for a given length
            if engine is not None:
                capability = engine.capability()
            elif pool is not None:
                capability = self._pool_capability(pool)
            else:  # degraded tier mid-restart
                capability = {
                    "weight_dtype": self._degraded_model_cfg.weight_dtype,
                    "sp_shards": rep.cfg.sp_shards,
                    "max_len": self._degraded_ladder.max_len,
                }
            replicas[rep.name] = {
                "state": (DEGRADED if rep.name == DEGRADED
                          else health_states.get(rep.name, "retired")),
                "pool": rep.pool,
                "capability": capability,
                "in_flight": rep.in_flight,
                "dispatches": rep.dispatches,
                "restarts": rep.restarts,
                "engine": engine.stats() if engine is not None else None,
            }
        pools = {}
        # ONE queue snapshot grouped by pool (not a full scan per pool):
        # stats() sits on the observability hot path (/statusz, the
        # stats-flusher thread, polling tests)
        depth_by_pool = {}
        for e in self._admission.entries():
            p = getattr(e, "pool", None)
            if p is not None:
                depth_by_pool[p] = depth_by_pool.get(p, 0) + 1
        for name, pool in self._pools.items():
            pools[name] = {
                "rank": pool.rank,
                "capability": self._pool_capability(pool),
                "replicas": sum(1 for r in reps
                                if r.pool == name and not r.retiring),
                "service_ema_s": pool.service_ema_s,
                "retry_after_s": self._pool_retry_after(
                    name, depth=depth_by_pool.get(name, 0)),
            }
        # publish the cost-plane ledgers so the registry snapshot below
        # agrees with the sections; deliberately NOT the full
        # sample_gauges sweep — its dedupe guard exists for the ticker
        # cadence, and a stats() poll must not consume an explicit
        # sample_gauges() caller's refresh window
        self.costs.publish()
        self.goodput.publish()
        out = {
            "closed": self._closed,
            "requests": counts,
            "shed": shed,
            "errors": errors,
            "queue_wait": self._queue_wait.snapshot(),
            "latency": self._latency.snapshot(),
            "admission": self._admission.snapshot(),
            "replicas": replicas,
            "pools": pools,
            "health": self._health.snapshot(),
            "costs": self.costs.snapshot(),
            "serve_goodput": self.goodput.snapshot(),
            "headroom": dict(self._last_headroom),
            "flights": self.flights.snapshot(),
            "telemetry": {
                "metrics": self.registry.snapshot(),
                "spans": self._tracer.summary(),
            },
        }
        if self._store is not None:
            out["artifact_store"] = self._store.snapshot()
        if self._frontdoor is not None:
            out["frontdoor"] = self._frontdoor.snapshot()
        if self._featurize is not None:
            out["featurize"] = self._featurize.stats()
        if self._autoscaler is not None:
            out["autoscale"] = self._autoscaler.snapshot()
        if self._pool_autoscalers:
            out["autoscale_pools"] = {
                pool: sc.snapshot()
                for pool, sc in sorted(self._pool_autoscalers.items())
            }
        if self._journal is not None:
            out["journal"] = self._journal.stats()
        if self._cascade is not None:
            # /statusz "cascade" section: escalation rate + per-tier
            # quality EMAs next to the policy that produced them, so an
            # escalation-rate spike can be read against its thresholds
            out["cascade"] = {
                "policy": dataclasses.asdict(self._cascade),
                **self._cascade_ledger.snapshot(),
            }
        if self._budget is not None:
            out["retry_budget"] = self._budget.snapshot()
        if self._hedger is not None:
            with self._hedge_lock:
                out["hedging"] = {
                    "issued": self._hedges_issued,
                    "denied": dict(self._hedge_denied),
                    "outstanding": len(self._outstanding),
                    "wasted_chip_seconds": round(
                        self._hedge_waste.value, 6),
                }
        return out

    def backpressure(self) -> dict:
        """The shed-advice surface an HTTP front end quotes on 429s
        (/statusz `backpressure` section): the global queue horizon,
        per-pool horizons when capability pools are explicit, and the
        retry-budget state when one is armed. Cheap enough to call per
        scrape."""
        out = {"queue_retry_after_s": round(
            self._admission.retry_after_s(), 3)}
        if not self._implicit_pools:
            depth_by_pool = {}
            for e in self._admission.entries():
                p = getattr(e, "pool", None)
                if p is not None:
                    depth_by_pool[p] = depth_by_pool.get(p, 0) + 1
            out["pools"] = {
                name: round(self._pool_retry_after(
                    name, depth=depth_by_pool.get(name, 0)), 3)
                for name in self._pools
            }
        if self._budget is not None:
            out["retry_budget"] = self._budget.snapshot()
        return out

    def replay_journal(self) -> dict:
        """Re-drive every journaled-but-unsettled request through the
        normal submit() path — call at startup, BEFORE admitting fresh
        traffic. Idempotent by construction, not bookkeeping: a replayed
        request re-enters front-door coalescing and the artifact store,
        so work that completed before the crash replays as a store hit
        and identical payloads coalesce — zero duplicate chip dispatch.
        Records whose absolute deadline already passed settle directly
        (journal_expired_total); a replay the submit path sheds/fails
        synchronously is already sealed AND settled by that path.
        Returns {replayed, expired, failed, requests} — `requests` holds
        the live FleetRequest futures so a caller can await them."""
        if self._journal is None:
            return {"replayed": 0, "expired": 0, "failed": 0,
                    "requests": []}
        replayed = expired = failed = 0
        requests = []
        for rec in self._journal.pending():
            if (rec.deadline_unix is not None
                    and rec.deadline_unix <= time.time()):
                self.registry.counter(
                    "journal_expired_total",
                    help="journal records dropped at replay because "
                         "their deadline had already passed").inc()
                self._journal.settle(rec.trace_id)
                expired += 1
                continue
            remaining = (None if rec.deadline_unix is None
                         else rec.deadline_unix - time.time())
            try:
                req = self.submit(
                    rec.seq, msa=rec.msa, msa_mask=rec.msa_mask,
                    timeout=remaining, priority=rec.priority,
                    trace_id=rec.trace_id)
            except ServingError:
                failed += 1
                continue
            self.registry.counter(
                "journal_replayed_total",
                help="journal records re-driven through submit() after "
                     "a restart").inc()
            replayed += 1
            requests.append(req)
        return {"replayed": replayed, "expired": expired,
                "failed": failed, "requests": requests}

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop the front door, the router, the supervisor, and every
        engine. drain=True serves what it still can (replica engines
        drain their queues); whatever cannot be served resolves with
        EngineClosedError — nothing is left unresolved. Idempotent."""
        # under the fleet lock: the dispatcher's crash guard flips the
        # same flag from its own thread (CONC001)
        with self._lock:
            self._closed = True
        self._drain_on_stop = drain
        if self._autoscaler is not None:
            # the control loop must not scale a closing fleet (tick()
            # also checks _closed; stopping the fallback thread is belt
            # and braces)
            self._autoscaler.stop()
        for scaler in self._pool_autoscalers.values():
            scaler.stop()
        if self._featurize is not None:
            # featurize first: its pending jobs resolve their entries
            # (drain=True runs them through admission; anything the
            # dispatcher no longer serves fails terminally below)
            self._featurize.shutdown(drain=drain)
        self._stop.set()
        self._dispatcher.join(timeout)
        if self._hedger is not None:
            self._hedger.join(timeout)
        self._health.stop()
        with self._lock:
            reps = list(self._replicas.values())
            if self._degraded_rep is not None:
                reps.append(self._degraded_rep)
        for rep in reps:
            engine = rep.engine
            if engine is not None:
                engine.shutdown(drain=drain, timeout=self.cfg.drain_timeout_s)
        # engine shutdown callbacks may have requeued entries after the
        # dispatcher died; fail every remaining queued entry terminally
        for entry in self._admission.drain():
            self._resolve_failed(entry, EngineClosedError(
                "fleet shut down before the request was served"))
        if self._frontdoor is not None:
            # every leader above settled its own coalition through a
            # terminal path; this catches followers whose leader never
            # reached one (e.g. stranded mid-submit) — nothing is left
            # unresolved, the front-door promise included
            for entry in self._frontdoor.drain():
                self._resolve_failed(entry, EngineClosedError(
                    "fleet shut down before the coalesced request was "
                    "served"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=True)
        return False

    # ------------------------------------------------------------- router

    def _dispatch_loop(self):
        try:
            while True:
                if self._stop.is_set():
                    if not self._drain_on_stop:
                        return
                    entry, expired = self._admission.poll(timeout=0)
                    if entry is None and not expired:
                        return  # queue fully drained
                else:
                    entry, expired = self._admission.poll(timeout=0.05)
                for e in expired:
                    self._resolve_shed(e, "deadline", RequestTimeoutError(
                        f"deadline passed after "
                        f"{time.monotonic() - e.enqueued_at:.3f}s in the "
                        f"fleet queue",
                        retry_after_s=self._admission.retry_after_s()))
                if entry is not None:
                    self._route(entry)
        except BaseException:  # noqa: BLE001 — last-resort guard (engine
            # worker stance): fail queued work loudly, refuse new traffic
            # (the `with` regions above released _lock during unwind, so
            # re-acquiring here cannot self-deadlock)
            with self._lock:
                self._closed = True
            traceback.print_exc()
            for entry in self._admission.drain():
                self._resolve_failed(entry, PredictionError(
                    "fleet dispatcher crashed; fleet is closed"))

    def _route(self, entry: FleetRequest):
        wait = time.monotonic() - entry.enqueued_at
        self._queue_wait.observe(wait)
        if self._tracer.enabled:
            self._tracer.add("fleet.queue_wait", wait, cat="fleet",
                             priority=entry.priority,
                             trace_id=entry.trace_id,
                             requeues=entry.requeues)
        overloaded = (self.cfg.degrade_depth > 0
                      and self._admission.depth() >= self.cfg.degrade_depth)
        # length-adaptive routing (ROADMAP item 4b): only replicas whose
        # capability pool's bucket ceiling covers the request are
        # candidates, preferred cheapest-pool-first (pool rank = ceiling
        # ascending, declaration order) then least-loaded — short work
        # lands on dense/int8 replicas, the SP pool keeps its headroom
        # for the lengths only it can serve
        length = (entry.features.length if entry.features is not None
                  else len(entry.seq))
        healthy = self._health.healthy_targets()
        with self._lock:
            # .get: a replica retired by the autoscaler may briefly
            # linger in the health view (or vice versa) mid-transition
            ranked = sorted(
                (r for r in (self._replicas.get(n) for n in healthy)
                 if r is not None and not r.retiring
                 and self._pools[r.pool].max_len >= length),
                key=lambda r: (self._pools[r.pool].rank, r.in_flight),
            )
            degraded = self._degraded_rep
        if degraded is not None and self._degraded_ladder.max_len < length:
            # the degraded tier's ladder cannot bucket this request —
            # never a candidate, whatever the overload state
            degraded = None
        # failover exclusion: a replica this request already FAILED on is
        # the worst candidate, not an equal one — prefer untried healthy
        # replicas, fall to the degraded tier when none remain, and only
        # then retry where it failed (better a retry than a starve)
        if self._cascade is not None:
            draft_name = self._cascade.draft_pool
            if entry.tier == "draft":
                draft_only = [r for r in ranked if r.pool == draft_name]
                if draft_only:
                    ranked = draft_only
                else:
                    # the whole draft pool is down/retired: PROMOTE rather
                    # than starve — the cascade is a cost optimization,
                    # never an availability reduction. The entry re-tags
                    # as full-tier so the store key, candidate set and
                    # accounting all agree from here on.
                    entry.tier = "full"
                    entry.pool = self._preferred_pool_name(
                        length, exclude=(draft_name,))
                    self._cascade_ledger.note_bypass("draft_unavailable")
                    self.flights.note(
                        entry.trace_id, "cascade_promote",
                        reason="draft_unavailable", pool=entry.pool)
                    ranked = [r for r in ranked if r.pool != draft_name]
            else:
                # full-tier (incl. escalated) work must never land on the
                # draft pool — a low-fidelity retry of a low-confidence
                # draft would be noise, not verification
                ranked = [r for r in ranked if r.pool != draft_name]
        fresh = [r for r in ranked if r.name not in entry.failed_on]
        stale = [r for r in ranked if r.name in entry.failed_on]
        targets = fresh
        if degraded is not None and (overloaded or not fresh):
            # the cheap tier catches the overload spill the full replicas
            # reject, and is the first resort once the request has failed
            # on (or lost) every full replica — the response says so
            targets = targets + [degraded]
        targets = targets + stale
        if not targets:
            # every CAPABLE replica is down (config-level incapacity —
            # a length past every pool's ceiling — already shed at submit
            # with sequence_too_long): answer NOW with the re-probe
            # horizon instead of letting the request age out silently
            self._resolve_shed(
                entry, "no_healthy_replica",
                NoHealthyReplicaError(
                    f"every replica capable of length {length} is down "
                    f"and no degraded tier covers it",
                    retry_after_s=self.cfg.reprobe_interval_s))
            return
        for rep in targets:
            if self._try_dispatch(entry, rep):
                return
        # nothing admitted it (queues full / engines mid-drain): the
        # entry stays accepted — requeue WITHOUT consuming failover
        # budget and let the router breathe. Exception: during shutdown
        # with every candidate engine already dead, nothing will ever
        # free up — resolve terminally instead of orbiting the queue.
        with self._lock:
            alive = any(
                r.engine is not None and not r.engine._closed
                for r in targets
            )
        if self._closed and not alive:
            self._resolve_failed(entry, EngineClosedError(
                "fleet shut down before the request was served"))
            return
        self._admission.requeue(entry)
        time.sleep(self.cfg.dispatch_backoff_s)

    def _try_dispatch(self, entry: FleetRequest, rep: _Replica, *,
                      hedge: bool = False) -> bool:
        engine = rep.engine
        if engine is None:
            return False
        now = time.monotonic()
        remaining = (None if entry.deadline is None
                     else entry.deadline - now)
        if remaining is not None and remaining <= 0:
            if hedge:
                # the PRIMARY dispatch owns the outcome — a hedge that
                # finds the deadline gone simply declines to launch
                return False
            self._resolve_shed(entry, "deadline", RequestTimeoutError(
                "deadline passed at dispatch",
                retry_after_s=self._admission.retry_after_s()))
            return True
        features = entry.features
        if (self._cascade is not None and features is not None
                and features.msa is not None):
            # one FeatureBundle rides every tier of the cascade
            # (featurization is never repaid), but the draft pool's
            # engines serve fewer MSA rows — hand each engine a VIEW
            # truncated to its own row budget instead of tripping its
            # featurized-for-a-different-deployment guard. Row truncation
            # is the reduced-fidelity featurization by construction
            # (featurize.py fills rows top-down), so the view is exactly
            # what that pool would have featurized itself.
            rows = getattr(getattr(engine, "cfg", None), "msa_rows", None)
            if rows == 0:
                features = dataclasses.replace(
                    features, msa=None, msa_mask=None)
            elif rows is not None and features.msa.shape[0] > rows:
                features = dataclasses.replace(
                    features, msa=features.msa[:rows],
                    msa_mask=(features.msa_mask[:rows]
                              if features.msa_mask is not None else None))
        try:
            # bind_trace: any span a helper records on the dispatcher
            # thread during THIS routing inherits the request's id
            with self._tracer.bind_trace(entry.trace_id):
                inner = engine.submit(
                    entry.seq, msa=entry.msa, msa_mask=entry.msa_mask,
                    # None would fall back to the ENGINE's default
                    # deadline; a deadline-less fleet request must stay
                    # deadline-less
                    timeout=remaining if remaining is not None else 1e9,
                    # the fleet's id, not a fresh engine-minted one: a
                    # requeued request keeps one id across replicas
                    trace_id=entry.trace_id,
                    # featurized once (tier or inline), dispatched many:
                    # a requeue onto another replica reuses the bundle
                    # (row-truncated to this engine's budget above)
                    features=features,
                )
        except QueueFullError:
            return False
        except (CircuitOpenError, EngineClosedError) as e:
            if rep.name != DEGRADED:
                self._health.record_failure(rep.name, e.code)
            return False
        except ServingError as e:
            # semantic rejection (bad MSA shape etc.): the request is the
            # problem — terminal, no failover
            if hedge:
                return False
            self._resolve_failed(entry, e)
            return True
        with self._lock:
            rep.in_flight += 1
            rep.dispatches += 1
            entry.inflight_dispatches += 1
            self._dispatch_count += 1
        # routed accounting: which capability pool actually took it, and
        # that pool's queue-wait distribution (the per-pool autoscaling
        # signal — a saturated pool's wait climbs even while another
        # pool's sits at zero)
        self._routed_counter(rep.pool).inc()
        cell = {}
        if entry.features is not None:
            cell_fn = getattr(rep.engine, "cell_for", None)
            if cell_fn is not None:
                try:
                    cell = dict(cell_fn(entry.features.bucket))
                except Exception:  # noqa: BLE001 — a stub engine without
                    # real cells must not break routing
                    cell = {}
            # the engine cell's pool IS rep.pool (passed at build) —
            # drop it so the explicit kwarg below stays the one source
            cell.pop("pool", None)
        if hedge:
            with self._lock:
                counter = self._hedge_counters.get(rep.pool)
                if counter is None:
                    counter = self.registry.counter(
                        "fleet_hedge_total",
                        help="hedged (duplicate) dispatches per pool",
                        pool=rep.pool)
                    self._hedge_counters[rep.pool] = counter
            counter.inc()
            self.flights.note(
                entry.trace_id, "hedge", replica=rep.name, pool=rep.pool,
                age_s=round(now - entry.enqueued_at, 6), **cell)
        else:
            self.flights.note(
                entry.trace_id, "dispatch", replica=rep.name,
                pool=rep.pool,
                queue_wait_s=round(now - entry.enqueued_at, 6),
                requeues=entry.requeues, **cell)
            hist = self._pool_wait.get(rep.pool)
            if hist is not None:
                hist.observe(now - entry.enqueued_at)
            if self._hedger is not None:
                # register the PRIMARY dispatch for the hedger's age scan;
                # hedges themselves are never re-hedged
                with self._hedge_lock:
                    self._outstanding[id(entry)] = {
                        "entry": entry, "rep": rep.name,
                        "pool": rep.pool, "at": now, "hedged": False,
                    }
        dispatched_at = now
        inner.add_done_callback(
            lambda r, e=entry, rp=rep, t=dispatched_at:
            self._on_replica_done(e, rp, r, t))
        return True

    # ---------------------------------------------------- completion path

    def _on_replica_done(self, entry: FleetRequest, rep: _Replica,
                         inner, dispatched_at: float):
        """Runs on the replica worker (or drain) thread: resolve, or
        requeue onto another replica. Never blocks, never raises."""
        with self._lock:
            rep.in_flight -= 1
            entry.inflight_dispatches -= 1
            twin_in_flight = entry.inflight_dispatches > 0
        with self._hedge_lock:
            self._outstanding.pop(id(entry), None)
        result, exc = inner.peek()
        degraded = rep.name == DEGRADED
        if exc is None:
            if not degraded:
                self._health.record_success(rep.name)
            service_s = time.monotonic() - dispatched_at
            self._admission.note_served(service_s)
            hist = self._pool_service.get(rep.pool)
            if hist is not None:
                hist.observe(service_s)
            if self._budget is not None:
                self._budget.on_success()
            pool = self._pools.get(rep.pool)
            if pool is not None:
                # per-pool drain-rate EMA: what pool-quoted retry_after_s
                # estimates are built from
                with self._lock:
                    pool.service_ema_s = (
                        service_s if pool.service_ema_s is None
                        else 0.2 * service_s + 0.8 * pool.service_ema_s)
            tier_meta = ""
            if (self._cascade is not None and not degraded
                    and rep.pool == self._cascade.draft_pool):
                if entry.escalated:
                    # a late draft arrival (hedge twin of the scored
                    # dispatch) after the escalation decision: the full
                    # tier owns the outcome now. The chip-second/health
                    # accounting above already happened — just do not
                    # finish, settle or persist the superseded draft.
                    self.flights.note(entry.trace_id, "draft_superseded",
                                      replica=rep.name)
                    return
                if entry.tier == "draft" and not entry.done():
                    try:
                        verdict = self._cascade_scorer.score(result)
                    except Exception:  # noqa: BLE001 — a broken scorer
                        # must degrade to "verify everything", never to
                        # dropped requests or an unscored accept
                        verdict = CascadeVerdict(
                            accept=False, confidence=0.0, stress=0.0,
                            reason="scorer_error")
                    self._cascade_ledger.note_scored(verdict)
                    if verdict.accept:
                        entry.draft_accepted = True
                    else:
                        # ESCALATE: re-tag as full-tier and requeue; the
                        # FeatureBundle rides (featurization is never
                        # repaid), _route now excludes the draft pool,
                        # and the draft result is discarded unstored.
                        entry.escalated = True
                        entry.tier = "full"
                        length = (entry.features.length
                                  if entry.features is not None
                                  else len(entry.seq))
                        entry.pool = self._preferred_pool_name(
                            length, exclude=(self._cascade.draft_pool,))
                        self.flights.note(
                            entry.trace_id, "escalate",
                            reason=verdict.reason,
                            confidence=round(verdict.confidence, 4),
                            stress=round(verdict.stress, 4),
                            from_pool=rep.pool, to_pool=entry.pool)
                        if entry.pool is not None:
                            # the escalation is NEW demand on the verify
                            # pool — count the arrival where the headroom
                            # model will have to absorb it
                            with self._arrivals_lock:
                                self._arrivals[entry.pool] = (
                                    self._arrivals.get(entry.pool, 0) + 1)
                        self._admission.requeue(entry)
                        return
            if self._cascade is not None:
                if entry.draft_accepted:
                    tier_meta = "draft"
                elif entry.escalated:
                    tier_meta = "escalated"
                else:
                    tier_meta = "full"
            if entry._finish(result=result, replica=rep.name,
                             degraded=degraded, tier=tier_meta,
                             latency_s=time.monotonic() - entry.enqueued_at):
                self._counts["completed"].inc()
                self._latency.observe(time.monotonic() - entry.enqueued_at)
                if degraded:
                    self._degraded_total.inc()
                finish_extra = {}
                if self._cascade is not None:
                    finish_extra["tier"] = tier_meta
                    if entry.escalated:
                        finish_extra["tier_path"] = "draft->escalated"
                    elif entry.draft_accepted:
                        finish_extra["tier_path"] = "draft-accepted"
                    if result.exit_depth:
                        finish_extra["exit_depth"] = result.exit_depth
                    self._cascade_ledger.note_served(
                        tier_meta,
                        confidence=result.mean_confidence,
                        stress=result.stress,
                        exit_depth=result.exit_depth)
                self.flights.finish(
                    entry.trace_id, "completed", replica=rep.name,
                    pool=rep.pool, degraded=degraded,
                    requeues=entry.requeues,
                    from_cache=result.from_cache, bucket=result.bucket,
                    latency_s=round(
                        time.monotonic() - entry.enqueued_at, 6),
                    **finish_extra)
                self._journal_settle(entry.trace_id)
            elif entry.hedges > 0:
                # _finish lost the race on a HEDGED entry: this side is
                # the hedge pair's loser — its chip-seconds bought nothing
                # but the tail cut. sp_shards chips burned concurrently.
                self._hedge_waste.inc(
                    service_s * max(1, rep.cfg.sp_shards or 1))
                self.flights.note(entry.trace_id, "hedge_lost",
                                  replica=rep.name,
                                  wasted_s=round(service_s, 6))
            # settle even when _finish lost a race (the result is still
            # the coalition's answer) — store put + follower resolution
            self._settle_waiters(entry, result=result, rep=rep)
            return
        if twin_in_flight and not entry.done():
            # a hedge twin of this dispatch is still running — IT owns
            # the outcome now; requeueing here would double-dispatch
            if isinstance(exc, _REPLICA_FAULT_ERRORS) and not degraded:
                self._health.record_failure(rep.name, exc.code)
            self.flights.note(entry.trace_id, "hedge_twin_pending",
                              failed_on=rep.name,
                              code=getattr(exc, "code",
                                           type(exc).__name__))
            return
        if isinstance(exc, RequestTimeoutError):
            # the request's OWN deadline expired inside the replica —
            # failover could not have saved it
            self._resolve_shed(entry, "deadline", exc)
            return
        if isinstance(exc, _REPLICA_FAULT_ERRORS):
            if not degraded:
                self._health.record_failure(rep.name, exc.code)
            entry.failed_on.add(rep.name)
            entry.last_error = exc
            if not self._closed and entry.requeues < self.cfg.requeue_limit:
                if (self._budget is not None
                        and not self._budget.try_spend("failover")):
                    # fleet-wide brownout: every replica failing means
                    # every requeue is amplification — shed with honest
                    # backoff advice instead of dogpiling
                    self._resolve_shed(
                        entry, "retry_budget", RetryBudgetExhaustedError(
                            "failover retry denied: fleet-wide retry "
                            "budget exhausted",
                            retry_after_s=self._budget.retry_after_s()))
                    return
                entry.requeues += 1
                self._requeue_total.inc()
                self.flights.note(entry.trace_id, "requeue",
                                  failed_on=rep.name, code=exc.code)
                self._admission.requeue(entry)
                return
            if entry.requeues >= self.cfg.requeue_limit > 0:
                err = RequeueLimitError(
                    f"failed on {entry.requeues + 1} replica(s) "
                    f"(requeue_limit {self.cfg.requeue_limit}); last: "
                    f"{type(exc).__name__}: {exc}")
                err.__cause__ = exc
                self._resolve_failed(entry, err)
                return
        self._resolve_failed(entry, exc)

    # -------------------------------------------------- hedged dispatch

    def _hedge_delay(self, pool_name: str) -> Optional[float]:
        """How long a dispatch into `pool_name` may run before it earns
        a hedge: the pool's own service-time p95 x hedge_p95_factor
        (floored at hedge_min_delay_s). None — never hedge — until the
        histogram holds `hedge_min_samples` observations: hedging off a
        cold estimate would duplicate perfectly healthy traffic."""
        hist = self._pool_service.get(pool_name)
        if hist is None:
            return None  # degraded-tier dispatches are never hedged
        snap = hist.snapshot()
        if snap.get("count", 0) < self.cfg.hedge_min_samples:
            return None
        p95 = snap.get("p95") or 0.0
        if p95 <= 0.0:
            return None
        return max(self.cfg.hedge_min_delay_s,
                   p95 * self.cfg.hedge_p95_factor)

    def _hedge_loop(self):
        """Dedicated scanner (armed only when hedge_p95_factor > 0):
        wakes every tick and hedges any outstanding PRIMARY dispatch
        older than its pool's hedge delay. First settle wins via
        FleetRequest._finish's resolve-once; the loser's service time
        lands in hedge_wasted_chip_seconds_total."""
        while not self._stop.wait(self.cfg.tick_interval_s):
            try:
                self._hedge_scan()
            except Exception:  # noqa: BLE001 — the scanner must outlive
                # a bad snapshot; a dead hedger silently disables hedging
                traceback.print_exc()

    def _hedge_scan(self):
        now = time.monotonic()
        with self._hedge_lock:
            stale = [st for st in list(self._outstanding.values())
                     if not st["hedged"]]
        for st in stale:
            entry = st["entry"]
            if entry.done():
                continue
            delay = self._hedge_delay(st["pool"])
            if delay is None or now - st["at"] < delay:
                continue
            self._issue_hedge(entry, st)

    def _hedge_deny(self, reason: str):
        with self._hedge_lock:
            self._hedge_denied[reason] = (
                self._hedge_denied.get(reason, 0) + 1)
        self.registry.counter(
            "hedge_denied_total",
            help="hedges declined by reason (rate_cap / budget / "
                 "no_replica / dispatch_full)",
            reason=reason).inc()

    def _issue_hedge(self, entry: FleetRequest, st: dict):
        """One budgeted duplicate dispatch for a straggling primary.
        Order matters: the cheap global rate-cap check first, then
        target selection, and the retry-budget token last — spent only
        when a launch will actually be attempted."""
        with self._lock:
            dispatches = self._dispatch_count
        with self._hedge_lock:
            issued = self._hedges_issued
        if issued + 1 > max(1, dispatches) * self.cfg.hedge_rate_cap:
            self._hedge_deny("rate_cap")
            return
        length = (entry.features.length if entry.features is not None
                  else len(entry.seq))
        healthy = self._health.healthy_targets()
        primary = st["rep"]
        with self._lock:
            # same candidate discipline as _route, minus the primary's
            # replica and anything this entry already failed on — a
            # hedge onto the straggler itself would measure nothing
            targets = sorted(
                (r for r in (self._replicas.get(n) for n in healthy)
                 if r is not None and not r.retiring
                 and r.name != primary
                 and r.name not in entry.failed_on
                 and self._pools[r.pool].max_len >= length),
                key=lambda r: (self._pools[r.pool].rank, r.in_flight),
            )
        if not targets:
            self._hedge_deny("no_replica")
            return
        if self._budget is not None and not self._budget.try_spend("hedge"):
            self._hedge_deny("budget")
            return
        with self._hedge_lock:
            cur = self._outstanding.get(id(entry))
            if cur is not st or st["hedged"]:
                return  # the primary settled (or another scan won) first
            st["hedged"] = True
            self._hedges_issued += 1
        entry.hedges += 1
        for rep in targets:
            if self._try_dispatch(entry, rep, hedge=True):
                return
        # token spent but no engine admitted the duplicate — the attempt
        # still counts against the rate cap (conservative by design)
        self._hedge_deny("dispatch_full")

    # ------------------------------------------------- terminal accounting

    def _shed_counter(self, reason: str):
        with self._lock:
            counter = self._shed_reasons.get(reason)
            if counter is None:
                counter = self.registry.counter(
                    "fleet_shed_total", help="load shed by reason",
                    reason=reason)
                self._shed_reasons[reason] = counter
            return counter

    def _routed_counter(self, pool: str):
        """fleet_routed_total{pool} — lazy so the degraded tier (not a
        capability pool) gets its own row on first spill."""
        with self._lock:
            counter = self._routed.get(pool)
            if counter is None:
                counter = self.registry.counter(
                    "fleet_routed_total",
                    help="requests dispatched per capability pool "
                         "(degraded-tier spills under pool=degraded)",
                    pool=pool)
                self._routed[pool] = counter
            return counter

    def _count_error(self, exc):
        code = getattr(exc, "code", "serving_error")
        with self._lock:
            counter = self._errors.get(code)
            if counter is None:
                counter = self.registry.counter(
                    "fleet_errors_total",
                    help="terminal failures and rejections by stable code",
                    code=code)
                self._errors[code] = counter
        counter.inc()

    def _journal_settle(self, trace_id: str):
        """Unlink the trace's intake-journal record: called at every
        terminal path (result, typed error, shed) so a restart replays
        only truly unfinished work. No-op without a journal; settle()
        itself is idempotent, so racing terminal paths are harmless."""
        if self._journal is not None:
            self._journal.settle(trace_id)

    def _resolve_shed(self, entry: FleetRequest, reason: str,
                      exc: ServingError) -> bool:
        if entry._finish(exc=exc):
            self._counts["shed"].inc()
            self._shed_counter(reason).inc()
            self._count_error(exc)
            self.flights.finish(entry.trace_id, "shed", reason=reason,
                                code=getattr(exc, "code", "serving_error"),
                                requeues=entry.requeues)
            self._journal_settle(entry.trace_id)
            self._settle_waiters(entry, exc=exc)
            return True
        return False

    def _resolve_failed(self, entry: FleetRequest,
                        exc: BaseException) -> bool:
        if entry._finish(exc=exc):
            self._counts["failed"].inc()
            self._count_error(exc)
            self.flights.finish(entry.trace_id, "failed",
                                code=getattr(exc, "code",
                                             type(exc).__name__),
                                requeues=entry.requeues)
            self._journal_settle(entry.trace_id)
            self._settle_waiters(entry, exc=exc)
            return True
        return False

    def _settle_waiters(self, entry: FleetRequest, *, result=None,
                        rep: Optional[_Replica] = None,
                        exc: Optional[BaseException] = None):
        """Settle the coalition `entry` leads, at its terminal path:
        persist a successful full-fidelity result into the artifact
        store and resolve every follower with the same outcome. Runs on
        whatever thread resolved the leader; never under the fleet lock.
        Followers never settle (their `coalesced` flag short-circuits),
        so a follower failing through _resolve_failed cannot pop a NEW
        leader's coalition registered under the same key after ours."""
        if (self._frontdoor is None or entry.store_key is None
                or entry.coalesced):
            return
        tag, key = entry.store_key
        degraded = rep is not None and rep.name == DEGRADED
        if result is not None and rep is not None and not degraded:
            # persist under the tag of the pool that actually SERVED the
            # request: a failover to another pool means another weight
            # precision / SP plan, i.e. another keyspace — storing it
            # under the preferred pool's tag would alias wrong numerics.
            # Compare TAGS, not pool names: an ESCALATED entry has
            # entry.pool == rep.pool (the verify pool) but a store_key
            # minted at admit time under the DRAFT tag — keying on pool
            # names would persist a full-fidelity result under the draft
            # keyspace (the exact cross-tier aliasing the tags forbid).
            persist = True
            if rep.pool in self._pools:
                serving_tag = self._store_tag(rep.pool)
                if serving_tag != tag:
                    tag = serving_tag
                    f = entry.features
                    key = request_key(f.seq, f.msa, tag,
                                      msa_mask=f.msa_mask)
            if (self._cascade is not None
                    and rep.pool == self._cascade.draft_pool
                    and not entry.draft_accepted):
                # only ACCEPTED drafts may vouch for future lookups under
                # the draft tag; an unscored/rejected draft result (e.g.
                # a finish-race loser) must never enter the store
                persist = False
            if persist:
                # normalize provenance before persisting: a cached
                # artifact carries no replica/latency history (each
                # reader's result() copy re-stamps its own), and
                # from_cache=True by decode
                self._store.put_result(tag, key, dataclasses.replace(
                    result, from_cache=True, latency_s=0.0, replica="",
                    degraded=False, requeues=0, trace_id=""))
        followers = self._frontdoor.settle(entry.store_key)
        # followers are served BY the coalition, not by a dispatch of
        # their own — their copy reads from_cache=True like a store hit
        shared = (None if result is None
                  else dataclasses.replace(result, from_cache=True))
        leader_tier = entry._meta.get("tier", "") if entry.done() else ""
        for follower in followers:
            if shared is not None and rep is not None:
                latency = time.monotonic() - follower.enqueued_at
                if follower._finish(result=shared, replica=rep.name,
                                    degraded=degraded, tier=leader_tier,
                                    latency_s=latency):
                    self._counts["completed"].inc()
                    self._latency.observe(latency)
                    if degraded:
                        self._degraded_total.inc()
                    self.flights.finish(
                        follower.trace_id, "completed", replica=rep.name,
                        pool=rep.pool, degraded=degraded, coalesced=True,
                        leader=entry.trace_id, from_cache=True,
                        bucket=result.bucket, latency_s=round(latency, 6))
                    self._journal_settle(follower.trace_id)
            elif isinstance(exc, QueueFullError):
                self._resolve_shed(follower, "coalesced_leader_shed", exc)
            elif isinstance(exc, RequestTimeoutError):
                # the LEADER's deadline expired; followers carry their
                # own deadlines, but without a leader there is nothing
                # left in flight to serve them — shed with retry advice
                self._resolve_shed(follower, "coalesced_leader_deadline",
                                   exc)
            else:
                self._resolve_failed(
                    follower, exc if exc is not None else ServingError(
                        "coalesced leader resolved without an outcome"))

    # -------------------------------------------------- health callbacks

    def _probe_replica(self, name: str) -> bool:
        """End-to-end heartbeat: one tiny request through the replica's
        real dispatch path (unique sequence per probe so the result
        cache cannot vouch for a dead engine). Restarts the engine first
        if a drain tore it down. Runs on the health thread."""
        with self._lock:
            rep = self._replicas.get(name)
        if rep is None or rep.retiring:
            return False  # mid-retirement: never vouch for a leaving slot
        with self._lock:
            engine = rep.engine
        if engine is None or getattr(engine, "_closed", False):
            engine = rep.factory()
            if engine is None:
                return False
            with self._lock:
                rep.engine = engine
                rep.restarts += 1
        rep.probe_counter += 1
        n, seq = rep.probe_counter, []
        for _ in range(4):  # base-len(AA_ORDER) counter encoding
            seq.append(AA_ORDER[n % len(AA_ORDER)])
            n //= len(AA_ORDER)
        try:
            # probe_span accounts the round trip as "probe" badput MINUS
            # whatever the engine accounts during it (the probe's own
            # execute/compile) — sums-to-wall survives reinstatement
            # probes whose first dispatch compiles
            with self.goodput.probe_span(name):
                req = engine.submit("".join(seq),
                                    timeout=self.cfg.probe_timeout_s)
                req.result(timeout=self.cfg.probe_timeout_s)
            return True
        except (ServingError, TimeoutError):
            return False

    def _drain_replica(self, name: str, reason: str):
        """Health-thread callback: take the sick (or retiring) engine out
        of rotation and fail its queued work BACK through the requeue
        path (shutdown drain=False resolves everything pending with
        EngineClosedError, which `_on_replica_done` converts into
        requeues). Idempotent — a failure drain racing an autoscale
        retirement finds engine=None the second time and only runs the
        retirement bookkeeping (the no-double-drain pin)."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                return
            engine, rep.engine = rep.engine, None
            retiring = rep.retiring
            if retiring:
                # the drain has run: the slot leaves the pool for good
                # (the health monitor unregisters its target right after
                # this callback returns)
                self._replicas.pop(name, None)
        self._up_gauges[name].set(0)
        if self._incident_hook is not None:
            try:
                self._incident_hook("replica_drain", replica=name,
                                    reason=reason)
            except Exception:  # noqa: BLE001 — observability must never
                # take the supervisor down
                traceback.print_exc()
        if engine is not None:
            t0 = time.monotonic()
            engine.shutdown(drain=False, timeout=self.cfg.drain_timeout_s)
            self.goodput.add(name, "drain", time.monotonic() - t0)

    def _reinstate_replica(self, name: str):
        gauge = self._up_gauges.get(name)
        if gauge is not None:
            gauge.set(1)
