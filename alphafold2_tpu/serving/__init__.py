"""Serving layer: request-level inference in front of the model stack.

`predict.py` is a one-shot CLI — one process, one request, a fresh XLA
trace per sequence length. This package is the production front end
(docs/SERVING.md): a pure pipeline function (`pipeline.predict_structure`),
a length-bucket ladder with an AOT-compiled-executable cache
(`bucketing`), a dynamic micro-batching scheduler with bounded-queue
backpressure (`engine.ServingEngine`), a result LRU (`cache`), a
fleet-wide content-addressed artifact store with front-door coalescing
(`artifact_store` + `frontdoor`), and serving metrics with latency
quantiles (`metrics`). `serve.py` at the repo root drives it over a
many-record FASTA as a traffic-replay harness.
"""

from alphafold2_tpu.serving.admission import (
    PRIORITIES,
    AdmissionConfig,
    AdmissionController,
)
from alphafold2_tpu.serving.artifact_store import (
    ArtifactStore,
    ArtifactStoreConfig,
)
from alphafold2_tpu.serving.bucketing import (
    DEFAULT_BUCKETS,
    BucketLadder,
    pad_batch,
)
from alphafold2_tpu.serving.autoscale import ReplicaAutoscaler, ScalePolicy
from alphafold2_tpu.serving.cache import ResultCache, request_key
from alphafold2_tpu.serving.cascade import (
    CascadeLedger,
    CascadePolicy,
    CascadeVerdict,
    ConfidenceScorer,
    EntropyStressScorer,
)
from alphafold2_tpu.serving.engine import (
    PredictionResult,
    ServingConfig,
    ServingEngine,
    ServingRequest,
)
from alphafold2_tpu.serving.errors import (
    CircuitOpenError,
    EngineClosedError,
    FeaturizeError,
    HungBatchError,
    InvalidSequenceError,
    NoHealthyReplicaError,
    PredictionError,
    QueueFullError,
    RequestTimeoutError,
    RequestTooLongError,
    RequeueLimitError,
    RetryBudgetExhaustedError,
    SequenceTooLongError,
    ScaleRejectedError,
    ServingError,
)
from alphafold2_tpu.serving.journal import IntakeJournal, JournalRecord
from alphafold2_tpu.serving.featurize import (
    FeatureBundle,
    FeaturizeConfig,
    FeaturizePool,
    featurize_request,
)
from alphafold2_tpu.serving.fleet import (
    FleetConfig,
    FleetRequest,
    PoolSpec,
    ServingFleet,
)
from alphafold2_tpu.serving.frontdoor import FrontDoor
from alphafold2_tpu.serving.sp_arm import (
    SP_SCHEDULES,
    choose_schedule,
    plan_bucket_schedules,
    schedule_residency,
)
from alphafold2_tpu.serving.metrics import ServingMetrics

# NOTE deliberately NOT re-exported here: serving.pipeline.predict_structure.
# `alphafold2_tpu.training` already package-exports a predict_structure with
# a different signature (E2EConfig -> refined 14-atom cloud); keeping the
# serving one at its module path (`from alphafold2_tpu.serving.pipeline
# import predict_structure`) avoids two same-named siblings whose mixup
# would surface only as a shape error deep in the trunk.

__all__ = [
    "DEFAULT_BUCKETS",
    "PRIORITIES",
    "AdmissionConfig",
    "AdmissionController",
    "ArtifactStore",
    "ArtifactStoreConfig",
    "FrontDoor",
    "BucketLadder",
    "pad_batch",
    "ResultCache",
    "request_key",
    "CascadeLedger",
    "CascadePolicy",
    "CascadeVerdict",
    "ConfidenceScorer",
    "EntropyStressScorer",
    "FeatureBundle",
    "FeaturizeConfig",
    "FeaturizePool",
    "featurize_request",
    "FleetConfig",
    "FleetRequest",
    "IntakeJournal",
    "JournalRecord",
    "PoolSpec",
    "SP_SCHEDULES",
    "choose_schedule",
    "plan_bucket_schedules",
    "schedule_residency",
    "PredictionResult",
    "ReplicaAutoscaler",
    "ScalePolicy",
    "ServingConfig",
    "ServingEngine",
    "ServingFleet",
    "ServingRequest",
    "ServingMetrics",
    "CircuitOpenError",
    "EngineClosedError",
    "FeaturizeError",
    "HungBatchError",
    "InvalidSequenceError",
    "NoHealthyReplicaError",
    "PredictionError",
    "QueueFullError",
    "RequestTimeoutError",
    "RequestTooLongError",
    "RequeueLimitError",
    "RetryBudgetExhaustedError",
    "SequenceTooLongError",
    "ScaleRejectedError",
    "ServingError",
]
