"""Durable intake journal: the crash-safe write-ahead log of accepted work.

Every reliability feature below this layer — breaker, health-monitor
drain/reinstate, featurize requeue, the PR 17 artifact store — protects
requests from REPLICA failure. None of them survives the death of the
serving process itself: every accepted-but-unfinished request lives only
in process memory (admission queue, featurize queue, replica in-flight
sets), so a crash or `kill -9` silently loses all of it and the clients
wait on sockets that will never answer. At ParaFold scale the front door
is the long-lived contract with users; the request plane has to be
durable, not just the replicas behind it.

The journal is a write-ahead intake log, deployed as a sibling of
``--flight-dir`` / the artifact store:

  accept   when the fleet ACCEPTS a request (before any dispatch), one
           record — seq + optional MSA arrays + priority + the ABSOLUTE
           wall-clock deadline — is written to ``<root>/<stem>.jr`` via
           write-to-temp + ``os.replace`` (atomic: a crash mid-write
           leaves a temp file, never a torn record under the final name).
  settle   when the request reaches ANY terminal state (result, typed
           error, shed), its record is unlinked. An absent record IS the
           terminal mark — there is no separate commit record to tear.

On restart, ``pending()`` returns every record that never settled and the
fleet replays each through its normal ``submit()`` path. Idempotence is
by construction, not bookkeeping: a replayed request re-enters front-door
coalescing and the content-addressed artifact store, so work that DID
complete before the crash (result persisted, settle unlink lost) replays
as a store hit, identical replayed payloads coalesce to one dispatch, and
the at-least-once journal yields exactly-zero duplicate chip dispatches.

Same checksum-verify-or-degrade discipline as ``artifact_store.py``: every
record carries a sha256 over its payload (own magic, ``AF2JRN1``), arrays
ride an npz with ``allow_pickle=False`` (a poisoned record can fail a
read, never execute code), and ANY framing/checksum/decode problem counts
into ``journal_corrupt_total``, quarantines (unlinks) the bad record, and
skips it — a torn journal entry degrades to one counted lost request,
never a crash or a wrong answer.

Thread safety: one leaf lock guards the live-record map; all disk I/O and
(de)serialization happen outside it. Record filenames are derived from
the trace id, so concurrent accepts never collide on a path.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
import re
import tempfile
import threading
from typing import List, Optional

import numpy as np

from alphafold2_tpu.telemetry import MetricRegistry

#: on-disk record framing: magic + 64 hex sha256 of the payload + "\n" + payload
_MAGIC = b"AF2JRN1\n"
_HEADER_LEN = len(_MAGIC) + 64 + 1

_RECORD_SUFFIX = ".jr"
_STEM_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


class JournalCorruptError(Exception):
    """A journal record failed framing/checksum/decode validation."""


def _read_bytes(path: str) -> bytes:
    """The read seam (artifact_store stance): module-level so tests can
    interpose torn/vanished reads without monkeypatching builtins."""
    with open(path, "rb") as fh:
        return fh.read()


def _stem(trace_id: str) -> str:
    """Filesystem-safe record name for a trace id. Fleet-minted ids are
    16 hex chars and pass through unchanged; a caller-supplied id with
    hostile characters gets a stable digest stem (the real id still
    rides the record meta)."""
    if _STEM_RE.match(trace_id):
        return trace_id
    return "x" + hashlib.sha256(trace_id.encode()).hexdigest()[:32]


@dataclasses.dataclass(frozen=True)
class JournalRecord:
    """One accepted-but-unsettled request, as recovered by `pending()`.
    `deadline_unix` is ABSOLUTE wall-clock (time.time) or None — a
    relative deadline would silently extend across a restart."""

    trace_id: str
    seq: str
    msa: Optional[np.ndarray]
    msa_mask: Optional[np.ndarray]
    priority: int
    deadline_unix: Optional[float]
    accepted_at_unix: float


def _pack_record(rec: JournalRecord) -> bytes:
    arrays = {}
    if rec.msa is not None:
        arrays["msa"] = np.ascontiguousarray(rec.msa)
    if rec.msa_mask is not None:
        arrays["msa_mask"] = np.ascontiguousarray(rec.msa_mask)
    meta = {
        "v": 1,
        "trace_id": rec.trace_id,
        "seq": rec.seq,
        "priority": int(rec.priority),
        "deadline_unix": rec.deadline_unix,
        "accepted_at_unix": rec.accepted_at_unix,
    }
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    blob = buf.getvalue()
    digest = hashlib.sha256(blob).hexdigest().encode()
    return _MAGIC + digest + b"\n" + blob


def _unpack_record(data: bytes) -> JournalRecord:
    """Inverse of `_pack_record`; raises JournalCorruptError on ANY
    framing, checksum, or decode problem (one failure class: counted
    skip)."""
    if len(data) < _HEADER_LEN or not data.startswith(_MAGIC):
        raise JournalCorruptError("bad magic / truncated header")
    digest = data[len(_MAGIC):len(_MAGIC) + 64]
    if data[_HEADER_LEN - 1:_HEADER_LEN] != b"\n":
        raise JournalCorruptError("bad header framing")
    blob = data[_HEADER_LEN:]
    if hashlib.sha256(blob).hexdigest().encode() != digest:
        raise JournalCorruptError("payload checksum mismatch")
    try:
        with np.load(io.BytesIO(blob), allow_pickle=False) as z:
            meta = json.loads(bytes(z["__meta__"].tobytes()).decode())
            msa = z["msa"] if "msa" in z.files else None
            msa_mask = z["msa_mask"] if "msa_mask" in z.files else None
    except JournalCorruptError:
        raise
    except Exception as e:  # np.load / json raise a zoo of types
        raise JournalCorruptError(f"payload decode failed: {e!r}") from e
    if meta.get("v") != 1 or "trace_id" not in meta or "seq" not in meta:
        raise JournalCorruptError(f"bad record meta: {meta!r}")
    return JournalRecord(
        trace_id=str(meta["trace_id"]),
        seq=str(meta["seq"]),
        msa=msa,
        msa_mask=msa_mask,
        priority=int(meta.get("priority", 0)),
        deadline_unix=(None if meta.get("deadline_unix") is None
                       else float(meta["deadline_unix"])),
        accepted_at_unix=float(meta.get("accepted_at_unix", 0.0)),
    )


class IntakeJournal:
    """Write-ahead intake journal over one directory.

    One instance per serving process; multiple processes may point at the
    same root (records are per-trace files, writes are atomic), though
    replay is meant to run before traffic is admitted.
    """

    def __init__(self, root: str, registry: Optional[MetricRegistry] = None):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self._live = {}  # stem -> path of accepted, not-yet-settled records
        self._accepted = 0
        self._settled = 0
        self._corrupt = 0
        self._write_errors = 0
        self._registry = registry
        if registry is not None:
            self.bind_registry(registry)

    def bind_registry(self, registry: MetricRegistry) -> "IntakeJournal":
        self._registry = registry
        registry.gauge(
            "journal_pending",
            help="journal records accepted but not yet settled",
        ).set(self.pending_count())
        return self

    def _count(self, event: str):
        reg = self._registry
        if reg is not None:
            reg.counter("journal_records_total", event=event).inc()
            with self._lock:
                pending = len(self._live)
            reg.gauge("journal_pending").set(pending)

    # ------------------------------------------------------------- lifecycle

    def accept(self, trace_id: str, seq: str, *,
               msa: Optional[np.ndarray] = None,
               msa_mask: Optional[np.ndarray] = None,
               priority: int = 0,
               deadline_unix: Optional[float] = None,
               accepted_at_unix: float = 0.0) -> bool:
        """Durably record an accepted request BEFORE any dispatch work.
        Returns False (and counts a write_error) if the disk write failed
        — the journal degrades to best-effort rather than failing the
        request it was meant to protect."""
        rec = JournalRecord(
            trace_id=trace_id, seq=seq, msa=msa, msa_mask=msa_mask,
            priority=priority, deadline_unix=deadline_unix,
            accepted_at_unix=accepted_at_unix,
        )
        stem = _stem(trace_id)
        path = os.path.join(self.root, stem + _RECORD_SUFFIX)
        try:
            blob = _pack_record(rec)
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as fh:
                    fh.write(blob)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except OSError:
            with self._lock:
                self._write_errors += 1
            self._count("write_error")
            return False
        with self._lock:
            self._accepted += 1
            self._live[stem] = path
        self._count("accept")
        return True

    def settle(self, trace_id: str) -> bool:
        """Mark a request terminal: unlink its record (the absent record
        IS the terminal mark — nothing to tear). Unknown / already-settled
        ids no-op cheaply; crash between the request's completion and this
        unlink is safe because replay is idempotent through the artifact
        store."""
        stem = _stem(trace_id)
        with self._lock:
            path = self._live.pop(stem, None)
            if path is not None:
                self._settled += 1
        if path is None:
            return False
        try:
            os.unlink(path)
        except OSError:
            pass  # already gone (concurrent settle / external sweep)
        self._count("settle")
        return True

    # ------------------------------------------------------------- recovery

    def pending(self) -> List[JournalRecord]:
        """Scan the root for unsettled records (a RESTART's view — also
        adopts records written by a previous process). A corrupt/torn
        record counts into `journal_corrupt_total`, is quarantined
        (unlinked), and skipped — never a crash."""
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return []
        out: List[JournalRecord] = []
        reg = self._registry
        for name in names:
            if not name.endswith(_RECORD_SUFFIX):
                if name.endswith(".tmp"):
                    # a crash mid-accept: the temp never reached its
                    # final name, so the request was never accepted —
                    # sweep the debris
                    try:
                        os.unlink(os.path.join(self.root, name))
                    except OSError:
                        pass
                continue
            path = os.path.join(self.root, name)
            try:
                rec = _unpack_record(_read_bytes(path))
            except (JournalCorruptError, OSError):
                with self._lock:
                    self._corrupt += 1
                if reg is not None:
                    reg.counter(
                        "journal_corrupt_total",
                        help="journal records dropped for failed "
                             "framing/checksum/decode",
                    ).inc()
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            stem = name[:-len(_RECORD_SUFFIX)]
            with self._lock:
                self._live[stem] = path
            out.append(rec)
        if reg is not None:
            reg.gauge("journal_pending").set(self.pending_count())
        return out

    # ------------------------------------------------------------- reading

    def pending_count(self) -> int:
        with self._lock:
            return len(self._live)

    def stats(self) -> dict:
        with self._lock:
            return {
                "root": self.root,
                "pending": len(self._live),
                "accepted": self._accepted,
                "settled": self._settled,
                "corrupt": self._corrupt,
                "write_errors": self._write_errors,
            }
