"""Length-bucket ladder + shape padding for the compiled-executable cache.

XLA specializes every executable to concrete input shapes, so a naive
serving loop pays a full trace+compile for EVERY new sequence length — on
real traffic that is a compile per request (HelixFold, arxiv 2207.05477,
measures exactly this failure mode). The fix is a fixed ladder of padded
lengths: a request of length L runs at the smallest bucket >= L, so an
arbitrary stream of lengths compiles at most `len(buckets)` executables,
ever. Padding is masked end to end (serving/pipeline.py): excluded from
attention, zero-weighted AND zero-distanced in the MDS objective,
zero-confidence in the output. One residual bucket sensitivity is
geometric — Torgerson double-centering and the Guttman `/n` step see the
padded matrix size — so a structure is a deterministic function of
(sequence, bucket): identical across batches and replicas, but not
bit-identical across DIFFERENT ladders (the engine's cache tag includes
the ladder for exactly this reason).

Batch rows are padded the same way: a partial batch is topped up by
DUPLICATING the last real row rather than all-pad rows. Duplicate rows
cost the same FLOPs, but keep every per-structure quantity finite — an
all-pad row has an all-zero MDS weight matrix, which turns the per-row
normalized stress into 0/0 NaNs.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import numpy as np

from alphafold2_tpu.constants import PAD_TOKEN_ID
from alphafold2_tpu.serving.errors import SequenceTooLongError

# ladder for real traffic: fine-grained at the short end where most
# sequences live, coarse past the median protein length
DEFAULT_BUCKETS: Tuple[int, ...] = (64, 128, 256, 384, 512)


@dataclasses.dataclass(frozen=True)
class BucketLadder:
    """Sorted, deduplicated ladder of padded sequence lengths."""

    buckets: Tuple[int, ...] = DEFAULT_BUCKETS

    def __post_init__(self):
        cleaned = tuple(sorted({int(b) for b in self.buckets}))
        if not cleaned:
            raise ValueError("bucket ladder must have at least one bucket")
        if cleaned[0] <= 0:
            raise ValueError(f"buckets must be positive, got {cleaned}")
        object.__setattr__(self, "buckets", cleaned)

    def __len__(self) -> int:
        return len(self.buckets)

    @property
    def max_len(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, length: int) -> int:
        """Smallest bucket that fits `length`; raises SequenceTooLongError
        (stable code `sequence_too_long`) past the top of the ladder — an
        explicit rejection the client can route to a bigger deployment,
        not a silent truncation. The fleet's length-adaptive router uses
        the UNION ladder here, so "too long" always means "no capability
        pool can serve it", the same signal the single engine raises."""
        if length <= 0:
            raise ValueError(f"sequence length must be positive, got {length}")
        for b in self.buckets:
            if length <= b:
                return b
        raise SequenceTooLongError(
            f"sequence length {length} exceeds the largest bucket "
            f"{self.max_len} (ladder: {self.buckets})"
        )


def batch_shape_ladder(max_batch: int) -> Tuple[int, ...]:
    """Power-of-two batch shapes {1, 2, 4, ...} up to `max_batch`.

    The batch-dim twin of the length ladder above: with it, a partial
    batch runs an executable compiled at the smallest rung >= its live
    count instead of paying phantom-row chip time at the full
    `max_batch` shape. `max_batch` itself is always the top rung even
    when it is not a power of two, so a full batch never splits.
    """
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    shapes = []
    b = 1
    while b < max_batch:
        shapes.append(b)
        b *= 2
    shapes.append(int(max_batch))
    return tuple(shapes)


def pad_tokens(tokens: np.ndarray, bucket: int):
    """(L,) int tokens -> ((bucket,) padded tokens, (bucket,) bool mask).
    Padding depends only on the target length, not on ladder state."""
    tokens = np.asarray(tokens, np.int32)
    length = tokens.shape[0]
    if length > bucket:
        raise ValueError(f"length {length} does not fit bucket {bucket}")
    out = np.full((bucket,), PAD_TOKEN_ID, np.int32)
    out[:length] = tokens
    mask = np.zeros((bucket,), bool)
    mask[:length] = True
    return out, mask


def pad_batch(rows: Sequence[np.ndarray], bucket: int, max_batch: int):
    """Assemble per-request token rows into one fixed-shape batch.

    Args:
      rows: 1..max_batch arrays of (L_i,) int tokens, each L_i <= bucket.
      bucket: padded length.
      max_batch: fixed batch dimension of the compiled executable.

    Returns:
      tokens: (max_batch, bucket) int32 — unused slots duplicate the last
        real row (see module docstring for why not all-pad).
      mask: (max_batch, bucket) bool — duplicate slots carry the
        duplicated row's real mask so their compute stays finite; callers
        slice results by `n_real` and never read duplicate slots.
      n_real: number of real rows.
    """
    if not rows:
        raise ValueError("pad_batch needs at least one row")
    if len(rows) > max_batch:
        raise ValueError(f"{len(rows)} rows exceed max_batch {max_batch}")
    tokens = np.empty((max_batch, bucket), np.int32)
    mask = np.empty((max_batch, bucket), bool)
    for i, row in enumerate(rows):
        tokens[i], mask[i] = pad_tokens(row, bucket)
    for i in range(len(rows), max_batch):
        tokens[i], mask[i] = tokens[len(rows) - 1], mask[len(rows) - 1]
    return tokens, mask, len(rows)
