"""CPU featurization tier: feature prep off the dispatch path.

Every served request needs host-side feature preparation before a chip
can see it — strict tokenization, MSA stream normalization/validation,
bucket assignment. Until this module that work ran INLINE: on the
client's submit() thread (fleet front door) and again per replica on
the engine worker that also owns device dispatch, so a burst of long
MSAs could starve the thread whose only irreplaceable job is keeping
the accelerator fed. This is the ParaFold split (arxiv 2111.06340):
CPU featurization and accelerator inference are separately-provisioned
tiers, so serving throughput tracks chip count instead of
preprocessing.

  `featurize_request`   the PURE featurization function — one place for
                        tokenize + MSA checks + bucket choice, shared
                        by the pool workers and every inline caller
                        (engine submit validation), which is what keeps
                        the tiered and inline paths bit-exact: the tier
                        changes WHERE features are computed, never what.
  `FeaturizePool`       a separately-sized CPU worker pool with its own
                        bounded queue and backpressure (`QueueFullError`
                        with an honest drain-rate `retry_after_s`),
                        per-stage spans (`featurize.queue_wait` /
                        `featurize.run`) and metrics, sitting in FRONT
                        of the fleet's admission controller
                        (serving/fleet.py wires it): raw-sequence
                        requests enter here; pre-featurized
                        `FeatureBundle` submissions bypass the tier
                        entirely.

Failure model: a job whose featurization raises a `ServingError`
(invalid residues, oversize sequence, malformed MSA) keeps that sharp
semantic error; an unexpected exception becomes `FeaturizeError`. A
worker THREAD death (`reliability` injects one via
`kill_featurize_worker`; an organic bug would look identical) respawns
the worker and requeues the in-flight job at the FRONT of the queue —
bounded by `retry_limit`, past which the job fails with
`FeaturizeError` instead of ping-ponging through dying workers. Nothing
is ever silently lost: every submitted job reaches its `on_done`
callback exactly once.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
import traceback
from typing import Callable, Optional

import numpy as np

from alphafold2_tpu.constants import aa_to_tokens
from alphafold2_tpu.serving.bucketing import BucketLadder
from alphafold2_tpu.serving.errors import (
    EngineClosedError,
    FeaturizeError,
    InvalidSequenceError,
    QueueFullError,
    RequestTimeoutError,
    RetryBudgetExhaustedError,
    ServingError,
)
from alphafold2_tpu.telemetry import NULL_TRACER, MetricRegistry


@dataclasses.dataclass
class FeatureBundle:
    """One request's prepared features (host numpy, pre-bucket-padding).

    Deterministic function of the raw inputs (`featurize_request`), so
    a bundle computed on a pool worker, inline on a submit thread, or
    by the client itself (the pre-featurized bypass) is interchangeable
    — the engine's cache keys and the fleet's bit-exactness pins see
    identical arrays either way. That determinism is also what lets the
    fleet artifact store (serving/artifact_store.py) persist bundles
    under a content hash and replay them across requeues, retries, and
    re-submissions: a stored bundle IS the recomputation, byte for
    byte, so the featurize tier is skipped entirely on a hit."""

    seq: str                      # normalized (stripped, uppercased)
    tokens: np.ndarray            # (L,) int32 strict tokenization
    msa: Optional[np.ndarray]     # (rows, L) int32, or None
    msa_mask: Optional[np.ndarray]  # (rows, L) bool, or None
    bucket: int                   # assigned ladder bucket

    @property
    def length(self) -> int:
        return int(self.tokens.shape[0])


def featurize_request(seq: str, msa=None, msa_mask=None, *,
                      ladder: BucketLadder,
                      msa_rows: int = 0) -> FeatureBundle:
    """The one featurization function: normalize + tokenize + validate +
    bucket. Raises the same typed ServingErrors the engine's inline
    validation always raised (InvalidSequenceError, RequestTooLongError
    via the ladder, plain ServingError for MSA-shape problems), so the
    tier's error surface is the inline path's error surface."""
    seq = seq.strip().upper()
    try:
        tokens = aa_to_tokens(seq, strict=True)
    except ValueError as e:
        raise InvalidSequenceError(str(e)) from None
    bucket = ladder.bucket_for(len(seq))

    msa_arr = None
    if msa is None and msa_mask is not None:
        raise ServingError("msa_mask given without msa")
    if msa is not None:
        if msa_rows == 0:
            raise ServingError(
                "engine is configured sequence-only (msa_rows=0); "
                "rebuild with ServingConfig(msa_rows=N) to serve MSAs"
            )
        msa_arr = np.asarray(msa, np.int32)
        if msa_arr.ndim != 2 or msa_arr.shape[1] != len(seq):
            raise ServingError(
                f"msa must be (rows, {len(seq)}) tokens, got {msa_arr.shape}"
            )
        if msa_arr.shape[0] > msa_rows:
            raise ServingError(
                f"msa has {msa_arr.shape[0]} rows; this engine serves at "
                f"most msa_rows={msa_rows} — subsample client-side or "
                f"deploy with a larger msa_rows"
            )
        if msa_mask is not None:
            msa_mask = np.asarray(msa_mask, bool)
            if msa_mask.shape != msa_arr.shape:
                raise ServingError(
                    f"msa_mask shape {msa_mask.shape} does not match msa "
                    f"shape {msa_arr.shape}"
                )
    return FeatureBundle(seq=seq, tokens=tokens, msa=msa_arr,
                         msa_mask=msa_mask, bucket=bucket)


@dataclasses.dataclass(frozen=True)
class FeaturizeConfig:
    """Featurize-tier sizing knobs (docs/SERVING.md "The featurization
    tier"). Sized independently of the replica pool — that independence
    is the tier's reason to exist."""

    workers: int = 2            # CPU featurization threads
    queue_capacity: int = 128   # bounded job queue (backpressure point)
    retry_limit: int = 1        # worker-death requeues per job
    min_retry_after_s: float = 0.05
    max_retry_after_s: float = 60.0
    ema_alpha: float = 0.2      # featurize-seconds EMA (retry_after basis)

    def __post_init__(self):
        if self.workers < 1:
            raise ValueError(f"workers must be >= 1, got {self.workers}")
        if self.queue_capacity < 1:
            raise ValueError(
                f"queue_capacity must be >= 1, got {self.queue_capacity}"
            )
        if self.retry_limit < 0:
            raise ValueError(
                f"retry_limit must be >= 0, got {self.retry_limit}"
            )


class _Job:
    __slots__ = ("seq", "msa", "msa_mask", "trace_id", "on_done",
                 "retries", "enqueued_at", "deadline")

    def __init__(self, seq, msa, msa_mask, trace_id, on_done,
                 deadline=None):
        self.seq = seq
        self.msa = msa
        self.msa_mask = msa_mask
        self.trace_id = trace_id
        self.on_done = on_done
        self.retries = 0
        self.enqueued_at = time.monotonic()
        self.deadline = deadline  # monotonic, or None


class FeaturizePool:
    """Bounded-queue CPU featurization worker pool (module docstring).

    Args:
      cfg: `FeaturizeConfig`.
      ladder / msa_rows: the serving tier's bucket ladder and MSA-row
        bound — featurization must agree with the engines it feeds.
      registry: metric sink (featurize_* families); None = fresh.
      tracer: span sink; `featurize.run` spans carry the job trace_id.
      fault_hook: chaos seam (`FaultInjector.featurize_hook()`): called
        with the pool's job index at the top of every job. A raised
        `WorkerKilled` kills THIS worker thread (respawned; job
        requeued); any other exception fails the job.
      incident_hook: optional `fn(kind, **attrs)` — worker deaths are
        reported as `featurize_worker_death` (flight-recorder seam).
    """

    def __init__(self, cfg: FeaturizeConfig, ladder: BucketLadder, *,
                 msa_rows: int = 0,
                 registry: Optional[MetricRegistry] = None,
                 tracer=None, fault_hook=None, incident_hook=None,
                 retry_budget=None):
        self.cfg = cfg
        self._ladder = ladder
        self._msa_rows = msa_rows
        self.registry = registry if registry is not None else MetricRegistry()
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self._fault_hook = fault_hook
        self._incident_hook = incident_hook
        # optional shared reliability.RetryBudget: worker-death requeues
        # draw from the same fleet-wide bucket as failovers and hedges —
        # during a brownout the tier sheds instead of ping-ponging jobs
        # through dying workers
        self._retry_budget = retry_budget

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._jobs: "collections.deque[_Job]" = collections.deque()
        self._closed = False
        self._drain_on_stop = True
        self._job_counter = 0
        self._inflight = 0
        self._ema_s: Optional[float] = None
        self._worker_seq = 0
        self._workers = {}  # thread name -> Thread

        self._counts = {
            name: self.registry.counter(
                "featurize_requests_total",
                help="featurize-tier job outcomes", outcome=name)
            for name in ("submitted", "completed", "failed", "requeued")
        }
        self._seconds = self.registry.histogram(
            "featurize_seconds",
            help="per-job CPU featurization seconds, sliding window")
        self._depth_gauge = self.registry.gauge(
            "featurize_queue_depth", help="featurize-tier queue depth")
        self._deaths = self.registry.counter(
            "featurize_worker_deaths_total",
            help="featurize worker threads that died and were respawned")
        self._busy = self.registry.gauge(
            "featurize_busy_seconds_total",
            help="cumulative featurize worker busy seconds (the overlap "
                 "bench's CPU-side numerator)")
        self._expired = self.registry.counter(
            "featurize_expired_total",
            help="jobs dropped before featurizing because their fleet "
                 "deadline had already passed in the queue")

        for _ in range(cfg.workers):
            self._spawn_worker()

    # ----------------------------------------------------------------- API

    def submit(self, seq: str, msa=None, msa_mask=None, *,
               trace_id: str = "",
               deadline: Optional[float] = None,
               on_done: Callable[[Optional[FeatureBundle],
                                  Optional[BaseException]], None]):
        """Enqueue one featurization job; `on_done(bundle, exc)` runs
        exactly once, on a pool worker thread (or on the shutdown
        thread for jobs failed at close). Raises QueueFullError
        synchronously — featurize backpressure is explicit, like every
        other queue in the serving stack. `deadline` (monotonic, the
        fleet request's own) lets a worker drop a job whose deadline
        passed while it queued — dead-on-arrival work never burns a
        featurize slot (`featurize_expired_total`; the job finishes with
        RequestTimeoutError)."""
        with self._lock:
            if self._closed:
                raise EngineClosedError("featurize pool is shut down")
            if len(self._jobs) >= self.cfg.queue_capacity:
                raise QueueFullError(
                    f"featurize queue at capacity "
                    f"({self.cfg.queue_capacity}); retry with backoff",
                    retry_after_s=self._retry_after_locked(),
                )
            self._counts["submitted"].inc()
            self._jobs.append(_Job(seq, msa, msa_mask, trace_id, on_done,
                                   deadline))
            self._cond.notify()

    def depth(self) -> int:
        with self._lock:
            return len(self._jobs)

    def sample_gauges(self):
        """Ticker hook: publish the live queue depth so `/metrics`
        scrapes see featurize pressure between jobs."""
        self._depth_gauge.set(self.depth())

    def retry_after_s(self) -> float:
        with self._lock:
            return self._retry_after_locked()

    def _retry_after_locked(self) -> float:
        est = ((self._ema_s or 0.05) * max(1, len(self._jobs))
               / max(1, self.cfg.workers))
        return float(min(self.cfg.max_retry_after_s,
                         max(self.cfg.min_retry_after_s, est)))

    def stats(self) -> dict:
        with self._lock:
            depth, inflight = len(self._jobs), self._inflight
            workers = sum(1 for t in self._workers.values() if t.is_alive())
        return {
            "workers": workers,
            "configured_workers": self.cfg.workers,
            "queue_depth": depth,
            "queue_capacity": self.cfg.queue_capacity,
            "in_flight": inflight,
            "requests": {k: int(c.value) for k, c in self._counts.items()},
            "worker_deaths": int(self._deaths.value),
            "busy_seconds": float(self._busy.value),
            "seconds": self._seconds.snapshot(),
            "retry_after_s": self.retry_after_s(),
            "closed": self._closed,
        }

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop the pool. drain=True featurizes what is queued first;
        drain=False (and anything left after a timed-out drain) fails
        with EngineClosedError through on_done — owners always hear the
        outcome. Idempotent."""
        with self._lock:
            self._closed = True
            self._drain_on_stop = drain
            self._cond.notify_all()
            workers = list(self._workers.values())
        for t in workers:
            t.join(timeout)
        leftovers = []
        with self._lock:
            while self._jobs:
                leftovers.append(self._jobs.popleft())
        for job in leftovers:
            self._finish(job, None, EngineClosedError(
                "featurize pool shut down before the job ran"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=True)
        return False

    # -------------------------------------------------------------- workers

    def _spawn_worker(self):
        with self._lock:
            if self._closed:
                return
            self._worker_seq += 1
            name = f"af2-featurize-{self._worker_seq}"
            t = threading.Thread(target=self._worker_loop, args=(name,),
                                 name=name, daemon=True)
            self._workers[name] = t
        t.start()

    def _worker_loop(self, name: str):
        while True:
            with self._lock:
                while not self._jobs and not self._closed:
                    self._cond.wait(0.1)
                # closed: drain=False leaves the queue for the shutdown
                # thread to fail; drain=True keeps claiming until empty
                if self._closed and (not self._drain_on_stop
                                     or not self._jobs):
                    return
                if not self._jobs:
                    continue  # spurious wake
                job = self._jobs.popleft()
                self._inflight += 1
                idx = self._job_counter
                self._job_counter += 1
            try:
                self._run_job(job, idx)
            except _WorkerDeath as death:
                self._on_worker_death(name, job, death)
                return  # the thread is "dead"; a replacement is running
            finally:
                with self._lock:
                    self._inflight -= 1

    def _run_job(self, job: _Job, idx: int):
        from alphafold2_tpu.reliability.faults import WorkerKilled

        wait = time.monotonic() - job.enqueued_at
        if self._tracer.enabled:
            self._tracer.add("featurize.queue_wait", wait, cat="featurize",
                             trace_id=job.trace_id)
        if job.deadline is not None and time.monotonic() >= job.deadline:
            # the fleet deadline passed while the job queued: CPU spent
            # featurizing it would be pure waste — drop before the work,
            # with the same typed timeout the dispatch path would raise
            self._expired.inc()
            self._finish(job, None, RequestTimeoutError(
                f"deadline passed after {wait:.3f}s in the featurize "
                f"queue", retry_after_s=self.retry_after_s()))
            return
        t0 = time.monotonic()
        try:
            with self._tracer.span("featurize.run", cat="featurize",
                                   length=len(job.seq),
                                   trace_id=job.trace_id):
                if self._fault_hook is not None:
                    self._fault_hook(idx)
                bundle = featurize_request(
                    job.seq, job.msa, job.msa_mask,
                    ladder=self._ladder, msa_rows=self._msa_rows,
                )
        except WorkerKilled as e:
            # not a job outcome: the WORKER dies (re-raised past the
            # loop's claim bookkeeping); the job rides along for requeue
            raise _WorkerDeath(job, e)
        except ServingError as e:
            # semantic rejection: the request's own sharp error code
            self._finish(job, None, e)
            return
        except Exception as e:  # noqa: BLE001 — isolate to the job
            err = FeaturizeError(
                f"featurization failed: {type(e).__name__}: {e}")
            err.__cause__ = e
            self._finish(job, None, err)
            return
        finally:
            dt = time.monotonic() - t0
            self._busy.inc(dt)
            self._seconds.observe(dt)
            with self._lock:
                a = self.cfg.ema_alpha
                self._ema_s = (dt if self._ema_s is None
                               else a * dt + (1 - a) * self._ema_s)
        self._finish(job, bundle, None)

    def _on_worker_death(self, name: str, job: _Job, death: "_WorkerDeath"):
        """A worker thread died mid-job: respawn capacity first, then
        requeue the victim job at the FRONT of the queue (it has waited
        longest), bounded by retry_limit."""
        self._deaths.inc()
        if self._incident_hook is not None:
            try:
                self._incident_hook("featurize_worker_death", worker=name,
                                    retries=job.retries)
            except Exception:  # noqa: BLE001 — observability must never
                # take the tier down
                traceback.print_exc()
        with self._lock:
            self._workers.pop(name, None)
        self._spawn_worker()
        if job.retries >= self.cfg.retry_limit:
            err = FeaturizeError(
                f"featurize job lost to {job.retries + 1} worker "
                f"death(s) (retry_limit {self.cfg.retry_limit})")
            err.__cause__ = death.cause
            self._finish(job, None, err)
            return
        if (self._retry_budget is not None
                and not self._retry_budget.try_spend("featurize")):
            # fleet-wide brownout: the requeue would be amplification —
            # shed the job with honest backoff advice instead
            self._finish(job, None, RetryBudgetExhaustedError(
                "featurize requeue denied: fleet-wide retry budget "
                "exhausted",
                retry_after_s=self._retry_budget.retry_after_s()))
            return
        job.retries += 1
        self._counts["requeued"].inc()
        with self._lock:
            if self._closed and not self._drain_on_stop:
                pass  # fall through: fail below, outside the lock
            else:
                self._jobs.appendleft(job)
                self._cond.notify()
                return
        self._finish(job, None, EngineClosedError(
            "featurize pool shut down before the job ran"))

    def _finish(self, job: _Job, bundle, exc):
        if exc is None:
            self._counts["completed"].inc()
        else:
            self._counts["failed"].inc()
        try:
            job.on_done(bundle, exc)
        except Exception:  # noqa: BLE001 — a callback bug must not kill
            # the worker (the engine-request callback stance)
            traceback.print_exc()


class _WorkerDeath(BaseException):
    """Internal control-flow carrier: a WorkerKilled fault travels past
    the per-job guards to the worker loop with its job attached.
    BaseException so a generic `except Exception` job guard can never
    swallow a worker death into a mere job failure."""

    def __init__(self, job: _Job, cause: BaseException):
        super().__init__(str(cause))
        self.job = job
        self.cause = cause
