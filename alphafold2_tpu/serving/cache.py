"""Result LRU cache: repeated queries are free.

Keyed by (sequence, MSA content hash + mask, engine config tag) so a hit
is guaranteed to be the byte-identical computation — two deployments of
the same engine config produce interchangeable keys, while changing any
knob that alters the numerics invalidates cleanly. The engine's config
tag covers the model config, MDS knobs, seed, checkpoint fingerprint,
AND the bucket ladder: a structure is a deterministic function of
(sequence, bucket) — Torgerson centering and the Guttman step see the
padded matrix size (serving/bucketing.py) — so a different ladder is a
different computation.

The key's config tag also versions on the kernel-dispatch
`resolution_tag` (ops/dispatch.py) and the deploy's `params_tag`
(rolling updates re-key the cache rather than serving the old weights'
structures; see ServingConfig.params_tag), both folded into the
engine's `config_tag` — and, one tier up, into the fleet store tags.

This per-engine LRU is TIER ONE of a two-tier memoization scheme. The
fleet-wide artifact store (serving/artifact_store.py) COMPOSES with it
— it does not replace it: the fleet tier intercepts at the front door
(before routing, shared across replicas and pools, persisted to disk),
while this LRU still absorbs repeats that reach one engine directly
(single-engine deployments, fleet probe traffic, replica-local retry
storms). Both tiers key on `request_key` with config-tag inputs drawn
from the same knobs, so an invalidation event (redeploy, precision
change, kernel arm flip) re-keys them in lockstep.
"""

from __future__ import annotations

import collections
import hashlib
import threading
from typing import Any, Optional

import numpy as np


def request_key(seq: str, msa: Optional[np.ndarray], config_tag: str,
                msa_mask: Optional[np.ndarray] = None) -> str:
    """Stable content hash for one request against one engine config.

    `config_tag` is the engine's repr of everything numerically relevant
    (model config, mds knobs, params fingerprint, kernel resolution tag,
    params_tag — see `ServingEngine.config_tag`); `msa` and `msa_mask`
    are hashed by bytes so equal alignments hit regardless of object
    identity. The mask is part of the key: the same alignment under a
    different mask is a different computation.

    The same function keys the fleet artifact store: the fleet passes
    its per-pool store tag (engine config-tag inputs + the pool ladder
    and SP plan, prefixed "af2store:") or the feature tag ("af2feat:")
    as `config_tag`, so one hashing scheme addresses every memoization
    tier and a key can never collide across tiers or deploys.
    """
    h = hashlib.sha256()
    h.update(config_tag.encode())
    h.update(b"\x00seq\x00")
    h.update(seq.encode())
    if msa is not None:
        arr = np.ascontiguousarray(np.asarray(msa, np.int32))
        h.update(b"\x00msa\x00")
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    if msa_mask is not None:
        arr = np.ascontiguousarray(np.asarray(msa_mask, bool))
        h.update(b"\x00msa_mask\x00")
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


class ResultCache:
    """Thread-safe LRU over prediction results — the PER-ENGINE tier.

    capacity=0 disables caching (every get misses, puts are dropped) —
    the engine code path stays identical either way.

    In a fleet this sits UNDER the fleet-wide artifact store
    (serving/artifact_store.py): the store absorbs cross-replica and
    cross-restart repeats at the front door, this LRU absorbs whatever
    still reaches its engine. They compose; neither replaces the other.
    """

    def __init__(self, capacity: int = 256):
        if capacity < 0:
            raise ValueError(f"capacity must be >= 0, got {capacity}")
        self.capacity = capacity
        self._data: "collections.OrderedDict[str, Any]" = collections.OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: str):
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
                self.hits += 1
                return self._data[key]
            self.misses += 1
            return None

    def put(self, key: str, value):
        if self.capacity == 0:
            return
        with self._lock:
            self._data[key] = value
            self._data.move_to_end(key)
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def snapshot(self) -> dict:
        with self._lock:
            hits, misses, size = self.hits, self.misses, len(self._data)
        total = hits + misses
        return {
            "hits": hits,
            "misses": misses,
            "size": size,
            "capacity": self.capacity,
            "hit_rate": (hits / total) if total else 0.0,
        }
