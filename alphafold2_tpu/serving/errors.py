"""Serving-engine exception taxonomy.

Every rejection the engine can hand a client is an explicit, typed error
— the backpressure contract is "fail loudly, never block silently"
(docs/SERVING.md). Kept in their own module so `bucketing`, `cache`, and
`engine` can share them without import cycles.

Every error carries a STABLE `code` string (the wire/ops identifier:
error-rate dashboards, client retry policies, and the engine's per-code
counters in `stats()["errors"]` all key on it — renaming a code is a
breaking API change) and serializes with `to_json()` for HTTP front ends.
"""

from __future__ import annotations


class ServingError(Exception):
    """Base class for all serving-engine errors."""

    code = "serving_error"

    def to_json(self) -> dict:
        """Wire-format payload: stable code + human-readable message."""
        return {
            "code": self.code,
            "error": type(self).__name__,
            "message": str(self),
        }


class InvalidSequenceError(ServingError):
    """Request sequence contains characters outside the residue vocabulary
    (constants.aa_to_tokens strict mode) or is empty."""

    code = "invalid_sequence"


class RequestTooLongError(ServingError):
    """Request sequence is longer than the largest configured bucket."""

    code = "request_too_long"


class QueueFullError(ServingError):
    """The bounded request queue is at capacity. Backpressure is explicit:
    the caller decides whether to retry, shed, or escalate — the engine
    never blocks a submitter."""

    code = "queue_full"


class RequestTimeoutError(ServingError):
    """The request's deadline passed before it was dispatched to the
    model (scheduler-side expiry)."""

    code = "request_timeout"


class PredictionError(ServingError):
    """The model call for this request raised. The original exception is
    chained as ``__cause__``; the engine itself keeps serving."""

    code = "prediction_failed"


class EngineClosedError(ServingError):
    """The engine is shut down (or shutting down without draining); the
    request was not and will not be served."""

    code = "engine_closed"


class CircuitOpenError(ServingError):
    """The circuit breaker is open: recent dispatches failed consecutively
    past the threshold, so the engine fast-rejects instead of queueing
    work it expects to fail. Retry after the breaker's reset window
    (reliability.breaker; `stats()["breaker"]` shows the state)."""

    code = "circuit_open"


class HungBatchError(ServingError):
    """The batch's model call exceeded the hung-batch watchdog timeout.
    The dispatch was abandoned (its thread is orphaned, not killed — a
    CPython constraint) and the batch's requests failed, so the worker
    keeps serving instead of wedging."""

    code = "hung_batch"
