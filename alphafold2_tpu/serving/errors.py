"""Serving-engine exception taxonomy.

Every rejection the engine can hand a client is an explicit, typed error
— the backpressure contract is "fail loudly, never block silently"
(docs/SERVING.md). Kept in their own module so `bucketing`, `cache`, and
`engine` can share them without import cycles.

Every error carries a STABLE `code` string (the wire/ops identifier:
error-rate dashboards, client retry policies, and the engine's per-code
counters in `stats()["errors"]` all key on it — renaming a code is a
breaking API change) and serializes with `to_json()` for HTTP front ends.

Load-shedding rejections (queue full, deadline exceeded, no healthy
replica) additionally carry a machine-readable `retry_after_s` hint: the
server's estimate of when a retry has a real chance of being admitted,
derived from queue depth and the recent service rate. Clients that honor
it retry at the rate the tier can absorb instead of hammering a wedged
queue; it rides `to_json()` (the HTTP analogue of a Retry-After header)
and the serve.py replay output.
"""

from __future__ import annotations

from typing import Optional


class ServingError(Exception):
    """Base class for all serving-engine errors.

    `retry_after_s` is optional backoff advice for retryable rejections
    (shed / queue-full / deadline classes set it; terminal semantic
    failures like invalid_sequence leave it None).
    """

    code = "serving_error"
    retry_after_s: Optional[float] = None
    #: HTTP status an HTTP front end should map this error to. Backpressure
    #: sheds that carry retry advice override this with 429 so clients see
    #: the standard Too Many Requests + Retry-After pairing; everything
    #: else is a generic 500 unless a subclass says otherwise.
    http_status = 500

    def __init__(self, *args, retry_after_s: Optional[float] = None):
        super().__init__(*args)
        if retry_after_s is not None:
            self.retry_after_s = float(retry_after_s)

    def to_json(self) -> dict:
        """Wire-format payload: stable code + human-readable message
        (+ retry_after_s backoff advice when the error carries it)."""
        payload = {
            "code": self.code,
            "error": type(self).__name__,
            "message": str(self),
        }
        if self.retry_after_s is not None:
            payload["retry_after_s"] = round(self.retry_after_s, 3)
        return payload


class InvalidSequenceError(ServingError):
    """Request sequence contains characters outside the residue vocabulary
    (constants.aa_to_tokens strict mode) or is empty."""

    code = "invalid_sequence"


class RequestTooLongError(ServingError):
    """Request sequence is longer than the largest configured bucket."""

    code = "request_too_long"


class SequenceTooLongError(RequestTooLongError):
    """The sequence exceeds EVERY bucket ceiling this deployment can
    serve: the single engine's ladder, or — in a heterogeneous fleet —
    the largest-capability pool's ladder. A subclass of
    `RequestTooLongError` so existing catch sites keep working, with its
    OWN stable code: the length-adaptive router's "no capable replica"
    path and the single-engine ladder rejection both raise exactly this
    class, so clients and dashboards see one sharp `sequence_too_long`
    signal (plus `fleet_shed_total{reason="too_long"}` fleet-side)
    wherever an unservable length is rejected. Deliberate code rename
    from the pre-PR-14 `request_too_long` (docs/SERVING.md changelog
    note)."""

    code = "sequence_too_long"


class QueueFullError(ServingError):
    """The bounded request queue is at capacity. Backpressure is explicit:
    the caller decides whether to retry, shed, or escalate — the engine
    never blocks a submitter. Carries `retry_after_s` when the rejecting
    tier can estimate its drain rate."""

    code = "queue_full"
    http_status = 429


class RequestTimeoutError(ServingError):
    """The request's deadline passed before it was dispatched to the
    model (scheduler- or admission-side expiry). `retry_after_s` advises
    when a fresh attempt would likely clear the queue in time."""

    code = "request_timeout"


class PredictionError(ServingError):
    """The model call for this request raised. The original exception is
    chained as ``__cause__``; the engine itself keeps serving."""

    code = "prediction_failed"


class EngineClosedError(ServingError):
    """The engine is shut down (or shutting down without draining); the
    request was not and will not be served."""

    code = "engine_closed"


class CircuitOpenError(ServingError):
    """The circuit breaker is open: recent dispatches failed consecutively
    past the threshold, so the engine fast-rejects instead of queueing
    work it expects to fail. Retry after the breaker's reset window
    (reliability.breaker; `stats()["breaker"]` shows the state)."""

    code = "circuit_open"


class HungBatchError(ServingError):
    """The batch's model call exceeded the hung-batch watchdog timeout.
    The dispatch was abandoned (its thread is orphaned, not killed — a
    CPython constraint) and the batch's requests failed, so the worker
    keeps serving instead of wedging."""

    code = "hung_batch"


class NoHealthyReplicaError(ServingError):
    """Fleet-tier rejection: every full-config replica is down and no
    degraded tier is configured, so the request cannot be served at all.
    `retry_after_s` is the health manager's re-probe cadence — the soonest
    a replica could possibly be reinstated."""

    code = "no_healthy_replica"


class RequeueLimitError(ServingError):
    """Fleet-tier terminal failure: the request was requeued off failing
    replicas `requeue_limit` times and still never completed — evidence
    the request itself (not one replica) is the problem, so it stops
    consuming fleet capacity. The last replica error is chained as
    ``__cause__``."""

    code = "requeue_limit"


class FeaturizeError(ServingError):
    """CPU featurization of the request failed (serving/featurize.py):
    the feature-prep worker raised while tokenizing / assembling the MSA
    stream / assigning the bucket, or the featurize tier lost the job
    past its retry budget (e.g. repeated worker deaths mid-job). The
    underlying exception is chained as ``__cause__`` when there is one.
    Semantic input rejections (invalid residues, oversize sequences)
    keep their own sharper codes — this code means the TIER failed the
    request, not that the request was malformed."""

    code = "featurize_failed"


class RetryBudgetExhaustedError(ServingError):
    """The fleet-wide retry budget (reliability/retry_budget.py) has no
    tokens left: featurize requeues, replica-failover retries, and hedged
    dispatches all draw from one token bucket refilled as a fraction of
    successful completions, so a fleet-wide brownout degrades to this
    fast typed shed instead of a retry storm that amplifies the outage.
    Always carries `retry_after_s` — the bucket's estimate of when refill
    (i.e. recovered success throughput) will have earned another token.
    HTTP front ends map it to 429 + Retry-After (same contract as
    `queue_full`); `fleet_shed_total{reason="retry_budget"}` counts it
    fleet-side."""

    code = "retry_budget_exhausted"
    http_status = 429


class ScaleRejectedError(ServingError):
    """The fleet refused a replica-pool scale action (serving/autoscale.py
    → `ServingFleet.add_replica` / `remove_replica`): shrinking below one
    replica, removing an unknown or already-retiring replica, scaling a
    closed fleet, or shrinking while the pool is unhealthy (a drain on
    top of failure-drained capacity would amplify the outage). Counted
    per code in `stats()["errors"]` so a wedged autoscaler loop is
    visible on dashboards, and carried in the autoscaler's decision
    log."""

    code = "scale_rejected"
