"""Serving-engine exception taxonomy.

Every rejection the engine can hand a client is an explicit, typed error
— the backpressure contract is "fail loudly, never block silently"
(docs/SERVING.md). Kept in their own module so `bucketing`, `cache`, and
`engine` can share them without import cycles.
"""

from __future__ import annotations


class ServingError(Exception):
    """Base class for all serving-engine errors."""


class InvalidSequenceError(ServingError):
    """Request sequence contains characters outside the residue vocabulary
    (constants.aa_to_tokens strict mode) or is empty."""


class RequestTooLongError(ServingError):
    """Request sequence is longer than the largest configured bucket."""


class QueueFullError(ServingError):
    """The bounded request queue is at capacity. Backpressure is explicit:
    the caller decides whether to retry, shed, or escalate — the engine
    never blocks a submitter."""


class RequestTimeoutError(ServingError):
    """The request's deadline passed before it was dispatched to the
    model (scheduler-side expiry)."""


class PredictionError(ServingError):
    """The model call for this request raised. The original exception is
    chained as ``__cause__``; the engine itself keeps serving."""


class EngineClosedError(ServingError):
    """The engine is shut down (or shutting down without draining); the
    request was not and will not be served."""
