"""Multi-precision weight residency for the serving tier.

A serving replica's HBM bill has two tenants: executables (bounded by
the bucket ladder, PR 2) and WEIGHTS — one full parameter tree per
distinct config tag resident on the device. With
`Alphafold2Config.weight_dtype="int8"` an engine serves per-channel-PTQ
int8 trunk weights (ops/quant.py) instead of the fp32 master: ~3.9x
fewer weight bytes on the north-star trunk, and int8 (not fp32) HBM
traffic on every dense layer via the fused-dequant kernel
(ops/quant_kernel.py).

This module is the build-time seam the engine calls BEFORE placing
params on device:

  * `resident_params(params, model_cfg)` — identity for f32 configs;
    for int8 configs returns the PTQ tree (fp32 master untouched),
    served from a small process-level cache keyed by the residency tag
    so a FLEET of replicas sharing one master tree (serving/fleet.py
    builds N engines over the same `params` object) quantizes ONCE, not
    N times.
  * `residency_tag(model_cfg, params_tag)` — the cache key and the
    label on the per-tag weight-bytes gauge (`serving_weight_bytes` in
    ServingMetrics): weight_dtype plus a short digest of the full
    model-config repr and the checkpoint fingerprint. Two checkpoints,
    or two precision arms of one checkpoint, can never share an entry —
    the same never-alias stance as the engine's result-cache config tag
    (which covers `weight_dtype` by repr construction).

The cache holds a strong reference to the SOURCE tree per entry and
revalidates by identity: a new params object under the same tag (e.g. a
reloaded checkpoint with an unchanged params_tag — caller error, but a
cheap one) re-quantizes instead of serving stale weights.
"""

from __future__ import annotations

import collections
import hashlib
import threading
from typing import Tuple

from alphafold2_tpu.ops.quant import quantize_tree, tree_weight_bytes

__all__ = ["resident_params", "residency_tag", "clear_residency_cache"]

_CACHE_MAX = 8  # distinct (config, checkpoint) tags held at once

_lock = threading.Lock()
# tag -> {"source": params, "tree": quantized tree, "info": dict}
_cache: "collections.OrderedDict[str, dict]" = collections.OrderedDict()


def residency_tag(model_cfg, params_tag: str = "") -> str:
    """Short, label-safe identity of one resident weight set:
    `<weight_dtype>-<12-hex digest of (repr(model_cfg), params_tag)>`.
    repr covers every Alphafold2Config field, so any knob that changes
    what should be resident changes the tag."""
    digest = hashlib.sha256(
        repr((model_cfg, params_tag)).encode()
    ).hexdigest()[:12]
    return f"{getattr(model_cfg, 'weight_dtype', 'f32')}-{digest}"


def resident_params(params, model_cfg, *, params_tag: str = "") -> Tuple[object, dict]:
    """The tree an engine should place on device for `model_cfg`, plus a
    residency info dict:

      {"tag", "weight_dtype", "weight_bytes" (the resident tree),
       "fp32_weight_bytes" (the master tree), "cached" (True when the
       quantized tree came from the process cache)}

    f32 configs return `params` unchanged. int8 configs return the PTQ
    tree (ops/quant.py `quantize_tree`, default trunk selection); the
    fp32 master is never mutated.
    """
    tag = residency_tag(model_cfg, params_tag)
    if getattr(model_cfg, "weight_dtype", "f32") != "int8":
        fp32_bytes = tree_weight_bytes(params)
        return params, {
            "tag": tag,
            "weight_dtype": "f32",
            "weight_bytes": fp32_bytes,
            "fp32_weight_bytes": fp32_bytes,
            "cached": False,
        }

    with _lock:
        entry = _cache.get(tag)
        if entry is not None and entry["source"] is params:
            # hit: the cached info already carries both byte counts — no
            # whole-tree walk on the N-1 replica builds after the first
            _cache.move_to_end(tag)
            return entry["tree"], {**entry["info"], "cached": True}

    fp32_bytes = tree_weight_bytes(params)
    qtree = quantize_tree(params)
    info = {
        "tag": tag,
        "weight_dtype": "int8",
        "weight_bytes": tree_weight_bytes(qtree),
        "fp32_weight_bytes": fp32_bytes,
        "cached": False,
    }
    with _lock:
        _cache[tag] = {"source": params, "tree": qtree, "info": info}
        _cache.move_to_end(tag)
        while len(_cache) > _CACHE_MAX:
            _cache.popitem(last=False)
    return qtree, dict(info)


def clear_residency_cache() -> None:
    """Drop every cached quantized tree (tests; also frees the host-side
    strong references to retired checkpoints)."""
    with _lock:
        _cache.clear()
