"""Fleet admission control: one shared queue, priorities, deadline shedding.

The single-engine queue (`ServingEngine`) is a plain bounded FIFO —
correct for one replica, but a fleet needs the front door to make
DECISIONS, not just hold requests:

  * **Priority classes** — "interactive" beats "normal" beats "batch".
    Dispatch order is (priority, arrival); under overload a higher-class
    arrival EVICTS the newest lowest-class entry rather than being shed
    behind it, so paying traffic is never starved by bulk backfill.
  * **Deadline enforcement** — an entry whose deadline passes while
    queued is shed at poll time with a structured `RequestTimeoutError`
    instead of burning a replica dispatch it can no longer use.
  * **Structured shedding** — every rejection carries `retry_after_s`
    derived from queue depth and the observed drain rate
    (`note_served`), so honest clients back off at the rate the fleet
    can actually absorb (the load-shedding half of the ParaFold
    split-and-pool serving story, arxiv 2111.06340).
  * **Requeue exemption** — entries requeued off a failed replica
    re-enter ahead of their class and are EXEMPT from capacity: a
    request the fleet already accepted is never shed by its own
    failover (the bounded requeue count lives in the fleet, not here).

Entries are duck-typed: anything with `priority` (int, lower = more
important), `deadline` (absolute monotonic seconds or None), and
`enqueued_at` works — the controller never resolves futures itself; it
RETURNS shed/evicted entries so the owner keeps sole authority over
terminal outcomes (and the counters that report them).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import List, Optional, Tuple

from alphafold2_tpu.serving.errors import QueueFullError

#: priority classes, lower value = dispatched first. Clients use the
#: names; the queue uses the ints.
PRIORITIES = {"interactive": 0, "normal": 1, "batch": 2}


def resolve_priority(priority) -> int:
    """Accept a class name or a raw int (smaller = more important)."""
    if isinstance(priority, str):
        try:
            return PRIORITIES[priority]
        except KeyError:
            raise ValueError(
                f"unknown priority class {priority!r}; expected one of "
                f"{sorted(PRIORITIES)} (or an int)"
            ) from None
    return int(priority)


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    """Front-door knobs (see docs/OPERATIONS.md "Fleet runbook")."""

    capacity: int = 64          # shared queue bound (backpressure point)
    min_retry_after_s: float = 0.05
    max_retry_after_s: float = 60.0
    service_rate_alpha: float = 0.2  # EMA weight for observed service time

    def __post_init__(self):
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {self.capacity}")


class AdmissionController:
    """Thread-safe shared priority queue with deadline + shed policy.

    `offer()` runs on submitter threads, `poll()` on the fleet dispatcher,
    `requeue()` on replica worker threads (failure callbacks) — one lock
    covers the queue; no callback ever runs under it.
    """

    def __init__(self, cfg: AdmissionConfig = AdmissionConfig(),
                 clock=time.monotonic):
        self.cfg = cfg
        self._clock = clock
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._entries: List[Tuple[int, int, object]] = []  # sorted keys
        self._seq = 0
        self._service_ema_s: Optional[float] = None  # observed drain rate
        self.sheds = {"queue_full": 0, "evicted": 0, "deadline": 0}

    # ------------------------------------------------------------ admission

    def offer(self, entry):
        """Admit `entry`, or shed. Returns the entry this admission
        EVICTED (a lower-priority one, for the owner to fail with a
        retry-after error) or None. Raises QueueFullError — carrying
        `retry_after_s` — when the entry itself must shed (queue full of
        equal-or-higher-priority work)."""
        key = (resolve_priority(entry.priority),)
        with self._lock:
            evicted = None
            if len(self._entries) >= self.cfg.capacity:
                worst_i = max(
                    range(len(self._entries)),
                    key=lambda i: self._entries[i][:2],
                )
                worst = self._entries[worst_i]
                if worst[0] > key[0]:
                    # incoming outranks the worst queued entry: that
                    # entry sheds instead (newest of the lowest class —
                    # max seqno — so the class's FIFO head keeps its slot)
                    evicted = self._entries.pop(worst_i)[2]
                    self.sheds["evicted"] += 1
                else:
                    self.sheds["queue_full"] += 1
                    raise QueueFullError(
                        f"fleet queue at capacity ({self.cfg.capacity}) "
                        f"with no lower-priority entry to displace",
                        retry_after_s=self._retry_after_locked(),
                    )
            self._seq += 1
            self._insert_locked((key[0], self._seq, entry))
            self._cond.notify()
            return evicted

    def requeue(self, entry):
        """Re-admit an entry the fleet already accepted (replica failover).
        Capacity-EXEMPT and sequenced ahead of its priority class (seqno
        0) — failover must neither shed accepted work nor send it to the
        back of the line behind traffic that arrived after it."""
        with self._lock:
            self._insert_locked((resolve_priority(entry.priority), 0, entry))
            self._cond.notify()

    def _insert_locked(self, item):
        # sorted insert; queue stays small (capacity-bounded), so O(n)
        # beats a heap once lazy-deletion bookkeeping is priced in
        import bisect

        keys = [e[:2] for e in self._entries]
        self._entries.insert(bisect.bisect_right(keys, item[:2]), item)

    # ------------------------------------------------------------- polling

    def poll(self, timeout: Optional[float] = None):
        """Next dispatchable entry (or None at timeout), plus the entries
        whose deadlines expired while queued — the owner sheds those with
        `RequestTimeoutError(retry_after_s=...)`. Expired entries are
        harvested BEFORE choosing, so a stale head never shadows live
        work behind it."""
        deadline = None if timeout is None else self._clock() + timeout
        expired = []
        with self._lock:
            while True:
                now = self._clock()
                live_i = None
                for i, (_, _, entry) in enumerate(self._entries):
                    if entry.deadline is not None and now >= entry.deadline:
                        expired.append(entry)
                        self.sheds["deadline"] += 1
                        continue
                    live_i = i
                    break
                # drop harvested expired entries from the front section
                if expired:
                    self._entries = [
                        e for e in self._entries if e[2] not in expired
                    ]
                    live_i = 0 if self._entries else None
                if live_i is not None and self._entries:
                    _, _, entry = self._entries.pop(live_i)
                    return entry, expired
                if expired:
                    # deliver expirations promptly even with nothing live
                    return None, expired
                wait = None if deadline is None else deadline - self._clock()
                if wait is not None and wait <= 0:
                    return None, expired
                self._cond.wait(wait)

    # ------------------------------------------------------------ estimates

    def note_served(self, service_s: float):
        """Feed one completed request's dispatch->done seconds into the
        drain-rate EMA behind `retry_after_s` estimates."""
        with self._lock:
            a = self.cfg.service_rate_alpha
            self._service_ema_s = (
                service_s if self._service_ema_s is None
                else a * service_s + (1 - a) * self._service_ema_s
            )

    def retry_after_s(self) -> float:
        with self._lock:
            return self._retry_after_locked()

    def _retry_after_locked(self) -> float:
        """Depth x per-request service estimate = honest drain horizon;
        clamped so a cold queue still says SOMETHING actionable."""
        est = (self._service_ema_s or 1.0) * max(1, len(self._entries))
        return float(min(self.cfg.max_retry_after_s,
                         max(self.cfg.min_retry_after_s, est)))

    # -------------------------------------------------------------- stats

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def entries(self) -> list:
        """Snapshot of the queued entries (dispatch order, no removal) —
        the fleet's per-capability-pool depth gauges group this by each
        entry's target pool, so a shed can quote the CAPABLE pool's
        backlog instead of the global queue's."""
        with self._lock:
            return [e[2] for e in self._entries]

    def drain(self) -> list:
        """Remove and return every queued entry (fleet shutdown path)."""
        with self._lock:
            out = [e[2] for e in self._entries]
            self._entries = []
            return out

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "depth": len(self._entries),
                "capacity": self.cfg.capacity,
                "sheds": dict(self.sheds),
                "retry_after_s": self._retry_after_locked(),
                "service_ema_s": self._service_ema_s,
            }
