"""Analytic model-FLOP accounting for the Alphafold2 trunk workload.

Why not XLA's `compiled.cost_analysis()["flops"]`: it counts the body of
a `lax.scan` / `lax.while_loop` ONCE, not times the trip count. The
north-star forward is a scan over reversible layers whose attention is
itself `lax.map`-tiled, so the reported number is ~2 orders of magnitude
low (measured: 0.607 TFLOP reported for a depth-12 forward whose matmul
arithmetic is 186 TFLOP). Every MFU computed from it is garbage. These
formulas count the matmul FLOPs (2*M*N*K per dot) of the model as
configured — the ~(1-3)% of elementwise/softmax/norm work is
deliberately excluded, so the count is a slight UNDERestimate and MFU
derived from it is conservative.

Validated against XLA's own count on a fully-unrolled dense (no-scan)
configuration in tests/test_flops.py, where cost_analysis IS complete.

Shape conventions (alphafold2_apply): pair grid (b, n, n, dim) with
n = 3*crop when full-atom elongated; MSA (b, r, c, dim). Reference
workload: reference train_pre.py:59-64 / BASELINE.md config 5.
"""

from __future__ import annotations

from alphafold2_tpu.models.config import Alphafold2Config


def attention_flops(
    tokens_q: float,
    tokens_kv: float,
    j_eff: float,
    dim: int,
    inner: int,
) -> float:
    """One multi-head attention pass (ops/attention.py attention_apply).

    tokens_q / tokens_kv: total query / key-value tokens projected.
    j_eff: keys each query actually attends (after folding/compression).
    """
    proj_q_out = 4.0 * tokens_q * dim * inner  # to_q + to_out
    proj_kv = 4.0 * tokens_kv * dim * inner  # to_kv (k and v)
    attn = 4.0 * tokens_q * j_eff * inner  # QK^T + attn@V
    return proj_q_out + proj_kv + attn


def ff_flops(tokens: float, dim: int, mult: int = 4) -> float:
    """GEGLU feed-forward (ops/feedforward.py): d -> 2*mult*d -> ... ->
    mult*d -> d."""
    return tokens * (4.0 * mult * dim * dim + 2.0 * mult * dim * dim)


def trunk_layer_op_flops(
    cfg: Alphafold2Config, n: int, r: int, c: int
) -> dict:
    """Per-op matmul FLOPs of ONE trunk layer at pair side n, MSA r x c.

    Mirrors models/trunk.py trunk_layer_apply: pair axial self-attention
    (row+col), MSA axial self-attention (row+col, tied rows cost the
    same contraction count), cross-attention both directions
    (mode-dependent, each including its k+v compression conv), and the
    feed-forwards (2 sequential / 4 reversible,
    models/reversible.py seq_ff2/msa_ff2). The decomposition bench
    (scripts/bench_decompose.py ops leg) consumes these keys directly —
    one formula source, so the per-op table always sums to
    trunk_layer_flops.
    """
    d, w = cfg.dim, cfg.heads * cfg.dim_head
    rho = max(1, cfg.cross_attn_compress_ratio)
    # grouped strided KV-compression conv (ops/attention.py
    # _compress_conv: inner->inner, kernel rho, groups=heads), applied
    # to k AND v: 4*j_kv*w^2/heads per cross direction
    conv = (lambda j_kv: 4.0 * j_kv * w * w / cfg.heads) if rho > 1 else (
        lambda j_kv: 0.0)

    ops = {
        # two passes (rows then cols), each a full QKVO over the n^2
        # grid and n-token attention within each line
        "pair_axial": 2 * attention_flops(n * n, n * n, n, d, w),
    }
    if r and c:
        ops["msa_axial"] = (
            attention_flops(r * c, r * c, c, d, w)  # along rows
            + attention_flops(r * c, r * c, r, d, w)  # along cols
        )
        if cfg.cross_attn_mode == "aligned":
            f = max(1, n // c)  # elongation factor (column fold)
            # pair<-msa: the context folds to (b*c, r) — every pair
            # token attends its column's r MSA rows, compressed rho-fold
            ops["cross_pair_from_msa"] = attention_flops(
                n * n, r * c, max(1.0, r / rho), d, w
            ) + conv(r * c)
            # msa<-pair: every MSA token attends its column's n*f pair
            # tokens (compressed)
            ops["cross_msa_from_pair"] = attention_flops(
                r * c, n * n, max(1.0, n * f / rho), d, w
            ) + conv(n * n)
        else:  # flat: all-to-all between the flattened streams
            ops["cross_pair_from_msa"] = attention_flops(
                n * n, r * c, r * c / rho, d, w) + conv(r * c)
            ops["cross_msa_from_pair"] = attention_flops(
                r * c, n * n, n * n / rho, d, w) + conv(n * n)

    ffs_per_stream = 2 if cfg.reversible else 1
    ops["ff_pair"] = ffs_per_stream * ff_flops(n * n, d)
    if r and c:
        ops["ff_msa"] = ffs_per_stream * ff_flops(r * c, d)
    return ops


def trunk_layer_flops(cfg: Alphafold2Config, n: int, r: int, c: int) -> float:
    """Matmul FLOPs of ONE trunk layer (sum of trunk_layer_op_flops)."""
    return sum(trunk_layer_op_flops(cfg, n, r, c).values())


def model_fwd_flops(cfg: Alphafold2Config, n: int, r: int, c: int) -> float:
    """Whole alphafold2_apply forward: trunk + distogram head (the
    front's embedding lookups and outer-sum are matmul-free)."""
    head = 2.0 * n * n * cfg.dim * cfg.num_buckets
    return cfg.depth * trunk_layer_flops(cfg, n, r, c) + head


def train_step_flops(
    cfg: Alphafold2Config,
    n: int,
    r: int,
    c: int,
    grad_accum: int = 1,
) -> float:
    """One optimizer step (or equivalently one value_and_grad) of the
    trunk workload.

    Backward of a matmul chain costs ~2x its forward; the reversible
    trunk RECOMPUTES the forward during backward (models/reversible.py),
    and so does a remat'd sequential trunk (cfg.remat: per-layer
    jax.checkpoint) — fwd multiplier 4 for either, 3 for plain
    sequential. Geometry (distogram centering + MDS + Kabsch) is
    O(iters * n^2) elementwise plus tiny 3x3 SVDs — well under 1% of
    the trunk at model scale — and is excluded.
    """
    mult = 4.0 if (cfg.reversible or cfg.remat) else 3.0
    return grad_accum * mult * model_fwd_flops(cfg, n, r, c)
