"""Host-side MSA file parsing: FASTA / A3M -> token arrays.

The model is MSA-centric (reference README.md:17-48 feeds `msa` alongside
the sequence; reference `constants.py:5` caps rows at MAX_NUM_MSA=20), but
the reference ships no way to get an alignment INTO the model. This module
closes that gap for the predict CLI: parse a FASTA or A3M alignment file
into the (rows, cols) token/mask arrays `alphafold2_apply` consumes.

A3M conventions honored: lowercase letters are insertions relative to the
query and are removed (standard a3m semantics, so every kept row aligns
column-wise with the first/query row); '-' and '.' are gaps. Gaps map to
the pad token and are masked out.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from alphafold2_tpu.constants import MAX_NUM_MSA, aa_to_tokens


def parse_alignment(path: str) -> list[tuple[str, str]]:
    """Read FASTA/A3M records as (header, sequence) pairs.

    Lowercase (a3m insertion) columns are stripped; '.' gaps normalize to
    '-'. Raises on an empty file or on aligned rows of unequal length.
    """
    records: list[tuple[str, str]] = []
    header, parts = None, []

    def flush():
        if header is not None:
            seq = "".join(parts)
            seq = "".join(c for c in seq if not c.islower()).replace(".", "-")
            records.append((header, seq))

    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith((";", "#")):
                continue
            if line.startswith(">"):
                flush()
                header, parts = line[1:].strip(), []
            else:
                if header is None:
                    header = ""  # headerless plain-text alignment
                parts.append(line)
    flush()

    if not records:
        raise ValueError(f"no sequences found in alignment file {path!r}")
    width = len(records[0][1])
    for name, seq in records:
        if len(seq) != width:
            raise ValueError(
                f"alignment rows differ in length after removing "
                f"insertions: {name!r} has {len(seq)}, query has {width} "
                f"(is this really a FASTA/A3M alignment?)"
            )
    return records


def load_msa(
    path: str,
    query: Optional[str] = None,
    max_rows: int = MAX_NUM_MSA,
) -> Tuple[np.ndarray, np.ndarray]:
    """Alignment file -> (msa_tokens (1, R, C) int32, msa_mask (1, R, C) bool).

    The first record is conventionally the query; when `query` is given it
    is checked against that row (gaps removed) so a mismatched alignment
    fails loudly instead of silently conditioning on the wrong protein.
    Rows beyond `max_rows` are dropped from the end (reference
    MAX_NUM_MSA=20 cap, constants.py:5).
    """
    records = parse_alignment(path)
    q_row = records[0][1].upper()
    if "-" in q_row:
        # Clustal/MUSCLE-style alignments may gap the query row; MSA columns
        # must line up with query residue positions (the model adds column
        # position embeddings by raw index), so drop query-gap columns —
        # this maps every row into query coordinates
        keep = [i for i, c in enumerate(q_row) if c != "-"]
        records = [(h, "".join(s[i] for i in keep)) for h, s in records]
    if query is not None:
        q = records[0][1].upper()
        if q != query.upper():
            raise ValueError(
                f"alignment query row ({len(q)} residues) does not match "
                f"--seq ({len(query)} residues): the MSA belongs to a "
                f"different protein or alignment"
            )
    rows = [seq.upper() for _, seq in records[:max_rows]]
    tokens = np.stack([aa_to_tokens(seq) for seq in rows])  # gaps -> pad id
    mask = np.stack(
        [np.array([c != "-" for c in seq], dtype=bool) for seq in rows]
    )
    return tokens[None].astype(np.int32), mask[None]
