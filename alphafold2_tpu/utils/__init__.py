"""Utility layer: observability (metrics logging, profiling, eval).

All new framework surface — the reference has no tracing, metrics, or eval
wiring at all (SURVEY.md §5). The metrics/tracing primitives now live in
`alphafold2_tpu.telemetry` (span tracer, metric registry, profiling
hooks, regression gate); `utils.observability` re-exports the migrated
names so existing imports keep working.
"""

from alphafold2_tpu.utils.observability import (
    LatencyHistogram,
    MetricsLogger,
    profile_trace,
    structure_eval,
)

__all__ = [
    "LatencyHistogram",
    "MetricsLogger",
    "profile_trace",
    "structure_eval",
]
