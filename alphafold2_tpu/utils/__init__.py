"""Utility layer: observability (metrics logging, profiling, eval).

All new framework surface — the reference has no tracing, metrics, or eval
wiring at all (SURVEY.md §5).
"""

from alphafold2_tpu.utils.observability import (
    LatencyHistogram,
    MetricsLogger,
    profile_trace,
    structure_eval,
)

__all__ = [
    "LatencyHistogram",
    "MetricsLogger",
    "profile_trace",
    "structure_eval",
]
