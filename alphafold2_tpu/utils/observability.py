"""Observability: metrics logging, step timing, profiler tracing, eval.

The reference has none of this — loss reaches the user through bare `print`
once per optimizer step (reference train_pre.py:99, train_end2end.py:180),
the structure-quality metrics exist only as library functions that no loop
ever calls (reference utils.py:563-624), and there is no profiler hook
anywhere (SURVEY.md §5). This module makes all three first-class:

  * `MetricsLogger` — windowed steps/sec + scalar metrics, streamed to
    stdout and optionally a JSONL file (host-side, async-friendly: pass
    jax arrays and they are fetched once per log call).
  * `profile_trace` — context manager over `jax.profiler` emitting a
    TensorBoard-loadable trace directory for a chosen step window.
  * `structure_eval` — the reference's own quality metrics (RMSD, GDT-TS,
    GDT-HA, TM-score) wired into an eval step over predicted vs true
    coordinate clouds, Kabsch-aligned first.
"""

from __future__ import annotations

import collections
import contextlib
import json
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.geometry import kabsch
from alphafold2_tpu.geometry.metrics import GDT_HA_CUTOFFS, GDT_TS_CUTOFFS, gdt, rmsd, tmscore


class MetricsLogger:
    """Step-cadence scalar logging with throughput tracking."""

    def __init__(self, jsonl_path: Optional[str] = None, print_every: int = 10):
        self.jsonl_path = jsonl_path
        self.print_every = print_every
        self._file = open(jsonl_path, "a") if jsonl_path else None
        self._t_last = time.perf_counter()
        self._step_last: Optional[int] = None

    def log(self, step: int, metrics: dict):
        """Record metrics for `step`. Values may be jax arrays (fetched here,
        one device sync per call) or plain numbers."""
        now = time.perf_counter()
        vals = {
            k: float(np.asarray(jax.device_get(v))) for k, v in metrics.items()
        }
        # throughput only when the step actually advanced (a second log call
        # at the same step — e.g. eval scores — must not zero it out)
        if self._step_last is not None and step > self._step_last and now > self._t_last:
            vals["steps_per_sec"] = (step - self._step_last) / (now - self._t_last)
            self._t_last, self._step_last = now, step
        elif self._step_last is None or step > self._step_last:
            self._t_last, self._step_last = now, step

        record = {"step": step, **{k: round(v, 6) for k, v in vals.items()}}
        if self._file is not None:
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        if step % self.print_every == 0:
            parts = "  ".join(f"{k} {v:.4f}" for k, v in vals.items())
            print(f"step {step}  {parts}")
        return vals

    def event(self, step: int, kind: str, **fields):
        """Structured non-scalar record (restart causes, preemptions,
        config changes): JSON-serializable fields pass through verbatim —
        no float coercion — into the same JSONL stream, tagged with
        `"event"` so curve-plotting consumers can filter them out.
        Always printed: events are rare and operationally load-bearing.
        """
        record = {"step": step, "event": kind, **fields}
        if self._file is not None:
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        parts = "  ".join(f"{k}={v}" for k, v in fields.items())
        print(f"step {step}  [{kind}]  {parts}")
        return record

    def close(self):
        # idempotent: context-manager exit followed by an explicit close()
        # (or two owners sharing one logger) must not hit a closed file
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class LatencyHistogram:
    """Streaming latency percentiles over a sliding window.

    The serving engine (serving/metrics.py) needs request-latency
    quantiles that (a) track the RECENT traffic mix, not the lifetime mix
    — a bucket-ladder warmup with two 30 s compiles must age out of p99
    once steady-state batches flow — and (b) cost O(window) memory
    regardless of how many requests pass through. A bounded deque of the
    last `window` observations gives both; percentiles are computed by
    nearest-rank over a sorted snapshot (window is small, sorting at
    snapshot time beats maintaining an order statistic per observe()).

    Thread-safe: `observe` is called from the scheduler worker thread
    while `snapshot` is called from health-check/stats readers.
    """

    def __init__(self, window: int = 2048):
        if window <= 0:
            raise ValueError(f"window must be positive, got {window}")
        self._values = collections.deque(maxlen=window)
        self._lock = threading.Lock()
        self._count = 0  # lifetime observations (window evicts, this doesn't)
        self._max = 0.0

    def observe(self, value: float):
        v = float(value)
        with self._lock:
            self._values.append(v)
            self._count += 1
            if v > self._max:
                self._max = v

    @staticmethod
    def _percentile(ordered, q: float) -> float:
        # nearest-rank on a pre-sorted list; q in [0, 100]
        if not ordered:
            return 0.0
        idx = min(len(ordered) - 1, int(round(q / 100.0 * (len(ordered) - 1))))
        return ordered[idx]

    def percentile(self, q: float) -> float:
        with self._lock:
            ordered = sorted(self._values)
        return self._percentile(ordered, q)

    def snapshot(self) -> dict:
        """Plain-float summary: count (lifetime), window stats, p50/p95/p99."""
        with self._lock:
            ordered = sorted(self._values)
            count, vmax = self._count, self._max
        return {
            "count": count,
            "window": len(ordered),
            "mean": (sum(ordered) / len(ordered)) if ordered else 0.0,
            "p50": self._percentile(ordered, 50.0),
            "p95": self._percentile(ordered, 95.0),
            "p99": self._percentile(ordered, 99.0),
            "max": vmax,
        }


@contextlib.contextmanager
def profile_trace(log_dir: str, enabled: bool = True):
    """Capture a jax.profiler trace (XLA device timelines included) into
    `log_dir` for the enclosed step window; view with TensorBoard's profile
    plugin or Perfetto."""
    if not enabled:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def structure_eval(pred, true, mask=None):
    """Quality metrics over predicted vs ground-truth coordinate clouds.

    Args:
      pred, true: (b, N, 3) point clouds (flatten atom axes first).
      mask: (b, N) bool validity.

    Returns dict of per-batch-mean floats: rmsd, gdt_ts, gdt_ha, tm.
    Prediction is Kabsch-aligned onto truth before scoring (the reference's
    eval intent, train_end2end.py:172-175, which it never wires up).
    """
    pred = jnp.transpose(jnp.asarray(pred, jnp.float32), (0, 2, 1))  # (b, 3, N)
    true = jnp.transpose(jnp.asarray(true, jnp.float32), (0, 2, 1))
    w = None if mask is None else jnp.asarray(mask, jnp.float32)
    pred_al, true_c = kabsch(pred, true, weights=w)

    d = {
        "rmsd": rmsd(pred_al, true_c, mask=w),
        "gdt_ts": gdt(pred_al, true_c, cutoffs=GDT_TS_CUTOFFS, mask=w),
        "gdt_ha": gdt(pred_al, true_c, cutoffs=GDT_HA_CUTOFFS, mask=w),
        "tm": tmscore(pred_al, true_c, mask=w),
    }
    return {k: float(jnp.mean(v)) for k, v in d.items()}
