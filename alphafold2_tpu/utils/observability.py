"""Back-compat shim: metrics/tracing primitives moved to `telemetry/`.

`MetricsLogger`, `LatencyHistogram`, and `profile_trace` grew into the
unified telemetry subsystem (`alphafold2_tpu.telemetry`: span tracer,
metric registry, profiling hooks, regression gate) and now live there;
this module re-exports them so every existing
`from alphafold2_tpu.utils.observability import ...` keeps working.

`structure_eval` stays here: it is structure-quality evaluation
(geometry), not telemetry plumbing.
"""

from __future__ import annotations

import jax.numpy as jnp

from alphafold2_tpu.geometry import kabsch
from alphafold2_tpu.geometry.metrics import GDT_HA_CUTOFFS, GDT_TS_CUTOFFS, gdt, rmsd, tmscore
from alphafold2_tpu.telemetry.logger import MetricsLogger
from alphafold2_tpu.telemetry.profiling import profile_trace
from alphafold2_tpu.telemetry.registry import LatencyHistogram

__all__ = [
    "LatencyHistogram",
    "MetricsLogger",
    "profile_trace",
    "structure_eval",
]


def structure_eval(pred, true, mask=None):
    """Quality metrics over predicted vs ground-truth coordinate clouds.

    Args:
      pred, true: (b, N, 3) point clouds (flatten atom axes first).
      mask: (b, N) bool validity.

    Returns dict of per-batch-mean floats: rmsd, gdt_ts, gdt_ha, tm.
    Prediction is Kabsch-aligned onto truth before scoring (the reference's
    eval intent, train_end2end.py:172-175, which it never wires up).
    """
    pred = jnp.transpose(jnp.asarray(pred, jnp.float32), (0, 2, 1))  # (b, 3, N)
    true = jnp.transpose(jnp.asarray(true, jnp.float32), (0, 2, 1))
    w = None if mask is None else jnp.asarray(mask, jnp.float32)
    pred_al, true_c = kabsch(pred, true, weights=w)

    d = {
        "rmsd": rmsd(pred_al, true_c, mask=w),
        "gdt_ts": gdt(pred_al, true_c, cutoffs=GDT_TS_CUTOFFS, mask=w),
        "gdt_ha": gdt(pred_al, true_c, cutoffs=GDT_HA_CUTOFFS, mask=w),
        "tm": tmscore(pred_al, true_c, mask=w),
    }
    return {k: float(jnp.mean(v)) for k, v in d.items()}
