"""Preemption-safe shutdown: catch SIGTERM, checkpoint, exit clean.

Preemptible TPU VMs get a SIGTERM and a short grace window before the
plug is pulled. The kernel default (die mid-step, mid-checkpoint-write)
loses up to a full checkpoint interval of work and can leave a torn
write behind; the handler here converts the signal into a cooperative
flag that `run_resilient` polls at its step boundary:

    with PreemptionHandler() as preemption:
        run_resilient(..., preemption=preemption)   # raises Preempted
                                                    # after a final save

On the flag, the loop force-saves the current state, drains the
checkpoint manager, and raises `Preempted` — the process exits clean,
and the NEXT run restores that exact state and continues bit-exact
(asserted in tests/test_chaos.py).

Signal-handler discipline: the handler itself only sets an Event and
remembers the signum — no I/O, no locks, nothing async-signal-unsafe.
All real work (checkpoint save, engine drain) happens on the polling
thread. Install is main-thread-only (a CPython constraint on signal());
`deliver()` is the in-process stand-in the fault injector uses, so chaos
tests exercise the identical polling path without cross-thread signal
timing, while one direct test covers real `signal.raise_signal` delivery.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional


class Preempted(RuntimeError):
    """Raised by the guarded loop after a preemption-triggered final save.

    Carries `step` and `checkpointed` so entry scripts can print an
    HONEST resume message and exit 0 — preemption is not a failure, but a
    run with no checkpoint manager must not claim its progress was saved.
    """

    def __init__(self, step: int, message: str = "", checkpointed: bool = True):
        self.step = step
        self.checkpointed = checkpointed
        if not message:
            message = (
                f"preempted: final checkpoint saved at step {step}; "
                "rerun with the same --ckpt-dir to resume"
                if checkpointed else
                f"preempted at step {step} with NO checkpoint manager — "
                "progress was not saved; rerun with --ckpt-dir to make "
                "future preemptions resumable"
            )
        super().__init__(message)


class PreemptionHandler:
    """Latching SIGTERM flag with handler install/restore.

    Usable uninstalled (the fault injector delivers via `deliver()`), as a
    context manager, or via explicit install()/uninstall(). `callbacks`
    added with `add_callback` run on the FIRST `check()` that observes the
    flag — on the polling thread, never in the signal handler — e.g. a
    serving engine's `shutdown(drain=True)`.
    """

    def __init__(self, signals=(signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._signum: Optional[int] = None
        self._previous = {}
        self._installed = False
        self._callbacks = []
        self._callbacks_fired = False
        self._lock = threading.Lock()

    # -- signal plumbing ----------------------------------------------------

    def _handler(self, signum, frame):
        # async-signal-safe: set a flag, remember who called, return
        self._signum = signum
        self._event.set()

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        for sig in self.signals:
            self._previous[sig] = signal.signal(sig, self._handler)
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- the cooperative surface ---------------------------------------------

    def deliver(self, signum: int = signal.SIGTERM):
        """In-process delivery (what a SIGTERM does, minus the kernel):
        the fault injector's `preempt` kind and unit tests call this."""
        self._handler(signum, None)

    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    @property
    def signum(self) -> Optional[int]:
        return self._signum

    def add_callback(self, fn):
        """Run `fn()` once, on the first check() after the flag trips."""
        self._callbacks.append(fn)

    def _pod_consensus(self, local: bool) -> bool:
        """Global OR of the preemption flag across processes. SIGTERM is
        per-process (the scheduler rarely signals every host in the same
        instant); if only the signaled process entered the collective
        checkpoint save, the others would march into the next step's
        collectives and the pod would deadlock on mismatched programs.
        Polling is a step-boundary event on every process in lockstep
        (run_resilient), so a tiny allgather here makes the WHOLE pod
        observe the preemption at the same boundary. Single-process (and
        any environment where the collective is unavailable): the local
        flag, unchanged."""
        try:
            import jax

            if jax.process_count() <= 1:
                return local
            import numpy as np

            from alphafold2_tpu import compat

            flags = compat.process_allgather(
                np.asarray([local], np.int32), tiled=True
            )
            return bool(np.asarray(flags).any())
        except Exception:
            return local

    def check(self) -> bool:
        """Poll point for long-running loops: returns True once preempted
        (on ANY process of a pod — see _pod_consensus), firing any
        registered drain callbacks exactly once."""
        if not self._pod_consensus(self._event.is_set()):
            return False
        # latch locally: on a pod the signal may have landed elsewhere
        self._event.set()
        with self._lock:
            if not self._callbacks_fired:
                self._callbacks_fired = True
                for fn in self._callbacks:
                    fn()
        return True
