"""Reliability layer: deterministic fault injection + the hardening it exercises.

Production-scale preemptible-TPU training (the FastFold/ScaleFold regime —
multi-day runs where preemption and corruption are statistically certain)
needs machine-verified recovery, not hand-written hope. This package makes
failure a first-class, TESTABLE input across the whole stack:

  * `faults` — `FaultPlan`/`FaultInjector`: a seeded, deterministic schedule
    of faults (step-N exception, NaN-poisoned grads, checkpoint write
    truncation/corruption, data-batch errors, slow/hung serving requests,
    SIGTERM-style preemption) delivered through small hook points in
    `training/harness.py`, `training/data.py`, `training/checkpoint.py`,
    and `serving/engine.py`.
  * `breaker` — `CircuitBreaker`: the serving engine's consecutive-failure
    circuit (open -> fast-reject, half-open probe -> close), with seeded
    reopen jitter so a fleet of breakers never re-probes in lockstep.
  * `health` — `HealthMonitor`/`ReplicaState`: heartbeat probes + drain/
    reinstate state machine over named replicas (the serving fleet's
    supervisor; clock-injectable, serving-agnostic).
  * `preemption` — `PreemptionHandler`/`Preempted`: SIGTERM-aware clean
    shutdown; `run_resilient` drains to a final checkpoint and a fresh run
    resumes bit-exact from it.

The chaos test matrix (`tests/test_chaos.py`, `-m chaos`) asserts the
recovery invariant for every fault kind: the guarded run completes and
matches the fault-free run's final state within declared tolerance (mostly
bit-exact), and never hangs.
"""

from alphafold2_tpu.reliability.breaker import CircuitBreaker, CircuitState
from alphafold2_tpu.reliability.faults import (
    FAULT_KINDS,
    REPLICA_FAULT_KINDS,
    Fault,
    FaultInjector,
    FaultPlan,
    InjectedFault,
    WorkerKilled,
)
from alphafold2_tpu.reliability.health import HealthMonitor, ReplicaState
from alphafold2_tpu.reliability.preemption import Preempted, PreemptionHandler
from alphafold2_tpu.reliability.retry_budget import RetryBudget

__all__ = [
    "RetryBudget",
    "FAULT_KINDS",
    "REPLICA_FAULT_KINDS",
    "Fault",
    "FaultInjector",
    "FaultPlan",
    "InjectedFault",
    "WorkerKilled",
    "CircuitBreaker",
    "CircuitState",
    "HealthMonitor",
    "ReplicaState",
    "Preempted",
    "PreemptionHandler",
]
