"""Replica health management: probes, drain decisions, reinstatement.

The fleet tier (`serving/fleet.py`) keeps N engine replicas; this module
owns the question "which of them should take traffic right now?". It is
deliberately serving-agnostic — targets are (name, probe_fn, callbacks)
triples, the clock is injectable, and every transition is driven either
by external dispatch evidence or by `tick()`, so tests cover the whole
state machine deterministically without threads or sleeps.

Per-target state machine:

  HEALTHY   takes traffic. Evidence against it accumulates two ways:
            dispatch failures reported by the router
            (`record_failure` — breaker trips, hung-batch watchdog,
            injected kills all land here) and failed heartbeat probes
            run by `tick()` at `probe_interval_s`. Either stream
            reaching `fail_threshold` CONSECUTIVE failures marks the
            target DOWN; any success resets both counts.
  DOWN      takes no traffic. The owner's `on_drain` callback runs on
            the next `tick()` (never on the reporting thread — the
            reporter is typically the replica's own worker, and a drain
            that joins that worker from itself would deadlock). Every
            `reprobe_interval_s` the target is re-probed; one probe
            success reinstates it (`on_reinstate`), because a probe is
            END-TO-END evidence the replica serves again — demanding N
            successes would just keep capacity parked during recovery.

`HealthMonitor.start()` runs `tick()` on a daemon thread for production
use; tests call `tick(now=...)` directly.
"""

from __future__ import annotations

import enum
import threading
import time
import traceback
from typing import Callable, Dict, Optional


class ReplicaState(str, enum.Enum):
    HEALTHY = "healthy"
    DOWN = "down"


class _Target:
    """One monitored replica (all fields guarded by the monitor lock)."""

    def __init__(self, name: str, probe: Optional[Callable[[], bool]],
                 on_drain: Optional[Callable[[str, str], None]],
                 on_reinstate: Optional[Callable[[str], None]]):
        self.name = name
        self.probe = probe
        self.on_drain = on_drain
        self.on_reinstate = on_reinstate
        self.state = ReplicaState.HEALTHY
        self.retiring = False           # deliberate removal in progress
        self.consecutive_failures = 0   # dispatch evidence (router-reported)
        self.consecutive_probe_failures = 0
        self.last_probe_at: Optional[float] = None
        self.down_since: Optional[float] = None
        self.down_reason = ""
        self.drain_pending = False      # drain decided, callback not yet run
        self.drains = 0                 # lifetime drain count (stats)
        self.reinstatements = 0


class HealthMonitor:
    """Heartbeat prober + drain/reinstate state machine over named targets.

    Args:
      probe_interval_s: heartbeat cadence for HEALTHY targets (0 disables
        proactive probing — dispatch evidence alone then drives drains).
      reprobe_interval_s: re-probe cadence for DOWN targets (the
        reinstatement path; also the honest `retry_after_s` to hand a
        client when nothing is serving).
      fail_threshold: consecutive failures (either evidence stream) that
        mark a target DOWN.
      clock: injectable monotonic clock.
    """

    def __init__(self, probe_interval_s: float = 2.0,
                 reprobe_interval_s: float = 1.0, fail_threshold: int = 3,
                 clock=time.monotonic):
        if fail_threshold < 1:
            raise ValueError(
                f"fail_threshold must be >= 1, got {fail_threshold}"
            )
        if probe_interval_s < 0 or reprobe_interval_s <= 0:
            raise ValueError(
                "probe_interval_s must be >= 0 and reprobe_interval_s > 0, "
                f"got {probe_interval_s}/{reprobe_interval_s}"
            )
        self.probe_interval_s = probe_interval_s
        self.reprobe_interval_s = reprobe_interval_s
        self.fail_threshold = fail_threshold
        self._clock = clock
        self._lock = threading.Lock()
        self._targets: Dict[str, _Target] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ registry

    def register(self, name: str, probe: Optional[Callable[[], bool]] = None,
                 on_drain: Optional[Callable[[str, str], None]] = None,
                 on_reinstate: Optional[Callable[[str], None]] = None):
        """Add a target (HEALTHY). `probe()` returns truthy when the
        replica serves end to end; `on_drain(name, reason)` /
        `on_reinstate(name)` run on the tick thread."""
        with self._lock:
            if name in self._targets:
                raise ValueError(f"target {name!r} already registered")
            self._targets[name] = _Target(name, probe, on_drain, on_reinstate)

    def state(self, name: str) -> ReplicaState:
        with self._lock:
            return self._targets[name].state

    def healthy_targets(self) -> list:
        """Names currently eligible for traffic (drain may still be
        pending on a DOWN target — it is already excluded here, which is
        what keeps the window between decision and drain safe)."""
        with self._lock:
            return [t.name for t in self._targets.values()
                    if t.state is ReplicaState.HEALTHY]

    # ----------------------------------------------- dispatch evidence

    def record_success(self, name: str):
        """Router-reported dispatch success: clears the failure streak.
        Deliberately does NOT reinstate a DOWN target — a straggler
        success from before the drain decision is stale evidence; the
        re-probe path owns reinstatement."""
        with self._lock:
            t = self._targets[name]
            t.consecutive_failures = 0
            t.consecutive_probe_failures = 0

    def record_failure(self, name: str, reason: str = "") -> bool:
        """Router-reported dispatch failure (replica-attributed: breaker
        open, hung batch, model exception, engine death). Returns True
        when this report crossed the threshold and marked the target
        DOWN. The drain callback runs on the next tick(), never here —
        the reporting thread may BE the replica worker being drained."""
        with self._lock:
            t = self._targets[name]
            if t.state is ReplicaState.DOWN:
                return False
            t.consecutive_failures += 1
            if t.consecutive_failures >= self.fail_threshold:
                self._mark_down(t, reason or "dispatch failures")
                return True
            return False

    def force_down(self, name: str, reason: str):
        """Immediate drain decision (operator action, breaker trip where
        one report IS conclusive). Same deferred-callback contract."""
        with self._lock:
            t = self._targets[name]
            if t.state is not ReplicaState.DOWN:
                self._mark_down(t, reason)

    def retire(self, name: str, reason: str = "retired"):
        """Deliberate permanent removal (autoscale scale-down, rolling
        replacement): the target takes no more traffic, its `on_drain`
        callback runs on the next tick — the SAME drain path a sick
        replica takes, so the owner's teardown logic is one code path —
        and it is never re-probed or reinstated; once the drain has run,
        the target is unregistered. Idempotent, and safe to call on a
        target that is already DOWN (e.g. a failure drain racing an
        autoscale decision): the drain callback is re-scheduled exactly
        once and the owner's callback must tolerate an already-torn-down
        replica (the fleet's does — that is the no-double-drain pin)."""
        with self._lock:
            t = self._targets.get(name)
            if t is None or t.retiring:
                return
            t.retiring = True
            if t.state is not ReplicaState.DOWN:
                self._mark_down(t, reason)
            else:
                # already down (possibly already drained): schedule one
                # cleanup pass through the same callback
                t.down_reason = t.down_reason or reason
                t.drain_pending = True

    def unregister(self, name: str):
        """Drop a target from supervision (no callbacks). The retire()
        path calls this itself after the final drain; direct use is for
        owners tearing down out-of-band."""
        with self._lock:
            self._targets.pop(name, None)

    def _mark_down(self, t: _Target, reason: str):
        t.state = ReplicaState.DOWN
        t.down_since = self._clock()
        t.down_reason = reason
        t.drain_pending = True
        t.drains += 1

    # ------------------------------------------------------------- ticking

    def tick(self, now: Optional[float] = None):
        """One supervision pass: run pending drains, heartbeat-probe due
        HEALTHY targets, re-probe due DOWN targets. Callbacks and probes
        run OUTSIDE the lock (they take seconds and may touch the fleet's
        own locks)."""
        now = self._clock() if now is None else now
        with self._lock:
            drains = [(t, t.down_reason) for t in self._targets.values()
                      if t.drain_pending]
            for t, _ in drains:
                t.drain_pending = False
            probes = [t for t in self._targets.values()
                      if self._probe_due(t, now)]
            for t in probes:
                t.last_probe_at = now
        for t, reason in drains:
            # re-check: a probe that was already in flight when the drain
            # was decided may have reinstated the target in between — a
            # stale drain against a now-healthy replica would tear down
            # the very engine the reinstatement just vouched for
            with self._lock:
                if t.state is not ReplicaState.DOWN:
                    continue
            if t.on_drain is not None:
                try:
                    t.on_drain(t.name, reason)
                except Exception:  # noqa: BLE001 — supervision must survive
                    traceback.print_exc()
            if t.retiring:
                # the final drain has run: the target leaves supervision
                # (no re-probe could ever reinstate it)
                self.unregister(t.name)
        for t in probes:
            self._run_probe(t)

    def _probe_due(self, t: _Target, now: float) -> bool:
        if t.probe is None or t.drain_pending or t.retiring:
            return False
        if t.state is ReplicaState.HEALTHY:
            if self.probe_interval_s <= 0:
                return False
            return (t.last_probe_at is None
                    or now - t.last_probe_at >= self.probe_interval_s)
        return (t.last_probe_at is None
                or now - t.last_probe_at >= self.reprobe_interval_s)

    def _run_probe(self, t: _Target):
        try:
            ok = bool(t.probe())
        except Exception:  # noqa: BLE001 — a raising probe is a failing probe
            ok = False
        reinstate = drain = None
        with self._lock:
            if ok:
                t.consecutive_probe_failures = 0
                t.consecutive_failures = 0
                if t.state is ReplicaState.DOWN:
                    t.state = ReplicaState.HEALTHY
                    t.down_since = None
                    t.down_reason = ""
                    t.drain_pending = False  # a queued drain is now moot
                    t.reinstatements += 1
                    reinstate = t.on_reinstate
            elif t.state is ReplicaState.HEALTHY:
                t.consecutive_probe_failures += 1
                if t.consecutive_probe_failures >= self.fail_threshold:
                    self._mark_down(t, "probe failures")
                    # drain immediately: we ARE the tick thread, and
                    # waiting a full tick just extends the window in
                    # which the router can still see stale state
                    t.drain_pending = False
                    drain = t.on_drain
            reason = t.down_reason
        # callbacks outside the lock
        if reinstate is not None:
            try:
                reinstate(t.name)
            except Exception:  # noqa: BLE001
                traceback.print_exc()
        if drain is not None:
            try:
                drain(t.name, reason)
            except Exception:  # noqa: BLE001
                traceback.print_exc()

    # ------------------------------------------------------------- thread

    def start(self, interval_s: float = 0.1):
        """Run tick() on a daemon thread every `interval_s` (the thread
        granularity; probe cadences are enforced by the state machine)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — supervision must survive
                    traceback.print_exc()

        self._thread = threading.Thread(
            target=loop, name="af2-health-monitor", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: Optional[float] = 5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    # -------------------------------------------------------------- stats

    def snapshot(self) -> dict:
        with self._lock:
            now = self._clock()
            return {
                "fail_threshold": self.fail_threshold,
                "probe_interval_s": self.probe_interval_s,
                "reprobe_interval_s": self.reprobe_interval_s,
                "targets": {
                    t.name: {
                        "state": t.state.value,
                        "retiring": t.retiring,
                        "consecutive_failures": t.consecutive_failures,
                        "drains": t.drains,
                        "reinstatements": t.reinstatements,
                        **({"down_for_s": now - t.down_since,
                            "down_reason": t.down_reason}
                           if t.down_since is not None else {}),
                    }
                    for t in self._targets.values()
                },
            }
