"""Fleet-wide retry budget: a token bucket refilled by SUCCESS.

Every internal retry the serving fleet can generate — featurize-tier
requeues after a worker death, replica-failover requeues, hedged
dispatches — amplifies load exactly when the fleet can least afford it:
a brownout where every replica is failing turns each accepted request
into `requeue_limit + 1` dispatch attempts, and the retry traffic itself
keeps the fleet pinned. The classic fix (the SRE-book "retry budget") is
to make retries a SHARED, bounded resource priced in recent successes:
the bucket starts full at `capacity` tokens, every retry of any kind
spends one token, and every SUCCESSFUL completion refills `refill_ratio`
tokens. While the fleet is healthy, successes keep the bucket topped up
and retries are free; when the whole fleet browns out, successes stop,
the bucket drains within `capacity` attempts, and further retries are
denied — the caller sheds with a typed
`RetryBudgetExhaustedError(retry_after_s)` instead of dogpiling.

`try_spend(reason)` is the single gate (reasons: "featurize" /
"failover" / "hedge" — each counted per-label in
`retry_budget_spent_total` / `retry_budget_exhausted_total`), and
`retry_after_s()` converts the deficit into backoff advice: how long,
at the recently observed success rate, until refill has earned the next
token. No successes observed recently means the honest answer is "the
max" — a client retrying into a fleet with zero throughput cannot be
admitted sooner.

Deliberately serving-agnostic (no serving imports — the fleet wraps the
denial in its own error type), clock-injectable, and guarded by one leaf
lock that never calls out while held.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class RetryBudget:
    """Thread-safe success-refilled token bucket for internal retries.

    capacity        bucket size == the largest retry burst the fleet may
                    emit with zero recent successes (the brownout bound).
    refill_ratio    tokens earned per successful completion. 0.1 means
                    "retries may be at most ~10% of success throughput"
                    once the initial capacity is spent.
    """

    def __init__(self, capacity: int, *, refill_ratio: float = 0.1,
                 min_retry_after_s: float = 0.25,
                 max_retry_after_s: float = 30.0,
                 clock=time.monotonic):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if not (0.0 < refill_ratio <= 1.0):
            raise ValueError(
                f"refill_ratio must be in (0, 1], got {refill_ratio}")
        self.capacity = int(capacity)
        self.refill_ratio = float(refill_ratio)
        self.min_retry_after_s = float(min_retry_after_s)
        self.max_retry_after_s = float(max_retry_after_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = float(capacity)
        self._spent = 0
        self._denied = 0
        self._successes = 0
        self._last_success_t: Optional[float] = None
        # EMA of the inter-success interval — the "how fast is the fleet
        # actually earning tokens" signal behind retry_after_s()
        self._success_interval_ema: Optional[float] = None
        self._registry = None

    def bind_registry(self, registry) -> "RetryBudget":
        """Attach a MetricRegistry: publishes `retry_budget_tokens` plus
        the per-reason spend/denial counters. Optional — the bucket works
        unmetered (unit tests, bench arms)."""
        self._registry = registry
        registry.gauge(
            "retry_budget_tokens",
            help="retry-budget tokens currently available",
        ).set(self.tokens())
        return self

    # ------------------------------------------------------------- spending

    def try_spend(self, reason: str) -> bool:
        """Spend one token for a retry of kind `reason`. False == denied:
        the caller must shed (RetryBudgetExhaustedError) instead of
        retrying. Never blocks."""
        with self._lock:
            ok = self._tokens >= 1.0
            if ok:
                self._tokens -= 1.0
                self._spent += 1
            else:
                self._denied += 1
            tokens = self._tokens
        reg = self._registry
        if reg is not None:
            if ok:
                reg.counter("retry_budget_spent_total",
                            reason=reason).inc()
            else:
                reg.counter("retry_budget_exhausted_total",
                            reason=reason).inc()
            reg.gauge("retry_budget_tokens").set(tokens)
        return ok

    def on_success(self):
        """Record one successful completion: refill `refill_ratio` tokens
        (capped at capacity) and update the success-rate estimate."""
        now = self._clock()
        with self._lock:
            self._tokens = min(float(self.capacity),
                               self._tokens + self.refill_ratio)
            self._successes += 1
            if self._last_success_t is not None:
                dt = max(1e-6, now - self._last_success_t)
                ema = self._success_interval_ema
                self._success_interval_ema = (
                    dt if ema is None else 0.2 * dt + 0.8 * ema)
            self._last_success_t = now
            tokens = self._tokens
        reg = self._registry
        if reg is not None:
            reg.gauge("retry_budget_tokens").set(tokens)

    # ------------------------------------------------------------- reading

    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def retry_after_s(self) -> float:
        """Backoff advice for a denied retry: time until refill earns the
        next whole token at the recently observed success rate, clamped
        to [min_retry_after_s, max_retry_after_s]. With no observed
        successes the answer is the max — a fleet earning nothing cannot
        promise sooner."""
        with self._lock:
            deficit = max(0.0, 1.0 - self._tokens)
            interval = self._success_interval_ema
        if deficit == 0.0:
            return self.min_retry_after_s
        if interval is None:
            return self.max_retry_after_s
        successes_needed = deficit / self.refill_ratio
        est = successes_needed * interval
        return min(self.max_retry_after_s, max(self.min_retry_after_s, est))

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "capacity": self.capacity,
                "tokens": round(self._tokens, 3),
                "refill_ratio": self.refill_ratio,
                "spent": self._spent,
                "denied": self._denied,
                "successes": self._successes,
            }
        snap["retry_after_s"] = round(self.retry_after_s(), 3)
        return snap
