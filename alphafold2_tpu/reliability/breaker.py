"""Consecutive-failure circuit breaker for the serving engine.

When the model call starts failing every dispatch (wedged device, bad
params push, poisoned executable cache), retry-per-request turns the
engine into a failure amplifier: every queued request burns a device call
to learn what the last one already proved. The breaker converts that into
fast rejection:

  closed     normal serving; `failures` consecutive dispatch failures trip
             it (any success resets the count).
  open       submit() fast-rejects with CircuitOpenError — no queue time,
             no device call — until `reset_s` has elapsed.
  half_open  exactly one probe dispatch is admitted; success closes the
             circuit, failure re-opens it for another `reset_s`.

The state machine is standalone and clock-injectable so tests drive it
deterministically; the engine wires it via `ServingConfig.breaker_threshold`
/ `breaker_reset_s` and reports dispatch outcomes from the worker thread.

Thread model: `allow()` runs on submitter threads, `record_*` on the
engine worker — every transition happens under one lock. A success
recorded while open (a straggler dispatch from before the trip) closes
the circuit: evidence the model works beats the timer.

Fleet deployments add one wrinkle: when a shared dependency (the device
runtime, a params push) fails every replica at once, N breakers with the
same `reset_s` all re-probe at the same instant — a thundering-herd
reopen that can re-wedge the dependency the moment it recovers. `jitter`
spreads the open→half-open delay: each open transition draws its window
from `reset_s * [1, 1 + jitter]` using a seeded PRNG, so a fleet of
breakers seeded differently de-synchronizes deterministically. The
default (jitter=0) keeps the exact fixed-window arm the chaos tests
drive.
"""

from __future__ import annotations

import enum
import random
import threading
import time


class CircuitState(str, enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreaker:
    """Args beyond the state-machine knobs: `on_open` is an optional
    callback invoked with `snapshot()` each time the circuit transitions
    to OPEN (a fresh trip or a failed half-open probe re-opening) — the
    flight recorder's incident seam (telemetry/ops_plane.py). It runs
    OUTSIDE the breaker lock on the thread that recorded the failure;
    exceptions are printed and swallowed (observability must never wedge
    the dispatch path)."""

    def __init__(self, threshold: int, reset_s: float, clock=time.monotonic,
                 jitter: float = 0.0, seed: int = 0, on_open=None):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        if reset_s < 0:
            raise ValueError(f"reset_s must be >= 0, got {reset_s}")
        if jitter < 0:
            raise ValueError(f"jitter must be >= 0, got {jitter}")
        self.threshold = threshold
        self.reset_s = reset_s
        self.jitter = jitter
        self.on_open = on_open
        # seeded, per-instance: two breakers with different seeds draw
        # different delay sequences; the same seed replays exactly
        self._rng = random.Random(seed)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CircuitState.CLOSED
        self._failures = 0          # consecutive failures while closed
        self._opened_at = 0.0
        self._current_reset_s = reset_s  # this open window's jittered length
        self._probe_in_flight = False
        self._trips = 0             # lifetime open transitions (stats)

    def _open(self, now: float):
        """Transition to OPEN (lock held): draw this window's length."""
        self._state = CircuitState.OPEN
        self._opened_at = now
        self._current_reset_s = self.reset_s * (
            1.0 + (self._rng.uniform(0.0, self.jitter) if self.jitter else 0.0)
        )
        self._trips += 1

    @property
    def state(self) -> CircuitState:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a new request be admitted right now? Claims the half-open
        probe slot when the reset window has elapsed."""
        with self._lock:
            if self._state is CircuitState.CLOSED:
                return True
            if (
                self._state is CircuitState.OPEN
                and self._clock() - self._opened_at >= self._current_reset_s
            ):
                self._state = CircuitState.HALF_OPEN
                self._probe_in_flight = True
                return True
            # open inside the reset window, or half-open with the probe
            # already out: shed
            return False

    def record_success(self):
        with self._lock:
            self._state = CircuitState.CLOSED
            self._failures = 0
            self._probe_in_flight = False

    def record_failure(self):
        opened = False
        with self._lock:
            now = self._clock()
            if self._state is CircuitState.HALF_OPEN:
                # the probe failed: back to open for a fresh window
                self._open(now)
                self._probe_in_flight = False
                opened = True
            elif self._state is CircuitState.CLOSED:
                self._failures += 1
                if self._failures >= self.threshold:
                    self._open(now)
                    opened = True
            # already open: stragglers from pre-trip dispatches are no news
        if opened and self.on_open is not None:
            try:
                self.on_open(self.snapshot())
            except Exception:  # noqa: BLE001 — see class docstring
                import traceback

                traceback.print_exc()

    def abandon_probe(self):
        """The admitted half-open probe never produced a dispatch outcome
        (queue full, scheduler-side expiry): return to open WITHOUT
        counting a failure or restarting the window, so the next submit
        can claim a fresh probe immediately. No-op outside half-open."""
        with self._lock:
            if self._state is CircuitState.HALF_OPEN:
                self._state = CircuitState.OPEN
                self._probe_in_flight = False

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "state": self._state.value,
                "consecutive_failures": self._failures,
                "threshold": self.threshold,
                "reset_s": self.reset_s,
                "trips": self._trips,
            }
            if self.jitter:
                snap["jitter"] = self.jitter
                snap["current_reset_s"] = self._current_reset_s
            if self._state is not CircuitState.CLOSED:
                snap["open_for_s"] = max(0.0, self._clock() - self._opened_at)
            return snap
