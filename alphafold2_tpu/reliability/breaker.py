"""Consecutive-failure circuit breaker for the serving engine.

When the model call starts failing every dispatch (wedged device, bad
params push, poisoned executable cache), retry-per-request turns the
engine into a failure amplifier: every queued request burns a device call
to learn what the last one already proved. The breaker converts that into
fast rejection:

  closed     normal serving; `failures` consecutive dispatch failures trip
             it (any success resets the count).
  open       submit() fast-rejects with CircuitOpenError — no queue time,
             no device call — until `reset_s` has elapsed.
  half_open  exactly one probe dispatch is admitted; success closes the
             circuit, failure re-opens it for another `reset_s`.

The state machine is standalone and clock-injectable so tests drive it
deterministically; the engine wires it via `ServingConfig.breaker_threshold`
/ `breaker_reset_s` and reports dispatch outcomes from the worker thread.

Thread model: `allow()` runs on submitter threads, `record_*` on the
engine worker — every transition happens under one lock. A success
recorded while open (a straggler dispatch from before the trip) closes
the circuit: evidence the model works beats the timer.
"""

from __future__ import annotations

import enum
import threading
import time


class CircuitState(str, enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(self, threshold: int, reset_s: float, clock=time.monotonic):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        if reset_s < 0:
            raise ValueError(f"reset_s must be >= 0, got {reset_s}")
        self.threshold = threshold
        self.reset_s = reset_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CircuitState.CLOSED
        self._failures = 0          # consecutive failures while closed
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._trips = 0             # lifetime open transitions (stats)

    @property
    def state(self) -> CircuitState:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a new request be admitted right now? Claims the half-open
        probe slot when the reset window has elapsed."""
        with self._lock:
            if self._state is CircuitState.CLOSED:
                return True
            if (
                self._state is CircuitState.OPEN
                and self._clock() - self._opened_at >= self.reset_s
            ):
                self._state = CircuitState.HALF_OPEN
                self._probe_in_flight = True
                return True
            # open inside the reset window, or half-open with the probe
            # already out: shed
            return False

    def record_success(self):
        with self._lock:
            self._state = CircuitState.CLOSED
            self._failures = 0
            self._probe_in_flight = False

    def record_failure(self):
        with self._lock:
            now = self._clock()
            if self._state is CircuitState.HALF_OPEN:
                # the probe failed: back to open for a fresh window
                self._state = CircuitState.OPEN
                self._opened_at = now
                self._probe_in_flight = False
                self._trips += 1
            elif self._state is CircuitState.CLOSED:
                self._failures += 1
                if self._failures >= self.threshold:
                    self._state = CircuitState.OPEN
                    self._opened_at = now
                    self._trips += 1
            # already open: stragglers from pre-trip dispatches are no news

    def abandon_probe(self):
        """The admitted half-open probe never produced a dispatch outcome
        (queue full, scheduler-side expiry): return to open WITHOUT
        counting a failure or restarting the window, so the next submit
        can claim a fresh probe immediately. No-op outside half-open."""
        with self._lock:
            if self._state is CircuitState.HALF_OPEN:
                self._state = CircuitState.OPEN
                self._probe_in_flight = False

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "state": self._state.value,
                "consecutive_failures": self._failures,
                "threshold": self.threshold,
                "reset_s": self.reset_s,
                "trips": self._trips,
            }
            if self._state is not CircuitState.CLOSED:
                snap["open_for_s"] = max(0.0, self._clock() - self._opened_at)
            return snap
