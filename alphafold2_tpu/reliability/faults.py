"""Deterministic fault injection: a seeded schedule of failures.

A `FaultPlan` is a declarative list of faults, loadable from JSON (the
`--fault-plan` trainer flag), and a `FaultInjector` is its stateful
executor: each hook site in the stack asks the injector whether a fault
fires at the current index, and the injector delivers it (raise, poison,
corrupt, sleep) at most `count` times. Determinism is the whole point —
the same plan against the same seeds produces the same failure sequence,
so the chaos suite can assert BIT-EXACT recovery instead of "it didn't
crash".

Hook sites (all optional, zero-cost when no injector is wired):

  training/harness.py   `with_fault_injection(step_fn, injector)` — the
                        host-side step wrapper; delivers `step_exception`,
                        `nan_grads` (the step's reported loss/grad_norm
                        come back NaN, so StepGuard must detect and roll
                        back), and `preempt` (SIGTERM-style, via a bound
                        PreemptionHandler).
  training/data.py      `resilient_batches(..., injector=...)` — delivers
                        `data_error` (raise) and `slow_data` (stall the
                        fetch `delay_s`) at fetch index N.
  training/checkpoint.py  `VerifiedCheckpointManager(fault_hook=
                        injector.checkpoint_hook())` — delivers
                        `ckpt_corrupt` (truncate / bit-corrupt /
                        manifest-missing) against the just-written step.
  serving/engine.py     `ServingEngine(fault_hook=injector.serving_hook())`
                        — delivers `request_error`, `slow_request`,
                        `hung_request` at dispatch index N.

Indices are per-site counters (train step number, batch fetch index,
checkpoint step, serving dispatch index), so one plan can script a whole
scenario: "data error at batch 2, corrupt the step-3 checkpoint, crash
step 4, preempt at step 6".

Replica-scoped faults (`kill_replica` / `slow_replica` / `flap_replica`)
target one NAMED replica of a serving fleet (`serving/fleet.py` wires
`injector.replica_hook(name)` into each replica engine). Their index is a
per-replica dispatch counter kept by the INJECTOR — not the engine — so
it survives the engine restarts that drain/reinstate cycles perform:

  kill_replica   every dispatch on `replica` raises from index `at` on,
                 FOREVER (latched; `count` is ignored — a killed replica
                 stays dead until the plan's author says otherwise).
  slow_replica   sleep `delay_s` per dispatch, `count` deliveries.
  flap_replica   raise per dispatch, `count` deliveries, then healthy —
                 the health manager's re-probe path reinstates it.

Validate a hand-written plan before paying for a run:

  python -m alphafold2_tpu.reliability.faults --check plan.json
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import List, Optional

import numpy as np

FAULT_KINDS = (
    "step_exception",   # raise InjectedFault before train step `at`
    "nan_grads",        # step `at` reports NaN loss/grad_norm (rollback bait)
    "preempt",          # SIGTERM-style preemption request at step `at`
    "ckpt_corrupt",     # damage the checkpoint written for step `at`
    "data_error",       # raise InjectedFault at batch fetch index `at`
    "slow_data",        # sleep `delay_s` at batch fetch index `at` — a
    #                     stalled input pipeline (slow FS / cold cache);
    #                     the goodput ledger must book it as data-stall
    #                     badput and the straggler detector must page
    #                     train_data_stall, never crash the run
    "request_error",    # raise InjectedFault at serving dispatch index `at`
    "slow_request",     # sleep `delay_s` at serving dispatch index `at`
    "hung_request",     # sleep `hang_s` (watchdog fodder) at dispatch `at`
    "kill_replica",     # named fleet replica: fail every dispatch from `at` on
    "slow_replica",     # named fleet replica: sleep `delay_s` per dispatch
    "flap_replica",     # named fleet replica: fail `count` dispatches, recover
    "slow_featurize",   # featurize tier: sleep `delay_s` at job index `at`
    "kill_featurize_worker",  # featurize tier: kill the worker thread
    #                     serving job index `at` (the pool must respawn it
    #                     and not lose the job)
    "scale_flap",       # autoscaler: force alternating up/down demands at
    #                     tick index `at` (`count` forced demands) — the
    #                     hysteresis window must absorb them
    "crash_process",    # kill -9 the WHOLE serving process (os._exit 137)
    #                     at process-wide dispatch index `at` — the intake
    #                     journal must replay every accepted-but-unsettled
    #                     request after restart
    "straggle_dispatch",  # named fleet replica: sleep `delay_s` per
    #                     dispatch, `count` deliveries — a long-tail
    #                     straggler that eventually SUCCEEDS (unlike
    #                     slow_replica's transient slowness, this is the
    #                     hedged-dispatch trigger: delay_s sits far past
    #                     the pool's p95)
)

#: kinds that target one named fleet replica and require `replica`
REPLICA_FAULT_KINDS = ("kill_replica", "slow_replica", "flap_replica",
                       "straggle_dispatch")

_CKPT_MODES = ("truncate", "corrupt", "no_manifest")


class InjectedFault(RuntimeError):
    """The exception every raising fault kind delivers — chaos tests (and
    recovery-path logs) can tell injected failures from organic ones."""


class WorkerKilled(InjectedFault):
    """`kill_featurize_worker`'s delivery: distinct from a plain
    InjectedFault because the featurize pool must treat it as the WORKER
    dying (respawn the thread, requeue the job) rather than the request
    failing — exactly how an organic thread death differs from a bad
    input."""


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scheduled fault. Fires while `index >= at` and fewer than
    `count` deliveries have happened — count=1 (the default) fires exactly
    once at index `at`; a large count models an always-failing component."""

    kind: str
    at: int = 0
    count: int = 1
    mode: str = "truncate"      # ckpt_corrupt: truncate | corrupt | no_manifest
    delay_s: float = 0.05       # slow_request / slow_replica sleep
    hang_s: float = 30.0        # hung_request sleep (past any sane watchdog)
    replica: str = ""           # *_replica kinds: the named fleet replica
    message: str = ""

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; expected one of {FAULT_KINDS}"
            )
        if self.kind == "ckpt_corrupt" and self.mode not in _CKPT_MODES:
            raise ValueError(
                f"ckpt_corrupt mode {self.mode!r} not in {_CKPT_MODES}"
            )
        if self.count < 1:
            raise ValueError(f"count must be >= 1, got {self.count}")
        if self.kind in REPLICA_FAULT_KINDS and not self.replica:
            raise ValueError(
                f"{self.kind} requires a 'replica' name (e.g. \"r0\") — a "
                f"replica-scoped fault with no target would silently no-op"
            )
        if self.replica and self.kind not in REPLICA_FAULT_KINDS:
            raise ValueError(
                f"'replica' is only meaningful for {REPLICA_FAULT_KINDS}, "
                f"not {self.kind!r}"
            )

    def describe(self) -> str:
        if self.message:
            return self.message
        where = f"replica {self.replica!r}, " if self.replica else ""
        return f"injected {self.kind} ({where}index {self.at})"


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Immutable fault schedule; `injector()` mints a fresh stateful
    executor (one per run — delivery counters live on the injector, so a
    plan can drive the faulted and fault-free arms of a comparison)."""

    faults: tuple = ()
    seed: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        unknown_top = set(d) - {"faults", "seed"}
        if unknown_top:
            raise ValueError(
                f"unknown fault-plan key(s) {sorted(unknown_top)}; a plan is "
                f"{{\"seed\": int, \"faults\": [...]}}"
            )
        allowed = {f.name for f in dataclasses.fields(Fault)}
        faults = []
        for i, f in enumerate(d.get("faults", ())):
            f = dict(f)
            # "step"/"index" read more naturally in hand-written plans
            for alias in ("step", "index"):
                if alias in f:
                    f["at"] = f.pop(alias)
            unknown = set(f) - allowed
            if unknown:
                # loud, not a generic TypeError (and NEVER a silent drop):
                # a typo'd field means the plan does not say what its
                # author thinks it says
                raise ValueError(
                    f"fault #{i} ({f.get('kind', '?')!r}): unknown field(s) "
                    f"{sorted(unknown)}; allowed: "
                    f"{sorted(allowed | {'step', 'index'})}"
                )
            faults.append(Fault(**f))
        return cls(faults=tuple(faults), seed=int(d.get("seed", 0)))

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls.from_dict(json.loads(text))

    @classmethod
    def from_file(cls, path: str) -> "FaultPlan":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "faults": [dataclasses.asdict(f) for f in self.faults],
        }, indent=2)

    def injector(self) -> "FaultInjector":
        return FaultInjector(self)


def poison_metrics(metrics: dict) -> dict:
    """NaN the scalar health signals a step reports (loss, grad_norm).

    This is what a NaN-poisoned gradient LOOKS LIKE to the supervisor — a
    non-finite metric crossing the host boundary — and the detection path
    (StepGuard's isfinite watchdog, rollback, retry) cannot tell where the
    NaN originated, so poisoning at the boundary exercises the identical
    recovery machinery for every task (the seq-only distogram task has no
    float model input to poison upstream).
    """
    out = dict(metrics)
    for key in ("loss", "grad_norm"):
        if key in out:
            out[key] = np.float32(np.nan)
    return out


class FaultInjector:
    """Stateful executor of a FaultPlan. Thread-safe: the serving hook is
    called from the engine worker thread while training hooks run on the
    main thread."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._fired = [0] * len(plan.faults)
        self._replica_dispatch = {}  # replica name -> injector-side counter
        self._preemption = None  # bound PreemptionHandler for `preempt`
        self.delivered: List[str] = []  # audit log of delivered faults
        # precomputed so fault-free dispatch hooks skip the extra lock
        # roundtrip the process-wide crash counter would cost
        self._has_crash = any(f.kind == "crash_process" for f in plan.faults)

    def bind_preemption(self, handler):
        """Attach the PreemptionHandler that `preempt` faults trip (the
        deterministic stand-in for the cluster's SIGTERM delivery)."""
        self._preemption = handler
        return self

    def _take(self, kind: str, index: int,
              replica: str = "") -> Optional[Fault]:
        """Claim a matching fault (at most `count` deliveries), or None.
        `kill_replica` is LATCHED: it keeps delivering past any count —
        a killed replica must stay dead across health re-probes."""
        with self._lock:
            for i, f in enumerate(self.plan.faults):
                if f.kind != kind or f.replica != replica or index < f.at:
                    continue
                if f.kind != "kill_replica" and self._fired[i] >= f.count:
                    continue
                self._fired[i] += 1
                # audit log: a latched kill delivers on EVERY dispatch and
                # re-probe forever — record only its first delivery so the
                # log (and the serve.py summary that prints it) stays
                # bounded over a long soak
                if f.kind != "kill_replica" or self._fired[i] == 1:
                    tag = f"{kind}[{replica}]" if replica else kind
                    self.delivered.append(f"{tag}@{index}")
                return f
        return None

    def exhausted(self) -> bool:
        """True when every scheduled fault has delivered all its counts —
        chaos tests assert this so a plan that never fired cannot pass.
        A latched `kill_replica` counts as exhausted after ONE delivery
        (it has no finite count to drain)."""
        with self._lock:
            return all(
                fired >= (1 if f.kind == "kill_replica" else f.count)
                for fired, f in zip(self._fired, self.plan.faults)
            )

    # -- hook: training step (training/harness.py) --------------------------

    def before_train_step(self, step: int, batch):
        """Called host-side before each train step; returns the batch or
        raises (step_exception) / trips preemption."""
        f = self._take("preempt", step)
        if f is not None:
            if self._preemption is None:
                raise RuntimeError(
                    "preempt fault scheduled but no PreemptionHandler bound "
                    "(injector.bind_preemption)"
                )
            self._preemption.deliver()
        f = self._take("step_exception", step)
        if f is not None:
            raise InjectedFault(f.describe())
        return batch

    def after_train_step(self, step: int, new_state, metrics):
        """Called host-side on each step's result; a `nan_grads` fault
        makes the step's reported metrics non-finite, which StepGuard must
        catch and roll back (the retry refetches the same step and, with
        the fault spent, reconverges bit-exact)."""
        if self._take("nan_grads", step) is not None:
            return new_state, poison_metrics(metrics)
        return new_state, metrics

    # -- hook: data pipeline (training/data.py) ------------------------------

    def before_batch(self, index: int):
        f = self._take("slow_data", index)
        if f is not None:
            import time

            time.sleep(f.delay_s)
        f = self._take("data_error", index)
        if f is not None:
            raise InjectedFault(f.describe())

    # -- hook: checkpoint writes (training/checkpoint.py) --------------------

    def checkpoint_hook(self):
        """Returns the VerifiedCheckpointManager fault_hook: called with
        (step, state_path, manifest_path) after a completed write, it
        damages the files the way a crash mid-write would."""
        import os

        def hook(step: int, state_path: str, manifest_path: str):
            f = self._take("ckpt_corrupt", step)
            if f is None:
                return
            if f.mode == "no_manifest":
                # crash between data write and manifest write
                os.unlink(manifest_path)
                return
            size = os.path.getsize(state_path)
            with open(state_path, "r+b") as fh:
                if f.mode == "truncate":
                    fh.truncate(max(1, size // 2))  # torn write
                else:  # corrupt: flip bytes mid-file, size preserved
                    fh.seek(size // 2)
                    fh.write(b"\xde\xad\xbe\xef")

        return hook

    def _maybe_crash(self):
        """Deliver a scheduled `crash_process`: die the way `kill -9` does
        — no atexit, no flushing, exit code 137 — at the PROCESS-wide
        dispatch index. Every serving dispatch advances the counter (the
        single-engine `serving_hook` and every fleet `replica_hook` feed
        one shared `__process__` counter), so "crash with N requests in
        flight" is a deterministic plan, not a sleep race. The intake
        journal (serving/journal.py) is what must survive this."""
        if not self._has_crash:
            return
        with self._lock:
            index = self._replica_dispatch.get("__process__", 0)
            self._replica_dispatch["__process__"] = index + 1
        if self._take("crash_process", index) is not None:
            import os

            os._exit(137)

    # -- hook: serving dispatch (serving/engine.py) --------------------------

    def serving_hook(self):
        """Returns the ServingEngine fault_hook: called with
        (dispatch_index, bucket) at the top of every model dispatch."""
        import time

        def hook(index: int, bucket: int):
            self._maybe_crash()
            f = self._take("slow_request", index)
            if f is not None:
                time.sleep(f.delay_s)
            f = self._take("hung_request", index)
            if f is not None:
                # a wedged device call: sleeps far past the watchdog, on
                # the (abandonable) dispatch thread
                time.sleep(f.hang_s)
            f = self._take("request_error", index)
            if f is not None:
                raise InjectedFault(f.describe())

        return hook

    # -- hook: fleet replica dispatch (serving/fleet.py) ---------------------

    def replica_hook(self, name: str):
        """Returns a ServingEngine fault_hook scoped to fleet replica
        `name`, delivering kill/slow/flap faults. The dispatch index is an
        injector-side per-replica counter (NOT the engine's): a drained
        replica is reinstated behind a FRESH engine whose own counter
        restarts at zero, and the fault schedule must not rewind with it.
        Health probes dispatch through the same hook, so a killed replica
        fails its re-probes too — exactly like a dead device would."""
        import time

        def hook(engine_index: int, bucket: int):
            self._maybe_crash()
            with self._lock:
                index = self._replica_dispatch.get(name, 0)
                self._replica_dispatch[name] = index + 1
            f = self._take("slow_replica", index, replica=name)
            if f is not None:
                time.sleep(f.delay_s)
            f = self._take("straggle_dispatch", index, replica=name)
            if f is not None:
                # long-tail straggler: stall the dispatch but let it
                # SUCCEED — the hedge timer, not the failure path, is
                # what should beat it
                time.sleep(f.delay_s)
            f = self._take("kill_replica", index, replica=name)
            if f is not None:
                raise InjectedFault(f.describe())
            f = self._take("flap_replica", index, replica=name)
            if f is not None:
                raise InjectedFault(f.describe())

        return hook

    # -- hook: featurize tier (serving/featurize.py) -------------------------

    def featurize_hook(self):
        """Returns the FeaturizePool fault_hook: called with the pool's
        job index at the top of every featurize job. The index is an
        INJECTOR-side counter (the replica_hook stance): a respawned
        worker thread must not rewind the schedule. `slow_featurize`
        sleeps on the worker; `kill_featurize_worker` raises
        `WorkerKilled`, which the pool converts into a worker death +
        job requeue rather than a request failure."""
        import time

        def hook(engine_index: int):
            with self._lock:
                index = self._replica_dispatch.get("__featurize__", 0)
                self._replica_dispatch["__featurize__"] = index + 1
            f = self._take("slow_featurize", index)
            if f is not None:
                time.sleep(f.delay_s)
            f = self._take("kill_featurize_worker", index)
            if f is not None:
                raise WorkerKilled(f.describe())

        return hook

    # -- hook: autoscaler ticks (serving/autoscale.py) -----------------------

    def autoscale_hook(self):
        """Returns the ReplicaAutoscaler fault_hook: called with the tick
        index on every evaluation; returns a FORCED scale demand
        ("up"/"down", alternating per delivery) while a `scale_flap`
        fault is live, None otherwise. A forced demand bypasses the
        policy's sustain counters but NOT its hysteresis window — the
        chaos suite asserts the window absorbs the flapping."""
        flips = [0]

        def hook(tick_index: int) -> Optional[str]:
            f = self._take("scale_flap", tick_index)
            if f is None:
                return None
            flips[0] += 1
            return "up" if flips[0] % 2 else "down"

        return hook


def _check_main(argv=None) -> int:
    """`python -m alphafold2_tpu.reliability.faults --check plan.json` —
    validate a fault plan's schema without running anything. Exit 0 and
    print the parsed schedule on success; exit 2 with the precise
    rejection on any unknown kind/field/mode (the same validation every
    loading path runs — the CLI just runs it before you pay for a run)."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m alphafold2_tpu.reliability.faults",
        description="validate a chaos fault-plan JSON schema",
    )
    ap.add_argument("--check", required=True, metavar="PLAN_JSON",
                    help="path to the fault-plan JSON to validate")
    args = ap.parse_args(argv)
    try:
        plan = FaultPlan.from_file(args.check)
    except (ValueError, TypeError, KeyError) as e:
        print(f"INVALID {args.check}: {e}", file=sys.stderr)
        return 2
    except (OSError, json.JSONDecodeError) as e:
        print(f"UNREADABLE {args.check}: {e}", file=sys.stderr)
        return 2
    print(f"OK {args.check}: {len(plan.faults)} fault(s), seed {plan.seed}")
    for f in plan.faults:
        extra = []
        if f.replica:
            extra.append(f"replica={f.replica}")
        if f.kind == "ckpt_corrupt":
            extra.append(f"mode={f.mode}")
        if f.kind in ("slow_request", "slow_replica", "slow_featurize",
                      "slow_data", "straggle_dispatch"):
            extra.append(f"delay_s={f.delay_s}")
        if f.kind == "crash_process":
            extra.append("exit=137")
        if f.kind == "hung_request":
            extra.append(f"hang_s={f.hang_s}")
        count = "latched" if f.kind == "kill_replica" else f"count={f.count}"
        print(f"  {f.kind:16s} at={f.at:<5d} {count}"
              + (f"  ({', '.join(extra)})" if extra else ""))
    return 0


if __name__ == "__main__":
    raise SystemExit(_check_main())
