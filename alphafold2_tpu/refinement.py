"""Physical refinement plugin boundary (FastRelax) + TPU-side fallback.

Parity: reference `scripts/refinement.py` — PyRosetta pose<->pdb converters
(:22-54) and a `run_fast_relax` hook that raises NotImplementedError (:56-74).
Here the boundary is completed:

  * the pose<->array contract is explicit: structures cross the boundary as
    `(coords (L*atoms, 3) numpy, sequence str)` pairs, PDB text as the wire
    format (the reference's choice, via its pdbfile round-trip);
  * PyRosetta, when importable, drives a real FastRelax through that
    contract (optional dependency gate, reference refinement.py:8-14);
  * without PyRosetta, `jax_relax` runs a WORKING geometric relaxation on
    the accelerator — gradient descent on ideal backbone bond lengths —
    instead of raising. It is deliberately simple (no physics force field)
    but differentiable, jittable, and honest about what it is.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

try:  # optional, exactly like the reference (refinement.py:8-14)
    import pyrosetta  # type: ignore

    _HAS_PYROSETTA = True
except Exception:
    pyrosetta = None
    _HAS_PYROSETTA = False

# ideal backbone geometry (standard values; the reference carries similar
# build constants at utils.py:20-28)
IDEAL_N_CA = 1.458
IDEAL_CA_C = 1.525
IDEAL_C_N = 1.329


def pyrosetta_available() -> bool:
    return _HAS_PYROSETTA


# ---------------------------------------------------------------------------
# pose <-> array contract
# ---------------------------------------------------------------------------


def coords_to_pose(coords, sequence: str):
    """(L*3, 3) backbone coords + sequence -> PyRosetta pose (via PDB text,
    the reference's pdbfile route, refinement.py:22-38). Requires PyRosetta."""
    if not _HAS_PYROSETTA:
        raise ImportError("PyRosetta is not installed")
    import os
    import tempfile

    from alphafold2_tpu.geometry.pdb import coords_to_pdb

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "pose.pdb")
        coords_to_pdb(path, coords, sequence=sequence)
        return pyrosetta.pose_from_pdb(path)


def pose_to_coords(pose) -> np.ndarray:
    """PyRosetta pose -> (L*3, 3) N/CA/C backbone coords
    (reference refinement.py:41-54's inverse direction)."""
    if not _HAS_PYROSETTA:
        raise ImportError("PyRosetta is not installed")
    out = []
    for i in range(1, pose.total_residue() + 1):
        res = pose.residue(i)
        for name in ("N", "CA", "C"):
            v = res.xyz(name)
            out.append([v.x, v.y, v.z])
    return np.asarray(out, np.float64)


# ---------------------------------------------------------------------------
# relaxation
# ---------------------------------------------------------------------------


def backbone_bond_energy(coords, mask=None, peptide_mask=None):
    """Sum of squared deviations from ideal backbone bond lengths.

    coords: (b, L*3, 3) in N/CA/C order. Differentiable; the quantity
    jax_relax descends on.

    peptide_mask: (b, L-1) — peptide-bond validity between residue i and
    i+1. Chain breaks and sequence gaps MUST be marked False here or the
    energy welds unrelated residues together with a 1.329 A bond.
    """
    coords = jnp.asarray(coords, jnp.float32)
    bb = coords.reshape(coords.shape[0], -1, 3, 3)  # (b, L, 3, 3)

    def bond(a, b_):
        return jnp.sqrt(jnp.sum((a - b_) ** 2, axis=-1) + 1e-12)

    n_ca = bond(bb[:, :, 0], bb[:, :, 1]) - IDEAL_N_CA  # (b, L)
    ca_c = bond(bb[:, :, 1], bb[:, :, 2]) - IDEAL_CA_C
    c_n = bond(bb[:, :-1, 2], bb[:, 1:, 0]) - IDEAL_C_N  # peptide bond

    if mask is not None:
        # accept bool or float masks (float32 is the convention elsewhere,
        # e.g. utils/observability.py) — bitwise & on floats would raise
        mask_b = jnp.asarray(mask).astype(bool)
        maskf = mask_b.astype(n_ca.dtype)
        n_ca = n_ca * maskf
        ca_c = ca_c * maskf
        c_n = c_n * (mask_b[:, :-1] & mask_b[:, 1:]).astype(c_n.dtype)
    if peptide_mask is not None:
        c_n = c_n * jnp.asarray(peptide_mask).astype(bool).astype(c_n.dtype)
    return jnp.sum(n_ca**2 + ca_c**2, axis=-1) + jnp.sum(c_n**2, axis=-1)


@partial(jax.jit, static_argnames=("iters",))
def jax_relax(coords, mask=None, iters: int = 100, lr: float = 0.05, peptide_mask=None):
    """Accelerator-side geometric relaxation: gradient descent restoring
    ideal backbone bond lengths while staying close to the input.

    coords: (b, L*3, 3) or (L*3, 3) N/CA/C backbone.
    peptide_mask: (b, L-1) or (L-1,) — False across chain breaks / gaps
    (see backbone_bond_energy).
    Returns (relaxed coords, energy history (iters, b)).
    """
    coords = jnp.asarray(coords, jnp.float32)
    squeeze = coords.ndim == 2
    if squeeze:
        coords = coords[None]
    if mask is not None and jnp.asarray(mask).ndim == 1:
        mask = jnp.asarray(mask)[None]
    if peptide_mask is not None and jnp.asarray(peptide_mask).ndim == 1:
        peptide_mask = jnp.asarray(peptide_mask)[None]
    anchor = coords

    def energy(c):
        e = backbone_bond_energy(c, mask, peptide_mask)
        # weak restraint to the predicted structure so relaxation repairs
        # bonds without drifting the fold (FastRelax's constrained spirit)
        rest = 0.01 * jnp.sum((c - anchor) ** 2, axis=(-1, -2))
        return jnp.sum(e + rest), e

    def step(c, _):
        (_, e), g = jax.value_and_grad(energy, has_aux=True)(c)
        return c - lr * g, e

    relaxed, history = jax.lax.scan(step, coords, None, length=iters)
    if squeeze:
        return relaxed[0], history[:, 0]
    return relaxed, history


def run_fast_relax(coords, sequence: str, iters: int = 100, peptide_mask=None):
    """The reference's unimplemented hook (refinement.py:56-74), completed.

    PyRosetta present: real FastRelax through the pose contract.
    Otherwise: jax_relax geometric fallback. Returns (L*3, 3) numpy coords.

    peptide_mask: (L-1,) bool, False across chain breaks / residue-number
    gaps so the fallback never welds unrelated residues.
    """
    has_breaks = peptide_mask is not None and not bool(np.all(peptide_mask))
    if _HAS_PYROSETTA and not has_breaks:
        pose = coords_to_pose(np.asarray(coords), sequence)
        scorefxn = pyrosetta.get_fa_scorefxn()
        relax = pyrosetta.rosetta.protocols.relax.FastRelax()
        relax.set_scorefxn(scorefxn)
        relax.apply(pose)
        return pose_to_coords(pose)
    if _HAS_PYROSETTA and has_breaks:
        # the pose contract renumbers residues into one continuous chain
        # (geometry/pdb.py coords_to_structure), so FastRelax would bond the
        # breaks — the exact welding peptide_mask exists to prevent
        print(
            "run_fast_relax: chain breaks present; using jax_relax fallback "
            "(the single-chain pose contract cannot represent breaks)"
        )
    relaxed, _ = jax_relax(
        np.asarray(coords, np.float32), iters=iters, peptide_mask=peptide_mask
    )
    return np.asarray(relaxed)
