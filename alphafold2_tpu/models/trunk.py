"""The dual-track trunk: pair-representation and MSA streams.

Re-design of the reference `SequentialSequence`
(reference alphafold2_pytorch/alphafold2.py:290-326). The reference keeps the
pair representation flattened to (b, n*n, d) and reshapes per axial pass; here
both streams stay in their natural grid layouts — pair (b, i, j, d), MSA
(b, rows, cols, d) — and only the cross-attention flattens, which keeps the
sharding story simple (the grid axes are the mesh axes, see parallel/).

Per layer, every op residual (reference alphafold2.py:309-324):
  pair axial self-attn -> msa axial self-attn (optionally tied rows) ->
  pair<-msa cross-attn (optionally KV-compressed) -> msa<-pair cross-attn ->
  pair FF -> msa FF.
The MSA branch is skipped entirely when no MSA stream exists
(reference alphafold2.py:311).

Trunk schedules (cfg.trunk_schedule; docs/ARCHITECTURE.md "Trunk
schedules"): the per-layer dataflow above has exactly one cross-track
dependency — the cross-attention exchange. Everything before it (each
track's self-attention) and after it (each track's feed-forward) touches
only its own stream, so the Parallel-Evoformer observation (arXiv
2211.00235) applies: the pair track and the MSA track are two independent
BRANCHES that join only at the exchange. "serial" emits the reference
op order; "branch_parallel" emits the SAME ops re-grouped as explicit
branches whose results meet at a `schedule_join` marker (an
optimization-barrier the compiler's latency-hiding scheduler — and
analysis/schedule_lint.py — can see). Identical math, allclose fwd +
grads; the join also pins the schedule: nothing from one branch may be
interleaved past the join into the other.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from alphafold2_tpu.models.config import Alphafold2Config
from alphafold2_tpu.ops.attention import (
    attention_apply,
    attention_init,
    axial_attention_apply,
    axial_attention_init,
)
from alphafold2_tpu.ops.core import layer_norm, layer_norm_init
from alphafold2_tpu.ops.feedforward import feed_forward_apply, feed_forward_init
from alphafold2_tpu.ops.sparse import sparse_attention_apply


_REMAT_POLICIES = {
    None: None,
    "dots": "dots_saveable",
    "dots_no_batch": "dots_with_no_batch_dims_saveable",
}


# --- the branch-parallel schedule join ---------------------------------------


@jax.custom_vjp
def _join_barrier(args):
    return jax.lax.optimization_barrier(args)


def _join_barrier_fwd(args):
    return _join_barrier(args), None


def _join_barrier_bwd(_, cts):
    return (cts,)


# identity with an explicit gradient rule: jax 0.4.x has no
# differentiation rule for optimization_barrier, and the barrier is a
# schedule marker, not math — cotangents pass straight through (the
# backward program carries no barrier)
_join_barrier.defvjp(_join_barrier_fwd, _join_barrier_bwd)


def schedule_join(*branches):
    """JOIN the branch-parallel schedule's independent branches.

    Emits ONE multi-operand `stablehlo.optimization_barrier` over every
    tensor of every branch. Semantically the identity (gradients pass
    through untouched); structurally it is the schedule contract the
    trunk claims and analysis/schedule_lint.py verifies:

      * nothing downstream of the join can be hoisted into a branch, and
        no branch op can sink past the join — the branches are
        schedulable as whole concurrent units;
      * the lint finds each join in the lowered StableHLO and asserts its
        operands split into >= 2 groups with DISJOINT compute slices
        (no shared dot/reduce/conv) — i.e. the branches really are
        data-independent before the join. A serialized twin (one branch
        coupled behind the other, `serialize_twin` below) must be
        flagged by the same check.

    Each branch is a tensor or tuple of tensors; returns them in the
    same structure."""
    flat, treedef = jax.tree_util.tree_flatten(branches)
    out = _join_barrier(tuple(flat))
    return jax.tree_util.tree_unflatten(treedef, out)


def schedule_fork(t):
    """Mark the START of a new branch region after a cross-track exchange.

    A SINGLE-operand barrier (identity, gradient passes through): the
    schedule lint exempts it from join analysis (joins have >= 2
    operands) but its slice walk stops here, so each join's pre-join
    region covers exactly its own layer's branches — without the fork,
    layer N+1's join would see layer N's (legitimately cross-track)
    exchange in both branch slices and read as serialized. Schedule-wise
    it pins the exchange ahead of the post-exchange branches."""
    (out,) = _join_barrier((t,))
    return out


def _remat_policy(cfg: Alphafold2Config):
    # membership is validated eagerly in Alphafold2Config.__post_init__
    name = _REMAT_POLICIES[cfg.remat_policy]
    return getattr(jax.checkpoint_policies, name) if name else None


def make_sparse_axial_fn(cfg: Alphafold2Config):
    """Inner-attention override running each axial pass block-sparsely.

    Replaces the dense inner attention with the variable-sparsity pattern
    for layers flagged in cfg.layer_sparse — the reference applies sparse
    attention to the pair-rep (seq) axial passes only
    (reference alphafold2.py:393), never to tied-row MSA attention
    (reference alphafold2.py:192).
    """
    attn_cfg = cfg.self_attn_config()
    scfg = cfg.sparse_config()

    def fn(params, x, *, axis, mask, tie_dim, rng, **ctx):
        del axis
        if ctx:
            raise ValueError("sparse attention is self-attention only")
        if tie_dim is not None:
            raise ValueError(
                "sparse attention is incompatible with tied-row attention "
                "(reference alphafold2.py:192)"
            )
        return sparse_attention_apply(
            params, attn_cfg, scfg, x, mask=mask, rng=rng,
            use_kernel=cfg.sparse_use_kernel,
        )

    return fn


# --- pre-norm wrapped blocks ------------------------------------------------


def prenorm_axial_init(key, cfg: Alphafold2Config, attn_cfg):
    return {"norm": layer_norm_init(cfg.dim), "attn": axial_attention_init(key, attn_cfg)}


def prenorm_cross_init(key, cfg: Alphafold2Config, attn_cfg):
    return {
        "norm": layer_norm_init(cfg.dim),
        "norm_context": layer_norm_init(cfg.dim),
        "attn": attention_init(key, attn_cfg),
    }


def prenorm_ff_init(key, cfg: Alphafold2Config):
    return {"norm": layer_norm_init(cfg.dim), "ff": feed_forward_init(key, cfg.dim)}


def prenorm_axial_apply(params, attn_cfg, x, **kwargs):
    return axial_attention_apply(params["attn"], attn_cfg, layer_norm(params["norm"], x), **kwargs)


def prenorm_cross_apply(params, attn_cfg, x, context, **kwargs):
    return attention_apply(
        params["attn"],
        attn_cfg,
        layer_norm(params["norm"], x),
        context=layer_norm(params["norm_context"], context),
        **kwargs,
    )


def prenorm_ff_apply(params, cfg: Alphafold2Config, x, rng=None):
    return feed_forward_apply(
        params["ff"],
        layer_norm(params["norm"], x),
        dropout_rate=cfg.ff_dropout,
        rng=rng,
        dtype=cfg.dtype,
        chunk=cfg.ff_chunk_size,
    )


# --- cross-attention over grids: flat vs column-aligned ---------------------


def _fold_by_msa_column(x, m, x_mask, msa_mask):
    """Group pair-grid columns by the MSA column they map to.

    Pair grid (b, n, n, d) with n = f*c (f = residue elongation factor, e.g.
    3 backbone atoms per residue, reference train_end2end.py:134-146); MSA
    (b, r, c, d). Returns per-column folds:
      xg (b*c, n*f, d) — the pair tokens whose grid column maps to column c;
      mg (b*c, r, d)   — that column's MSA residues;
    plus the matching folded masks (or None).
    """
    b, n, n2, d = x.shape
    r, c = m.shape[1], m.shape[2]
    if n != n2 or n % c != 0:
        raise ValueError(
            f"aligned cross-attention needs a square pair grid whose side is "
            f"a multiple of the MSA column count; got pair ({n}, {n2}), "
            f"msa cols {c}"
        )
    f = n // c
    xg = x.reshape(b, n, c, f, d).transpose(0, 2, 1, 3, 4).reshape(b * c, n * f, d)
    mg = jnp.swapaxes(m, 1, 2).reshape(b * c, r, d)
    xg_mask = (
        x_mask.reshape(b, n, c, f).transpose(0, 2, 1, 3).reshape(b * c, n * f)
        if x_mask is not None
        else None
    )
    mg_mask = (
        jnp.swapaxes(msa_mask, 1, 2).reshape(b * c, r)
        if msa_mask is not None
        else None
    )
    return xg, mg, xg_mask, mg_mask, f


def _unfold_pair(xg, b, n, f, d):
    c = xg.shape[0] // b
    return xg.reshape(b, c, n, f, d).transpose(0, 2, 1, 3, 4).reshape(b, n, n, d)


def _unfold_msa(mg, b, r, d):
    c = mg.shape[0] // b
    return jnp.swapaxes(mg.reshape(b, c, r, d), 1, 2)


def cross_apply_grids(
    params, cfg: Alphafold2Config, q_grid, ctx_grid, q_mask, ctx_mask, rng, direction
):
    """Pre-norm cross-attention between the pair and MSA streams, on grids.

    direction: "pair_from_msa" (q_grid = pair (b,n,n,d), ctx = MSA
    (b,r,c,d)) or "msa_from_pair" (the mirror). Dispatches on
    cfg.cross_attn_mode:

      * "flat" — both streams fully flattened, every query attends every
        context token (reference alphafold2.py:316-317). O(n^2 * r*c)
        logits; blockwise-streamed at scale but FLOP-bound beyond small
        crops.
      * "aligned" — each pair token attends only the MSA column its grid
        column maps to; each MSA token attends only its column's pair-grid
        block. The column fold becomes the attention batch: O(n^2 * r)
        total. KV compression still applies along the folded key axis.

    Returns the attention output in the query grid's layout (pre-residual).
    """
    cross_cfg = cfg.cross_attn_config()
    if cfg.cross_attn_mode == "flat":
        qb = q_grid.shape[0]
        d = q_grid.shape[-1]
        qf = q_grid.reshape(qb, -1, d)
        cf = ctx_grid.reshape(qb, -1, d)
        qm = q_mask.reshape(qb, -1) if q_mask is not None else None
        cm = ctx_mask.reshape(qb, -1) if ctx_mask is not None else None
        out = prenorm_cross_apply(
            params, cross_cfg, qf, cf, mask=qm, context_mask=cm, rng=rng
        )
        return out.reshape(q_grid.shape)

    # aligned
    b = q_grid.shape[0]
    d = q_grid.shape[-1]
    if direction == "pair_from_msa":
        x, m = q_grid, ctx_grid
        xg, mg, xg_mask, mg_mask, f = _fold_by_msa_column(x, m, q_mask, ctx_mask)
        out = prenorm_cross_apply(
            params, cross_cfg, xg, mg, mask=xg_mask, context_mask=mg_mask, rng=rng
        )
        return _unfold_pair(out, b, x.shape[1], f, d)
    elif direction == "msa_from_pair":
        m, x = q_grid, ctx_grid
        xg, mg, xg_mask, mg_mask, f = _fold_by_msa_column(x, m, ctx_mask, q_mask)
        out = prenorm_cross_apply(
            params, cross_cfg, mg, xg, mask=mg_mask, context_mask=xg_mask, rng=rng
        )
        return _unfold_msa(out, b, m.shape[1], d)
    raise ValueError(f"unknown cross direction {direction!r}")


# --- trunk layer ------------------------------------------------------------


def trunk_layer_init(key, cfg: Alphafold2Config, *, reversible: bool = False):
    """One trunk layer's params.

    Sequential layers carry 6 blocks; reversible layers carry 8 — the
    reference drops the 4th feed-forward of each half-layer when sequential
    (reference alphafold2.py:407-408).
    """
    keys = jax.random.split(key, 8)
    self_cfg = cfg.self_attn_config()
    cross_cfg = cfg.cross_attn_config()
    params = {
        "seq_attn": prenorm_axial_init(keys[0], cfg, self_cfg),
        "msa_attn": prenorm_axial_init(keys[1], cfg, self_cfg),
        "seq_cross": prenorm_cross_init(keys[2], cfg, cross_cfg),
        "msa_cross": prenorm_cross_init(keys[3], cfg, cross_cfg),
        "seq_ff": prenorm_ff_init(keys[4], cfg),
        "msa_ff": prenorm_ff_init(keys[5], cfg),
    }
    if reversible:
        params["seq_ff2"] = prenorm_ff_init(keys[6], cfg)
        params["msa_ff2"] = prenorm_ff_init(keys[7], cfg)
    return params


def trunk_layer_apply(
    layer,
    cfg: Alphafold2Config,
    x,
    m,
    *,
    x_mask=None,
    msa_mask=None,
    rngs=(None,) * 6,
    sparse_fn=None,
):
    """ONE sequential trunk layer — the single source of the layer order
    (reference alphafold2.py:309-324), shared by the sequential trunk here
    and the pipeline-parallel trunk (parallel/pipeline.py).

    rngs: six per-op dropout keys (None = deterministic). sparse_fn: inner
    block-sparse attention override for the pair self-attention pass, or
    None for dense.

    cfg.trunk_schedule selects the intra-layer schedule: "serial" runs
    the reference order below; "branch_parallel" runs the SAME ops with
    the two tracks' self-attentions grouped as independent branches that
    join (schedule_join) at the cross-attention exchange — identical
    dataflow, explicit branch structure. Layers without an MSA stream
    have a single track and always run serially.
    """
    if cfg.trunk_schedule == "branch_parallel" and m is not None:
        return branch_parallel_layer_apply(
            layer, cfg, x, m,
            x_mask=x_mask, msa_mask=msa_mask, rngs=rngs, sparse_fn=sparse_fn,
        )
    self_cfg = cfg.self_attn_config()
    # pair axial self-attention (reference alphafold2.py:309), with the
    # block-sparse inner attention when sparse_fn is given — applied PER
    # LAYER, fixing the reference bug that ignores the per-layer tuple
    # (reference alphafold2.py:392)
    x = prenorm_axial_apply(
        layer["seq_attn"],
        self_cfg,
        x,
        mask=x_mask,
        rng=rngs[0],
        attention_fn=sparse_fn,
    ) + x

    if m is not None:
        # msa axial self-attention, optionally tied rows
        # (reference alphafold2.py:312)
        m = prenorm_axial_apply(
            layer["msa_attn"],
            self_cfg,
            m,
            mask=msa_mask,
            tie_row=cfg.msa_tie_row_attn,
            rng=rngs[1],
        ) + m

        # cross-attention both ways, flat or column-aligned
        # (reference alphafold2.py:316-317; cfg.cross_attn_mode)
        x = cross_apply_grids(
            layer["seq_cross"], cfg, x, m, x_mask, msa_mask,
            rngs[2], "pair_from_msa",
        ) + x
        m = cross_apply_grids(
            layer["msa_cross"], cfg, m, x, msa_mask, x_mask,
            rngs[3], "msa_from_pair",
        ) + m

    # feed-forwards (reference alphafold2.py:321-324)
    x = prenorm_ff_apply(layer["seq_ff"], cfg, x, rng=rngs[4]) + x
    if m is not None:
        m = prenorm_ff_apply(layer["msa_ff"], cfg, m, rng=rngs[5]) + m
    return x, m


def branch_parallel_layer_apply(
    layer,
    cfg: Alphafold2Config,
    x,
    m,
    *,
    x_mask=None,
    msa_mask=None,
    rngs=(None,) * 6,
    sparse_fn=None,
    serialize_twin: bool = False,
):
    """ONE trunk layer under the BRANCH-PARALLEL schedule.

    The same six residual ops as the serial `trunk_layer_apply` — same
    params, same rng slots, allclose fwd + grads — re-grouped into the
    Parallel-Evoformer branch structure (arXiv 2211.00235):

        pair branch:  x += pair_self_attn(x)     \\  independent,
        msa  branch:  m += msa_self_attn(m)      /   schedulable together
        ---------------- schedule_join ----------------
        exchange:     x += cross(x, m); m += cross(m, x)
        pair branch:  x += pair_ff(x)            \\  independent again
        msa  branch:  m += msa_ff(m)             /   (joins at the NEXT
                                                      layer's exchange)

    Between consecutive exchanges each track's ops (this layer's FF, the
    next layer's self-attention) form one contiguous data-independent
    branch, so one join per layer — placed immediately before the
    exchange — pins the whole schedule.

    serialize_twin: the schedule-lint fixture (analysis/schedule_lint.py
    self-check) — couples the MSA branch's input behind the pair branch's
    output through an identity barrier, producing exactly the lowered
    structure a re-serialized schedule would have. Numerics unchanged;
    never set outside the lint/tests.
    """
    self_cfg = cfg.self_attn_config()

    x1 = prenorm_axial_apply(
        layer["seq_attn"], self_cfg, x,
        mask=x_mask, rng=rngs[0], attention_fn=sparse_fn,
    ) + x
    if serialize_twin:
        # deliberately thread the MSA branch behind the pair branch via an
        # exact-identity arithmetic coupling (+ 0 * sum(pair branch)): the
        # join below then has overlapping operand slices — the pair
        # branch's dots reach the MSA operand — which the schedule lint
        # must flag (detector self-check). A barrier could not serve here:
        # the lint's slice walk deliberately stops at barriers (each join
        # scopes its own pre-join region), so the coupling must flow
        # through ordinary value ops.
        m = m + (0.0 * jnp.sum(x1)).astype(m.dtype)
    m1 = prenorm_axial_apply(
        layer["msa_attn"], self_cfg, m,
        mask=msa_mask, tie_row=cfg.msa_tie_row_attn, rng=rngs[1],
    ) + m

    x1, m1 = schedule_join(x1, m1)

    # the exchange (reference alphafold2.py:316-317): the ONLY cross-track
    # dataflow — msa<-pair reads the UPDATED pair stream, like serial
    x2 = cross_apply_grids(
        layer["seq_cross"], cfg, x1, m1, x_mask, msa_mask,
        rngs[2], "pair_from_msa",
    ) + x1
    m2 = cross_apply_grids(
        layer["msa_cross"], cfg, m1, x2, msa_mask, x_mask,
        rngs[3], "msa_from_pair",
    ) + m1

    # post-exchange branches (they run up to the next layer's join); the
    # forks close the exchange region so the NEXT join's branch slices
    # start here instead of reaching back through the shared exchange
    x2 = schedule_fork(x2)
    m2 = schedule_fork(m2)
    x3 = prenorm_ff_apply(layer["seq_ff"], cfg, x2, rng=rngs[4]) + x2
    m3 = prenorm_ff_apply(layer["msa_ff"], cfg, m2, rng=rngs[5]) + m2
    return x3, m3


def sequential_trunk_apply(
    layers,
    cfg: Alphafold2Config,
    x,
    m,
    *,
    x_mask=None,
    msa_mask=None,
    rng=None,
):
    """Run the sequential trunk.

    Args:
      layers: list of trunk_layer_init params.
      x: pair representation (b, n, n, d).
      m: MSA stream (b, rows, cols, d) or None.
      x_mask: (b, n, n) bool.
      msa_mask: (b, rows, cols) bool.
      rng: dropout key (None = deterministic).

    Returns: (x, m) in the same layouts.
    """
    layer_sparse = cfg.layer_sparse
    sparse_fn = make_sparse_axial_fn(cfg) if any(layer_sparse) else None

    def one_layer(sparse_this_layer):
        def body(layer, x, m, rngs):
            return trunk_layer_apply(
                layer, cfg, x, m,
                x_mask=x_mask, msa_mask=msa_mask, rngs=rngs,
                sparse_fn=sparse_fn if sparse_this_layer else None,
            )

        if cfg.remat:
            # recompute this layer's activations in the backward pass
            # instead of storing them: O(1) trunk activation memory in
            # depth, the jax.checkpoint sibling of the reversible trunk
            # (reference reversible.py's motivation, SURVEY.md §2.2).
            # cfg.remat_policy trades memory back for backward FLOPs by
            # saving matmul outputs (models/config.py)
            return jax.checkpoint(body, policy=_remat_policy(cfg))
        return body

    if cfg.scan_layers:
        # scan each uniform-sparse-flag run of layers as ONE compiled body
        # (depth-stacked params), mirroring the reversible trunk's
        # segmentation (models/reversible.py). Per-layer dropout keys are
        # re-derived from the GLOBAL layer index inside the scan, so the
        # unrolled and scanned trunks draw identical masks.
        #
        # The in-trace jnp.stack copies the trunk params once per step
        # (~2 ms of HBM traffic per GB at v5e) — negligible against the
        # tens-of-seconds steps this flag exists for; the win is compile
        # time (one layer body instead of `depth` clones). Keep params as
        # the plain layer list so every trunk variant (SP, pipeline,
        # converter) shares one layout.
        from alphafold2_tpu.models.reversible import stack_layers

        segments = []
        start = 0
        for i in range(1, len(layers) + 1):
            if i == len(layers) or layer_sparse[i] != layer_sparse[start]:
                segments.append((start, i))
                start = i

        for seg_start, seg_end in segments:
            stacked = stack_layers(layers[seg_start:seg_end])
            body = one_layer(layer_sparse[seg_start])

            def scan_body(carry, inp):
                lp, li = inp
                cx, cm = carry
                lrng = jax.random.fold_in(rng, li) if rng is not None else None
                rngs = (
                    jax.random.split(lrng, 6) if lrng is not None else [None] * 6
                )
                return body(lp, cx, cm, rngs), None

            (x, m), _ = jax.lax.scan(
                scan_body, (x, m), (stacked, jnp.arange(seg_start, seg_end))
            )
        return x, m

    for li, layer in enumerate(layers):
        lrng = jax.random.fold_in(rng, li) if rng is not None else None
        rngs = (
            jax.random.split(lrng, 6) if lrng is not None else [None] * 6
        )
        x, m = one_layer(layer_sparse[li])(layer, x, m, rngs)

    return x, m
