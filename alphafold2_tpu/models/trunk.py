"""The dual-track trunk: pair-representation and MSA streams.

Re-design of the reference `SequentialSequence`
(reference alphafold2_pytorch/alphafold2.py:290-326). The reference keeps the
pair representation flattened to (b, n*n, d) and reshapes per axial pass; here
both streams stay in their natural grid layouts — pair (b, i, j, d), MSA
(b, rows, cols, d) — and only the cross-attention flattens, which keeps the
sharding story simple (the grid axes are the mesh axes, see parallel/).

Per layer, every op residual (reference alphafold2.py:309-324):
  pair axial self-attn -> msa axial self-attn (optionally tied rows) ->
  pair<-msa cross-attn (optionally KV-compressed) -> msa<-pair cross-attn ->
  pair FF -> msa FF.
The MSA branch is skipped entirely when no MSA stream exists
(reference alphafold2.py:311).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from alphafold2_tpu.models.config import Alphafold2Config
from alphafold2_tpu.ops.attention import (
    attention_apply,
    attention_init,
    axial_attention_apply,
    axial_attention_init,
)
from alphafold2_tpu.ops.core import layer_norm, layer_norm_init
from alphafold2_tpu.ops.feedforward import feed_forward_apply, feed_forward_init
from alphafold2_tpu.ops.sparse import sparse_attention_apply


def make_sparse_axial_fn(cfg: Alphafold2Config):
    """Inner-attention override running each axial pass block-sparsely.

    Replaces the dense inner attention with the variable-sparsity pattern
    for layers flagged in cfg.layer_sparse — the reference applies sparse
    attention to the pair-rep (seq) axial passes only
    (reference alphafold2.py:393), never to tied-row MSA attention
    (reference alphafold2.py:192).
    """
    attn_cfg = cfg.self_attn_config()
    scfg = cfg.sparse_config()

    def fn(params, x, *, axis, mask, tie_dim, rng, **ctx):
        del axis
        if ctx:
            raise ValueError("sparse attention is self-attention only")
        if tie_dim is not None:
            raise ValueError(
                "sparse attention is incompatible with tied-row attention "
                "(reference alphafold2.py:192)"
            )
        return sparse_attention_apply(
            params, attn_cfg, scfg, x, mask=mask, rng=rng,
            use_kernel=cfg.sparse_use_kernel,
        )

    return fn


# --- pre-norm wrapped blocks ------------------------------------------------


def prenorm_axial_init(key, cfg: Alphafold2Config, attn_cfg):
    return {"norm": layer_norm_init(cfg.dim), "attn": axial_attention_init(key, attn_cfg)}


def prenorm_cross_init(key, cfg: Alphafold2Config, attn_cfg):
    return {
        "norm": layer_norm_init(cfg.dim),
        "norm_context": layer_norm_init(cfg.dim),
        "attn": attention_init(key, attn_cfg),
    }


def prenorm_ff_init(key, cfg: Alphafold2Config):
    return {"norm": layer_norm_init(cfg.dim), "ff": feed_forward_init(key, cfg.dim)}


def prenorm_axial_apply(params, attn_cfg, x, **kwargs):
    return axial_attention_apply(params["attn"], attn_cfg, layer_norm(params["norm"], x), **kwargs)


def prenorm_cross_apply(params, attn_cfg, x, context, **kwargs):
    return attention_apply(
        params["attn"],
        attn_cfg,
        layer_norm(params["norm"], x),
        context=layer_norm(params["norm_context"], context),
        **kwargs,
    )


def prenorm_ff_apply(params, cfg: Alphafold2Config, x, rng=None):
    return feed_forward_apply(
        params["ff"],
        layer_norm(params["norm"], x),
        dropout_rate=cfg.ff_dropout,
        rng=rng,
        dtype=cfg.dtype,
    )


# --- trunk layer ------------------------------------------------------------


def trunk_layer_init(key, cfg: Alphafold2Config, *, reversible: bool = False):
    """One trunk layer's params.

    Sequential layers carry 6 blocks; reversible layers carry 8 — the
    reference drops the 4th feed-forward of each half-layer when sequential
    (reference alphafold2.py:407-408).
    """
    keys = jax.random.split(key, 8)
    self_cfg = cfg.self_attn_config()
    cross_cfg = cfg.cross_attn_config()
    params = {
        "seq_attn": prenorm_axial_init(keys[0], cfg, self_cfg),
        "msa_attn": prenorm_axial_init(keys[1], cfg, self_cfg),
        "seq_cross": prenorm_cross_init(keys[2], cfg, cross_cfg),
        "msa_cross": prenorm_cross_init(keys[3], cfg, cross_cfg),
        "seq_ff": prenorm_ff_init(keys[4], cfg),
        "msa_ff": prenorm_ff_init(keys[5], cfg),
    }
    if reversible:
        params["seq_ff2"] = prenorm_ff_init(keys[6], cfg)
        params["msa_ff2"] = prenorm_ff_init(keys[7], cfg)
    return params


def sequential_trunk_apply(
    layers,
    cfg: Alphafold2Config,
    x,
    m,
    *,
    x_mask=None,
    msa_mask=None,
    rng=None,
):
    """Run the sequential trunk.

    Args:
      layers: list of trunk_layer_init params.
      x: pair representation (b, n, n, d).
      m: MSA stream (b, rows, cols, d) or None.
      x_mask: (b, n, n) bool.
      msa_mask: (b, rows, cols) bool.
      rng: dropout key (None = deterministic).

    Returns: (x, m) in the same layouts.
    """
    self_cfg = cfg.self_attn_config()
    cross_cfg = cfg.cross_attn_config()
    b = x.shape[0]
    n = x.shape[1]
    d = cfg.dim

    x_mask_flat = x_mask.reshape(b, -1) if x_mask is not None else None
    msa_mask_flat = msa_mask.reshape(b, -1) if msa_mask is not None else None

    layer_sparse = cfg.layer_sparse
    sparse_fn = make_sparse_axial_fn(cfg) if any(layer_sparse) else None

    def one_layer(sparse_this_layer):
        def body(layer, x, m, rngs):
            # pair axial self-attention (reference alphafold2.py:309), with
            # the block-sparse inner attention on layers flagged sparse —
            # applied PER LAYER, fixing the reference bug that ignores the
            # per-layer tuple (reference alphafold2.py:392)
            x = prenorm_axial_apply(
                layer["seq_attn"],
                self_cfg,
                x,
                mask=x_mask,
                rng=rngs[0],
                attention_fn=sparse_fn if sparse_this_layer else None,
            ) + x

            if m is not None:
                # msa axial self-attention, optionally tied rows
                # (reference alphafold2.py:312)
                m = prenorm_axial_apply(
                    layer["msa_attn"],
                    self_cfg,
                    m,
                    mask=msa_mask,
                    tie_row=cfg.msa_tie_row_attn,
                    rng=rngs[1],
                ) + m

                # cross-attention both ways over flattened streams
                # (reference alphafold2.py:316-317)
                xf = x.reshape(b, n * n, d)
                mf = m.reshape(b, -1, d)
                xf = prenorm_cross_apply(
                    layer["seq_cross"],
                    cross_cfg,
                    xf,
                    mf,
                    mask=x_mask_flat,
                    context_mask=msa_mask_flat,
                    rng=rngs[2],
                ) + xf
                x = xf.reshape(x.shape)
                mf = prenorm_cross_apply(
                    layer["msa_cross"],
                    cross_cfg,
                    mf,
                    xf,
                    mask=msa_mask_flat,
                    context_mask=x_mask_flat,
                    rng=rngs[3],
                ) + mf
                m = mf.reshape(m.shape)

            # feed-forwards (reference alphafold2.py:321-324)
            x = prenorm_ff_apply(layer["seq_ff"], cfg, x, rng=rngs[4]) + x
            if m is not None:
                m = prenorm_ff_apply(layer["msa_ff"], cfg, m, rng=rngs[5]) + m
            return x, m

        if cfg.remat:
            # recompute this layer's activations in the backward pass
            # instead of storing them: O(1) trunk activation memory in
            # depth, the jax.checkpoint sibling of the reversible trunk
            # (reference reversible.py's motivation, SURVEY.md §2.2)
            return jax.checkpoint(body)
        return body

    for li, layer in enumerate(layers):
        lrng = jax.random.fold_in(rng, li) if rng is not None else None
        rngs = (
            jax.random.split(lrng, 6) if lrng is not None else [None] * 6
        )
        x, m = one_layer(layer_sparse[li])(layer, x, m, rngs)

    return x, m
