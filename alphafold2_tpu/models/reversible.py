"""Reversible dual-stream trunk: O(1) activation memory in depth.

TPU-native re-design of the reference's RevNet machinery
(reference alphafold2_pytorch/reversible.py). The reference implements
reversibility with a hand-written `torch.autograd.Function` that walks an
nn.ModuleList backwards, reconstructing activations block by block and
replaying captured RNG state so dropout matches on recompute
(reference reversible.py:266-292, 26-56). Here the whole trunk is ONE
`jax.custom_vjp` wrapping a `lax.scan` over stacked per-layer parameters:

  * forward: scan the layer body over the depth axis, saving only the FINAL
    (seq, msa) channel-halved state — true O(1) activation memory, and a
    single compiled layer body regardless of depth;
  * backward: reverse scan that inverts each layer (x2 = y2 - g(y1), ...)
    and accumulates parameter cotangents via per-block `jax.vjp`;
  * dropout determinism is free: op keys are `fold_in(rng, layer)` splits,
    re-derived identically in the backward pass (no RNG state capture).

Semantics match the reference exactly:
  * both streams are channel-doubled on entry and the two halves averaged on
    exit (reference reversible.py:319, 327);
  * each trunk layer is a self-attention block (f=seq axial attn, g=seq FF,
    j=msa axial attn, k=msa FF; reference reversible.py:60-83) followed by a
    cross-attention block (f=seq<-msa cross, g=seq FF, j=msa<-seq cross on
    the UPDATED seq half y2, k=msa FF; reference reversible.py:160-182) —
    note the y2 coupling, whose cotangent path
    (reference reversible.py:213-225) the backward here reproduces;
  * reversibility requires an MSA stream (reference reversible.py:316).

`reverse=False` computes the identical function through plain autodiff
(scan saves carries), mirroring `irreversible_apply`
(reference reversible.py:296-300); it is the oracle for the grad-parity test
(reference tests/test_reversible.py:48-52).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.models.config import Alphafold2Config
from alphafold2_tpu.models.trunk import (
    cross_apply_grids,
    make_sparse_axial_fn,
    prenorm_axial_apply,
    prenorm_ff_apply,
    trunk_layer_init,
)


def reversible_trunk_init(key, cfg: Alphafold2Config):
    """Stacked (depth-leading) params for the reversible trunk.

    Stacking per-layer pytrees along a leading depth axis is what lets the
    trunk run as a single scanned body: one compilation of the layer,
    whatever the depth.
    """
    layers = [
        trunk_layer_init(k, cfg, reversible=True)
        for k in jax.random.split(key, cfg.depth)
    ]
    return stack_layers(layers)


def stack_layers(layers):
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layers(stacked):
    """(depth, ...) stacked pytree -> per-layer params list, the inverse
    of `stack_layers` (e.g. to predict with a pipeline-sharded train
    state's trunk through the sequential apply)."""
    depth = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    return [
        jax.tree_util.tree_map(lambda t, i=i: t[i], stacked)
        for i in range(depth)
    ]


# --- the four block functions, parameter-explicit for jax.vjp ---------------


def _f_seq(cfg, params, x2, x_mask, rng, sparse=False):
    # seq axial self-attention (reference reversible f, alphafold2.py:393),
    # block-sparse on layers flagged sparse (reference allows
    # sparse_self_attn with reversible=True, alphafold2.py:349,407-411)
    fn = make_sparse_axial_fn(cfg) if sparse else None
    return prenorm_axial_apply(
        params, cfg.self_attn_config(), x2, mask=x_mask, rng=rng,
        attention_fn=fn,
    )


def _j_msa(cfg, params, m2, msa_mask, rng):
    # msa axial self-attention, optionally tied rows (alphafold2.py:395)
    return prenorm_axial_apply(
        params,
        cfg.self_attn_config(),
        m2,
        mask=msa_mask,
        tie_row=cfg.msa_tie_row_attn,
        rng=rng,
    )


def _ff(cfg, params, t, rng):
    return prenorm_ff_apply(params, cfg, t, rng=rng)


def _cross(cfg, params, q_grid, ctx_grid, q_mask, ctx_mask, rng, direction):
    # cross-attention on grids, flat or column-aligned per
    # cfg.cross_attn_mode, optionally KV-compressed (alphafold2.py:401-403)
    return cross_apply_grids(
        params, cfg, q_grid, ctx_grid, q_mask, ctx_mask, rng, direction
    )


def _op_rngs(rng, layer_idx):
    """Eight per-op dropout keys for one layer, re-derivable in backward."""
    if rng is None:
        return (None,) * 8
    return tuple(jax.random.split(jax.random.fold_in(rng, layer_idx), 8))


# --- one layer forward (used by scan in both primal and fwd rule) -----------


def _layer_forward(cfg, lp, state, x_mask, msa_mask, rngs, sparse=False):
    x1, x2, m1, m2 = state
    (r_fs, r_gs, r_js, r_ks, r_fc, r_gc, r_jc, r_kc) = rngs

    # self-attention block (reference reversible.py:68-83). The seq half
    # (f, g) and msa half (j, k) touch only their own streams — under the
    # branch-parallel schedule they are the layer's two pre-exchange
    # branches, joined (models/trunk.py schedule_join) before the cross
    # block; identical math either way, the reversible inversion below is
    # untouched (the join is the identity)
    y1 = x1 + _f_seq(cfg, lp["seq_attn"], x2, x_mask, r_fs, sparse)
    y2 = x2 + _ff(cfg, lp["seq_ff"], y1, r_gs)
    n1 = m1 + _j_msa(cfg, lp["msa_attn"], m2, msa_mask, r_js)
    n2 = m2 + _ff(cfg, lp["msa_ff"], n1, r_ks)
    if cfg.trunk_schedule == "branch_parallel":
        from alphafold2_tpu.models.trunk import schedule_join

        (y1, y2), (n1, n2) = schedule_join((y1, y2), (n1, n2))

    # cross-attention block (reference reversible.py:168-182); note the msa
    # cross attends the UPDATED seq half z2
    z1 = y1 + _cross(cfg, lp["seq_cross"], y2, n2, x_mask, msa_mask, r_fc,
                     "pair_from_msa")
    z2 = y2 + _ff(cfg, lp["seq_ff2"], z1, r_gc)
    o1 = n1 + _cross(cfg, lp["msa_cross"], n2, z2, msa_mask, x_mask, r_jc,
                     "msa_from_pair")
    o2 = n2 + _ff(cfg, lp["msa_ff2"], o1, r_kc)

    return (z1, z2, o1, o2)


def _layer_backward(cfg, lp, state, cts, x_mask, msa_mask, rngs, sparse=False):
    """Invert one layer and propagate cotangents (reference
    reversible.py:85-156 and 184-262, re-derived with jax.vjp)."""
    z1, z2, o1, o2 = state
    dz1, dz2, do1, do2 = cts
    (r_fs, r_gs, r_js, r_ks, r_fc, r_gc, r_jc, r_kc) = rngs

    # --- invert cross block (reference reversible.py:184-262) ---
    # k: o2 = n2 + K(o1)
    ko1, k_vjp = jax.vjp(lambda p, t: _ff(cfg, p, t, r_kc), lp["msa_ff2"], o1)
    n2 = o2 - ko1
    dk, do1_k = k_vjp(do2)
    dn1 = do1 + do1_k
    # j: o1 = n1 + J(n2, z2)  — the y2-coupling (reference :213-225)
    jn2, j_vjp = jax.vjp(
        lambda p, q, c: _cross(cfg, p, q, c, msa_mask, x_mask, r_jc,
                               "msa_from_pair"),
        lp["msa_cross"],
        n2,
        z2,
    )
    n1 = o1 - jn2
    dj, dn2_j, dz2_j = j_vjp(dn1)
    dn2 = do2 + dn2_j
    dz2_acc = dz2 + dz2_j
    # g: z2 = y2 + G(z1)
    gz1, g_vjp = jax.vjp(lambda p, t: _ff(cfg, p, t, r_gc), lp["seq_ff2"], z1)
    y2 = z2 - gz1
    dg, dz1_g = g_vjp(dz2_acc)
    dy1 = dz1 + dz1_g
    # f: z1 = y1 + F(y2, n2)
    fy2, f_vjp = jax.vjp(
        lambda p, q, c: _cross(cfg, p, q, c, x_mask, msa_mask, r_fc,
                               "pair_from_msa"),
        lp["seq_cross"],
        y2,
        n2,
    )
    y1 = z1 - fy2
    df, dy2_f, dn2_f = f_vjp(dy1)
    dy2 = dz2_acc + dy2_f
    dn2 = dn2 + dn2_f

    # --- invert self block (reference reversible.py:85-156) ---
    # seq stream
    gy1, gs_vjp = jax.vjp(lambda p, t: _ff(cfg, p, t, r_gs), lp["seq_ff"], y1)
    x2 = y2 - gy1
    dgs, dy1_g = gs_vjp(dy2)
    dx1 = dy1 + dy1_g
    fx2, fs_vjp = jax.vjp(
        lambda p, t: _f_seq(cfg, p, t, x_mask, r_fs, sparse), lp["seq_attn"], x2
    )
    x1 = y1 - fx2
    dfs, dx2_f = fs_vjp(dx1)
    dx2 = dy2 + dx2_f
    # msa stream
    kn1, ks_vjp = jax.vjp(lambda p, t: _ff(cfg, p, t, r_ks), lp["msa_ff"], n1)
    m2 = n2 - kn1
    dks, dn1_k = ks_vjp(dn2)
    dm1 = dn1 + dn1_k
    jm2, js_vjp = jax.vjp(
        lambda p, t: _j_msa(cfg, p, t, msa_mask, r_js), lp["msa_attn"], m2
    )
    m1 = n1 - jm2
    djs, dm2_j = js_vjp(dm1)
    dm2 = dn2 + dm2_j

    dlp = {
        "seq_attn": dfs,
        "seq_ff": dgs,
        "msa_attn": djs,
        "msa_ff": dks,
        "seq_cross": df,
        "seq_ff2": dg,
        "msa_cross": dj,
        "msa_ff2": dk,
    }
    return (x1, x2, m1, m2), (dx1, dx2, dm1, dm2), dlp


def _num_layers(stacked):
    return jax.tree_util.tree_leaves(stacked)[0].shape[0]


def uniform_flag_runs(flags):
    """[(start, end)] runs of equal per-layer flags — the segmentation
    invariant shared by the reversible trunk, the sequential scan trunk
    (trunk.py), and the segmented multi-execution step
    (training/segmented.py): a scanned layer body is specialized on its
    flag, so segment boundaries must never cross a flag change."""
    runs = []
    start = 0
    for i in range(1, len(flags) + 1):
        if i == len(flags) or flags[i] != flags[start]:
            runs.append((start, i))
            start = i
    return runs


def _scan_forward(meta, stacked, state, x_mask, msa_mask, rng):
    """meta: (cfg, sparse, layer_offset) — static per uniform-flag segment.

    The layer offset keeps `fold_in(rng, layer)` keys GLOBAL layer indices,
    so a segmented trunk (mixed sparse flags) draws the same dropout keys a
    single-segment one would.
    """
    cfg, sparse, offset = meta

    def body(carry, inp):
        lp, li = inp
        return (
            _layer_forward(cfg, lp, carry, x_mask, msa_mask, _op_rngs(rng, li), sparse),
            None,
        )

    L = _num_layers(stacked)
    carry, _ = jax.lax.scan(body, state, (stacked, jnp.arange(offset, offset + L)))
    return carry


# --- the custom-vjp core ----------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _reversible_core(meta, stacked, x1, x2, m1, m2, x_mask, msa_mask, rng):
    return _scan_forward(meta, stacked, (x1, x2, m1, m2), x_mask, msa_mask, rng)


def _reversible_core_fwd(meta, stacked, x1, x2, m1, m2, x_mask, msa_mask, rng):
    out = _scan_forward(meta, stacked, (x1, x2, m1, m2), x_mask, msa_mask, rng)
    # residuals: ONLY the final state (+ params and non-diff aux) — this is
    # the entire point (reference reversible.py:277 saves the same)
    return out, (stacked, out, x_mask, msa_mask, rng)


def _zero_cotangent(x):
    """float0 cotangents for non-differentiable (bool/int) aux arguments."""
    return jax.tree_util.tree_map(
        lambda t: np.zeros(np.shape(t), jax.dtypes.float0), x
    )


def _reversible_core_bwd(meta, residuals, cts):
    cfg, sparse, offset = meta
    stacked, out, x_mask, msa_mask, rng = residuals
    L = _num_layers(stacked)

    def body(carry, inp):
        state, dstate = carry
        lp, li = inp
        state, dstate, dlp = _layer_backward(
            cfg, lp, state, dstate, x_mask, msa_mask, _op_rngs(rng, li), sparse
        )
        return (state, dstate), dlp

    (_, (dx1, dx2, dm1, dm2)), dstacked = jax.lax.scan(
        body, (out, cts), (stacked, jnp.arange(offset, offset + L)), reverse=True
    )
    return (
        dstacked,
        dx1,
        dx2,
        dm1,
        dm2,
        _zero_cotangent(x_mask),
        _zero_cotangent(msa_mask),
        _zero_cotangent(rng),
    )


_reversible_core.defvjp(_reversible_core_fwd, _reversible_core_bwd)


# --- public API -------------------------------------------------------------


def reversible_trunk_apply(
    stacked,
    cfg: Alphafold2Config,
    x,
    m,
    *,
    x_mask=None,
    msa_mask=None,
    rng=None,
    reverse: bool = True,
):
    """Run the reversible trunk.

    Args:
      stacked: depth-stacked layer params (reversible_trunk_init), or a list
        of per-layer params (stacked on the fly).
      x: pair representation (b, n, n, d).
      m: MSA stream (b, rows, cols, d) — REQUIRED
        (reference reversible.py:316).
      x_mask: (b, n, n) bool. msa_mask: (b, rows, cols) bool.
      rng: dropout key (None = deterministic).
      reverse: True = O(1)-memory custom-vjp path; False = identical math
        through plain autodiff (the parity oracle,
        reference reversible.py:296-300).

    Returns: (x, m) — the channel-halved streams averaged back to dim d
      (reference reversible.py:327).
    """
    if m is None:
        raise ValueError("the reversible trunk requires an MSA stream "
                         "(reference reversible.py:316)")
    if isinstance(stacked, (list, tuple)):
        stacked = stack_layers(list(stacked))

    # segment the depth by runs of equal sparse flags: each segment scans a
    # uniform layer body through its own reversible core. A uniform config
    # ((False,)*depth or (True,)*depth) is one segment — the original single
    # scan; the reference's interleaved (True, False)*6 becomes 12 chained
    # cores, whose chaining stores one (4-tensor) boundary state per segment
    # — still far below storing every layer.
    flags = cfg.layer_sparse
    segments = uniform_flag_runs(flags)

    state = (x, x, m, m)  # channel-double (reference reversible.py:319)
    for seg_start, seg_end in segments:
        seg = jax.tree_util.tree_map(lambda t: t[seg_start:seg_end], stacked)
        meta = (cfg, flags[seg_start], seg_start)
        if reverse:
            state = _reversible_core(meta, seg, *state, x_mask, msa_mask, rng)
        else:
            state = _scan_forward(meta, seg, state, x_mask, msa_mask, rng)
    z1, z2, o1, o2 = state
    return (z1 + z2) * 0.5, (o1 + o2) * 0.5
