"""Model layer: the dual-track (pair representation + MSA) attention trunk
and the Alphafold2 model (reference alphafold2_pytorch/alphafold2.py:290-545),
re-designed as pure init/apply functions over param pytrees.
"""

from alphafold2_tpu.models.alphafold2 import (
    Alphafold2Config,
    alphafold2_init,
    alphafold2_apply,
    alphafold2_front,
    alphafold2_head,
)
from alphafold2_tpu.models.convert import convert_alphafold2
from alphafold2_tpu.models.trunk import (
    trunk_layer_init,
    sequential_trunk_apply,
)
from alphafold2_tpu.models.reversible import (
    reversible_trunk_init,
    reversible_trunk_apply,
    stack_layers,
)
from alphafold2_tpu.models.refiner import (
    RefinerConfig,
    refiner_init,
    refiner_apply,
)
from alphafold2_tpu.models.embedder import (
    EmbedderConfig,
    convert_esm_state_dict,
    convert_hf_esm_state_dict,
    embed_sequences,
    embedder_apply,
    embedder_init,
    esm_tokenize,
)

__all__ = [
    "EmbedderConfig",
    "convert_esm_state_dict",
    "convert_hf_esm_state_dict",
    "embed_sequences",
    "embedder_apply",
    "embedder_init",
    "esm_tokenize",
    "RefinerConfig",
    "refiner_init",
    "refiner_apply",
    "Alphafold2Config",
    "alphafold2_init",
    "alphafold2_apply",
    "alphafold2_front",
    "alphafold2_head",
    "trunk_layer_init",
    "sequential_trunk_apply",
    "reversible_trunk_init",
    "reversible_trunk_apply",
    "stack_layers",
    "convert_alphafold2",
]
